"""Host-side content-addressed store of prefix-KV page runs: the warm
handoff seam between fleet replicas — now shipped over the transport seam.

A replica's :class:`~consensus_tpu.ops.kv_pages.PrefixCache` holds
device-resident KV pages keyed by chained blake2b content keys over
(model identity, page-aligned prompt-token prefix).  Those keys make KV
state PORTABLE: any replica with the same ``kv_cache_identity()`` computes
the same key for the same tokens, so a page run captured from one replica
can be adopted by another — PagedAttention block tables plus
RadixAttention content addressing taken across the replica seam.

Publishing and fetching cross :mod:`consensus_tpu.serve.transport`:

* **Shipping is chunked and resumable.**  A run serializes to one blob;
  the client ships it as ``begin`` / ``chunk``* / ``commit`` messages.
  ``begin`` returns the chunk indices the store already holds, so a
  transfer interrupted by drops resumes instead of restarting; each chunk
  carries its own hash (rejected chunks are re-sent), and ``commit``
  verifies the END-TO-END content hash before admission.
* **Corrupt or truncated runs are never admitted.**  Admission —
  including the local, non-transport path — goes through
  :meth:`PageStore.admit_blob`, which re-verifies the blob's content hash
  and raises the typed :class:`PageIntegrityError` (counted in
  ``pagestore_integrity_rejects_total``) on any mismatch, BEFORE the blob
  is ever deserialized.
* **Runs carry a lease.**  With ``lease_s`` set, a published run expires
  that many seconds after its last (re-)admission; an expired run can
  vanish mid-fetch, and the client aborts that adoption cleanly (counted
  in ``pagestore_fetch_aborts_total``) rather than seeding a partial run.
* **Degradation is graceful.**  When the seam is down — peer partitioned,
  transport erroring past its retry budget — a client marks itself
  degraded (``pagestore_degraded`` gauge; enter/exit windows surfaced in
  :meth:`PageStore.stats`), fast-fails capture/seed with a single probe
  instead of hanging, and recovers automatically when a probe succeeds.

The store keeps, per run: the chained content ``key``, the ``tokens``
prefix, block-table metadata (``n_tokens``, ``page_size``, page count),
and the page PAYLOAD — raw KV bytes via the backend's optional
``export_kv_pages`` / ``import_kv_pages`` hooks; backends without the
hooks (the fake backend) store an empty payload: for them the tokens ARE
the state, and adoption reconstructs byte-identical results by
construction — what the warm-handoff byte-identity test pins.

Adoption rules (enforced in :meth:`PageStoreClient.seed_engine`):

* identity must match the adopting cache's identity EXACTLY — a different
  model tier, quantization mode, or tp width names different KV bytes for
  the same tokens, and the store refuses (counted, never silent);
* page_size must match the adopting pool's;
* runs seed most-recently-captured first, so when the adopting cache's
  LRU budget is smaller than the store, the hottest prefixes win.

The :class:`~consensus_tpu.serve.fleet.ReplicaManager` harvests healthy
replicas' caches into one fleet-wide store on its monitor cadence (each
replica through its OWN named transport client, so per-replica partitions
bite) and pre-seeds every replica it spawns BEFORE registering it with
the router — so a respawned replica's first requests hit warm prefixes
instead of re-prefilling.
"""

from __future__ import annotations

import hashlib
import math
import os
import pathlib
import pickle
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

from consensus_tpu.obs.metrics import Registry, get_registry
from consensus_tpu.ops.kv_pages import PagePoolExhausted
from consensus_tpu.serve.transport import LoopbackTransport, TransportError
from consensus_tpu.utils.io_atomic import atomic_write_bytes

#: Default bound on retained runs — LRU over capture recency.  Sized so a
#: scenario-heavy loadgen run (dozens of distinct prompts) fits whole.
DEFAULT_MAX_RUNS = 256

#: Default shipping chunk size.  Small enough that a multi-page KV payload
#: spans several chunks (so resume/partial-transfer paths are real), large
#: enough that loopback shipping stays one or two calls for fake payloads.
DEFAULT_CHUNK_BYTES = 64 * 1024

#: The store's well-known transport peer name.
STORE_PEER = "pagestore"


class PageIntegrityError(RuntimeError):
    """Serialized run bytes failed content-hash verification."""


def _content_hash(blob: bytes) -> str:
    return hashlib.blake2b(blob, digest_size=16).hexdigest()


def _serialize_run(run: Dict[str, Any]) -> bytes:
    """Canonical blob for one run (stable field order, protocol pinned)."""
    record = (
        tuple(run["identity"]),
        bytes(run["key"]),
        tuple(run["tokens"]),
        int(run["n_tokens"]),
        int(run["page_size"]),
        int(run["n_pages"]),
        bytes(run["payload"]),
    )
    return pickle.dumps(record, protocol=4)


def _deserialize_run(blob: bytes) -> Dict[str, Any]:
    identity, key, tokens, n_tokens, page_size, n_pages, payload = (
        pickle.loads(blob)
    )
    return {
        "identity": tuple(identity),
        "key": key,
        "tokens": tuple(tokens),
        "n_tokens": int(n_tokens),
        "page_size": int(page_size),
        "n_pages": int(n_pages),
        "payload": payload,
    }


def _chunks_of(blob: bytes, chunk_bytes: int) -> List[bytes]:
    if not blob:
        return [b""]
    return [blob[i:i + chunk_bytes] for i in range(0, len(blob), chunk_bytes)]


class PageStore:
    """Fleet-wide LRU of exported prefix-KV runs, keyed by
    ``(kv_cache_identity, chained content key)``, published and fetched
    over a message transport.

    The store registers itself on the transport as peer ``"pagestore"``
    with ``ship`` / ``fetch`` / ``probe`` handlers;
    :meth:`client` mints named :class:`PageStoreClient` endpoints whose
    traffic crosses the transport — and therefore any
    :class:`~consensus_tpu.serve.transport.FaultyTransport` wrapped
    around it.  The legacy direct API (``capture_engine`` /
    ``capture_cache`` / ``seed_engine``) delegates to the ``"local"``
    client, so existing callers transparently ride the seam.
    """

    def __init__(
        self,
        max_runs: int = DEFAULT_MAX_RUNS,
        registry: Optional[Registry] = None,
        transport: Any = None,
        lease_s: Optional[float] = None,
        chunk_bytes: int = DEFAULT_CHUNK_BYTES,
        clock: Callable[[], float] = time.monotonic,
        peer: str = STORE_PEER,
        spill_dir=None,
        disk_budget_bytes: Optional[int] = None,
    ):
        self.max_runs = max(1, int(max_runs))
        self.lease_s = None if lease_s is None else float(lease_s)
        self.chunk_bytes = max(1, int(chunk_bytes))
        self.peer = peer
        self._clock = clock
        self._lock = threading.Lock()
        #: Disk backing (None = memory-only, the pre-durability store).
        #: Every admitted run is also atomically spilled as
        #: ``<spill_dir>/<content-hash>.run`` under an LRU byte budget; a
        #: NEW store over the same directory re-indexes the files (each
        #: verified against the hash its name claims) and serves them
        #: lazily — a respawned or upgraded replica warm-seeds from disk
        #: instead of re-prefilling cold.  Memory eviction never deletes
        #: disk files; only the disk budget does.
        self.spill_dir = pathlib.Path(spill_dir) if spill_dir else None
        self.disk_budget_bytes = (
            None if disk_budget_bytes is None else max(1, int(disk_budget_bytes))
        )
        #: content hash -> {path, size, meta}; insertion order == spill /
        #: touch recency (LRU for the disk budget).
        self._disk: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        #: (identity, key) -> content hash, for lazy restore lookups.
        self._disk_by_key: Dict[Tuple[Tuple, bytes], str] = {}
        self._disk_bytes = 0
        self._n_spilled = 0
        self._n_restored = 0
        self._n_disk_evicted = 0
        #: (identity, key) -> run dict; insertion order == capture recency
        #: (move_to_end on re-capture), so iteration from the END yields
        #: most-recently-seen first.
        self._runs: "OrderedDict[Tuple[Tuple, bytes], Dict[str, Any]]" = (
            OrderedDict()
        )
        #: In-flight ship transfers: transfer id -> {hash, n_chunks,
        #: blob_len, chunks}.
        self._transfers: Dict[str, Dict[str, Any]] = {}
        self._registry = registry if registry is not None else get_registry()
        reg = self._registry
        self._m_captured = reg.counter(
            "pagestore_runs_captured_total",
            "Prefix-KV runs harvested from replica caches into the "
            "fleet PageStore (re-captures of a known run count too).",
        )
        self._m_adopted = reg.counter(
            "pagestore_runs_adopted_total",
            "Stored runs adopted into a joining replica's prefix cache "
            "(the warm-handoff seeding path).",
        )
        self._m_rejected = reg.counter(
            "pagestore_identity_rejects_total",
            "Runs refused at adoption because the joining cache's "
            "kv_cache_identity (model tier / quant / tp width) did not "
            "match the run's — mismatched identities name different KV "
            "bytes for the same tokens.",
        )
        self._m_integrity = reg.counter(
            "pagestore_integrity_rejects_total",
            "Run blobs refused at admission because their serialized "
            "bytes failed content-hash verification (corrupt or "
            "truncated transfers; never admitted).",
        )
        self._m_aborts = reg.counter(
            "pagestore_fetch_aborts_total",
            "Run fetches aborted cleanly because the run expired or was "
            "evicted mid-transfer (no partial run is ever adopted).",
        )
        self._m_runs = reg.gauge(
            "pagestore_runs",
            "Prefix-KV runs currently retained by the fleet PageStore.",
        )
        self._m_degraded = reg.gauge(
            "pagestore_degraded",
            "PageStore transport clients currently degraded (seam down "
            "or peer partitioned; replicas fall back to cold prefill).",
        )
        self._m_spilled = reg.counter(
            "pagestore_spilled_runs_total",
            "Prefix-KV runs spilled to the on-disk store (admission-time "
            "write-through under the disk LRU budget).",
        )
        self._m_restored = reg.counter(
            "pagestore_disk_restores_total",
            "Runs restored from disk into the in-memory store (lazy, at "
            "first fetch after a restart).",
        )
        self._m_disk_evicted = reg.counter(
            "pagestore_disk_evictions_total",
            "Spilled run files evicted (LRU) to stay under the disk "
            "byte budget.",
        )
        self._m_disk_runs = reg.gauge(
            "pagestore_disk_runs",
            "Run files currently in the on-disk store.",
        )
        self._m_disk_bytes = reg.gauge(
            "pagestore_disk_bytes",
            "Bytes currently held by the on-disk store.",
        )
        if self.spill_dir is not None:
            self.spill_dir.mkdir(parents=True, exist_ok=True)
            self._index_spill_dir()
        self.transport = (
            transport if transport is not None else LoopbackTransport()
        )
        self.transport.register(self.peer, {
            "ship": self._handle_ship,
            "fetch": self._handle_fetch,
            "probe": self._handle_probe,
        })
        self._clients: Dict[str, "PageStoreClient"] = {}

    def __len__(self) -> int:
        with self._lock:
            self._expire_locked()
            return len(self._runs)

    # -- disk backing ---------------------------------------------------------

    def _index_spill_dir(self) -> None:
        """Re-index spilled run files at construction (restart path).

        Each ``<hash>.run`` file's bytes are verified against the hash
        its NAME claims — a torn or tampered file is deleted and counted,
        never indexed.  Files are indexed oldest-first (mtime) so disk
        LRU order survives the restart; runs are NOT loaded into memory
        here — restore is lazy, at first fetch."""
        files = sorted(
            self.spill_dir.glob("*.run"),
            key=lambda p: (p.stat().st_mtime, p.name),
        )
        for path in files:
            claimed = path.stem
            try:
                blob = path.read_bytes()
            except OSError:
                continue
            if _content_hash(blob) != claimed:
                self._m_integrity.inc()
                try:
                    path.unlink()
                except OSError:
                    pass
                continue
            try:
                run = _deserialize_run(blob)
            except Exception:
                self._m_integrity.inc()
                try:
                    path.unlink()
                except OSError:
                    pass
                continue
            self._disk[claimed] = {
                "path": path,
                "size": len(blob),
                "meta": {
                    "identity": run["identity"],
                    "key": run["key"],
                    "page_size": run["page_size"],
                    "n_tokens": run["n_tokens"],
                    "n_pages": run["n_pages"],
                    "hash": claimed,
                    "blob_len": len(blob),
                },
            }
            self._disk_by_key[(run["identity"], run["key"])] = claimed
            self._disk_bytes += len(blob)
        self._m_disk_runs.set(len(self._disk))
        self._m_disk_bytes.set(self._disk_bytes)

    def _spill_locked(self, run: Dict[str, Any]) -> None:
        """Write-through one admitted run to disk (caller holds _lock).
        A run already on disk is just touched (LRU recency); budget
        overflow evicts coldest files first."""
        if self.spill_dir is None:
            return
        blob_hash = run["hash"]
        if blob_hash in self._disk:
            self._disk.move_to_end(blob_hash)
            return
        path = self.spill_dir / f"{blob_hash}.run"
        atomic_write_bytes(path, run["blob"])
        self._disk[blob_hash] = {
            "path": path,
            "size": len(run["blob"]),
            "meta": {
                "identity": run["identity"],
                "key": run["key"],
                "page_size": run["page_size"],
                "n_tokens": run["n_tokens"],
                "n_pages": run["n_pages"],
                "hash": blob_hash,
                "blob_len": len(run["blob"]),
            },
        }
        self._disk_by_key[(run["identity"], run["key"])] = blob_hash
        self._disk_bytes += len(run["blob"])
        self._n_spilled += 1
        self._m_spilled.inc()
        if self.disk_budget_bytes is not None:
            while (self._disk_bytes > self.disk_budget_bytes
                   and len(self._disk) > 1):
                evicted_hash, entry = self._disk.popitem(last=False)
                self._disk_by_key.pop(
                    (entry["meta"]["identity"], entry["meta"]["key"]), None)
                self._disk_bytes -= entry["size"]
                try:
                    os.unlink(entry["path"])
                except OSError:
                    pass
                self._n_disk_evicted += 1
                self._m_disk_evicted.inc()
        self._m_disk_runs.set(len(self._disk))
        self._m_disk_bytes.set(self._disk_bytes)

    def _restore_locked(self, identity: Tuple,
                        key: bytes) -> Optional[Dict[str, Any]]:
        """Lazily restore one spilled run into the in-memory table
        (caller holds _lock).  The file's bytes are hash-verified again
        at restore time (bit rot between index and use); restored runs
        get a fresh lease.  Returns the run, or None."""
        blob_hash = self._disk_by_key.get((identity, key))
        if blob_hash is None:
            return None
        entry = self._disk.get(blob_hash)
        if entry is None:
            return None
        try:
            blob = entry["path"].read_bytes()
        except OSError:
            return None
        if _content_hash(blob) != blob_hash:
            self._m_integrity.inc()
            self._disk.pop(blob_hash, None)
            self._disk_by_key.pop((identity, key), None)
            self._disk_bytes -= entry["size"]
            self._m_disk_runs.set(len(self._disk))
            self._m_disk_bytes.set(self._disk_bytes)
            try:
                os.unlink(entry["path"])
            except OSError:
                pass
            return None
        run = _deserialize_run(blob)
        run["hash"] = blob_hash
        run["blob"] = blob
        if self.lease_s is not None:
            run["expires_s"] = self._clock() + self.lease_s
        store_key = (run["identity"], run["key"])
        self._runs[store_key] = run
        self._runs.move_to_end(store_key)
        while len(self._runs) > self.max_runs:
            self._runs.popitem(last=False)
        self._m_runs.set(len(self._runs))
        self._disk.move_to_end(blob_hash)
        self._n_restored += 1
        self._m_restored.inc()
        return run

    # -- admission (shared by transport and local paths) ---------------------

    def admit_blob(self, blob: bytes, expected_hash: str) -> Dict[str, Any]:
        """Verify-then-admit one serialized run.  EVERY admission — local
        capture or transport commit — lands here: the hash is re-checked
        against the actual bytes and a mismatch raises
        :class:`PageIntegrityError` BEFORE deserialization, so corrupt or
        truncated blobs never reach the run table (nor the unpickler)."""
        actual = _content_hash(blob)
        if actual != expected_hash:
            self._m_integrity.inc()
            raise PageIntegrityError(
                f"run blob hash mismatch: expected {expected_hash}, "
                f"got {actual} ({len(blob)} bytes)"
            )
        try:
            run = _deserialize_run(blob)
        except Exception as exc:
            self._m_integrity.inc()
            raise PageIntegrityError(
                f"run blob failed to deserialize: {exc}"
            ) from exc
        run["hash"] = expected_hash
        run["blob"] = blob
        with self._lock:
            if self.lease_s is not None:
                run["expires_s"] = self._clock() + self.lease_s
            store_key = (run["identity"], run["key"])
            self._runs[store_key] = run
            self._runs.move_to_end(store_key)
            while len(self._runs) > self.max_runs:
                self._runs.popitem(last=False)
            self._m_runs.set(len(self._runs))
            self._spill_locked(run)
        self._m_captured.inc()
        return run

    def _expire_locked(self) -> None:
        if self.lease_s is None:
            return
        now = self._clock()
        expired = [
            key for key, run in self._runs.items()
            if run.get("expires_s") is not None and run["expires_s"] <= now
        ]
        for key in expired:
            del self._runs[key]
        if expired:
            self._m_runs.set(len(self._runs))

    # -- transport handlers ---------------------------------------------------

    def _handle_probe(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        with self._lock:
            self._expire_locked()
            return {"ok": True, "runs": len(self._runs)}

    def _handle_ship(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        phase = msg.get("phase")
        if phase == "begin":
            with self._lock:
                self._expire_locked()
                for run in self._runs.values():
                    if run.get("hash") == msg["hash"]:
                        self._runs.move_to_end(
                            (run["identity"], run["key"]))
                        return {"ok": True, "done": True, "have": []}
                if msg["hash"] in self._disk:
                    # Known content already durable on disk (e.g. spilled
                    # before a restart evicted it from memory): no bytes
                    # need to move.
                    self._disk.move_to_end(msg["hash"])
                    return {"ok": True, "done": True, "have": []}
                transfer = self._transfers.setdefault(msg["transfer"], {
                    "hash": msg["hash"],
                    "n_chunks": int(msg["n_chunks"]),
                    "blob_len": int(msg["blob_len"]),
                    "chunks": {},
                })
                if (transfer["hash"] != msg["hash"]
                        or transfer["n_chunks"] != int(msg["n_chunks"])):
                    # Same transfer id, different content: restart clean.
                    transfer = {
                        "hash": msg["hash"],
                        "n_chunks": int(msg["n_chunks"]),
                        "blob_len": int(msg["blob_len"]),
                        "chunks": {},
                    }
                    self._transfers[msg["transfer"]] = transfer
                return {
                    "ok": True,
                    "done": False,
                    "have": sorted(transfer["chunks"]),
                }
        if phase == "chunk":
            with self._lock:
                transfer = self._transfers.get(msg["transfer"])
            if transfer is None:
                return {"ok": False, "reason": "unknown_transfer"}
            data = bytes(msg["data"])
            if _content_hash(data) != msg["chunk_hash"]:
                return {"ok": False, "reason": "chunk_integrity"}
            with self._lock:
                transfer["chunks"][int(msg["index"])] = data
            return {"ok": True}
        if phase == "commit":
            with self._lock:
                transfer = self._transfers.get(msg["transfer"])
                if transfer is None:
                    return {"ok": False, "reason": "unknown_transfer"}
                missing = [
                    i for i in range(transfer["n_chunks"])
                    if i not in transfer["chunks"]
                ]
            if missing:
                return {
                    "ok": False, "reason": "missing_chunks",
                    "missing": missing,
                }
            blob = b"".join(
                transfer["chunks"][i] for i in range(transfer["n_chunks"])
            )
            with self._lock:
                self._transfers.pop(msg["transfer"], None)
            try:
                self.admit_blob(blob, transfer["hash"])
            except PageIntegrityError:
                return {"ok": False, "reason": "integrity"}
            return {"ok": True}
        return {"ok": False, "reason": "bad_phase"}

    def _handle_fetch(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        phase = msg.get("phase")
        if phase == "list":
            with self._lock:
                self._expire_locked()
                metas = [
                    {
                        "identity": run["identity"],
                        "key": run["key"],
                        "page_size": run["page_size"],
                        "n_tokens": run["n_tokens"],
                        "n_pages": run["n_pages"],
                        "hash": run["hash"],
                        "blob_len": len(run["blob"]),
                        "n_chunks": len(
                            _chunks_of(run["blob"], self.chunk_bytes)),
                    }
                    for run in reversed(self._runs.values())
                ]
                # Disk-only runs (spilled before a restart or evicted
                # from memory) list AFTER the in-memory ones: memory
                # order encodes capture recency, disk is the cold tier.
                in_memory = {
                    (run["identity"], run["key"])
                    for run in self._runs.values()
                }
                for entry in reversed(self._disk.values()):
                    meta = entry["meta"]
                    if (meta["identity"], meta["key"]) in in_memory:
                        continue
                    metas.append(dict(
                        meta,
                        n_chunks=max(
                            1,
                            math.ceil(meta["blob_len"] / self.chunk_bytes)),
                    ))
            return {"ok": True, "runs": metas, "chunk_bytes": self.chunk_bytes}
        if phase == "chunk":
            with self._lock:
                self._expire_locked()
                run = self._runs.get((tuple(msg["identity"]), msg["key"]))
                if run is None:
                    # Not resident: lazily restore from the disk tier
                    # (hash-verified) before declaring the run gone.
                    run = self._restore_locked(
                        tuple(msg["identity"]), msg["key"])
                if run is None:
                    # Expired or evicted mid-transfer: the client must
                    # abort this adoption, never assemble a partial run.
                    return {"ok": False, "reason": "gone"}
                index = int(msg["index"])
                chunks = _chunks_of(run["blob"], self.chunk_bytes)
                if not 0 <= index < len(chunks):
                    return {"ok": False, "reason": "bad_index"}
                data = chunks[index]
            return {
                "ok": True,
                "data": data,
                "chunk_hash": _content_hash(data),
            }
        return {"ok": False, "reason": "bad_phase"}

    # -- clients --------------------------------------------------------------

    def client(self, name: str) -> "PageStoreClient":
        """The named transport client for one endpoint (one per replica,
        plus ``"local"`` for the legacy direct API).  Cached per name so
        degradation state and per-client fault addressing persist."""
        with self._lock:
            existing = self._clients.get(name)
        if existing is not None:
            return existing
        created = PageStoreClient(
            self.transport,
            name,
            store_peer=self.peer,
            registry=self._registry,
            chunk_bytes=self.chunk_bytes,
            clock=self._clock,
            on_degraded=self._on_client_degraded,
        )
        with self._lock:
            return self._clients.setdefault(name, created)

    def _on_client_degraded(self) -> None:
        with self._lock:
            clients = list(self._clients.values())
        self._m_degraded.set(sum(1 for c in clients if c.degraded))

    # -- legacy direct API (rides the "local" client) -------------------------

    def capture_engine(self, engine: Any) -> int:
        """Harvest every dp shard's prefix cache of ``engine``.  Returns
        runs captured (including refreshes of already-known runs)."""
        return self.client("local").capture_engine(engine)

    def capture_cache(self, cache: Any, inner: Any = None) -> int:
        return self.client("local").capture_cache(cache, inner)

    def seed_engine(self, engine: Any) -> int:
        return self.client("local").seed_engine(engine)

    # -- introspection -------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            self._expire_locked()
            runs = list(self._runs.values())
            identities = sorted({repr(r["identity"]) for r in runs})
            clients = dict(self._clients)
        windows: List[Dict[str, Any]] = []
        for name, client in sorted(clients.items()):
            for enter_s, exit_s in client.degradation_windows():
                windows.append({
                    "client": name, "enter_s": enter_s, "exit_s": exit_s,
                })
        windows.sort(key=lambda w: w["enter_s"])
        stats = {
            "runs": len(runs),
            "max_runs": self.max_runs,
            "pages": sum(r["n_pages"] for r in runs),
            "tokens": sum(r["n_tokens"] for r in runs),
            "payload_bytes": sum(len(r["payload"]) for r in runs),
            "identities": identities,
            "lease_s": self.lease_s,
            "degraded_clients": sorted(
                name for name, c in clients.items() if c.degraded),
            "degradation_windows": windows,
        }
        if self.spill_dir is not None:
            with self._lock:
                stats["disk"] = {
                    "spill_dir": str(self.spill_dir),
                    "runs": len(self._disk),
                    "bytes": self._disk_bytes,
                    "budget_bytes": self.disk_budget_bytes,
                    "spilled": self._n_spilled,
                    "restored": self._n_restored,
                    "evicted": self._n_disk_evicted,
                }
        return stats

    def runs(self) -> List[Dict[str, Any]]:
        """Point-in-time copy of retained runs, most recent first (blob
        bytes elided — the hash names them)."""
        with self._lock:
            self._expire_locked()
            return [
                {k: v for k, v in run.items() if k != "blob"}
                for run in reversed(self._runs.values())
            ]


class PageStoreClient:
    """One endpoint's view of the PageStore across the transport seam.

    All capture/seed traffic goes through :meth:`_call`, which retries
    transient transport failures with a small backoff and flips the
    client into DEGRADED mode when the budget is exhausted — from then on
    capture/seed fast-fail behind a single probe (cold prefill instead of
    hanging) until a probe succeeds and the degradation window closes.
    """

    def __init__(
        self,
        transport: Any,
        name: str,
        store_peer: str = STORE_PEER,
        registry: Optional[Registry] = None,
        retries: int = 3,
        retry_backoff_s: float = 0.005,
        chunk_bytes: int = DEFAULT_CHUNK_BYTES,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
        on_degraded: Optional[Callable[[], None]] = None,
    ):
        self.transport = transport
        self.name = name
        self.store_peer = store_peer
        self.chunk_bytes = max(1, int(chunk_bytes))
        self.retries = max(0, int(retries))
        self.retry_backoff_s = float(retry_backoff_s)
        self._clock = clock
        self._sleep = sleep
        self._on_degraded = on_degraded
        self._lock = threading.Lock()
        self._degraded = False
        self._windows: List[List[Optional[float]]] = []
        reg = registry if registry is not None else get_registry()
        self._m_adopted = reg.counter(
            "pagestore_runs_adopted_total",
            "Stored runs adopted into a joining replica's prefix cache "
            "(the warm-handoff seeding path).",
        )
        self._m_rejected = reg.counter(
            "pagestore_identity_rejects_total",
            "Runs refused at adoption because the joining cache's "
            "kv_cache_identity (model tier / quant / tp width) did not "
            "match the run's — mismatched identities name different KV "
            "bytes for the same tokens.",
        )
        self._m_aborts = reg.counter(
            "pagestore_fetch_aborts_total",
            "Run fetches aborted cleanly because the run expired or was "
            "evicted mid-transfer (no partial run is ever adopted).",
        )

    # -- degradation state ----------------------------------------------------

    @property
    def degraded(self) -> bool:
        with self._lock:
            return self._degraded

    def degradation_windows(self) -> List[Tuple[float, Optional[float]]]:
        """Closed and open ``(enter_s, exit_s)`` windows on this client's
        clock (``exit_s`` is None while still degraded)."""
        with self._lock:
            return [(w[0], w[1]) for w in self._windows]

    def _mark_degraded(self) -> None:
        with self._lock:
            if self._degraded:
                return
            self._degraded = True
            self._windows.append([self._clock(), None])
            if len(self._windows) > 64:
                del self._windows[:-64]
        if self._on_degraded is not None:
            self._on_degraded()

    def _mark_healthy(self) -> None:
        with self._lock:
            if not self._degraded:
                return
            self._degraded = False
            self._windows[-1][1] = self._clock()
        if self._on_degraded is not None:
            self._on_degraded()

    # -- transport plumbing ---------------------------------------------------

    def _call(self, op: str, msg: Dict[str, Any],
              attempts: Optional[int] = None) -> Optional[Dict[str, Any]]:
        """One store RPC with retries.  Returns the response dict, or
        None when the transport stayed down past the retry budget (the
        client is then degraded)."""
        total = attempts if attempts is not None else self.retries + 1
        for attempt in range(total):
            try:
                response = self.transport.call(
                    self.name, self.store_peer, op, msg)
            except TransportError:
                if attempt + 1 < total:
                    self._sleep(self.retry_backoff_s * (attempt + 1))
                continue
            self._mark_healthy()
            return response
        self._mark_degraded()
        return None

    def probe(self, attempts: int = 1) -> bool:
        """Is the store reachable from this endpoint right now?  A
        success clears the degraded flag (closing the window)."""
        return self._call("probe", {}, attempts=attempts) is not None

    def _fast_fail(self) -> bool:
        """Degraded clients pay ONE probe per operation instead of the
        full retry ladder — cold prefill beats hanging on a dead seam."""
        return self.degraded and not self.probe(attempts=1)

    # -- capture (publish) ----------------------------------------------------

    def capture_engine(self, engine: Any) -> int:
        caches = getattr(engine, "prefix_caches", None) or []
        inner = getattr(engine, "inner", None)
        captured = 0
        for cache in caches:
            if cache is not None:
                captured += self.capture_cache(cache, inner)
        return captured

    def capture_cache(self, cache: Any, inner: Any = None) -> int:
        """Serialize and ship one :class:`PrefixCache`'s runs to the
        store.  ``inner`` is the backend owning the cache's device pages;
        when it exposes ``export_kv_pages(page_ids) -> bytes`` the run's
        payload is the real KV bytes, otherwise the payload is empty and
        the tokens carry the state (fake/CPU backends)."""
        if self._fast_fail():
            return 0
        identity = tuple(getattr(cache, "identity", ()))
        exporter = getattr(inner, "export_kv_pages", None)
        captured = 0
        for run in cache.export_runs():
            payload = b""
            if callable(exporter):
                try:
                    payload = exporter(run["pages"])
                except Exception:
                    # A replica dying mid-harvest must not poison the
                    # store — skip the run, keep what we have.
                    continue
            blob = _serialize_run({
                "identity": identity,
                "key": run["key"],
                "tokens": tuple(run["tokens"]),
                "n_tokens": int(run["n_tokens"]),
                "page_size": int(run["page_size"]),
                "n_pages": len(run["pages"]),
                "payload": payload,
            })
            if self._ship_blob(blob, _content_hash(blob)):
                captured += 1
            elif self.degraded:
                break  # seam is down; stop burning the probe budget
        return captured

    def _ship_blob(self, blob: bytes, blob_hash: str) -> bool:
        """Chunked, resumable, verified publish of one run blob."""
        chunks = _chunks_of(blob, self.chunk_bytes)
        transfer = f"{self.name}:{blob_hash}"
        for _pass in range(self.retries + 1):
            begun = self._call("ship", {
                "phase": "begin",
                "transfer": transfer,
                "hash": blob_hash,
                "n_chunks": len(chunks),
                "blob_len": len(blob),
            })
            if begun is None:
                return False
            if begun.get("done"):
                return True
            have = set(begun.get("have", ()))
            for index, data in enumerate(chunks):
                if index in have:
                    continue
                sent = None
                for _try in range(self.retries + 1):
                    sent = self._call("ship", {
                        "phase": "chunk",
                        "transfer": transfer,
                        "index": index,
                        "data": data,
                        "chunk_hash": _content_hash(data),
                    })
                    if sent is None:
                        return False
                    if sent.get("ok"):
                        break
                    # chunk_integrity: the bytes were corrupted in flight
                    # — re-send this chunk.
                if sent is None or not sent.get("ok"):
                    break
            committed = self._call("ship", {
                "phase": "commit", "transfer": transfer,
            })
            if committed is None:
                return False
            if committed.get("ok"):
                return True
            # missing_chunks / integrity / unknown_transfer: next pass
            # resumes (begin returns what the store holds) or restarts.
        return False

    # -- adoption (fetch + seed) ----------------------------------------------

    def seed_engine(self, engine: Any) -> int:
        """Fetch stored runs over the transport and pre-seed a joining
        replica's prefix caches, hottest runs first, round-robin over the
        engine's dp shards (a run's pages live in ONE shard's pool;
        spreading runs balances the per-shard LRU budgets).  Returns runs
        adopted.  Identity/page-size checks happen on the METADATA before
        any chunk moves; assembled blobs are hash-verified before
        deserialization; a run that expires mid-fetch aborts cleanly."""
        caches = [
            c for c in (getattr(engine, "prefix_caches", None) or [])
            if c is not None
        ]
        if not caches:
            return 0
        if self._fast_fail():
            return 0
        inner = getattr(engine, "inner", None)
        importer = getattr(inner, "import_kv_pages", None)
        listing = self._call("fetch", {"phase": "list"})
        if listing is None or not listing.get("ok"):
            return 0
        adopted = 0
        shard = 0
        for meta in listing["runs"]:
            cache = caches[shard % len(caches)]
            if tuple(meta["identity"]) != tuple(cache.identity):
                self._m_rejected.inc()
                continue
            if meta["page_size"] != cache.pool.page_size:
                self._m_rejected.inc()
                continue
            blob = self._fetch_blob(meta)
            if blob is None:
                if self.degraded:
                    break
                continue
            run = _deserialize_run(blob)
            try:
                pages = cache.pool.alloc(run["n_pages"], owner=self)
            except PagePoolExhausted:
                break
            if cache.insert(run["tokens"], pages):
                if callable(importer):
                    try:
                        importer(pages, run["payload"])
                    except Exception:
                        pass
                adopted += 1
                self._m_adopted.inc()
                shard += 1
            # Drop the seeding reference either way: on success the cache
            # holds its own reference (pages stay resident); on a dup/
            # over-budget refusal the pages go straight back to the pool.
            cache.pool.free(pages)
        return adopted

    def _fetch_blob(self, meta: Dict[str, Any]) -> Optional[bytes]:
        """Fetch + verify one run blob; None on abort (gone mid-transfer,
        transport down, or unrecoverable corruption)."""
        for _pass in range(self.retries + 1):
            parts: List[Optional[bytes]] = [None] * int(meta["n_chunks"])
            aborted = False
            for index in range(int(meta["n_chunks"])):
                got = None
                for _try in range(self.retries + 1):
                    got = self._call("fetch", {
                        "phase": "chunk",
                        "identity": meta["identity"],
                        "key": meta["key"],
                        "index": index,
                    })
                    if got is None:
                        return None
                    if not got.get("ok"):
                        # gone: expired/evicted mid-transfer — abort this
                        # run cleanly, never assemble a partial blob.
                        self._m_aborts.inc()
                        return None
                    data = bytes(got["data"])
                    if _content_hash(data) == got["chunk_hash"]:
                        parts[index] = data
                        break
                    # corrupted in flight: re-fetch this chunk
                if parts[index] is None:
                    aborted = True
                    break
            if aborted:
                continue
            blob = b"".join(parts)  # type: ignore[arg-type]
            if _content_hash(blob) == meta["hash"]:
                return blob
            # End-to-end mismatch (e.g. per-chunk hashes corrupted in the
            # same message as their data): refuse and re-fetch the run.
        return None
