"""Host-side content-addressed store of prefix-KV page runs: the warm
handoff seam between fleet replicas.

A replica's :class:`~consensus_tpu.ops.kv_pages.PrefixCache` holds
device-resident KV pages keyed by chained blake2b content keys over
(model identity, page-aligned prompt-token prefix).  Those keys make KV
state PORTABLE: any replica with the same ``kv_cache_identity()`` computes
the same key for the same tokens, so a page run captured from one replica
can be adopted by another — PagedAttention block tables plus
RadixAttention content addressing taken across the replica seam.

The store keeps, per run:

* the chained content ``key`` (the run's identity within a model identity),
* the ``tokens`` prefix (needed to rebuild the chain on the adopting side),
* block-table metadata (``n_tokens``, ``page_size``, page count), and
* the page PAYLOAD — raw KV bytes, captured via the backend's optional
  ``export_kv_pages(page_ids)`` hook and restored via
  ``import_kv_pages(page_ids, payload)``.  Backends without the hooks
  (the fake backend, whose "KV" is derived deterministically from tokens)
  store an empty payload: for them the tokens ARE the state, and adoption
  reconstructs byte-identical results by construction — which is exactly
  what the warm-handoff byte-identity test pins.

Adoption rules (enforced in :meth:`seed_engine`):

* identity must match the adopting cache's identity EXACTLY — a different
  model tier, quantization mode, or tp width names different KV bytes for
  the same tokens, and the store refuses (counted, never silent);
* page_size must match the adopting pool's;
* runs seed most-recently-captured first, so when the adopting cache's
  LRU budget is smaller than the store, the hottest prefixes win.

The :class:`~consensus_tpu.serve.fleet.ReplicaManager` harvests healthy
replicas' caches into one fleet-wide store on its monitor cadence and
pre-seeds every replica it spawns BEFORE registering it with the router —
so a respawned replica's first requests hit warm prefixes instead of
re-prefilling (the availability is the router's; the latency floor is
this store's).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

from consensus_tpu.obs.metrics import Registry, get_registry
from consensus_tpu.ops.kv_pages import PagePoolExhausted

#: Default bound on retained runs — LRU over capture recency.  Sized so a
#: scenario-heavy loadgen run (dozens of distinct prompts) fits whole.
DEFAULT_MAX_RUNS = 256


class PageStore:
    """Fleet-wide LRU of exported prefix-KV runs, keyed by
    ``(kv_cache_identity, chained content key)``."""

    def __init__(
        self,
        max_runs: int = DEFAULT_MAX_RUNS,
        registry: Optional[Registry] = None,
    ):
        self.max_runs = max(1, int(max_runs))
        self._lock = threading.Lock()
        #: (identity, key) -> run dict; insertion order == capture recency
        #: (move_to_end on re-capture), so iteration from the END yields
        #: most-recently-seen first.
        self._runs: "OrderedDict[Tuple[Tuple, bytes], Dict[str, Any]]" = (
            OrderedDict()
        )
        reg = registry if registry is not None else get_registry()
        self._m_captured = reg.counter(
            "pagestore_runs_captured_total",
            "Prefix-KV runs harvested from replica caches into the "
            "fleet PageStore (re-captures of a known run count too).",
        )
        self._m_adopted = reg.counter(
            "pagestore_runs_adopted_total",
            "Stored runs adopted into a joining replica's prefix cache "
            "(the warm-handoff seeding path).",
        )
        self._m_rejected = reg.counter(
            "pagestore_identity_rejects_total",
            "Runs refused at adoption because the joining cache's "
            "kv_cache_identity (model tier / quant / tp width) did not "
            "match the run's — mismatched identities name different KV "
            "bytes for the same tokens.",
        )
        self._m_runs = reg.gauge(
            "pagestore_runs",
            "Prefix-KV runs currently retained by the fleet PageStore.",
        )

    def __len__(self) -> int:
        with self._lock:
            return len(self._runs)

    # -- capture -------------------------------------------------------------

    def capture_engine(self, engine: Any) -> int:
        """Harvest every dp shard's prefix cache of ``engine``.  Returns
        runs captured (including refreshes of already-known runs)."""
        caches = getattr(engine, "prefix_caches", None) or []
        inner = getattr(engine, "inner", None)
        captured = 0
        for cache in caches:
            if cache is not None:
                captured += self.capture_cache(cache, inner)
        return captured

    def capture_cache(self, cache: Any, inner: Any = None) -> int:
        """Harvest one :class:`PrefixCache`'s runs.  ``inner`` is the
        backend owning the cache's device pages; when it exposes
        ``export_kv_pages(page_ids) -> bytes`` the run's payload is the
        real KV bytes, otherwise the payload is empty and the tokens carry
        the state (fake/CPU backends)."""
        identity = tuple(getattr(cache, "identity", ()))
        exporter = getattr(inner, "export_kv_pages", None)
        captured = 0
        for run in cache.export_runs():
            payload = b""
            if callable(exporter):
                try:
                    payload = exporter(run["pages"])
                except Exception:
                    # A replica dying mid-harvest must not poison the
                    # store — skip the run, keep what we have.
                    continue
            with self._lock:
                store_key = (identity, run["key"])
                self._runs[store_key] = {
                    "identity": identity,
                    "key": run["key"],
                    "tokens": tuple(run["tokens"]),
                    "n_tokens": int(run["n_tokens"]),
                    "page_size": int(run["page_size"]),
                    "n_pages": len(run["pages"]),
                    "payload": payload,
                }
                self._runs.move_to_end(store_key)
                while len(self._runs) > self.max_runs:
                    self._runs.popitem(last=False)
                self._m_runs.set(len(self._runs))
            captured += 1
            self._m_captured.inc()
        return captured

    # -- adoption ------------------------------------------------------------

    def seed_engine(self, engine: Any) -> int:
        """Pre-seed a joining replica's prefix caches from the store,
        hottest runs first, round-robin over the engine's dp shards (a
        run's pages live in ONE shard's pool; spreading runs balances the
        per-shard LRU budgets).  Returns runs adopted."""
        caches = [
            c for c in (getattr(engine, "prefix_caches", None) or [])
            if c is not None
        ]
        if not caches:
            return 0
        inner = getattr(engine, "inner", None)
        importer = getattr(inner, "import_kv_pages", None)
        with self._lock:
            runs = [dict(run) for run in reversed(self._runs.values())]
        adopted = 0
        shard = 0
        for run in runs:
            cache = caches[shard % len(caches)]
            if tuple(run["identity"]) != tuple(cache.identity):
                self._m_rejected.inc()
                continue
            if run["page_size"] != cache.pool.page_size:
                self._m_rejected.inc()
                continue
            try:
                pages = cache.pool.alloc(run["n_pages"], owner=self)
            except PagePoolExhausted:
                break
            if cache.insert(run["tokens"], pages):
                if callable(importer):
                    try:
                        importer(pages, run["payload"])
                    except Exception:
                        pass
                adopted += 1
                self._m_adopted.inc()
                shard += 1
            # Drop the seeding reference either way: on success the cache
            # holds its own reference (pages stay resident); on a dup/
            # over-budget refusal the pages go straight back to the pool.
            cache.pool.free(pages)
        return adopted

    # -- introspection -------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            runs = list(self._runs.values())
            identities = sorted({repr(r["identity"]) for r in runs})
            return {
                "runs": len(runs),
                "max_runs": self.max_runs,
                "pages": sum(r["n_pages"] for r in runs),
                "tokens": sum(r["n_tokens"] for r in runs),
                "payload_bytes": sum(len(r["payload"]) for r in runs),
                "identities": identities,
            }

    def runs(self) -> List[Dict[str, Any]]:
        """Point-in-time copy of retained runs, most recent first."""
        with self._lock:
            return [dict(run) for run in reversed(self._runs.values())]
