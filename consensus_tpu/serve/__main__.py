"""``python -m consensus_tpu.serve`` — run the consensus HTTP server.

Quickstart (hardware-free):

    python -m consensus_tpu.serve --backend fake --port 8080

    curl -s localhost:8080/v1/consensus -d '{
      "issue": "Should we invest in public transport?",
      "agent_opinions": {"Agent 1": "Yes, buses are vital.",
                         "Agent 2": "Only with congestion pricing."},
      "method": "best_of_n", "params": {"n": 4, "max_tokens": 32},
      "seed": 7}'

SIGINT/SIGTERM drains gracefully: admission closes (new requests get 429),
queued and in-flight requests finish, then the process exits.
"""

from __future__ import annotations

import argparse
import json
import logging
import signal
import threading


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m consensus_tpu.serve",
        description="Online consensus-statement server.",
    )
    parser.add_argument("--backend", default="fake",
                        help="backend name: fake | tpu | api (default: fake)")
    parser.add_argument("--backend-options", default="{}",
                        help="JSON object of backend constructor kwargs "
                             '(e.g. \'{"checkpoint": "/path/to/hf"}\')')
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8080)
    parser.add_argument("--max-queue-depth", type=int, default=64,
                        help="admission queue bound; beyond it requests get "
                             "an explicit 429 (default: 64)")
    parser.add_argument("--max-inflight", type=int, default=4,
                        help="worker pool size = concurrently executing "
                             "requests sharing one BatchingBackend "
                             "(default: 4)")
    parser.add_argument("--default-timeout-s", type=float, default=120.0,
                        help="per-request deadline when the client sends "
                             "none (default: 120)")
    parser.add_argument("--max-retries", type=int, default=2,
                        help="transient-failure retries per request "
                             "(default: 2)")
    parser.add_argument("--flush-ms", type=float, default=10.0,
                        help="BatchingBackend quiescence window (default: 10)")
    parser.add_argument("--generation-model", default="")
    parser.add_argument("--brownout", action="store_true",
                        help="enable the brownout controller: under load "
                             "pressure, scale down per-request search "
                             "budgets (degraded answers) instead of "
                             "timing out")
    parser.add_argument("--target-p95-ms", type=float, default=None,
                        help="latency SLO fed into the brownout pressure "
                             "signal (implies --brownout)")
    parser.add_argument("--engine", action=argparse.BooleanOptionalAction,
                        default=True,
                        help="serve through the continuous-batching decode "
                             "engine (slot table + paged KV cache) — the "
                             "default; --no-engine opts back into the "
                             "legacy flush-snapshot merge (results are "
                             "byte-identical either way)")
    parser.add_argument("--engine-options", default="{}",
                        help="JSON object of DecodeEngine kwargs (e.g. "
                             '\'{"slots": 16, "page_size": 16}\')')
    parser.add_argument("--decode-steps", type=int, default=None,
                        metavar="K",
                        help="multi-token decode: the engine dispatches "
                             "K-step on-device decode windows per cohort "
                             "(shorthand for --engine-options "
                             '\'{"decode_steps": K}\')')
    parser.add_argument("--speculative", action="store_true",
                        help="engine-native speculative decoding: each "
                             "decode window drafts K tokens per row (n-gram "
                             "self-draft) and verifies them in one dispatch, "
                             "emitting 1 + accepted real tokens; output "
                             "stays byte-identical (shorthand for "
                             '--engine-options \'{"speculative": true}\')')
    parser.add_argument("--fleet", type=int, default=1, metavar="N",
                        help="run N backend replicas behind the fleet "
                             "router (health-gated routing, scenario "
                             "affinity, transparent failover); 1 = "
                             "single-scheduler path, router bypassed "
                             "(default: 1)")
    parser.add_argument("--fleet-options", default="{}",
                        help="JSON object of fleet options: tiers, "
                             "tier_backend_options, hedge_after_s, "
                             "probe_timeout_s, engine (per-replica list — "
                             "legacy flush vs --engine is chosen per "
                             "replica), elastic, elastic_options, "
                             "autoscale, watchdog_timeout_s, ... (see "
                             "create_server docs)")
    parser.add_argument("--elastic", action="store_true",
                        help="(fleet) run the replica lifecycle manager: "
                             "lost replicas respawn under their old name "
                             "with warm PageStore prefix pages, flapping "
                             "ones are quarantined")
    parser.add_argument("--autoscale", action="store_true",
                        help="(fleet) run the pressure-driven autoscaler "
                             "on top of the lifecycle manager (implies "
                             "--elastic); scales the replica target on "
                             "brownout pressure before quality degrades")
    parser.add_argument("--watchdog-timeout-s", type=float, default=None,
                        metavar="S",
                        help="(fleet) arm each replica engine's hang "
                             "watchdog: a device dispatch wedged longer "
                             "than S marks the replica lost and the "
                             "elastic ladder respawns it")
    parser.add_argument("--mesh", default=None, metavar="dp=N,tp=M",
                        help="serve over the (data, model) device mesh: "
                             "shard TPU backend params Megatron-style over "
                             "tp and partition the decode engine's slots + "
                             "page pools over dp (e.g. --mesh dp=4,tp=2)")
    parser.add_argument("--state-dir", default=None, metavar="DIR",
                        help="arm the durable-state layer: fsync'd request "
                             "WAL + idempotency snapshots (single server) "
                             "and the disk-backed PageStore spill tier "
                             "(elastic fleets), all under DIR; relaunching "
                             "with the same DIR after a crash replays "
                             "unresolved requests and warm-seeds KV from "
                             "disk")
    parser.add_argument("--blackbox", default=None, metavar="PATH",
                        help="write the flight recorder's blackbox JSON "
                             "(recent iterations + fleet events) to PATH on "
                             "watchdog trip, replica loss, or SIGTERM "
                             "(env: CONSENSUS_BLACKBOX)")
    parser.add_argument("--telemetry", action="store_true",
                        help="welfare telemetry plane: latency + welfare "
                             "quantile sketches (mergeable across replicas), "
                             "per-tier degraded welfare-gap gauges, fairness "
                             "drift detector; fleets federate /metrics")
    parser.add_argument("--slo", action="store_true",
                        help="run the multi-window burn-rate SLO engine "
                             "(availability, p95 latency, degraded fraction, "
                             "KV headroom, welfare drift) at GET /v1/slo "
                             "and inside /healthz")
    parser.add_argument("--slo-specs", default=None, metavar="JSON",
                        help="JSON list of SLO spec dicts overriding the "
                             "defaults (implies --slo)")
    parser.add_argument("--log-level", default="INFO")
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=args.log_level.upper(),
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )

    from consensus_tpu.obs.trace import get_flight_recorder
    from consensus_tpu.serve import create_server

    if args.blackbox:
        get_flight_recorder().configure(args.blackbox)

    engine_options = json.loads(args.engine_options) or {}
    if args.decode_steps is not None:
        engine_options.setdefault("decode_steps", args.decode_steps)
    if args.speculative:
        engine_options.setdefault("speculative", True)

    fleet_options = json.loads(args.fleet_options) or {}
    if args.elastic or args.autoscale:
        fleet_options.setdefault("elastic", True)
    if args.autoscale:
        fleet_options.setdefault("autoscale", True)
    if args.watchdog_timeout_s is not None:
        fleet_options.setdefault("watchdog_timeout_s", args.watchdog_timeout_s)

    server = create_server(
        backend=args.backend,
        backend_options=json.loads(args.backend_options),
        host=args.host,
        port=args.port,
        max_queue_depth=args.max_queue_depth,
        max_inflight=args.max_inflight,
        default_timeout_s=args.default_timeout_s,
        max_retries=args.max_retries,
        flush_ms=args.flush_ms,
        generation_model=args.generation_model,
        brownout=args.brownout or args.target_p95_ms is not None,
        target_p95_ms=args.target_p95_ms,
        engine=args.engine,
        engine_options=engine_options or None,
        fleet_size=args.fleet,
        fleet_options=fleet_options or None,
        mesh=args.mesh,
        telemetry=args.telemetry,
        slo=(json.loads(args.slo_specs) if args.slo_specs else args.slo),
        state_dir=args.state_dir,
    )
    stop = threading.Event()
    shutdown_reason = ["exit"]

    def handle_signal(signum, frame):
        logging.getLogger("consensus_tpu.serve").info(
            "signal %d: draining and shutting down", signum)
        shutdown_reason[0] = (
            "sigterm" if signum == signal.SIGTERM else "sigint")
        stop.set()

    signal.signal(signal.SIGINT, handle_signal)
    signal.signal(signal.SIGTERM, handle_signal)

    server.start()
    print(json.dumps({
        "serving": server.base_url,
        "endpoints": ["POST /v1/consensus", "GET /healthz", "GET /metrics",
                      "GET /v1/trace/<request_id>", "GET /v1/slo"],
        "backend": args.backend,
        "max_queue_depth": args.max_queue_depth,
        "max_inflight": args.max_inflight,
        "brownout": args.brownout or args.target_p95_ms is not None,
        "engine": args.engine,
        "speculative": bool(engine_options.get("speculative")),
        "fleet": args.fleet,
        "elastic": bool(fleet_options.get("elastic")
                        or fleet_options.get("autoscale")),
        "autoscale": bool(fleet_options.get("autoscale")),
        "mesh": args.mesh,
    }))
    try:
        stop.wait()
    finally:
        _shutdown(server, shutdown_reason[0])
    return 0


def _shutdown(server, reason: str) -> None:
    """Deterministic shutdown ordering: drain → WAL seal → blackbox dump.

    The signal handler only records the reason and sets the stop event;
    the actual teardown happens here, on the main thread.  ``stop()``
    drains the scheduler, which seals the WAL as its last act — so by the
    time the flight recorder dumps, the journal is sealed and the
    blackbox can never capture a half-sealed journal (pinned in
    tests/test_durability.py)."""
    from consensus_tpu.obs.trace import get_flight_recorder

    server.stop(drain=True)
    if reason != "exit":
        get_flight_recorder().dump(reason)


if __name__ == "__main__":
    raise SystemExit(main())
