"""Online consensus service: one validated request → one statement + scores.

The offline driver (``experiment.py``) turns a YAML config into a grid of
(seed × method × param) runs; this module is the same L4 surface folded
down to a single request so the scheduler can drive it concurrently.  A
:class:`ConsensusRequest` carries exactly what one ``Experiment`` run row
carries — issue, agent opinions, method name, per-method params, seed —
and :meth:`ConsensusService.run` produces the statement through the same
``get_method_generator`` factory, so a served statement is byte-identical
to the same (method, params, seed) run through ``Experiment`` (per-request
PRNG keys make it independent of batch composition; pinned in
tests/test_serve.py).

Validation reuses the config surface of ``experiment.py`` rather than
inventing a parallel schema: method names resolve through
``GENERATOR_MAP``, and params are rejected when
``Experiment.expand_param_grid`` would expand them into MORE than one run
config — list-valued params are a sweep axis, not a request.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional

from consensus_tpu.backends.base import Backend, RequestCancelled
from consensus_tpu.methods import GENERATOR_MAP, get_method_generator
from consensus_tpu.methods.anytime import BudgetClock

#: Params that must be scalars of these types when present.
_PARAM_SCALARS = (str, int, float, bool)

#: Welfare metric keys surfaced in the response (subset of the evaluation
#: columns; names match evaluation.py / the reference's CSV schema).
_WELFARE_KEYS = (
    "egalitarian_welfare_cosine",
    "utilitarian_welfare_cosine",
    "log_nash_welfare_cosine",
    "egalitarian_welfare_avg_prob",
    "utilitarian_welfare_avg_prob",
    "log_nash_welfare_avg_prob",
    "egalitarian_welfare_perplexity",
    "utilitarian_welfare_perplexity",
    "log_nash_welfare_perplexity",
)


class RequestValidationError(ValueError):
    """The request payload is malformed; ``errors`` lists every problem."""

    def __init__(self, errors: List[str]):
        super().__init__("; ".join(errors))
        self.errors = list(errors)


@dataclasses.dataclass(frozen=True)
class ConsensusRequest:
    """One consensus-statement request (the unit the scheduler queues)."""

    issue: str
    agent_opinions: Dict[str, str]
    method: str
    params: Dict[str, Any] = dataclasses.field(default_factory=dict)
    seed: int = 42
    #: Compute per-agent utilities + welfare for the response (two extra
    #: backend batches: one embed, one score — they merge through the same
    #: BatchingBackend as everything else).
    evaluate: bool = True
    #: Client-requested deadline in seconds (None → server default).
    timeout_s: Optional[float] = None
    request_id: str = ""
    #: Attach the span tree + critical-path debug block to the response.
    trace: bool = False


def parse_request(payload: Any) -> ConsensusRequest:
    """Validate a decoded JSON payload into a :class:`ConsensusRequest`.

    Collects EVERY problem before raising so a client gets one round trip
    of feedback, not a fix-resubmit loop per field.
    """
    errors: List[str] = []
    if not isinstance(payload, dict):
        raise RequestValidationError(
            [f"request body must be a JSON object, got {type(payload).__name__}"]
        )

    # A scenario ref replaces inline issue/opinions: the request names a
    # registry scenario (``aamas:3``, ``corpus:v2:polarized-500``) and the
    # server resolves it — same text every client, no 75 KB payloads for
    # the 500-agent scenarios.
    scenario_ref = payload.get("scenario")
    if scenario_ref is not None:
        if "issue" in payload or "agent_opinions" in payload:
            errors.append("'scenario' replaces 'issue'/'agent_opinions'; "
                          "send one or the other")
        if not isinstance(scenario_ref, str) or not scenario_ref.strip():
            errors.append("'scenario' must be a ref string like "
                          "'aamas:3' or 'corpus:v2:polarized-500'")
            scenario_ref = None

    if scenario_ref is not None:
        from consensus_tpu.data.scenarios.registry import resolve_scenario_ref

        try:
            resolved = resolve_scenario_ref(scenario_ref)
            issue = resolved["issue"]
            opinions = dict(resolved["agent_opinions"])
        except (ValueError, KeyError, FileNotFoundError) as exc:
            errors.append(f"'scenario': {exc}")
            issue, opinions = "", {}
    else:
        issue = payload.get("issue")
        if not isinstance(issue, str) or not issue.strip():
            errors.append("'issue' must be a non-empty string")

        opinions = payload.get("agent_opinions")
        if not isinstance(opinions, dict) or not opinions:
            errors.append("'agent_opinions' must be a non-empty object of "
                          "{agent name: opinion text}")
            opinions = {}
        else:
            for name, text in opinions.items():
                if not isinstance(text, str) or not text.strip():
                    errors.append(f"opinion for agent {name!r} must be a "
                                  "non-empty string")

    method = payload.get("method")
    if not isinstance(method, str) or method not in GENERATOR_MAP:
        errors.append(
            f"'method' must be one of {sorted(GENERATOR_MAP)}, got {method!r}"
        )

    params = payload.get("params", {})
    if params is None:
        params = {}
    if not isinstance(params, dict):
        errors.append("'params' must be an object of per-method parameters")
        params = {}
    else:
        # Reuse the experiment config surface: list-valued params expand to
        # a Cartesian sweep there — a single online request must stay a
        # single run config.
        from consensus_tpu.experiment import Experiment

        if len(Experiment.expand_param_grid(dict(params))) != 1:
            listed = sorted(k for k, v in params.items() if isinstance(v, list))
            errors.append(
                f"list-valued params {listed} define a sweep grid; submit "
                "one request per grid point (or use run_sweep offline)"
            )
        for key, value in params.items():
            if key == "seed":
                errors.append("'params.seed' conflicts with top-level 'seed'")
            elif value is not None and not isinstance(
                value, _PARAM_SCALARS + (list,)
            ):
                errors.append(
                    f"param {key!r} must be a scalar, got "
                    f"{type(value).__name__}"
                )

    seed = payload.get("seed", 42)
    if isinstance(seed, bool) or not isinstance(seed, int):
        errors.append(f"'seed' must be an integer, got {seed!r}")
        seed = 42

    evaluate = payload.get("evaluate", True)
    if not isinstance(evaluate, bool):
        errors.append("'evaluate' must be a boolean")
        evaluate = True

    timeout_s = payload.get("timeout_s")
    if timeout_s is not None:
        if isinstance(timeout_s, bool) or not isinstance(timeout_s, (int, float)):
            errors.append("'timeout_s' must be a number of seconds")
            timeout_s = None
        elif timeout_s <= 0:
            errors.append("'timeout_s' must be positive")
            timeout_s = None

    request_id = payload.get("request_id", "")
    if not isinstance(request_id, str):
        errors.append("'request_id' must be a string")
        request_id = ""

    trace = payload.get("trace", False)
    if not isinstance(trace, bool):
        errors.append("'trace' must be a boolean")
        trace = False

    unknown = sorted(
        set(payload)
        - {"issue", "agent_opinions", "scenario", "method", "params", "seed",
           "evaluate", "timeout_s", "request_id", "trace"}
    )
    if unknown:
        errors.append(f"unknown fields: {unknown}")

    if errors:
        raise RequestValidationError(errors)
    return ConsensusRequest(
        issue=issue.strip(),
        agent_opinions={str(k): str(v) for k, v in opinions.items()},
        method=method,
        params=dict(params),
        seed=int(seed),
        evaluate=evaluate,
        timeout_s=float(timeout_s) if timeout_s is not None else None,
        request_id=request_id,
        trace=trace,
    )


class ConsensusService:
    """Run one validated request through the decoder (and optionally the
    evaluator), against whichever backend the scheduler hands us — the
    per-worker handle is the shared BatchingBackend, so concurrent
    requests' generate/score/embed calls merge into wide device batches."""

    def __init__(
        self,
        backend: Backend,
        generation_model: str = "",
    ):
        self.backend = backend
        self.generation_model = generation_model

    def run(
        self,
        request: ConsensusRequest,
        backend: Optional[Backend] = None,
        budget_clock: Optional[BudgetClock] = None,
    ) -> Dict[str, Any]:
        """One request → one response dict.

        ``budget_clock`` (scheduler-injected) bounds the method's search:
        on expiry the method returns its best-so-far statement and the
        response is tagged ``degraded=true`` with ``budget_spent``
        accounting; absent a clock the method runs its full configured
        budget and the response is byte-identical to pre-anytime builds."""
        engine = backend if backend is not None else self.backend
        run_config = dict(request.params)
        run_config["seed"] = request.seed
        start = time.perf_counter()
        generator = get_method_generator(
            request.method, engine, run_config, self.generation_model
        )
        if budget_clock is not None:
            generator.budget_clock = budget_clock
        try:
            statement = generator.generate_statement(
                request.issue, request.agent_opinions
            )
        except RequestCancelled:
            # The batching layer dropped one of this request's device calls
            # (ticket cancelled before dispatch).  If a wave already
            # completed, salvage its checkpoint instead of wasting the work;
            # with nothing banked, _degrade raises BudgetExpired and the
            # scheduler reports the timeout.
            if generator.anytime is None:
                raise
            if budget_clock is not None:
                budget_clock.expired()  # latch the "cancelled" reason
            statement = generator._degrade()
        response: Dict[str, Any] = {
            "request_id": request.request_id,
            "method": request.method,
            "seed": request.seed,
            "statement": statement,
        }
        if generator.degraded:
            response["degraded"] = True
            response["degraded_reason"] = generator.degraded_reason
            response["budget_spent"] = dict(generator.budget_spent)
        if generator.pre_brushup_statement is not None and request.params.get(
            "brushup", False
        ):
            response["pre_brushup_statement"] = generator.pre_brushup_statement
        # Evaluation is skipped when the budget died mid-search (spending
        # MORE device time after the deadline defeats the early exit);
        # budget_scaled runs completed with headroom, so they still score.
        if request.evaluate and generator.degraded_reason not in (
            "deadline", "cancelled"
        ):
            try:
                response.update(self._evaluate(request, statement, engine))
            except RequestCancelled:
                response.setdefault("degraded", True)
                response.setdefault("degraded_reason", "cancelled")
                response["evaluation_skipped"] = "cancelled mid-evaluation"
        response["generation_time_s"] = round(time.perf_counter() - start, 3)
        return response

    def _evaluate(
        self, request: ConsensusRequest, statement: str, engine: Backend
    ) -> Dict[str, Any]:
        """Per-agent utilities + welfare, batched through ``engine`` so the
        evaluation calls co-merge with other in-flight requests."""
        from consensus_tpu.embedding import LMPoolEmbedder
        from consensus_tpu.evaluation import StatementEvaluator

        evaluator = StatementEvaluator(
            engine, embedder=LMPoolEmbedder(engine)
        )
        metrics = evaluator.evaluate_statement(
            statement, request.issue, request.agent_opinions
        )
        utilities = {
            name: {
                "cosine_similarity": metrics[f"cosine_similarity_{name}"],
                "avg_logprob": metrics[f"avg_logprob_{name}"],
                "perplexity": metrics[f"perplexity_{name}"],
            }
            for name in request.agent_opinions
        }
        welfare = {key: metrics[key] for key in _WELFARE_KEYS if key in metrics}
        return {"utilities": utilities, "welfare": welfare}
