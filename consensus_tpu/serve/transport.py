"""Message transport seam: loopback impl + deterministic fault wrapper.

Everything that moves between replicas — KV page-run shipping, fetches,
health probes — crosses this seam, so this is where partial transfers,
corrupt bytes, duplicate deliveries, and partitions are *made real* for
tests.  Two implementations:

* :class:`LoopbackTransport` — an in-process hub: peers register named op
  handlers, ``call(src, dst, op, msg)`` invokes the destination handler
  synchronously.  This is the fault-free seam the single-host fleet uses;
  a cross-host transport would implement the same three methods.
* :class:`FaultyTransport` — wraps any transport and injects faults from a
  seeded :class:`~consensus_tpu.backends.faults.FaultPlan`, reusing the
  backend fault plan's addressing (``op``/``call_index``/``after_s``/
  ``rate``) for the transport ops ``ship`` / ``fetch`` / ``probe``:

  - ``latency`` — sleep ``latency_s`` before delivery.
  - ``drop`` — the message never arrives (:class:`TransportDropped`).
  - ``duplicate`` — the destination handler runs TWICE; the first response
    is discarded.  Handlers must be idempotent (PageStore's are).
  - ``reorder`` — delivery is delayed until the next call on the same
    route passes it (degenerates to extra latency for serial callers).
  - ``bit_flip`` — one deterministic bit of the message's ``data`` bytes
    (or of the response's, when the request carries none) is flipped:
    the corruption end-to-end hash verification exists to catch.
  - ``partition`` — scheduled window ``[after_s, after_s + duration_s)``
    during which every call to/from ``spec.peer`` (or every call at all,
    when ``peer`` is None) raises :class:`TransportPartitioned`.
    Bidirectional by construction: the hub sees both directions.

  Injections are counted in the same ``faults_injected_total{kind,op}``
  registry family the backend wrapper uses, so one scrape shows the whole
  scripted incident.

Messages are plain dicts.  By convention a payload's raw bytes ride under
``"data"`` (requests) or ``"data"`` in the response; ``bit_flip`` targets
whichever side carries bytes so both ship (client->store) and fetch
(store->client) directions are corruptible.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

from consensus_tpu.backends.faults import (
    FaultPlan,
    TRANSPORT_OPS,
    _hash_unit,
)
from consensus_tpu.obs.metrics import Registry, get_registry

Message = Dict[str, Any]
Handler = Callable[[Message], Message]


class TransportError(RuntimeError):
    """Base class for transport-seam failures."""


class TransportDropped(TransportError):
    """The message was dropped in flight (injected or real loss)."""


class TransportTimeout(TransportError):
    """The peer did not answer in time."""


class TransportPartitioned(TransportError):
    """The route is inside a scheduled partition window."""


class LoopbackTransport:
    """In-process hub: named peers expose op handlers; calls are local.

    ``register(peer, handlers)`` binds ``{op: callable}`` for a peer;
    ``call(src, dst, op, msg)`` runs ``dst``'s handler for ``op``
    synchronously and returns its response dict.  Unknown destinations or
    ops raise :class:`TransportError` — the same failure shape a remote
    transport would surface for an unreachable or incompatible peer.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._peers: Dict[str, Dict[str, Handler]] = {}

    def register(self, peer: str, handlers: Dict[str, Handler]) -> None:
        with self._lock:
            self._peers[peer] = dict(handlers)

    def unregister(self, peer: str) -> None:
        with self._lock:
            self._peers.pop(peer, None)

    def peers(self) -> List[str]:
        with self._lock:
            return sorted(self._peers)

    def call(self, src: str, dst: str, op: str, msg: Message) -> Message:
        with self._lock:
            handlers = self._peers.get(dst)
        if handlers is None:
            raise TransportError(f"unknown peer {dst!r}")
        handler = handlers.get(op)
        if handler is None:
            raise TransportError(f"peer {dst!r} has no handler for op {op!r}")
        return handler(msg)


class FaultyTransport:
    """Wrap ``inner`` and inject the plan's transport faults into calls.

    Wraps the HUB, not one endpoint: every ``(src, dst)`` pair's traffic
    crosses this object, which is what makes ``partition`` specs
    bidirectional — during the window, calls where EITHER end is the
    partitioned peer fail.  Per-op call indices and the plan seed make
    every injection deterministic given the call order.
    """

    def __init__(
        self,
        inner: Union[LoopbackTransport, "FaultyTransport"],
        plan: Union[FaultPlan, Dict[str, Any], str, None],
        registry: Optional[Registry] = None,
        sleep=time.sleep,
        clock=time.monotonic,
    ) -> None:
        self.inner = inner
        self.plan = FaultPlan.from_spec(plan) or FaultPlan()
        self._sleep = sleep
        self._clock = clock
        self.t0 = clock()
        self._lock = threading.Lock()
        self._call_index = {op: 0 for op in TRANSPORT_OPS}
        #: Parked (reorder) messages keyed by route; each entry is released
        #: by the next call on the same route or by its deadline passing.
        self._parked: Dict[Tuple[str, str], float] = {}
        self._windows = self.plan.partition_windows()
        reg = registry if registry is not None else get_registry()
        self._injected = reg.counter(
            "faults_injected_total",
            "Faults injected by the fault-injection backend, by kind and op.",
            labels=("kind", "op"),
        )

    # -- introspection -------------------------------------------------------

    def peers(self) -> List[str]:
        return self.inner.peers()

    def partition_windows(self) -> List[Tuple[Optional[str], float, float]]:
        """Scheduled partitions as absolute ``(peer, start, end)`` on this
        wrapper's monotonic clock — recovery-time math reads this."""
        return [
            (peer, self.t0 + start, self.t0 + end)
            for peer, start, end in self._windows
        ]

    def partitioned(self, src: str, dst: str,
                    now: Optional[float] = None) -> bool:
        """Is the (src, dst) route inside a partition window right now?"""
        elapsed = (now if now is not None else self._clock()) - self.t0
        for peer, start, end in self._windows:
            if not start <= elapsed < end:
                continue
            if peer is None or peer == src or peer == dst:
                return True
        return False

    # -- registration passthrough -------------------------------------------

    def register(self, peer: str, handlers: Dict[str, Handler]) -> None:
        self.inner.register(peer, handlers)

    def unregister(self, peer: str) -> None:
        self.inner.unregister(peer)

    # -- injection core ------------------------------------------------------

    def _next_index(self, op: str) -> int:
        with self._lock:
            index = self._call_index.setdefault(op, 0)
            self._call_index[op] = index + 1
            return index

    @staticmethod
    def _flip_bit(data: bytes, seed: int, index: int) -> bytes:
        if not data:
            return data
        pos = int(_hash_unit(seed, "bit_flip", index) * len(data) * 8)
        pos = min(pos, len(data) * 8 - 1)
        out = bytearray(data)
        out[pos // 8] ^= 1 << (pos % 8)
        return bytes(out)

    def call(self, src: str, dst: str, op: str, msg: Message) -> Message:
        index = self._next_index(op)
        now = self._clock()
        if self.partitioned(src, dst, now):
            self._injected.labels("partition", op).inc()
            raise TransportPartitioned(
                f"route {src}->{dst} partitioned (op={op}, call={index})"
            )
        specs = self.plan.firing(op, index, now - self.t0)
        duplicate = False
        corrupt_request = corrupt_response = False
        for spec in specs:
            if spec.kind == "latency":
                self._injected.labels("latency", op).inc()
                self._sleep(spec.latency_s)
            elif spec.kind == "drop":
                self._injected.labels("drop", op).inc()
                raise TransportDropped(
                    f"message {src}->{dst} dropped (op={op}, call={index})"
                )
            elif spec.kind == "transient_error":
                self._injected.labels("transient_error", op).inc()
                raise TransportError(
                    f"injected transport fault (op={op}, call={index})"
                )
            elif spec.kind == "timeout_error":
                self._injected.labels("timeout_error", op).inc()
                raise TransportTimeout(
                    f"injected transport timeout (op={op}, call={index})"
                )
            elif spec.kind == "duplicate":
                self._injected.labels("duplicate", op).inc()
                duplicate = True
            elif spec.kind == "reorder":
                # Park this delivery until the next call on the same route
                # has gone first (bounded by a short deadline so a serial
                # caller sees plain extra latency, not a deadlock).
                self._injected.labels("reorder", op).inc()
                route = (src, dst)
                with self._lock:
                    self._parked[route] = now + 0.05
                deadline = now + 0.05
                while self._clock() < deadline:
                    with self._lock:
                        if self._parked.get(route, 0.0) <= self._clock():
                            break
                    self._sleep(0.005)
                with self._lock:
                    self._parked.pop(route, None)
            elif spec.kind == "bit_flip":
                self._injected.labels("bit_flip", op).inc()
                if isinstance(msg.get("data"), (bytes, bytearray)):
                    corrupt_request = True
                else:
                    corrupt_response = True
            # Backend-only kinds (nan/inf/truncate/device_lost/hang) have
            # no transport meaning; ignore them so one plan can address
            # both domains.
        # A later call on a parked route releases the parked one first.
        with self._lock:
            for route in list(self._parked):
                if route == (src, dst):
                    self._parked[route] = 0.0
        if corrupt_request:
            msg = dict(msg, data=self._flip_bit(
                bytes(msg["data"]), self.plan.seed, index))
        if duplicate:
            self.inner.call(src, dst, op, msg)
        response = self.inner.call(src, dst, op, msg)
        if corrupt_response and isinstance(
                response.get("data"), (bytes, bytearray)):
            response = dict(response, data=self._flip_bit(
                bytes(response["data"]), self.plan.seed, index))
        return response
