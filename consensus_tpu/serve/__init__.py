"""Online serving subsystem: scheduler + service + HTTP front end.

Turns the batch pipeline into a service (ROADMAP north star: "serve heavy
traffic").  The layering, front to back:

    HTTP handler threads      http_frontend.ConsensusHTTPServer
      └─ admission + queue    scheduler.RequestScheduler (bounded FIFO,
         └─ worker pool          deadlines, retry, drain)
            └─ decode+score   service.ConsensusService (GENERATOR_MAP)
               └─ merge layer backends.batching.BatchingBackend (shared)
                  └─ engine   FakeBackend / TPUBackend

``python -m consensus_tpu.serve --backend fake`` runs a hardware-free
server; ``serve.loadgen`` replays AAMAS scenarios against it.
"""

from consensus_tpu.serve.autoscale import Autoscaler  # noqa: F401
from consensus_tpu.serve.brownout import BrownoutController  # noqa: F401
from consensus_tpu.serve.fleet import Replica, ReplicaManager  # noqa: F401
from consensus_tpu.serve.http_frontend import ConsensusServer  # noqa: F401
from consensus_tpu.serve.pagestore import (  # noqa: F401
    PageIntegrityError,
    PageStore,
    PageStoreClient,
)
from consensus_tpu.serve.router import FleetRouter, FleetTicket  # noqa: F401
from consensus_tpu.serve.transport import (  # noqa: F401
    FaultyTransport,
    LoopbackTransport,
    TransportDropped,
    TransportError,
    TransportPartitioned,
    TransportTimeout,
)
from consensus_tpu.serve.scheduler import (  # noqa: F401
    IdempotencyCache,
    RequestScheduler,
    RequestTimeout,
    SchedulerRejected,
    Ticket,
)
from consensus_tpu.serve.service import (  # noqa: F401
    ConsensusRequest,
    ConsensusService,
    RequestValidationError,
    parse_request,
)


def create_server(
    backend="fake",
    backend_options=None,
    host: str = "127.0.0.1",
    port: int = 8080,
    max_queue_depth: int = 64,
    max_inflight: int = 4,
    default_timeout_s=120.0,
    max_retries: int = 2,
    flush_ms: float = 10.0,
    generation_model: str = "",
    registry=None,
    fault_plan=None,
    supervise=None,
    brownout: bool = False,
    target_p95_ms=None,
    anytime_margin_s: float = 0.2,
    engine: bool = True,
    engine_options=None,
    fleet_size: int = 1,
    fleet_options=None,
    mesh=None,
    telemetry: bool = False,
    telemetry_options=None,
    slo=False,
    slo_options=None,
    state_dir=None,
) -> ConsensusServer:
    """Wire backend → service → scheduler → HTTP server (not yet started).

    ``state_dir`` (``--state-dir`` on the CLI) arms the durable-state
    layer, all files under one directory so crash recovery is "relaunch
    with the same flag": the fsync'd request WAL
    (:mod:`consensus_tpu.serve.wal`; single-scheduler path), durable
    idempotency-cache snapshots (``idempotency.json``), and — for elastic
    fleets — a disk-backed PageStore spill tier (``pages/``).  A
    relaunched server replays unresolved journal entries through normal
    admission and serves already-answered ones from the snapshot as
    ``idempotent_replay``.  Unset (the default), the serving path is
    byte-identical to the non-durable build (pinned in
    tests/test_durability.py).

    ``fault_plan`` (chaos testing) and ``supervise`` layer the
    fault-tolerance stack over the engine via
    :func:`consensus_tpu.backends.wrap_backend`; a supervised engine's
    circuit breaker is picked up by the scheduler's admission control and
    surfaced in ``/healthz``.

    ``brownout=True`` installs a :class:`BrownoutController`: under load
    pressure, newly dispatched requests run at a scaled-down search budget
    (responses tagged ``degraded``) instead of queueing into 504s.
    ``target_p95_ms`` adds a latency-SLO term to the pressure signal.
    The continuous-batching decode engine is the DEFAULT merge layer:
    byte-identical results to the legacy flush path, no flush barrier,
    and /healthz gains slot-table + KV-page-pool pressure.
    ``engine=False`` (``--no-engine`` on the CLI) opts back into the
    legacy flush-snapshot BatchingBackend.

    ``fleet_size > 1`` (or any ``fleet_options``) builds N full replica
    stacks — each with its OWN backend instance, kill switch, supervisor +
    breaker, optional brownout controller, and scheduler — behind a
    :class:`FleetRouter` (health-gated routing, scenario affinity,
    transparent failover, optional hedging and tier routing).
    ``fleet_options`` keys: ``tiers`` (per-replica tier names; first tier
    listed is the default/full tier), ``tier_backend_options`` (dict tier →
    backend kwargs, e.g. a smaller model for the ``small`` tier),
    ``fault_plans`` (per-replica FaultPlan list for chaos runs),
    ``engine`` (per-replica bool list overriding the global ``engine``
    flag — the flush-vs-engine merge layer is chosen PER REPLICA),
    ``hedge_after_s``, ``probe_interval_s``, ``probe_timeout_s``,
    ``tier_enter_pressure``, ``tier_exit_pressure``, ``tier_min_dwell_s``.

    Elastic fleets (``fleet_options["elastic"]=True``) additionally get a
    :class:`~consensus_tpu.serve.fleet.ReplicaManager` (respawn lost
    replicas under the same name with warm prefix-KV handoff through a
    fleet :class:`~consensus_tpu.serve.pagestore.PageStore`, flap
    quarantine, target-count reconciliation; knobs via
    ``fleet_options["elastic_options"]``) and, with
    ``fleet_options["autoscale"]`` (True or an options dict), an
    :class:`~consensus_tpu.serve.autoscale.Autoscaler` driving the
    manager's target from brownout pressure.
    ``fleet_options["watchdog_timeout_s"]`` arms each replica engine's
    hang watchdog (a dispatch wedged that long latches ``backend_lost``,
    so the ladder — and the manager — treat the hang as a loss).

    With ``fleet_size=1`` and no ``fleet_options`` the router is bypassed
    entirely — the server runs the exact single-scheduler path below, so
    responses stay byte-identical to that path (pinned in
    tests/test_fleet.py).

    ``mesh`` (``"dp=4,tp=2"`` or ``{'dp': 4, 'tp': 2}``) makes the device
    mesh the serving path: TPU backends are built sharded over the
    ``(data, model)`` mesh and the decode engine partitions its slot table
    and page pools over the dp replicas (``--mesh`` on the CLI).  Non-TPU
    backends only see the engine-side partitioning.

    ``telemetry=True`` installs a
    :class:`~consensus_tpu.obs.welfare.ServeTelemetry` sink: latency and
    welfare quantile sketches (mergeable, replica-labelled), per-tier
    degraded-vs-full welfare-gap gauges, and the fairness drift detector.
    ``slo=True`` (or a sequence of spec dicts) runs an
    :class:`~consensus_tpu.obs.slo.SLOEngine` over the request stream plus
    polled ``kv_headroom``/``welfare_drift`` signals, served at ``/v1/slo``
    and inside ``/healthz``.  Both default OFF: with them off the serving
    path takes zero extra allocations and responses stay byte-identical
    (pinned in tests/test_welfare_telemetry.py).

    Resilience/brownout/fleet features default OFF so a quiet server's
    responses stay byte-identical to offline Experiment runs (pinned in
    tests/test_serve.py — the engine default keeps that identity)."""
    from consensus_tpu.backends import get_backend, wrap_backend

    telemetry_obj = None
    if telemetry:
        from consensus_tpu.obs.welfare import ServeTelemetry, set_welfare_sink

        telemetry_obj = ServeTelemetry(
            registry=registry, **dict(telemetry_options or {})
        )
        set_welfare_sink(telemetry_obj)

    if mesh is not None:
        from consensus_tpu.parallel.mesh import parse_mesh_spec

        mesh = parse_mesh_spec(mesh)
        if backend == "tpu":
            backend_options = {"mesh": mesh, **dict(backend_options or {})}
        engine_options = {"mesh": mesh, **dict(engine_options or {})}

    if fleet_size > 1 or fleet_options:
        return _create_fleet_server(
            backend=backend,
            backend_options=backend_options,
            host=host,
            port=port,
            max_queue_depth=max_queue_depth,
            max_inflight=max_inflight,
            default_timeout_s=default_timeout_s,
            max_retries=max_retries,
            flush_ms=flush_ms,
            generation_model=generation_model,
            registry=registry,
            fault_plan=fault_plan,
            supervise=supervise,
            brownout=brownout,
            target_p95_ms=target_p95_ms,
            anytime_margin_s=anytime_margin_s,
            engine=engine,
            engine_options=engine_options,
            fleet_size=max(1, fleet_size),
            fleet_options=dict(fleet_options or {}),
            telemetry_obj=telemetry_obj,
            slo=slo,
            slo_options=slo_options,
            state_dir=state_dir,
        )

    inner = get_backend(backend, **(backend_options or {}))
    if fault_plan is not None or supervise:
        inner = wrap_backend(
            inner, fault_plan=fault_plan, supervise=supervise,
            registry=registry,
        )
    controller = None
    if brownout:
        controller = BrownoutController(
            target_p95_s=(
                target_p95_ms / 1000.0 if target_p95_ms else None
            ),
            registry=registry,
        )
    service = ConsensusService(inner, generation_model=generation_model)
    wal = None
    idempotency = None
    if state_dir is not None:
        import pathlib

        from consensus_tpu.serve.wal import RequestWAL

        state_path = pathlib.Path(state_dir)
        # snapshot_every=1: the WAL already fsyncs per record, so the
        # snapshot matching that cadence is what makes "crash after
        # resolve" deterministically replay from cache (not recompute).
        idempotency = IdempotencyCache(
            snapshot_path=state_path / "idempotency.json",
            snapshot_every=1)
        wal = RequestWAL(state_path, registry=registry)
    scheduler = RequestScheduler(
        handler=service.run,
        backend=inner,
        max_queue_depth=max_queue_depth,
        max_inflight=max_inflight,
        default_timeout_s=default_timeout_s,
        max_retries=max_retries,
        flush_ms=flush_ms,
        registry=registry,
        brownout=controller,
        anytime_margin_s=anytime_margin_s,
        engine=engine,
        engine_options=engine_options,
        telemetry=telemetry_obj,
        idempotency=idempotency,
        wal=wal,
    )
    slo_engine = _build_slo_engine(
        slo, slo_options, registry, scheduler.stats, telemetry_obj
    )
    return ConsensusServer(
        scheduler, host=host, port=port, registry=registry,
        slo_engine=slo_engine, telemetry=telemetry_obj,
    )


def _kv_headroom_signal(stats_fn):
    """Poll signal: min KV-page headroom across whatever ``stats_fn`` sees.

    Single-scheduler stats carry an ``engine`` block; router stats carry
    ``fleet.replicas.<name>.engine``.  Returns None (sample skipped) when
    no engine stats are available — e.g. the legacy flush path."""
    def signal():
        try:
            stats = stats_fn()
        except Exception:
            return None
        engine_stats = stats.get("engine")
        if isinstance(engine_stats, dict):
            value = engine_stats.get("kv_page_headroom")
            if value is not None:
                return value
        fleet = stats.get("fleet")
        if isinstance(fleet, dict):
            values = []
            for rep in fleet.get("replicas", {}).values():
                if not isinstance(rep, dict):
                    continue
                eng = rep.get("engine")
                if isinstance(eng, dict):
                    value = eng.get("kv_page_headroom")
                    if value is not None:
                        values.append(value)
            if values:
                return min(values)
        return None

    return signal


def _build_slo_engine(slo, slo_options, registry, stats_fn, telemetry_obj):
    """Construct the SLOEngine (or None when ``slo`` is falsy).

    ``slo`` is True (default specs) or a sequence of SLOSpec/spec dicts;
    ``slo_options`` passes through engine kwargs (``clock``,
    ``dump_blackbox``, extra ``signals`` — explicit signals win over the
    built-in ``kv_headroom``/``welfare_drift`` closures)."""
    if not slo:
        return None
    from consensus_tpu.obs.slo import SLOEngine

    options = dict(slo_options or {})
    specs = options.pop("specs", None)
    if specs is None and slo is not True:
        specs = slo
    signals = dict(options.pop("signals", None) or {})
    signals.setdefault("kv_headroom", _kv_headroom_signal(stats_fn))
    if telemetry_obj is not None:
        signals.setdefault("welfare_drift", telemetry_obj.drift_status)
    return SLOEngine(
        specs=specs, registry=registry, signals=signals, **options
    )


def _create_fleet_server(
    *,
    backend,
    backend_options,
    host,
    port,
    max_queue_depth,
    max_inflight,
    default_timeout_s,
    max_retries,
    flush_ms,
    generation_model,
    registry,
    fault_plan,
    supervise,
    brownout,
    target_p95_ms,
    anytime_margin_s,
    engine,
    engine_options,
    fleet_size,
    fleet_options,
    telemetry_obj=None,
    slo=False,
    slo_options=None,
    state_dir=None,
):
    """Build N replica stacks behind a :class:`FleetRouter`.

    Every replica gets its OWN backend instance (``get_backend`` with
    ``fresh=True`` — cached instances would alias one device across
    "replicas" and a single injected loss would kill them all), its own
    breaker/supervisor (supervision defaults ON for fleets: the breaker is
    the router's passive health signal), and optionally its own brownout
    controller.  Scalar ``fault_plan`` arms every replica identically;
    ``fleet_options["fault_plans"]`` is a per-replica list (``None``
    entries = no chaos on that replica).  Chaos plans arm a replica's
    FIRST life only: a respawned name gets a clean backend, so a
    deterministic kill cannot respawn-loop the fleet into quarantine.
    """
    from consensus_tpu.backends import get_backend
    from consensus_tpu.serve.fleet import _name_index

    tiers = fleet_options.get("tiers")
    if tiers is not None and len(tiers) != fleet_size:
        raise ValueError(
            f"fleet_options['tiers'] has {len(tiers)} entries for "
            f"fleet_size={fleet_size}"
        )
    tier_backend_options = fleet_options.get("tier_backend_options", {})
    fault_plans = fleet_options.get("fault_plans")
    if fault_plans is not None and len(fault_plans) != fleet_size:
        raise ValueError(
            f"fleet_options['fault_plans'] has {len(fault_plans)} entries "
            f"for fleet_size={fleet_size}"
        )
    engines = fleet_options.get("engine")
    if engines is not None and not isinstance(engines, (list, tuple)):
        engines = [engines] * fleet_size
    watchdog_timeout_s = fleet_options.get("watchdog_timeout_s")
    if watchdog_timeout_s is not None:
        engine_options = {
            "watchdog_timeout_s": watchdog_timeout_s,
            **dict(engine_options or {}),
        }

    built = set()  # names whose first life already consumed its fault plan

    # One fleet-shared completed-result cache: schedulers record terminal
    # results, the router consults it before failover re-dispatch — a
    # request that completed on a dying replica is re-delivered, never
    # re-executed (the zero-duplicates chaos invariant).  With a state
    # dir, the cache is durable: snapshots survive a full-fleet restart,
    # and the disk-backed PageStore (below) survives warm KV with it —
    # the fleet's durability story; the per-request WAL stays single-path
    # (one journal cannot have N replica writers).
    state_path = None
    if state_dir is not None:
        import pathlib

        state_path = pathlib.Path(state_dir)
        state_path.mkdir(parents=True, exist_ok=True)
    idempotency = IdempotencyCache(
        max_entries=fleet_options.get("idempotency_entries", 1024),
        snapshot_path=(
            state_path / "idempotency.json"
            if state_path is not None else None
        ),
    )

    def replica_factory(name, tier=None):
        """Build one UNSTARTED replica stack.  Used for the initial fleet
        AND by the ReplicaManager for respawns/scale-ups — the one place
        the full stack recipe lives."""
        i = _name_index(name)
        if tier is None:
            tier = (
                tiers[i] if tiers is not None and 0 <= i < len(tiers)
                else "full"
            )
        options = dict(backend_options or {})
        options.update(tier_backend_options.get(tier, {}))
        inner = get_backend(backend, fresh=True, **options)
        controller = None
        if brownout:
            controller = BrownoutController(
                target_p95_s=(
                    target_p95_ms / 1000.0 if target_p95_ms else None
                ),
                registry=registry,
            )
        plan = None
        if name not in built:
            built.add(name)
            plan = (
                fault_plans[i]
                if fault_plans is not None and 0 <= i < len(fault_plans)
                else fault_plan
            )
        engine_flag = (
            engines[i] if engines is not None and 0 <= i < len(engines)
            else engine
        )
        return Replica(
            name=name,
            backend=inner,
            tier=tier,
            registry=registry,
            fault_plan=plan,
            supervise=supervise if supervise is not None else True,
            brownout=controller,
            generation_model=generation_model,
            scheduler_options={
                "max_queue_depth": max_queue_depth,
                "max_inflight": max_inflight,
                "default_timeout_s": default_timeout_s,
                "max_retries": max_retries,
                "flush_ms": flush_ms,
                "anytime_margin_s": anytime_margin_s,
                "engine": engine_flag,
                "engine_options": engine_options,
                "telemetry": telemetry_obj,
                "idempotency": idempotency,
            },
        )

    replicas = [replica_factory(f"r{i}") for i in range(fleet_size)]
    router = FleetRouter(
        replicas,
        registry=registry,
        default_timeout_s=default_timeout_s,
        hedge_after_s=fleet_options.get("hedge_after_s"),
        probe_interval_s=fleet_options.get("probe_interval_s", 1.0),
        probe_timeout_s=fleet_options.get("probe_timeout_s"),
        tier_enter_pressure=fleet_options.get("tier_enter_pressure", 0.85),
        tier_exit_pressure=fleet_options.get("tier_exit_pressure", 0.5),
        tier_min_dwell_s=fleet_options.get("tier_min_dwell_s", 2.0),
        idempotency_cache=idempotency,
    )

    autoscale = fleet_options.get("autoscale")
    transport_fault_plan = fleet_options.get("transport_fault_plan")
    if fleet_options.get("elastic") or autoscale or transport_fault_plan:
        from consensus_tpu.serve.autoscale import Autoscaler
        from consensus_tpu.serve.fleet import ReplicaManager
        from consensus_tpu.serve.pagestore import PageStore

        elastic_options = dict(fleet_options.get("elastic_options") or {})
        # The PageStore ships page runs over the transport seam; a
        # transport_fault_plan wraps the loopback hub in the seeded
        # FaultyTransport so drops/corruption/partitions hit real traffic.
        transport = LoopbackTransport()
        if transport_fault_plan is not None:
            from consensus_tpu.backends.faults import FaultPlan

            transport = FaultyTransport(
                transport,
                FaultPlan.from_spec(transport_fault_plan),
                registry=registry,
            )
        store_kwargs = {}
        if "page_store_chunk_bytes" in elastic_options:
            store_kwargs["chunk_bytes"] = elastic_options.pop(
                "page_store_chunk_bytes")
        disk_budget = elastic_options.pop(
            "page_store_disk_budget_bytes", None)
        if state_path is not None:
            store_kwargs["spill_dir"] = state_path / "pages"
            store_kwargs["disk_budget_bytes"] = disk_budget
        store = PageStore(
            max_runs=elastic_options.pop("page_store_runs", 256),
            registry=registry,
            transport=transport,
            lease_s=elastic_options.pop("page_store_lease_s", None),
            **store_kwargs,
        )
        manager = ReplicaManager(
            router,
            replica_factory,
            page_store=store,
            registry=registry,
            **elastic_options,
        )
        if autoscale:
            autoscale_options = (
                dict(autoscale) if isinstance(autoscale, dict) else {}
            )
            autoscale_options.setdefault("max_replicas", fleet_size * 2)
            Autoscaler(manager, registry=registry, **autoscale_options)

    slo_engine = _build_slo_engine(
        slo, slo_options, registry, router.stats, telemetry_obj
    )
    return ConsensusServer(
        router, host=host, port=port, registry=registry,
        slo_engine=slo_engine, telemetry=telemetry_obj,
        federate_metrics=telemetry_obj is not None,
    )
