"""Online serving subsystem: scheduler + service + HTTP front end.

Turns the batch pipeline into a service (ROADMAP north star: "serve heavy
traffic").  The layering, front to back:

    HTTP handler threads      http_frontend.ConsensusHTTPServer
      └─ admission + queue    scheduler.RequestScheduler (bounded FIFO,
         └─ worker pool          deadlines, retry, drain)
            └─ decode+score   service.ConsensusService (GENERATOR_MAP)
               └─ merge layer backends.batching.BatchingBackend (shared)
                  └─ engine   FakeBackend / TPUBackend

``python -m consensus_tpu.serve --backend fake`` runs a hardware-free
server; ``serve.loadgen`` replays AAMAS scenarios against it.
"""

from consensus_tpu.serve.brownout import BrownoutController  # noqa: F401
from consensus_tpu.serve.http_frontend import ConsensusServer  # noqa: F401
from consensus_tpu.serve.scheduler import (  # noqa: F401
    RequestScheduler,
    RequestTimeout,
    SchedulerRejected,
    Ticket,
)
from consensus_tpu.serve.service import (  # noqa: F401
    ConsensusRequest,
    ConsensusService,
    RequestValidationError,
    parse_request,
)


def create_server(
    backend="fake",
    backend_options=None,
    host: str = "127.0.0.1",
    port: int = 8080,
    max_queue_depth: int = 64,
    max_inflight: int = 4,
    default_timeout_s=120.0,
    max_retries: int = 2,
    flush_ms: float = 10.0,
    generation_model: str = "",
    registry=None,
    fault_plan=None,
    supervise=None,
    brownout: bool = False,
    target_p95_ms=None,
    anytime_margin_s: float = 0.2,
    engine: bool = False,
    engine_options=None,
) -> ConsensusServer:
    """Wire backend → service → scheduler → HTTP server (not yet started).

    ``fault_plan`` (chaos testing) and ``supervise`` layer the
    fault-tolerance stack over the engine via
    :func:`consensus_tpu.backends.wrap_backend`; a supervised engine's
    circuit breaker is picked up by the scheduler's admission control and
    surfaced in ``/healthz``.

    ``brownout=True`` installs a :class:`BrownoutController`: under load
    pressure, newly dispatched requests run at a scaled-down search budget
    (responses tagged ``degraded``) instead of queueing into 504s.
    ``target_p95_ms`` adds a latency-SLO term to the pressure signal.
    ``engine=True`` swaps the scheduler's merge layer from the legacy
    flush-snapshot BatchingBackend to the continuous-batching decode
    engine (``--engine`` on the CLI): same byte-identical results, no
    flush barrier, and /healthz gains slot-table + KV-page-pool pressure.

    Defaults OFF so a quiet server's responses stay byte-identical to
    offline Experiment runs (pinned in tests/test_serve.py)."""
    from consensus_tpu.backends import get_backend, wrap_backend

    inner = get_backend(backend, **(backend_options or {}))
    if fault_plan is not None or supervise:
        inner = wrap_backend(
            inner, fault_plan=fault_plan, supervise=supervise,
            registry=registry,
        )
    controller = None
    if brownout:
        controller = BrownoutController(
            target_p95_s=(
                target_p95_ms / 1000.0 if target_p95_ms else None
            ),
            registry=registry,
        )
    service = ConsensusService(inner, generation_model=generation_model)
    scheduler = RequestScheduler(
        handler=service.run,
        backend=inner,
        max_queue_depth=max_queue_depth,
        max_inflight=max_inflight,
        default_timeout_s=default_timeout_s,
        max_retries=max_retries,
        flush_ms=flush_ms,
        registry=registry,
        brownout=controller,
        anytime_margin_s=anytime_margin_s,
        engine=engine,
        engine_options=engine_options,
    )
    return ConsensusServer(scheduler, host=host, port=port, registry=registry)
