"""Online serving subsystem: scheduler + service + HTTP front end.

Turns the batch pipeline into a service (ROADMAP north star: "serve heavy
traffic").  The layering, front to back:

    HTTP handler threads      http_frontend.ConsensusHTTPServer
      └─ admission + queue    scheduler.RequestScheduler (bounded FIFO,
         └─ worker pool          deadlines, retry, drain)
            └─ decode+score   service.ConsensusService (GENERATOR_MAP)
               └─ merge layer backends.batching.BatchingBackend (shared)
                  └─ engine   FakeBackend / TPUBackend

``python -m consensus_tpu.serve --backend fake`` runs a hardware-free
server; ``serve.loadgen`` replays AAMAS scenarios against it.
"""

from consensus_tpu.serve.brownout import BrownoutController  # noqa: F401
from consensus_tpu.serve.fleet import Replica  # noqa: F401
from consensus_tpu.serve.http_frontend import ConsensusServer  # noqa: F401
from consensus_tpu.serve.router import FleetRouter, FleetTicket  # noqa: F401
from consensus_tpu.serve.scheduler import (  # noqa: F401
    RequestScheduler,
    RequestTimeout,
    SchedulerRejected,
    Ticket,
)
from consensus_tpu.serve.service import (  # noqa: F401
    ConsensusRequest,
    ConsensusService,
    RequestValidationError,
    parse_request,
)


def create_server(
    backend="fake",
    backend_options=None,
    host: str = "127.0.0.1",
    port: int = 8080,
    max_queue_depth: int = 64,
    max_inflight: int = 4,
    default_timeout_s=120.0,
    max_retries: int = 2,
    flush_ms: float = 10.0,
    generation_model: str = "",
    registry=None,
    fault_plan=None,
    supervise=None,
    brownout: bool = False,
    target_p95_ms=None,
    anytime_margin_s: float = 0.2,
    engine: bool = False,
    engine_options=None,
    fleet_size: int = 1,
    fleet_options=None,
    mesh=None,
) -> ConsensusServer:
    """Wire backend → service → scheduler → HTTP server (not yet started).

    ``fault_plan`` (chaos testing) and ``supervise`` layer the
    fault-tolerance stack over the engine via
    :func:`consensus_tpu.backends.wrap_backend`; a supervised engine's
    circuit breaker is picked up by the scheduler's admission control and
    surfaced in ``/healthz``.

    ``brownout=True`` installs a :class:`BrownoutController`: under load
    pressure, newly dispatched requests run at a scaled-down search budget
    (responses tagged ``degraded``) instead of queueing into 504s.
    ``target_p95_ms`` adds a latency-SLO term to the pressure signal.
    ``engine=True`` swaps the scheduler's merge layer from the legacy
    flush-snapshot BatchingBackend to the continuous-batching decode
    engine (``--engine`` on the CLI): same byte-identical results, no
    flush barrier, and /healthz gains slot-table + KV-page-pool pressure.

    ``fleet_size > 1`` (or any ``fleet_options``) builds N full replica
    stacks — each with its OWN backend instance, kill switch, supervisor +
    breaker, optional brownout controller, and scheduler — behind a
    :class:`FleetRouter` (health-gated routing, scenario affinity,
    transparent failover, optional hedging and tier routing).
    ``fleet_options`` keys: ``tiers`` (per-replica tier names; first tier
    listed is the default/full tier), ``tier_backend_options`` (dict tier →
    backend kwargs, e.g. a smaller model for the ``small`` tier),
    ``fault_plans`` (per-replica FaultPlan list for chaos runs),
    ``engine`` (per-replica bool list overriding the global ``engine``
    flag — the flush-vs-engine merge layer is chosen PER REPLICA),
    ``hedge_after_s``, ``probe_interval_s``, ``probe_timeout_s``,
    ``tier_enter_pressure``, ``tier_exit_pressure``, ``tier_min_dwell_s``.

    With ``fleet_size=1`` and no ``fleet_options`` the router is bypassed
    entirely — the server runs the exact single-scheduler path below, so
    responses stay byte-identical to that path (pinned in
    tests/test_fleet.py).

    ``mesh`` (``"dp=4,tp=2"`` or ``{'dp': 4, 'tp': 2}``) makes the device
    mesh the serving path: TPU backends are built sharded over the
    ``(data, model)`` mesh and the decode engine partitions its slot table
    and page pools over the dp replicas (``--mesh`` on the CLI).  Non-TPU
    backends only see the engine-side partitioning.

    Defaults OFF so a quiet server's responses stay byte-identical to
    offline Experiment runs (pinned in tests/test_serve.py)."""
    from consensus_tpu.backends import get_backend, wrap_backend

    if mesh is not None:
        from consensus_tpu.parallel.mesh import parse_mesh_spec

        mesh = parse_mesh_spec(mesh)
        if backend == "tpu":
            backend_options = {"mesh": mesh, **dict(backend_options or {})}
        engine_options = {"mesh": mesh, **dict(engine_options or {})}

    if fleet_size > 1 or fleet_options:
        return _create_fleet_server(
            backend=backend,
            backend_options=backend_options,
            host=host,
            port=port,
            max_queue_depth=max_queue_depth,
            max_inflight=max_inflight,
            default_timeout_s=default_timeout_s,
            max_retries=max_retries,
            flush_ms=flush_ms,
            generation_model=generation_model,
            registry=registry,
            fault_plan=fault_plan,
            supervise=supervise,
            brownout=brownout,
            target_p95_ms=target_p95_ms,
            anytime_margin_s=anytime_margin_s,
            engine=engine,
            engine_options=engine_options,
            fleet_size=max(1, fleet_size),
            fleet_options=dict(fleet_options or {}),
        )

    inner = get_backend(backend, **(backend_options or {}))
    if fault_plan is not None or supervise:
        inner = wrap_backend(
            inner, fault_plan=fault_plan, supervise=supervise,
            registry=registry,
        )
    controller = None
    if brownout:
        controller = BrownoutController(
            target_p95_s=(
                target_p95_ms / 1000.0 if target_p95_ms else None
            ),
            registry=registry,
        )
    service = ConsensusService(inner, generation_model=generation_model)
    scheduler = RequestScheduler(
        handler=service.run,
        backend=inner,
        max_queue_depth=max_queue_depth,
        max_inflight=max_inflight,
        default_timeout_s=default_timeout_s,
        max_retries=max_retries,
        flush_ms=flush_ms,
        registry=registry,
        brownout=controller,
        anytime_margin_s=anytime_margin_s,
        engine=engine,
        engine_options=engine_options,
    )
    return ConsensusServer(scheduler, host=host, port=port, registry=registry)


def _create_fleet_server(
    *,
    backend,
    backend_options,
    host,
    port,
    max_queue_depth,
    max_inflight,
    default_timeout_s,
    max_retries,
    flush_ms,
    generation_model,
    registry,
    fault_plan,
    supervise,
    brownout,
    target_p95_ms,
    anytime_margin_s,
    engine,
    engine_options,
    fleet_size,
    fleet_options,
):
    """Build N replica stacks behind a :class:`FleetRouter`.

    Every replica gets its OWN backend instance (``get_backend`` with
    ``fresh=True`` — cached instances would alias one device across
    "replicas" and a single injected loss would kill them all), its own
    breaker/supervisor (supervision defaults ON for fleets: the breaker is
    the router's passive health signal), and optionally its own brownout
    controller.  Scalar ``fault_plan`` arms every replica identically;
    ``fleet_options["fault_plans"]`` is a per-replica list (``None``
    entries = no chaos on that replica).
    """
    from consensus_tpu.backends import get_backend

    tiers = fleet_options.get("tiers")
    if tiers is not None and len(tiers) != fleet_size:
        raise ValueError(
            f"fleet_options['tiers'] has {len(tiers)} entries for "
            f"fleet_size={fleet_size}"
        )
    tier_backend_options = fleet_options.get("tier_backend_options", {})
    fault_plans = fleet_options.get("fault_plans")
    if fault_plans is not None and len(fault_plans) != fleet_size:
        raise ValueError(
            f"fleet_options['fault_plans'] has {len(fault_plans)} entries "
            f"for fleet_size={fleet_size}"
        )
    engines = fleet_options.get("engine")
    if engines is not None and not isinstance(engines, (list, tuple)):
        engines = [engines] * fleet_size

    replicas = []
    for i in range(fleet_size):
        tier = tiers[i] if tiers is not None else "full"
        options = dict(backend_options or {})
        options.update(tier_backend_options.get(tier, {}))
        inner = get_backend(backend, fresh=True, **options)
        controller = None
        if brownout:
            controller = BrownoutController(
                target_p95_s=(
                    target_p95_ms / 1000.0 if target_p95_ms else None
                ),
                registry=registry,
            )
        plan = fault_plans[i] if fault_plans is not None else fault_plan
        replicas.append(
            Replica(
                name=f"r{i}",
                backend=inner,
                tier=tier,
                registry=registry,
                fault_plan=plan,
                supervise=supervise if supervise is not None else True,
                brownout=controller,
                generation_model=generation_model,
                scheduler_options={
                    "max_queue_depth": max_queue_depth,
                    "max_inflight": max_inflight,
                    "default_timeout_s": default_timeout_s,
                    "max_retries": max_retries,
                    "flush_ms": flush_ms,
                    "anytime_margin_s": anytime_margin_s,
                    "engine": engines[i] if engines is not None else engine,
                    "engine_options": engine_options,
                },
            )
        )
    router = FleetRouter(
        replicas,
        registry=registry,
        default_timeout_s=default_timeout_s,
        hedge_after_s=fleet_options.get("hedge_after_s"),
        probe_interval_s=fleet_options.get("probe_interval_s", 1.0),
        probe_timeout_s=fleet_options.get("probe_timeout_s"),
        tier_enter_pressure=fleet_options.get("tier_enter_pressure", 0.85),
        tier_exit_pressure=fleet_options.get("tier_exit_pressure", 0.5),
        tier_min_dwell_s=fleet_options.get("tier_min_dwell_s", 2.0),
    )
    return ConsensusServer(router, host=host, port=port, registry=registry)
