"""Replica lifecycle for fleet serving.

A :class:`Replica` owns one complete single-replica serving stack — its
own backend instance, fault-tolerance wrappers (fault injection below a
supervised backend with its own circuit breaker), kill switch, optional
brownout controller, :class:`~consensus_tpu.serve.service.ConsensusService`
and :class:`~consensus_tpu.serve.scheduler.RequestScheduler` (which in turn
owns the replica's ``BatchingBackend`` / ``DecodeEngine``).  The fleet
router (``serve/router.py``) composes N of these: replica failure becomes
an isolated, routable event instead of an outage.

The wrapper stack, bottom to top::

    supervisor( killswitch( faults( engine ) ) )

* ``faults`` (optional) is the chaos seam — ``FaultPlan.replica_lost``
  arms a deterministic per-replica death.
* ``killswitch`` is the operational seam — ``Replica.kill()`` makes every
  subsequent backend call raise ``BackendLostError``, exactly what a
  preempted device looks like from above.  It sits ABOVE fault injection
  (a killed replica stops injecting anything else) and BELOW the
  supervisor (so the breaker records the loss and trips: the passive
  health signal the router reads).
* ``supervisor`` retries transients, bisects poison rows, and owns the
  replica's :class:`~consensus_tpu.backends.supervisor.CircuitBreaker`.

Health is a derived property, not a stored state: ``lost`` latches (from
an explicit kill, a probe timeout, or the passive device-loss flags the
supervisor and engine latch), draining follows the scheduler, and an open
breaker demotes the replica to ``degraded`` — routable as a last resort,
skipped while healthier peers exist.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from consensus_tpu.backends.base import Backend, BackendLostError
from consensus_tpu.obs.metrics import Registry, get_registry
from consensus_tpu.obs.trace import get_flight_recorder
from consensus_tpu.serve.brownout import BrownoutController
from consensus_tpu.serve.scheduler import RequestScheduler
from consensus_tpu.serve.service import ConsensusService

#: Health states, in decreasing order of routability.
HEALTHY = "healthy"
DEGRADED = "degraded"  # breaker open: routable only as a last resort
DRAINING = "draining"
LOST = "lost"


class ReplicaKillSwitch:
    """Backend wrapper with an off button.

    Until :meth:`kill`, every call passes straight through.  After it,
    every call raises :class:`BackendLostError` — the sticky device-loss
    contract, so the supervised stack above reacts exactly as it would to
    a real preemption.  Deliberately does NOT expose
    ``open_fused_token_search``: fused sessions bypass the protocol seam,
    and a killed replica must be dead on EVERY path.
    """

    name = "killswitch"

    def __init__(self, inner: Backend):
        self.inner = inner
        self._lost = threading.Event()
        self._reason = ""

    def kill(self, reason: str = "killed") -> None:
        self._reason = reason
        self._lost.set()

    @property
    def lost(self) -> bool:
        return self._lost.is_set()

    # -- passthrough surface ----------------------------------------------

    @property
    def deterministic_greedy(self) -> bool:
        return bool(getattr(self.inner, "deterministic_greedy", False))

    @property
    def token_counts(self):
        return getattr(self.inner, "token_counts", {})

    # -- protocol -----------------------------------------------------------

    def _check(self, op: str) -> None:
        if self._lost.is_set():
            raise BackendLostError(
                f"replica backend is gone ({self._reason}); {op} refused"
            )

    def generate(self, requests):
        self._check("generate")
        return self.inner.generate(requests)

    def score(self, requests):
        self._check("score")
        return self.inner.score(requests)

    def next_token_logprobs(self, requests):
        self._check("next_token_logprobs")
        return self.inner.next_token_logprobs(requests)

    def embed(self, texts):
        self._check("embed")
        return self.inner.embed(texts)


class Replica:
    """One backend replica: wrapped stack + service + scheduler + health."""

    def __init__(
        self,
        name: str,
        backend: Backend,
        *,
        tier: str = "full",
        registry: Optional[Registry] = None,
        fault_plan=None,
        supervise=True,
        brownout: Optional[BrownoutController] = None,
        generation_model: str = "",
        scheduler_options: Optional[Dict[str, Any]] = None,
    ):
        self.name = name
        self.tier = tier
        reg = registry if registry is not None else get_registry()
        stack: Backend = backend
        if fault_plan is not None:
            from consensus_tpu.backends.faults import FaultInjectingBackend

            stack = FaultInjectingBackend(stack, fault_plan, registry=reg)
        self.kill_switch = ReplicaKillSwitch(stack)
        stack = self.kill_switch
        self._supervisor = None
        if supervise:
            from consensus_tpu.backends.supervisor import (
                CircuitBreaker,
                SupervisedBackend,
            )

            options = dict(supervise) if isinstance(supervise, dict) else {}
            breaker = CircuitBreaker(
                failure_threshold=options.get("failure_threshold", 5),
                cooldown_s=options.get("cooldown_s", 5.0),
                registry=reg,
                name=name,
            )
            stack = SupervisedBackend(
                stack, breaker=breaker, registry=reg, **options
            )
            self._supervisor = stack
        self.backend = stack
        self.brownout = brownout
        service = ConsensusService(stack, generation_model=generation_model)
        self.scheduler = RequestScheduler(
            handler=service.run,
            backend=stack,
            registry=reg,
            brownout=brownout,
            **(scheduler_options or {}),
        )
        # Spans and per-replica health report which replica served; the
        # tier label feeds welfare-by-tier telemetry (obs/welfare.py) so
        # degraded-tier responses are accounted against full-tier welfare.
        self.scheduler.replica_name = name
        self.scheduler.replica_tier = tier
        self._lost = threading.Event()
        self._lost_reason = ""
        #: Transport-seam health (set by the manager's transport probes):
        #: a partitioned replica is DEGRADED — routed around while healthy
        #: peers exist, but still a last resort (a partitioned seam stops
        #: warm handoff, not serving) — and auto-heals when probes pass.
        self.transport_ok = True
        self.transport_reason = ""

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "Replica":
        self.scheduler.start()
        return self

    def shutdown(self, drain: bool = True,
                 timeout: Optional[float] = None) -> None:
        self.scheduler.shutdown(drain=drain, timeout=timeout)

    def kill(self, reason: str = "killed") -> None:
        """Operational kill: the backend starts raising BackendLostError
        (in-flight requests fail fast and fail over) and routing skips the
        replica immediately."""
        self.kill_switch.kill(reason)
        self.mark_lost(reason)

    def mark_lost(self, reason: str) -> None:
        """Routing-only loss mark (probe timeout, observed BackendLostError):
        the backend is left as-is — if it is truly gone its own calls keep
        failing; marking just stops new placements."""
        if not self._lost.is_set():
            self._lost_reason = reason
            self._lost.set()
            recorder = get_flight_recorder()
            recorder.record_event(
                "replica_lost", replica=self.name, reason=reason)
            recorder.dump("replica_loss")

    # -- health -------------------------------------------------------------

    @property
    def lost(self) -> bool:
        """Explicit mark, kill switch, or the passive device-loss flags the
        supervisor / engine latched while serving."""
        if self._lost.is_set() or self.kill_switch.lost:
            return True
        if self._supervisor is not None and getattr(
            self._supervisor, "backend_lost", False
        ):
            return True
        engine = self.scheduler.batching.engine
        if engine is not None and engine.backend_lost:
            return True
        return False

    @property
    def health(self) -> str:
        if self.lost:
            return LOST
        if self.scheduler.draining:
            return DRAINING
        breaker = self.scheduler.circuit_breaker
        if breaker is not None and breaker.state == "open":
            return DEGRADED
        if not self.transport_ok:
            return DEGRADED
        return HEALTHY

    @property
    def lost_reason(self) -> str:
        if self._lost_reason:
            return self._lost_reason
        return "backend_lost" if self.lost else ""

    def probe(self, timeout_s: float) -> bool:
        """Active liveness probe: one tiny ``embed`` call against the
        wrapped stack (below the batching layer, so it cannot jam the
        request path), bounded by ``timeout_s``.  A hung or lost backend
        marks the replica lost.  Off by default at the router (active
        probes consume fault-plan call indices, which deterministic chaos
        tests pin)."""
        if self.lost:
            return False
        result: Dict[str, Any] = {}
        done = threading.Event()

        def run() -> None:
            try:
                self.backend.embed(["__fleet_probe__"])
                result["ok"] = True
            except Exception as exc:  # noqa: BLE001 - classified below
                result["error"] = exc
            done.set()

        thread = threading.Thread(
            target=run, name=f"probe-{self.name}", daemon=True
        )
        thread.start()
        if not done.wait(timeout_s):
            self.mark_lost("probe_timeout")
            return False
        if "ok" in result:
            return True
        if isinstance(result.get("error"), BackendLostError):
            self.mark_lost("backend_lost")
        return False

    # -- reporting ----------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """Per-replica /healthz block: tier, health, breaker, brownout,
        occupancy — the router aggregates these."""
        stats = self.scheduler.stats()
        snap: Dict[str, Any] = {
            "tier": self.tier,
            "health": self.health,
            "queue_depth": stats["queue_depth"],
            "inflight": stats["inflight"],
            "max_queue_depth": stats["max_queue_depth"],
            "max_inflight": stats["max_inflight"],
            "workers_alive": stats["workers_alive"],
            "device_batches": stats["device_batches"],
        }
        if self.lost_reason:
            snap["lost_reason"] = self.lost_reason
        if not self.transport_ok:
            snap["transport"] = {
                "ok": False, "reason": self.transport_reason,
            }
        for key in ("engine", "circuit_breaker", "brownout"):
            if key in stats:
                snap[key] = stats[key]
        return snap


def _name_index(name: str) -> int:
    """Numeric suffix of a replica name (``r12`` -> 12); -1 when absent.
    Spawn naming and scale-down victim selection both key on it."""
    digits = ""
    for ch in reversed(name):
        if ch.isdigit():
            digits = ch + digits
        else:
            break
    return int(digits) if digits else -1


class ReplicaManager:
    """Replica lifecycle: respawn lost members, reconcile a target count,
    and warm-hand prefix KV over the replica seam.

    The health ladder (above) DETECTS loss; this layer makes loss
    recoverable.  A monitor thread runs three duties per tick:

    1. **Harvest** — healthy replicas' prefix caches are captured into the
       fleet :class:`~consensus_tpu.serve.pagestore.PageStore` on a
       bounded cadence, so the store always holds a recent snapshot of the
       fleet's hottest page runs (a replica's last harvest survives its
       death — that is the whole point).
    2. **Respawn** — a member whose ladder latched ``lost`` is removed
       from the router immediately, its corpse retired on a background
       thread (``drain=False`` with a short timeout: a wedged worker must
       not block the fleet), and a fresh stack is built by the
       ``replica_factory`` under the SAME name after a bounded exponential
       backoff — same name means rendezvous hashing restores the exact
       pre-loss scenario mapping, so the warm pages seeded from the store
       land where their scenarios route.  A flap detector quarantines a
       name that dies ``flap_threshold`` times within ``flap_window_s``
       instead of respawn-looping it; quarantined slots are NOT backfilled
       (the effective target shrinks) until an operator calls
       :meth:`clear_quarantine` — a flapping unit signals a fault no fresh
       stack will outrun.
    3. **Reconcile** — live-plus-pending membership converges on
       ``target`` (driven by the autoscaler or :meth:`set_target`):
       scale-up spawns fresh names seeded warm from the store; scale-down
       retires the highest-numbered healthy member with a full drain.

    ``replica_factory(name, tier)`` must return an UNSTARTED
    :class:`Replica` over a fresh backend instance; the manager starts it,
    seeds its engine's prefix caches from the store, and only then
    registers it with the router — a joining replica never takes traffic
    cold.
    """

    def __init__(
        self,
        router,
        replica_factory: Callable[[str, Optional[str]], Replica],
        *,
        page_store=None,
        registry: Optional[Registry] = None,
        respawn_backoff_s: float = 0.25,
        respawn_backoff_max_s: float = 5.0,
        flap_window_s: float = 30.0,
        flap_threshold: int = 3,
        check_interval_s: float = 0.2,
        harvest_interval_s: float = 0.5,
        retire_timeout_s: float = 2.0,
        transport_probe_failures: int = 2,
        auto_start: bool = True,
        clock=time.monotonic,
    ):
        self.router = router
        self.factory = replica_factory
        self.page_store = page_store
        self.respawn_backoff_s = float(respawn_backoff_s)
        self.respawn_backoff_max_s = float(respawn_backoff_max_s)
        self.flap_window_s = float(flap_window_s)
        self.flap_threshold = max(1, int(flap_threshold))
        self.check_interval_s = float(check_interval_s)
        self.harvest_interval_s = float(harvest_interval_s)
        self.retire_timeout_s = float(retire_timeout_s)
        self._clock = clock
        self._lock = threading.RLock()
        self.target = len(router.replicas)
        self.respawns = 0
        self.losses = 0
        self._loss_times: Dict[str, List[float]] = {}
        self._backoffs: Dict[str, float] = {}
        #: name -> (due time, tier) for pending respawns.
        self._pending: Dict[str, Any] = {}
        self._quarantined: Dict[str, str] = {}
        self._last_harvest = 0.0
        #: Transport-probe ladder: consecutive failures per replica name;
        #: at ``transport_probe_failures`` the replica is marked
        #: transport-partitioned (DEGRADED, auto-healing) — the seam
        #: analogue of the flap quarantine, except probes clear it.
        self.transport_probe_failures = max(1, int(transport_probe_failures))
        self._transport_fails: Dict[str, int] = {}
        self._partitioned: Dict[str, float] = {}
        self._partition_events: List[Dict[str, float]] = []
        self._next_index = 1 + max(
            (_name_index(r.name) for r in router.replicas), default=-1
        )
        #: Rolling-restart state: while True, reconciliation is paused so
        #: the one-at-a-time drain window is not "fixed" by a scale-up.
        self._restarting = False
        self.restarts = 0
        #: Completed per-replica restart events (monotonic stamps, for the
        #: loadgen timeline): {replica, started_s, completed_s,
        #: warm_seeded}.
        self._restart_events: List[Dict[str, Any]] = []
        #: name -> runs adopted by the last _spawn's warm seed (the
        #: warm-seed-fraction evidence for respawned/restarted replicas).
        self._warm_seeded: Dict[str, int] = {}

        reg = registry if registry is not None else get_registry()
        self._m_respawns = reg.counter(
            "fleet_respawns_total",
            "Lost replicas replaced with a fresh stack under the same "
            "name (warm-seeded from the PageStore when one is attached).",
        )
        self._m_quarantined = reg.counter(
            "fleet_quarantined_total",
            "Replica names quarantined by the flap detector (>= threshold "
            "losses inside the window) instead of respawned.",
        )
        self._m_target = reg.gauge(
            "fleet_target_replicas",
            "Replica count the lifecycle manager is converging the fleet "
            "toward (autoscaler-driven when one is attached).",
        )
        self._m_target.set(self.target)
        self._m_rolling = reg.counter(
            "fleet_rolling_restarts_total",
            "Replicas cycled by rolling_restart() (drain -> capture -> "
            "respawn -> warm-seed -> health-gated rejoin).",
        )

        router.manager = self
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if auto_start:
            self._thread = threading.Thread(
                target=self._loop, name="replica-manager", daemon=True
            )
            self._thread.start()

    # -- lifecycle ----------------------------------------------------------

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=5.0)

    def _loop(self) -> None:
        while not self._stop.wait(self.check_interval_s):
            try:
                self.tick()
            except Exception:  # pragma: no cover - monitor must survive
                pass

    # -- control surface ----------------------------------------------------

    def set_target(self, n: int) -> int:
        """Desired replica count (autoscaler / operator).  Clamped to >= 1;
        reconciliation happens on the next tick."""
        with self._lock:
            self.target = max(1, int(n))
            self._m_target.set(self.target)
            get_flight_recorder().record_event(
                "scale_target", target=self.target)
            return self.target

    def clear_quarantine(self, name: str) -> bool:
        """Operator override: forget a name's flap history and schedule an
        immediate respawn for it."""
        with self._lock:
            if name not in self._quarantined:
                return False
            del self._quarantined[name]
            self._loss_times.pop(name, None)
            self._backoffs.pop(name, None)
            self._pending[name] = (self._clock(), None)
            return True

    # -- monitor duties -----------------------------------------------------

    def tick(self) -> None:
        """One monitor pass (public so tests can step deterministically)."""
        now = self._clock()
        self._probe_transport(now)
        self._harvest(now)
        self._detect_losses(now)
        self._process_pending(now)
        self._reconcile(now)

    def _store_client(self, name: str):
        """The store's named transport client for one replica (falls back
        to the store itself for stores without the transport seam)."""
        client_of = getattr(self.page_store, "client", None)
        return client_of(name) if callable(client_of) else self.page_store

    def _probe_transport(self, now: float) -> None:
        """Per-replica transport health: each live replica probes the
        store through its OWN named client, so a partition isolating one
        peer fails exactly that peer's probes.  ``transport_probe_failures``
        consecutive failures mark the replica DEGRADED (routed around,
        not lost); the first passing probe heals it and records the
        partition event with its detect/clear timestamps — recovery time
        after a partition heals is ``cleared_s`` minus the window end."""
        if self.page_store is None:
            return
        if not callable(getattr(self.page_store, "client", None)):
            return
        for replica in self.router.replicas:
            if replica.lost:
                continue
            try:
                ok = self._store_client(replica.name).probe(attempts=1)
            except Exception:
                ok = False
            name = replica.name
            if ok:
                self._transport_fails[name] = 0
                detected = self._partitioned.pop(name, None)
                if detected is not None:
                    replica.transport_ok = True
                    replica.transport_reason = ""
                    with self._lock:
                        self._partition_events.append({
                            "replica": name,
                            "detected_s": detected,
                            "cleared_s": now,
                        })
                        del self._partition_events[:-32]
                    get_flight_recorder().record_event(
                        "transport_heal", replica=name,
                        partitioned_s=now - detected)
                continue
            fails = self._transport_fails.get(name, 0) + 1
            self._transport_fails[name] = fails
            if fails >= self.transport_probe_failures and (
                    name not in self._partitioned):
                self._partitioned[name] = now
                replica.transport_ok = False
                replica.transport_reason = (
                    f"transport probe failed x{fails}")
                get_flight_recorder().record_event(
                    "transport_partition", replica=name, failures=fails)

    def _harvest(self, now: float) -> None:
        if self.page_store is None:
            return
        if now - self._last_harvest < self.harvest_interval_s:
            return
        self._last_harvest = now
        for replica in self.router.replicas:
            if replica.lost:
                continue
            engine = replica.scheduler.batching.engine
            if engine is not None:
                try:
                    self._store_client(replica.name).capture_engine(engine)
                except Exception:
                    # A replica dying mid-harvest is the loss path's
                    # problem, not the harvester's.
                    continue

    def _detect_losses(self, now: float) -> None:
        for replica in self.router.replicas:
            if not replica.lost:
                continue
            corpse = self.router.remove_replica(replica.name)
            if corpse is None:
                continue
            self._retire_async(corpse, drain=False)
            with self._lock:
                self.losses += 1
                history = [
                    t for t in self._loss_times.get(replica.name, [])
                    if now - t <= self.flap_window_s
                ]
                history.append(now)
                self._loss_times[replica.name] = history
                if len(history) >= self.flap_threshold:
                    self._quarantined[replica.name] = (
                        f"{len(history)} losses in {self.flap_window_s:g}s"
                    )
                    self._pending.pop(replica.name, None)
                    self._m_quarantined.inc()
                    get_flight_recorder().record_event(
                        "quarantine", replica=replica.name,
                        losses=len(history),
                        window_s=self.flap_window_s)
                    continue
                backoff = self._backoffs.get(
                    replica.name, self.respawn_backoff_s
                )
                self._backoffs[replica.name] = min(
                    backoff * 2.0, self.respawn_backoff_max_s
                )
                self._pending[replica.name] = (now + backoff, corpse.tier)

    def _process_pending(self, now: float) -> None:
        with self._lock:
            due = [
                (name, tier) for name, (at, tier) in self._pending.items()
                if now >= at
            ]
            for name, _ in due:
                del self._pending[name]
        for name, tier in due:
            try:
                self._spawn(name, tier, respawn=True)
            except Exception:
                # Factory failure: back off and try again — a transient
                # (e.g. the replaced backend still tearing down) must not
                # permanently shrink the fleet.
                with self._lock:
                    backoff = self._backoffs.get(
                        name, self.respawn_backoff_s)
                    self._backoffs[name] = min(
                        backoff * 2.0, self.respawn_backoff_max_s)
                    self._pending[name] = (now + backoff, tier)

    def _reconcile(self, now: float) -> None:
        if self._restarting:
            # A rolling restart deliberately runs one member below target
            # during each drain window; backfilling that hole would spawn
            # an extra replica the restart never asked for.
            return
        with self._lock:
            effective_target = max(1, self.target - len(self._quarantined))
            pending = len(self._pending)
        live = [r for r in self.router.replicas if not r.lost]
        have = len(live) + pending
        if have < effective_target:
            for _ in range(effective_target - have):
                with self._lock:
                    name = f"r{self._next_index}"
                    self._next_index += 1
                try:
                    self._spawn(name, None, respawn=False)
                except Exception:
                    break
        elif have > effective_target and live:
            # Retire the newest (highest-numbered) healthy member with a
            # full drain; in-flight work completes, then the stack closes.
            victims = sorted(
                (r for r in live if r.health == HEALTHY),
                key=lambda r: _name_index(r.name),
            )
            for _ in range(min(have - effective_target, len(victims))):
                victim = victims.pop()
                removed = self.router.remove_replica(victim.name)
                if removed is not None:
                    get_flight_recorder().record_event(
                        "scale_down", replica=removed.name)
                    self._retire_async(removed, drain=True)

    # -- rolling restart ----------------------------------------------------

    def rolling_restart(
        self,
        drain_timeout_s: float = 10.0,
        health_timeout_s: float = 10.0,
        poll_interval_s: float = 0.02,
    ) -> Dict[str, Any]:
        """Cycle every live replica through a zero-loss restart, ONE at a
        time: drain → capture prefix KV to the store (and, when the store
        is disk-backed, to disk) → respawn a fresh stack under the same
        name → warm-seed it from the store → health-gated rejoin.  The
        next member starts only after the previous one rejoined HEALTHY;
        a member that fails its health gate ABORTS the remainder (the
        fleet is left with N-0 members serving — the unhealthy respawn
        stays registered so the loss ladder/respawn path deals with it).

        Removing the member from the router BEFORE its drain means new
        traffic fails over immediately; in-flight work completes inside
        the drain.  Reconciliation is paused for the duration so the
        deliberate one-member hole is not backfilled, and the member's
        flap history is cleared — a deliberate restart is not a flap.

        Returns ``{restarted, aborted, events}`` (monotonic stamps, ready
        for the loadgen timeline)."""
        plan = [
            (r.name, r.tier) for r in self.router.replicas if not r.lost
        ]
        result: Dict[str, Any] = {
            "restarted": [], "aborted": None, "events": [],
        }
        self._restarting = True
        get_flight_recorder().record_event(
            "rolling_restart_begin", replicas=len(plan))
        try:
            for name, tier in plan:
                replica = next(
                    (r for r in self.router.replicas if r.name == name),
                    None,
                )
                if replica is None or replica.lost:
                    continue  # lost since planning: the respawn path owns it
                started = self._clock()
                if self.page_store is not None:
                    engine = replica.scheduler.batching.engine
                    if engine is not None:
                        try:
                            self._store_client(name).capture_engine(engine)
                        except Exception:
                            pass  # restart proceeds; the rejoin seeds cold
                corpse = self.router.remove_replica(name)
                if corpse is None:
                    continue
                # Synchronous drain — the "one at a time" contract.
                corpse.shutdown(drain=True, timeout=drain_timeout_s)
                with self._lock:
                    # A deliberate restart is not a flap.
                    self._loss_times.pop(name, None)
                    self._backoffs.pop(name, None)
                fresh = self._spawn(name, tier, respawn=False)
                deadline = self._clock() + health_timeout_s
                while (fresh.health != HEALTHY
                       and self._clock() < deadline):
                    time.sleep(poll_interval_s)
                if fresh.health != HEALTHY:
                    result["aborted"] = name
                    get_flight_recorder().record_event(
                        "rolling_restart_abort", replica=name,
                        health=fresh.health)
                    break
                completed = self._clock()
                with self._lock:
                    self.restarts += 1
                    event = {
                        "replica": name,
                        "started_s": started,
                        "completed_s": completed,
                        "warm_seeded": self._warm_seeded.get(name, 0),
                    }
                    self._restart_events.append(event)
                    del self._restart_events[:-32]
                self._m_rolling.inc()
                result["restarted"].append(name)
                result["events"].append(dict(event))
                get_flight_recorder().record_event(
                    "rolling_restart_member", replica=name,
                    took_s=completed - started,
                    warm_seeded=event["warm_seeded"])
        finally:
            self._restarting = False
        return result

    # -- spawn / retire -----------------------------------------------------

    def _spawn(self, name: str, tier: Optional[str],
               respawn: bool) -> Replica:
        replica = self.factory(name, tier)
        replica.start()
        if self.page_store is not None:
            engine = replica.scheduler.batching.engine
            if engine is not None:
                try:
                    adopted = self._store_client(name).seed_engine(engine)
                except Exception:
                    adopted = 0  # cold join is a degraded start, not a failure
                with self._lock:
                    self._warm_seeded[name] = int(adopted or 0)
        self.router.add_replica(replica)
        get_flight_recorder().record_event(
            "respawn" if respawn else "scale_up", replica=name)
        if respawn:
            with self._lock:
                self.respawns += 1
            self._m_respawns.inc()
        return replica

    def _retire_async(self, corpse: Replica, drain: bool) -> None:
        """Corpse teardown on a background thread: a wedged worker (the
        hang the watchdog just converted to a loss) would otherwise block
        the monitor for the full drain timeout."""
        thread = threading.Thread(
            target=corpse.shutdown,
            kwargs={"drain": drain, "timeout": self.retire_timeout_s},
            name=f"retire-{corpse.name}", daemon=True,
        )
        thread.start()

    # -- reporting ----------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "target": self.target,
                "effective_target": max(
                    1, self.target - len(self._quarantined)),
                "respawns": self.respawns,
                "losses": self.losses,
                "pending_respawns": sorted(self._pending),
                "quarantined": dict(self._quarantined),
                "partitioned": dict(self._partitioned),
                "partition_events": [
                    dict(e) for e in self._partition_events],
                "flap_threshold": self.flap_threshold,
                "flap_window_s": self.flap_window_s,
                "restarts": self.restarts,
                "restarting": self._restarting,
                "restart_events": [dict(e) for e in self._restart_events],
                "warm_seeded": dict(self._warm_seeded),
                "page_store": (
                    self.page_store.stats()
                    if self.page_store is not None else None
                ),
            }
