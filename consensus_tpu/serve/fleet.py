"""Replica lifecycle for fleet serving.

A :class:`Replica` owns one complete single-replica serving stack — its
own backend instance, fault-tolerance wrappers (fault injection below a
supervised backend with its own circuit breaker), kill switch, optional
brownout controller, :class:`~consensus_tpu.serve.service.ConsensusService`
and :class:`~consensus_tpu.serve.scheduler.RequestScheduler` (which in turn
owns the replica's ``BatchingBackend`` / ``DecodeEngine``).  The fleet
router (``serve/router.py``) composes N of these: replica failure becomes
an isolated, routable event instead of an outage.

The wrapper stack, bottom to top::

    supervisor( killswitch( faults( engine ) ) )

* ``faults`` (optional) is the chaos seam — ``FaultPlan.replica_lost``
  arms a deterministic per-replica death.
* ``killswitch`` is the operational seam — ``Replica.kill()`` makes every
  subsequent backend call raise ``BackendLostError``, exactly what a
  preempted device looks like from above.  It sits ABOVE fault injection
  (a killed replica stops injecting anything else) and BELOW the
  supervisor (so the breaker records the loss and trips: the passive
  health signal the router reads).
* ``supervisor`` retries transients, bisects poison rows, and owns the
  replica's :class:`~consensus_tpu.backends.supervisor.CircuitBreaker`.

Health is a derived property, not a stored state: ``lost`` latches (from
an explicit kill, a probe timeout, or the passive device-loss flags the
supervisor and engine latch), draining follows the scheduler, and an open
breaker demotes the replica to ``degraded`` — routable as a last resort,
skipped while healthier peers exist.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from consensus_tpu.backends.base import Backend, BackendLostError
from consensus_tpu.obs.metrics import Registry, get_registry
from consensus_tpu.serve.brownout import BrownoutController
from consensus_tpu.serve.scheduler import RequestScheduler
from consensus_tpu.serve.service import ConsensusService

#: Health states, in decreasing order of routability.
HEALTHY = "healthy"
DEGRADED = "degraded"  # breaker open: routable only as a last resort
DRAINING = "draining"
LOST = "lost"


class ReplicaKillSwitch:
    """Backend wrapper with an off button.

    Until :meth:`kill`, every call passes straight through.  After it,
    every call raises :class:`BackendLostError` — the sticky device-loss
    contract, so the supervised stack above reacts exactly as it would to
    a real preemption.  Deliberately does NOT expose
    ``open_fused_token_search``: fused sessions bypass the protocol seam,
    and a killed replica must be dead on EVERY path.
    """

    name = "killswitch"

    def __init__(self, inner: Backend):
        self.inner = inner
        self._lost = threading.Event()
        self._reason = ""

    def kill(self, reason: str = "killed") -> None:
        self._reason = reason
        self._lost.set()

    @property
    def lost(self) -> bool:
        return self._lost.is_set()

    # -- passthrough surface ----------------------------------------------

    @property
    def deterministic_greedy(self) -> bool:
        return bool(getattr(self.inner, "deterministic_greedy", False))

    @property
    def token_counts(self):
        return getattr(self.inner, "token_counts", {})

    # -- protocol -----------------------------------------------------------

    def _check(self, op: str) -> None:
        if self._lost.is_set():
            raise BackendLostError(
                f"replica backend is gone ({self._reason}); {op} refused"
            )

    def generate(self, requests):
        self._check("generate")
        return self.inner.generate(requests)

    def score(self, requests):
        self._check("score")
        return self.inner.score(requests)

    def next_token_logprobs(self, requests):
        self._check("next_token_logprobs")
        return self.inner.next_token_logprobs(requests)

    def embed(self, texts):
        self._check("embed")
        return self.inner.embed(texts)


class Replica:
    """One backend replica: wrapped stack + service + scheduler + health."""

    def __init__(
        self,
        name: str,
        backend: Backend,
        *,
        tier: str = "full",
        registry: Optional[Registry] = None,
        fault_plan=None,
        supervise=True,
        brownout: Optional[BrownoutController] = None,
        generation_model: str = "",
        scheduler_options: Optional[Dict[str, Any]] = None,
    ):
        self.name = name
        self.tier = tier
        reg = registry if registry is not None else get_registry()
        stack: Backend = backend
        if fault_plan is not None:
            from consensus_tpu.backends.faults import FaultInjectingBackend

            stack = FaultInjectingBackend(stack, fault_plan, registry=reg)
        self.kill_switch = ReplicaKillSwitch(stack)
        stack = self.kill_switch
        self._supervisor = None
        if supervise:
            from consensus_tpu.backends.supervisor import (
                CircuitBreaker,
                SupervisedBackend,
            )

            options = dict(supervise) if isinstance(supervise, dict) else {}
            breaker = CircuitBreaker(
                failure_threshold=options.get("failure_threshold", 5),
                cooldown_s=options.get("cooldown_s", 5.0),
                registry=reg,
                name=name,
            )
            stack = SupervisedBackend(
                stack, breaker=breaker, registry=reg, **options
            )
            self._supervisor = stack
        self.backend = stack
        self.brownout = brownout
        service = ConsensusService(stack, generation_model=generation_model)
        self.scheduler = RequestScheduler(
            handler=service.run,
            backend=stack,
            registry=reg,
            brownout=brownout,
            **(scheduler_options or {}),
        )
        self._lost = threading.Event()
        self._lost_reason = ""

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "Replica":
        self.scheduler.start()
        return self

    def shutdown(self, drain: bool = True,
                 timeout: Optional[float] = None) -> None:
        self.scheduler.shutdown(drain=drain, timeout=timeout)

    def kill(self, reason: str = "killed") -> None:
        """Operational kill: the backend starts raising BackendLostError
        (in-flight requests fail fast and fail over) and routing skips the
        replica immediately."""
        self.kill_switch.kill(reason)
        self.mark_lost(reason)

    def mark_lost(self, reason: str) -> None:
        """Routing-only loss mark (probe timeout, observed BackendLostError):
        the backend is left as-is — if it is truly gone its own calls keep
        failing; marking just stops new placements."""
        if not self._lost.is_set():
            self._lost_reason = reason
            self._lost.set()

    # -- health -------------------------------------------------------------

    @property
    def lost(self) -> bool:
        """Explicit mark, kill switch, or the passive device-loss flags the
        supervisor / engine latched while serving."""
        if self._lost.is_set() or self.kill_switch.lost:
            return True
        if self._supervisor is not None and getattr(
            self._supervisor, "backend_lost", False
        ):
            return True
        engine = self.scheduler.batching.engine
        if engine is not None and engine.backend_lost:
            return True
        return False

    @property
    def health(self) -> str:
        if self.lost:
            return LOST
        if self.scheduler.draining:
            return DRAINING
        breaker = self.scheduler.circuit_breaker
        if breaker is not None and breaker.state == "open":
            return DEGRADED
        return HEALTHY

    @property
    def lost_reason(self) -> str:
        if self._lost_reason:
            return self._lost_reason
        return "backend_lost" if self.lost else ""

    def probe(self, timeout_s: float) -> bool:
        """Active liveness probe: one tiny ``embed`` call against the
        wrapped stack (below the batching layer, so it cannot jam the
        request path), bounded by ``timeout_s``.  A hung or lost backend
        marks the replica lost.  Off by default at the router (active
        probes consume fault-plan call indices, which deterministic chaos
        tests pin)."""
        if self.lost:
            return False
        result: Dict[str, Any] = {}
        done = threading.Event()

        def run() -> None:
            try:
                self.backend.embed(["__fleet_probe__"])
                result["ok"] = True
            except Exception as exc:  # noqa: BLE001 - classified below
                result["error"] = exc
            done.set()

        thread = threading.Thread(
            target=run, name=f"probe-{self.name}", daemon=True
        )
        thread.start()
        if not done.wait(timeout_s):
            self.mark_lost("probe_timeout")
            return False
        if "ok" in result:
            return True
        if isinstance(result.get("error"), BackendLostError):
            self.mark_lost("backend_lost")
        return False

    # -- reporting ----------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """Per-replica /healthz block: tier, health, breaker, brownout,
        occupancy — the router aggregates these."""
        stats = self.scheduler.stats()
        snap: Dict[str, Any] = {
            "tier": self.tier,
            "health": self.health,
            "queue_depth": stats["queue_depth"],
            "inflight": stats["inflight"],
            "max_queue_depth": stats["max_queue_depth"],
            "max_inflight": stats["max_inflight"],
            "workers_alive": stats["workers_alive"],
            "device_batches": stats["device_batches"],
        }
        if self.lost_reason:
            snap["lost_reason"] = self.lost_reason
        for key in ("engine", "circuit_breaker", "brownout"):
            if key in stats:
                snap[key] = stats[key]
        return snap
