"""Cross-run device batching: the TPU-native thread pool replacement.

The reference hides HTTP latency by fanning (method × param × seed) combos
across a ``ThreadPoolExecutor`` (src/experiment.py:283-322).  On-device the
model is the bottleneck, so the win is different: independent runs that are
at the same phase should share ONE padded device batch instead of issuing
small batches back-to-back (SURVEY §2.16 "batch/shard (seeds × scenarios ×
methods) across chips").

:class:`BatchingBackend` wraps an inner backend.  Worker threads (one per
concurrent run) register a :meth:`session`; each protocol call enqueues its
requests and blocks.  A batch flushes when EVERY active session has a call
pending (all threads blocked → nothing more can arrive) or when a waiter
times out (``flush_ms`` — a session doing host-side work shouldn't stall
the others).  The flushing thread concatenates same-kind requests, executes
them on the inner backend as one batch, and distributes the slices.

Correctness: per-request PRNG keys (backends/tpu.py) make every result
independent of batch composition, so merged batches are bit-identical to
solo execution — concurrency changes throughput, never results.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from consensus_tpu.backends.base import (
    Backend,
    GenerationRequest,
    GenerationResult,
    NextTokenRequest,
    PartialBatchError,
    RequestCancelled,
    ScoreRequest,
    ScoreResult,
    TokenCandidate,
)
from consensus_tpu.obs.metrics import (
    DEFAULT_COUNT_BUCKETS,
    Registry,
    get_registry,
)


class _Pending:
    __slots__ = (
        "requests", "result", "error", "done", "enqueued", "in_flight",
        "cancelled",
    )

    def __init__(self, requests, cancelled=None):
        self.requests = requests
        self.result = None
        self.error = None
        self.done = False
        self.enqueued = time.perf_counter()
        #: True once a flush has snapshotted this entry off its queue — its
        #: waiter then parks on the kind's DISPATCH condition, which is only
        #: notified when the entry's own batch completes (or aborts).
        self.in_flight = False
        #: Session-scoped cancellation probe (the serving ticket's
        #: ``cancelled`` flag), or None.  Consulted ONCE, at the flush
        #: snapshot: a cancelled entry is dropped from the merged batch and
        #: failed with :class:`RequestCancelled` before any device time is
        #: spent on it.  Once an entry is in flight it always completes —
        #: device programs are not preemptible, and co-batched siblings'
        #: slices must stay bit-identical.
        self.cancelled = cancelled


class BatchingBackend:
    """Merge concurrent sessions' backend calls into shared device batches.

    ``engine_options`` passes through to the decode engine verbatim —
    notably ``{"decode_steps": K}`` turns on multi-token decode: the engine
    dispatches K-step on-device decode windows per cohort
    (``inner.generate_stream``) instead of one blocking ``generate`` call,
    overlapping host admission/prefill with device decode.  Adding
    ``{"speculative": true}`` upgrades each window to draft-and-verify:
    an n-gram self-draft proposes K tokens per row and one dispatch
    verifies them, emitting ``1 + accepted`` real tokens per window with
    byte-identical output (exact sequential PRNG replay).
    """

    name = "batching"

    def __init__(
        self,
        inner: Backend,
        flush_ms: float = 10.0,
        expected_sessions: int = 1,
        registry: Optional[Registry] = None,
        engine: bool = True,
        engine_options: Optional[Dict[str, Any]] = None,
        prefix_cache: bool = False,
        mesh: Optional[Any] = None,
    ):
        self.inner = inner
        #: Convenience flag: ``prefix_cache=True`` folds into the engine
        #: options (engine mode only — the flush-snapshot path has no page
        #: pool to cache into).  An explicit ``engine_options`` key wins.
        if prefix_cache:
            engine_options = {"prefix_cache": True, **dict(engine_options or {})}
        #: Mesh passthrough: ``mesh={'dp': N, 'tp': M}`` (or "dp=4,tp=2")
        #: reaches the decode engine's shard partitioning the same way.
        #: Left unset, the engine inherits the inner backend's mesh_plan.
        if mesh is not None:
            engine_options = {"mesh": mesh, **dict(engine_options or {})}
        self.flush_s = flush_ms / 1000.0
        # obs: queue-wait (enqueue -> dispatch), batch-fill (sessions merged
        # per flush), and flush-reason accounting.  ``registry`` isolates
        # tests from the process-global registry.
        reg = registry if registry is not None else get_registry()
        self._queue_wait = reg.histogram(
            "batching_queue_wait_seconds",
            "Time a session's call waited in the merge queue before its "
            "batch dispatched.",
            labels=("kind",),
        )
        self._batch_fill = reg.histogram(
            "batching_batch_fill_sessions",
            "Sessions merged into one device batch per flush.",
            labels=("kind",),
            buckets=DEFAULT_COUNT_BUCKETS,
        )
        self._merged_requests = reg.counter(
            "batching_merged_requests_total",
            "Requests merged into shared device batches.",
            labels=("kind",),
        )
        self._flushes = reg.counter(
            "batching_flushes_total",
            "Batch flushes by trigger: all active sessions blocked vs. "
            "flush_ms quiescence timeout.",
            labels=("kind", "reason"),
        )
        self._row_errors = reg.counter(
            "batching_row_errors_total",
            "Rows of a merged device batch that failed with a typed "
            "per-row error while sibling rows succeeded (PartialBatchError "
            "unpacking; poison-row isolation).",
            labels=("kind",),
        )
        self._cancelled_requests = reg.counter(
            "batching_cancelled_requests_total",
            "Queued calls dropped at the flush snapshot because their "
            "session's cancellation probe fired before dispatch (failed "
            "with RequestCancelled; no device time spent).",
            labels=("kind",),
        )
        self._spurious_wakeups = reg.counter(
            "batching_spurious_wakeups_total",
            "Mid-flush waiters woken while their own request was still "
            "pending.  Stays 0 when completion wakeups are routed per kind; "
            "a cross-kind broadcast would charge every parked waiter one "
            "wakeup per other kind's dispatch.",
            labels=("kind",),
        )
        #: Shares the engine's dedup family: identical score rows merged
        #: into one flush are computed once regardless of which dispatch
        #: loop (engine iteration or legacy flush-snapshot) runs them.
        self._score_dedup = reg.counter(
            "engine_score_dedup_total",
            "Duplicate score rows removed from merged dispatches — "
            "identical (prompt, continuation) rows in one flush are "
            "computed once and fanned back out.",
        )
        #: Until this many sessions have STARTED, the all-blocked heuristic
        #: is suppressed — otherwise the first worker to enqueue during pool
        #: ramp-up sees active==1 and flushes a batch of one.
        self.expected_sessions = max(1, expected_sessions)
        #: One lock guards all queues/flags; each kind waits on its OWN pair
        #: of conditions over that lock.  ``_conds[kind]`` is the QUEUE
        #: condition (entry still on its queue: flush decisions, flush-end
        #: re-evaluation); ``_dispatch_conds[kind]`` is the DISPATCH
        #: condition (entry snapshotted into a running flush: woken exactly
        #: when its batch completes or the flush aborts).  The split is what
        #: lets a completed generate batch wake precisely the waiters whose
        #: entries finished — same-kind requests that arrived DURING the
        #: flush park on the queue condition and sleep through it (ADVICE r5
        #: item 4; ``batching_spurious_wakeups_total`` pins this at 0 under
        #: mixed-kind serving load).
        self._lock = threading.Lock()
        self._active = 0
        self._started = 0
        self._flushing = False
        self._queues: Dict[str, List[_Pending]] = {
            "generate": [], "score": [], "next_token": [], "embed": [],
            "score_matrix": [],
        }
        self._conds: Dict[str, threading.Condition] = {
            kind: threading.Condition(self._lock) for kind in self._queues
        }
        self._dispatch_conds: Dict[str, threading.Condition] = {
            kind: threading.Condition(self._lock) for kind in self._queues
        }
        #: Device batches actually issued per kind — the measurable win:
        #: N concurrent runs << N× the solo batch count.
        self.batch_counts = {
            "generate": 0, "score": 0, "next_token": 0, "embed": 0,
            "score_matrix": 0,
        }
        #: Per-thread session cancellation probe (set by ``session()``).
        self._tls = threading.local()
        #: Continuous-batching engine (backends/engine.py): when enabled,
        #: every protocol call routes straight into the engine's iteration
        #: loop and the whole flush-snapshot path above is UNREACHABLE —
        #: no quiescence windows, so ``flush_reason="timeout"`` can never
        #: be emitted and ``batching_spurious_wakeups_total`` stays pinned
        #: at 0 (there are no parked flush waiters to wake).  The engine IS
        #: the constructor default now; ``engine=False`` is the explicit
        #: opt-out for the legacy flush-snapshot path (kept for A/B benches
        #: and the flush-semantics tests).
        self.engine = None
        if engine:
            from consensus_tpu.backends.engine import DecodeEngine

            self.engine = DecodeEngine(
                inner,
                registry=reg,
                cancelled_counter=self._cancelled_requests,
                **dict(engine_options or {}),
            )
            # Serve stats read ``batch_counts`` for device-batch totals;
            # alias it to the engine's dispatch counters so the surface
            # keeps one meaning across both paths.
            self.batch_counts = self.engine.dispatch_counts

    @property
    def deterministic_greedy(self) -> bool:
        """Merging requests into shared batches never changes per-request
        results (per-row PRNG keys), so determinism is the inner backend's."""
        return bool(getattr(self.inner, "deterministic_greedy", False))

    def open_fused_token_search(self, spec):
        """Fused token-search sessions bypass the request queue: each session
        step is already ONE fused device program on the inner backend, so
        there is nothing to merge — and without this delegation a concurrent
        sweep cell would silently fall back to the O(T^2) full-prefix
        session.  The inner backend's session budget bounds how many run at
        once; if the inner backend has no fused sessions (or declines the
        spec), FusedSessionUnavailable propagates and the factory builds the
        full-prefix fallback over THIS wrapper, keeping its calls merged
        through the queue."""
        from consensus_tpu.backends.session import FusedSessionUnavailable

        maker = getattr(self.inner, "open_fused_token_search", None)
        if maker is None:
            raise FusedSessionUnavailable(
                f"inner backend {self.inner.name!r} has no fused sessions"
            )
        session = maker(spec)
        if self.engine is not None:
            # Fused sessions dispatch their own programs, but their slot
            # footprint still counts as engine pressure (/healthz).
            session = self.engine.track_session(session, spec)
        return session

    def close(self) -> None:
        """Stop the decode engine's iteration loop (no-op on the legacy
        path, which holds no threads of its own)."""
        if self.engine is not None:
            self.engine.close()

    def _notify(self, kinds) -> None:
        """Wake the given kinds' waiters.  Caller holds ``_lock`` (every
        per-kind condition shares it)."""
        for kind in kinds:
            self._conds[kind].notify_all()

    @contextlib.contextmanager
    def session(self, cancelled: Optional[Callable[[], bool]] = None):
        """Register the calling thread as an active run for flush accounting.

        ``cancelled`` (optional) is a zero-arg probe — typically the serving
        ticket's cancellation flag — attached to every call this thread
        enqueues while the session is open.  Queued calls whose probe fires
        are dropped at the next flush snapshot with
        :class:`RequestCancelled` instead of riding the merged device batch;
        calls already in flight complete normally (their co-batched
        siblings' results must not change)."""
        self._tls.cancelled = cancelled
        with self._lock:
            self._active += 1
            self._started += 1
        try:
            yield self
        finally:
            self._tls.cancelled = None
            with self._lock:
                self._active -= 1
                # A departing session may complete the "all blocked"
                # condition for a waiter of ANY kind.  Mid-flush the
                # predicate can't be acted on anyway (waiters are parked
                # untimed and re-evaluate at flush end), so skip the
                # broadcast rather than charge every parked waiter a
                # spurious wakeup.
                if not self._flushing:
                    self._notify(self._queues)

    # -- protocol ----------------------------------------------------------

    def generate(self, requests: Sequence[GenerationRequest]) -> List[GenerationResult]:
        return self._call("generate", list(requests), self.inner.generate)

    def score(self, requests: Sequence[ScoreRequest]) -> List[ScoreResult]:
        return self._call("score", list(requests), self.inner.score)

    def score_matrix(self, requests: Sequence[Any]) -> List[Any]:
        """(candidates x agents) utility matrices through the batching seam:
        engine mode merges co-batched sessions' matrices into one
        iteration-loop dispatch; the legacy flush path queues them like any
        other kind and routes to the inner backend's fused path (or the
        exact per-call fallback for backends without one)."""
        return self._call(
            "score_matrix", list(requests), self._score_matrix_inner
        )

    def _score_matrix_inner(self, requests: List[Any]) -> List[Any]:
        from consensus_tpu.backends.score_matrix import score_matrix_many

        return score_matrix_many(self.inner, requests)

    def next_token_logprobs(
        self, requests: Sequence[NextTokenRequest]
    ) -> List[List[TokenCandidate]]:
        return self._call(
            "next_token", list(requests), self.inner.next_token_logprobs
        )

    def embed(self, texts: Sequence[str]) -> np.ndarray:
        out = self._call("embed", list(texts), self.inner.embed)
        return np.asarray(out)

    # -- core --------------------------------------------------------------

    def _window_s(self, kind: str) -> float:
        """Quiescence window before a timeout flush.

        A flat 10 ms window fragments phase transitions: decode steps are
        weights-bound, so a 4-row 700-token generate costs nearly the same
        multi-second wall as a 48-row one, yet the first run to reach a new
        phase used to flush its rows solo while its 29 siblings were still
        parsing the previous phase host-side.  Patience worth ~5% of the
        queued batch's expected decode wall (~8 ms/step) is host-side noise
        next to the dispatch it saves; cheap calls keep the fast window, and
        the all-blocked fast path still flushes singleton sessions
        immediately."""
        if kind != "generate":
            return self.flush_s
        queued = self._queues["generate"]
        if not queued:
            return self.flush_s
        longest = max(r.max_tokens for e in queued for r in e.requests)
        # Cap only the scaled term: a configured flush_s above the cap is an
        # operator choice that generate must honor like every other kind.
        return max(self.flush_s, min(0.5, 0.05 * 0.008 * longest))

    def _call(self, kind: str, requests: List[Any], fn: Callable) -> Any:
        if not requests:
            return fn(requests)
        if self.engine is not None:
            # Engine path: the iteration loop replaces both flush triggers
            # (all-blocked snapshot AND the quiescence timeout), so none of
            # the flush-reason/window accounting below runs — see _flush's
            # guard.
            return self.engine.submit(
                kind, requests, probe=getattr(self._tls, "cancelled", None)
            )
        entry = _Pending(
            requests, cancelled=getattr(self._tls, "cancelled", None)
        )
        cond = self._conds[kind]
        with cond:
            self._queues[kind].append(entry)
            # An append changes the pending count that feeds EVERY kind's
            # all-blocked predicate, so it broadcasts across kinds — except
            # mid-flush, when nobody can act on the predicate (parked
            # waiters re-evaluate at flush end, which notifies every kind
            # whose queue refilled).
            if not self._flushing:
                self._notify(self._queues)
            while not entry.done:
                if self._flushing:
                    # A device batch is executing with the lock released.
                    # Snapshotted entries park on the dispatch condition:
                    # it is notified exactly when their own batch completes
                    # (or the flush aborts), so a completed generate batch
                    # never stampedes score waiters in the same flush, and
                    # generate requests that arrived AFTER the snapshot
                    # sleep on the queue condition until flush end.  Both
                    # waits are untimed: flush end / completion wakes them
                    # under the lock, so polling would only burn host
                    # cycles.  Waking here with the flush still running and
                    # this entry still pending means a wakeup was wasted.
                    if entry.in_flight:
                        self._dispatch_conds[kind].wait()
                    else:
                        cond.wait()
                    if self._flushing and not entry.done:
                        self._spurious_wakeups.labels(kind).inc()
                    continue
                pending = sum(len(q) for q in self._queues.values())
                ramped = self._started >= self.expected_sessions
                if ramped and pending >= max(self._active, 1):
                    # Every active session is blocked on a call: flush
                    # EVERYTHING — nobody is coming to widen any batch.
                    self._flush(tuple(self._queues), reason="all_blocked")
                elif not cond.wait(timeout=self._window_s(kind)):
                    # Quiescent for a full window (appends notify): flush
                    # THIS kind only — other kinds run their own windows
                    # (a 10 ms score timeout must not fragment a generate
                    # batch sitting out its longer patience window).  The
                    # wait released the lock, so another thread may have
                    # started a flush meanwhile: re-check before claiming.
                    if not self._flushing and not entry.done:
                        self._flush((kind,), reason="timeout")
        if entry.error is not None:
            raise entry.error
        return entry.result

    def _flush(self, kinds: Sequence[str], reason: str = "all_blocked") -> None:
        """Snapshot the given kinds' queues and execute them with the lock
        RELEASED.

        Called with the lock held and ``_flushing`` False.  Releasing during
        the inner calls lets other sessions enqueue while the device is busy
        — their requests accumulate into one merged batch dispatched the
        moment this flush returns, which is what keeps phase-drifted sweep
        cells riding full-width device batches.  ``_flushing`` keeps the
        flush single-file (one chip; results must map back to their
        waiters)."""
        if self.engine is not None:  # pragma: no cover - _call routes away
            raise AssertionError(
                "flush-snapshot path reached with the decode engine active; "
                'flush_reason="timeout" must never be emitted in engine mode'
            )
        self._flushing = True
        snapshot: Dict[str, List[_Pending]] = {k: [] for k in self._queues}
        dropped_kinds = set()
        released = False
        try:
            for k in kinds:
                queue = self._queues[k]
                self._queues[k] = []
                live: List[_Pending] = []
                for entry in queue:
                    # Cancellation seam: consult the session's probe exactly
                    # once, here, before the entry joins the merged batch.
                    # Dropping pre-dispatch keeps sibling slices
                    # bit-identical (per-request PRNG keys make results
                    # independent of batch composition) and spends zero
                    # device time on abandoned work.  A broken probe must
                    # not abort the whole flush — treat it as not cancelled.
                    probe = entry.cancelled
                    try:
                        is_cancelled = probe is not None and probe()
                    except Exception:
                        is_cancelled = False
                    if is_cancelled:
                        entry.error = RequestCancelled(
                            f"session cancelled before its {k} call "
                            "dispatched"
                        )
                        entry.done = True
                        self._cancelled_requests.labels(k).inc()
                        dropped_kinds.add(k)
                    else:
                        entry.in_flight = True
                        live.append(entry)
                snapshot[k] = live
            # Snapshotted kinds' waiters may be sitting in TIMED queue-cond
            # waits; wake them (still under the lock) so they re-park on the
            # dispatch condition — otherwise they'd miss their completion
            # wakeup and sleep out the rest of their quiescence window.
            # Kinds that only had entries DROPPED also wake: those waiters'
            # entries are done (RequestCancelled) and must return now.
            self._notify(
                k for k in kinds if snapshot[k] or k in dropped_kinds
            )
            self._lock.release()
            released = True
            self._run_batches(snapshot, reason)
        finally:
            # Guard the WHOLE flush, not just _run_batches: an abort during
            # the snapshot/release lines must still clear _flushing (waiters
            # park in an untimed wait) and fail stranded entries.
            if released:
                self._lock.acquire()
            self._flushing = False
            # A non-Exception abort (KeyboardInterrupt between per-kind
            # dispatches) can leave snapshotted entries undone AND already
            # off their queues; without this their waiters would block
            # forever.  Normal completion marks every entry done, so this
            # loop is a no-op on the happy path.
            for queue in snapshot.values():
                for entry in queue:
                    if not entry.done:
                        entry.error = RuntimeError(
                            "batch flush aborted before this request was "
                            "dispatched"
                        )
                        entry.done = True
            # Flush end wakes only conditions that can have a waiter parked:
            # snapshot kinds' DISPATCH conditions (happy-path waiters
            # already woke mid-flush and are gone — this covers the abort
            # path that errored entries just above) and the QUEUE conditions
            # of kinds whose queues refilled during the flush (those waiters
            # sat out the untimed wait and must re-evaluate now that
            # _flushing cleared).
            for k, q in snapshot.items():
                if q:
                    self._dispatch_conds[k].notify_all()
            self._notify(k for k, q in self._queues.items() if q)

    def _run_batches(
        self, snapshot: Dict[str, List[_Pending]], reason: str
    ) -> None:
        """Dispatch each kind's merged batch; no lock held (waiters re-check
        ``entry.done`` under the lock after the flush-end notify)."""
        for kind, fn in (
            ("generate", self.inner.generate),
            ("score", self.inner.score),
            ("next_token", self.inner.next_token_logprobs),
            ("embed", self.inner.embed),
            ("score_matrix", self._score_matrix_inner),
        ):
            queue = snapshot[kind]
            if not queue:
                continue
            merged: List[Any] = []
            now = time.perf_counter()
            for entry in queue:
                merged.extend(entry.requests)
                self._queue_wait.labels(kind).observe(now - entry.enqueued)
            self._flushes.labels(kind, reason).inc()
            self._batch_fill.labels(kind).observe(len(queue))
            self._merged_requests.labels(kind).inc(len(merged))
            self.batch_counts[kind] += 1
            # Identical score rows across co-batched sessions (beam rounds
            # re-scoring shared prefixes, matrix fallbacks repeating agent
            # rows) compute once and fan back out.
            dispatch = merged
            mapping = None
            if kind == "score":
                from consensus_tpu.backends.score_matrix import (
                    dedup_score_requests,
                )

                dispatch, mapping = dedup_score_requests(merged)
                if len(dispatch) < len(merged):
                    self._score_dedup.inc(len(merged) - len(dispatch))
            try:
                results = fn(dispatch)
                if mapping is not None:
                    from consensus_tpu.backends.score_matrix import (
                        expand_deduped,
                    )

                    results = expand_deduped(results, mapping)
                cursor = 0
                for entry in queue:
                    n = len(entry.requests)
                    if kind == "embed":
                        entry.result = np.asarray(results[cursor : cursor + n])
                    else:
                        entry.result = list(results[cursor : cursor + n])
                    cursor += n
                    entry.done = True
            except PartialBatchError as exc:
                # Typed per-row propagation (supervisor poison isolation):
                # a waiter whose rows all survived gets its slice; a waiter
                # owning a failed row gets that row's typed error — one bad
                # row fails one session's call, not the whole device batch.
                if mapping is not None:
                    from consensus_tpu.backends.score_matrix import (
                        expand_partial_error,
                    )

                    exc = expand_partial_error(exc, mapping)
                self._distribute_partial(kind, queue, exc)
            except Exception as exc:  # fail every waiter in this batch
                for entry in queue:
                    entry.error = exc
                    entry.done = True
            # Wake this kind's completed waiters NOW rather than at flush
            # end: their host-side work (parsing, prompt building) overlaps
            # the remaining kinds' device dispatches — mid-flush waiters
            # park in an untimed wait and would otherwise sleep out the
            # whole flush.  Only THIS kind's DISPATCH condition is notified,
            # and only snapshotted (now done) entries wait there: other
            # kinds' waiters have nothing new to learn, and same-kind
            # requests that arrived after the snapshot park on the queue
            # condition until flush end — so every wakeup issued here finds
            # a finished entry (the spurious-wakeup counter pins this at
            # zero).
            cond = self._dispatch_conds[kind]
            with cond:
                cond.notify_all()

    def _distribute_partial(
        self, kind: str, queue: List[_Pending], exc: PartialBatchError
    ) -> None:
        """Slice a PartialBatchError back onto its waiters.

        Entries with only surviving rows get their result slice
        (bit-identical to a clean batch: per-request PRNG keys).  Entries
        owning failed rows get the typed row error — the single-row error
        itself when the whole slice failed, or a per-entry
        PartialBatchError when the entry mixes good and bad rows."""
        cursor = 0
        for entry in queue:
            n = len(entry.requests)
            slice_errors = {
                i - cursor: err
                for i, err in exc.row_errors.items()
                if cursor <= i < cursor + n
            }
            if not slice_errors:
                if kind == "embed":
                    entry.result = np.asarray(exc.results[cursor : cursor + n])
                else:
                    entry.result = list(exc.results[cursor : cursor + n])
            else:
                self._row_errors.labels(kind).inc(len(slice_errors))
                if len(slice_errors) == n:
                    entry.error = next(iter(slice_errors.values()))
                else:
                    entry.error = PartialBatchError(
                        f"{len(slice_errors)}/{n} rows of this session's "
                        f"{kind} call failed inside a merged device batch",
                        results=list(exc.results[cursor : cursor + n]),
                        row_errors=slice_errors,
                    )
            cursor += n
            entry.done = True
