"""Continuous-batching decode engine: always-on iteration loop over a slot
table backed by a paged KV cache.

The legacy :class:`~consensus_tpu.backends.batching.BatchingBackend` model
is flush-snapshot: worker calls queue until EVERY active session blocks (or
a quiescence window expires), then one merged batch dispatches and the
cycle restarts.  That barrier is the dominant throughput loss BENCH_r05's
``mfu_accounting`` names — rows pad to the widest bucket, and the device
idles between flushes while stragglers finish host work.

This engine replaces the barrier with ITERATION-LEVEL batching (Orca, Yu
et al., OSDI '22): a persistent loop over a fixed table of ``n_slots``
request slots.  Each iteration

1. consults cancellation probes — queued work is dropped before any pages
   are spent, resident rows are EVICTED and their pages freed;
2. admits queued generate rows into free slots under a conservative page
   reservation (prompt + max_tokens pages must fit the pool, so a resident
   row can always finish — no mid-decode preemption);
3. advances chunked PREFILL: each mid-prefill slot ingests one
   ``prefill_chunk``-token chunk of its prompt, allocating pages as the
   chunk crosses page boundaries — long prompts interleave with decode
   instead of stalling it;
4. dispatches the DECODE cohort: all prefill-complete slots run as one
   batch on the inner backend, then retire, freeing their pages — new
   arrivals admitted meanwhile join the next iteration (requests join and
   leave at iteration granularity; there is no full-batch flush barrier
   and no timeout reason);
5. batches every queued score / next_token / embed call into one inner
   call per kind.

Correctness: per-request PRNG keys (backends/tpu.py) and (prompt,
seed)-keyed hashing (backends/fake.py) make every result independent of
batch composition, so engine cohorts are byte-identical to legacy flushes
and to solo execution — pinned for all seven methods in
tests/test_engine.py.

KV residency is tracked in PAGES (ops/kv_pages.py): a slot's stream maps
to a block table over one fixed pool, so ragged-length slots coexist
without bucket padding.  On the device side the matching fixed-shape slot
programs are ``models/stepper.paged_prefill_chunk`` /
``paged_decode_step`` over ``ops/decode_attention.paged_attention`` —
compiled ONCE per slot-table shape, with slot lengths entering as data
only.  The engine delegates token generation itself to the inner backend
(that is what keeps the seven methods byte-identical across engine
on/off), while the pool/block-table accounting here is exactly the
residency contract those programs consume.

A request that could NEVER fit the pool (prompt + max_tokens pages >
pool) is rejected gracefully with the serving tier's
``SchedulerRejected`` (lazy import — backends must not import serve at
module load).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from consensus_tpu.backends.base import (
    BackendLostError,
    PartialBatchError,
    RequestCancelled,
)
from consensus_tpu.obs.metrics import (
    DEFAULT_COUNT_BUCKETS,
    Registry,
    get_registry,
)
from consensus_tpu.obs.trace import (
    IterationLedger,
    get_flight_recorder,
    trace_current,
)
from consensus_tpu.ops.kv_pages import BlockTable, PagePool, PrefixCache

#: Engine defaults.  ``NUM_PAGES``/``PAGE_SIZE`` give a 16k-token pool —
#: roomy for CPU/fake runs; real TPU runs size the pool from the backend's
#: HBM session budget via ``suggest_kv_page_pool``.
DEFAULT_SLOTS = 8
DEFAULT_PAGE_SIZE = 16
DEFAULT_NUM_PAGES = 1024
DEFAULT_PREFILL_CHUNK = 128

_PREFILL = "prefill"
_READY = "ready"


class _Item:
    """One submitted call: ``requests`` fan out to rows (generate) or ride
    whole (score/next_token/embed)."""

    __slots__ = (
        "kind", "requests", "probe", "event", "result", "error",
        "rows_left", "row_results", "row_errors", "failed", "trace", "span",
    )

    def __init__(self, kind: str, requests: List[Any], probe):
        self.kind = kind
        self.requests = requests
        self.probe = probe
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None
        self.rows_left = len(requests)
        self.row_results: Dict[int, Any] = {}
        self.row_errors: Dict[int, BaseException] = {}
        #: Set when the whole item is being failed (cancel/reject): rows
        #: still resident are evicted, rows still queued are dropped.
        self.failed = False
        #: Request-scoped trace (obs.trace) captured at submit; span 0 (and
        #: trace None) mean "untraced" and every trace op is a no-op.
        self.trace = None
        self.span = 0

    def cancelled(self) -> bool:
        if self.probe is None:
            return False
        try:
            return bool(self.probe())
        except Exception:
            # A broken probe must not take down the loop — treat as live.
            return False


class _Row:
    __slots__ = (
        "item", "index", "request", "prompt_tokens", "prompt_ids",
        "trace", "span",
    )

    def __init__(
        self, item: _Item, index: int, request, prompt_ids: List[Any]
    ):
        self.item = item
        self.index = index
        self.request = request
        #: Tokenized prompt (ids on real backends, pseudo-tokens on the
        #: fake one) — page accounting AND the prefix-cache content key.
        self.prompt_ids = prompt_ids
        self.prompt_tokens = max(1, len(prompt_ids))
        self.trace = None
        self.span = 0


class _Slot:
    __slots__ = (
        "idx", "row", "table", "prefilled", "state", "reserved",
        "cached_tokens", "shard",
    )

    def __init__(self, idx: int, row: _Row, reserved: int, shard: int = 0):
        self.idx = idx
        self.row = row
        self.table = BlockTable(idx)
        self.prefilled = 0
        self.state = _PREFILL
        #: Worst-case pages this row may ever need (prompt + max_tokens
        #: minus any cached prefix) — held against the pool so a resident
        #: row can always decode to completion without preemption.
        self.reserved = reserved
        #: Prompt tokens adopted from the prefix cache (page-aligned) —
        #: their prefill chunks are skipped entirely.
        self.cached_tokens = 0
        #: Data-parallel shard this slot lives on (mesh mode): its pages
        #: come from ``pools[shard]`` and its prefix hits from that shard's
        #: cache — pages never cross dp replicas.
        self.shard = shard


class DecodeEngine:
    """Iteration-loop scheduler over ``n_slots`` slots and one page pool."""

    def __init__(
        self,
        inner,
        *,
        slots: int = DEFAULT_SLOTS,
        page_size: int = DEFAULT_PAGE_SIZE,
        num_pages: Optional[int] = None,
        prefill_chunk: int = DEFAULT_PREFILL_CHUNK,
        min_fill: Optional[int] = None,
        registry: Optional[Registry] = None,
        cancelled_counter=None,
        auto_start: bool = True,
        prefix_cache: bool = False,
        prefix_cache_pages: Optional[int] = None,
        mesh: Optional[Any] = None,
        watchdog_timeout_s: Optional[float] = None,
        decode_steps: Optional[int] = None,
        speculative: bool = False,
    ):
        self.inner = inner
        self.n_slots = max(1, int(slots))
        #: Multi-token decode (ROADMAP item 3): decode up to K tokens per
        #: inner dispatch through the backend's ``generate_stream`` seam
        #: instead of one blocking ``generate`` per cohort.  ``None`` (the
        #: default) preserves the per-cohort blocking path byte-for-byte;
        #: backends without a stream seam silently fall back to it.  The
        #: per-cohort clamp the option promises is a PER-ROW MASK, not a
        #: shorter program: rows whose remaining budget is under K freeze
        #: mid-scan (they write only the sink page and emit pads), so one
        #: compiled K-step program serves every budget mix.
        self.decode_steps = (
            max(1, int(decode_steps)) if decode_steps is not None else None
        )
        #: Engine-native speculative decoding: each decode window drafts K
        #: tokens per row (n-gram self-draft) and verifies them in ONE
        #: dispatch, emitting ``1 + accepted`` real tokens instead of 1.
        #: Off by default — the plain ``paged_decode_steps`` byte-path is
        #: untouched; on, results stay byte-identical (exact sequential
        #: PRNG replay) while tokens-per-dispatch floats with acceptance.
        #: Requires ``decode_steps`` (the draft window IS the decode
        #: window); backends without the stream seam fall back exactly
        #: like plain multi-token decode.
        self.speculative = bool(speculative)
        if self.speculative and self.decode_steps is None:
            # The draft window IS the decode window; speculative alone
            # implies a default K so ``{"speculative": true}`` works.
            self.decode_steps = 4
        #: Cumulative draft accounting across streams (stats / ledger).
        self.spec_proposed_tokens = 0
        self.spec_accepted_tokens = 0
        self._stream_spec_seen = (0, 0)
        self._stream: Optional[Any] = None
        self._stream_slots: List[Optional["_Slot"]] = []
        # Mesh mode: ``mesh`` is a {'dp': N, 'tp': M} dict, a "dp=4,tp=2"
        # string, or a MeshPlan.  Left unset, the engine inherits the inner
        # backend's mesh — a TPUBackend built over the full slice serves
        # mesh-wide by default, no extra plumbing.
        if mesh is None:
            mesh = getattr(inner, "mesh_plan", None)
        if mesh is not None:
            from consensus_tpu.parallel.mesh import parse_mesh_spec

            mesh = parse_mesh_spec(mesh)
        self.mesh_dp = int(mesh["dp"]) if mesh else 1
        self.mesh_tp = int(mesh["tp"]) if mesh else 1
        if num_pages is None:
            suggest = getattr(inner, "suggest_kv_page_pool", None)
            num_pages = (
                suggest(page_size) if callable(suggest) else DEFAULT_NUM_PAGES
            )
        #: One page pool PER data-parallel shard, each at the full per-chip
        #: size (dp chips carry dp× the HBM, so aggregate KV capacity scales
        #: with the mesh).  Pages never migrate between shards — a slot's
        #: block table names pages of its own shard's pool only.  dp=1
        #: degenerates to the single pool of the PR 6 engine, byte-for-byte.
        self.pools: List[PagePool] = [
            PagePool(int(num_pages), page_size) for _ in range(self.mesh_dp)
        ]
        self.pool = self.pools[0]  # dp=1 alias; shard-0 pool under a mesh
        #: Cross-request prefix KV reuse (ROADMAP item 3): completed
        #: prompts donate their page-aligned prefix pages to a
        #: content-addressed LRU; admission adopts the longest cached
        #: prefix and skips its prefill chunks entirely.  The budget
        #: defaults to a quarter of the pool — the share
        #: ``suggest_kv_page_pool`` already reserves headroom for.
        #: Mesh mode keeps one cache PER dp shard (cached pages live in a
        #: shard's pool and cannot be adopted across shards); the identity
        #: already carries the backend's tp width via kv_cache_identity, so
        #: tp=1 and tp=2 content keys never alias.
        self.prefix_caches: List[Optional[PrefixCache]] = [
            None for _ in range(self.mesh_dp)
        ]
        if prefix_cache:
            identity_fn = getattr(inner, "kv_cache_identity", None)
            identity = (
                identity_fn() if callable(identity_fn)
                else (getattr(inner, "name", type(inner).__name__),)
            )
            budget = (
                int(prefix_cache_pages)
                if prefix_cache_pages is not None
                else max(1, self.pool.num_pages // 4)
            )
            self.prefix_caches = [
                PrefixCache(pool, budget, identity=identity)
                for pool in self.pools
            ]
        self.prefix_cache = self.prefix_caches[0]
        self.prefill_chunk = max(1, int(prefill_chunk))
        #: Decode dispatch heuristic: with prefills still in progress, hold
        #: the cohort until at least this many slots are ready — avoids
        #: fragmenting into narrow cohorts while prompts trickle in.  Once
        #: nothing is mid-prefill the cohort dispatches at any width, so
        #: progress is guaranteed (every iteration advances every prefill
        #: by a chunk).
        self.min_fill = (
            max(1, self.n_slots // 2) if min_fill is None else max(1, min_fill)
        )

        reg = registry if registry is not None else get_registry()
        self._m_occupancy = reg.gauge(
            "engine_slot_occupancy",
            "Occupied fraction of the decode engine's slot table at the "
            "latest iteration.",
        )
        self._m_mesh_dp = reg.gauge(
            "engine_mesh_dp",
            "Data-parallel width of the mesh this engine partitions its "
            "slots and page pools over (1 = single device).",
        )
        self._m_mesh_tp = reg.gauge(
            "engine_mesh_tp",
            "Tensor-parallel width of the mesh under this engine's inner "
            "backend (1 = unsharded params).",
        )
        self._m_mesh_dp.set(self.mesh_dp)
        self._m_mesh_tp.set(self.mesh_tp)
        self._m_tokens_iter = reg.histogram(
            "engine_tokens_per_iteration",
            "Generated tokens retired per decode-cohort iteration.",
            buckets=DEFAULT_COUNT_BUCKETS,
        )
        self._m_pages = reg.histogram(
            "kv_pages_in_use",
            "KV pages allocated from the engine's fixed page pool, sampled "
            "at each decode dispatch.",
            buckets=DEFAULT_COUNT_BUCKETS,
        )
        self._m_admitted = reg.counter(
            "engine_admitted_total",
            "Generate rows admitted into decode-engine slots.",
        )
        self._m_evicted = reg.counter(
            "engine_evicted_total",
            "Resident rows evicted before completion (cancellation or "
            "sibling-row failure); their KV pages return to the pool.",
        )
        self._m_prefill_chunks = reg.counter(
            "engine_prefill_chunks_total",
            "Prompt chunks ingested by interleaved chunked prefill.",
        )
        self._m_prefill_tokens = reg.counter(
            "engine_prefill_tokens_total",
            "Prompt tokens actually ingested by chunked prefill "
            "(prefix-cache hits skip theirs, so this is the honest "
            "prefill-work series).",
        )
        self._m_prefix_hits = reg.counter(
            "prefix_cache_hits_total",
            "Admissions that adopted a cached page-aligned prompt prefix.",
        )
        self._m_prefix_misses = reg.counter(
            "prefix_cache_misses_total",
            "Admissions that found no cached prefix.",
        )
        self._m_prefix_evictions = reg.counter(
            "prefix_cache_evictions_total",
            "Prefix-cache entries evicted by the LRU page budget.",
        )
        self._m_prefix_inserted = reg.counter(
            "prefix_cache_inserted_pages_total",
            "KV pages donated to the prefix cache by retiring prompts.",
        )
        self._m_prefix_saved = reg.counter(
            "prefix_tokens_saved_total",
            "Prompt tokens whose prefill was skipped via a cached prefix.",
        )
        self._m_score_dedup = reg.counter(
            "engine_score_dedup_total",
            "Duplicate score rows removed from merged dispatches — "
            "identical (prompt, continuation) rows in one flush are "
            "computed once and fanned back out.",
        )
        self._m_watchdog_trips = reg.counter(
            "engine_watchdog_trips_total",
            "Hang-watchdog trips: a dispatched inner-backend call made no "
            "progress for watchdog_timeout_s, so the engine latched "
            "backend_lost (the silent-hang -> recoverable-loss conversion).",
        )
        self._m_heartbeat_age = reg.gauge(
            "engine_heartbeat_age_s",
            "Seconds since the decode engine's iteration loop last proved "
            "liveness (sampled by the watchdog monitor thread).",
        )
        self._m_mfu_device = reg.gauge(
            "engine_mfu_device_fraction",
            "Fraction of engine wall time spent inside inner-backend device "
            "dispatches (iteration-ledger aggregate).",
        )
        self._m_mfu_host = reg.gauge(
            "engine_mfu_host_fraction",
            "Fraction of engine wall time spent in host-side iteration "
            "bookkeeping (sweep/admit/prefill/cohort/merge/other) — the "
            "per-iteration host round-trip loss.",
        )
        self._m_mfu_idle = reg.gauge(
            "engine_mfu_idle_fraction",
            "Fraction of engine wall time spent idle between iterations.",
        )
        self._m_tokens_dispatch = reg.histogram(
            "engine_tokens_per_dispatch",
            "Generated tokens returned by one device dispatch (one K-step "
            "multi-token window in stream mode; one whole cohort generate "
            "in the legacy blocking path).",
            buckets=DEFAULT_COUNT_BUCKETS,
        )
        self._m_host_iter_per_token = reg.gauge(
            "engine_host_iterations_per_token",
            "Engine iterations per generated token (ledger aggregate): 1.0 "
            "means one host round-trip per token; decode_steps=K drives "
            "this toward 1/K on decode-bound load.",
        )
        #: Queued-call cancellations share the batching adapter's counter
        #: family so PR 1 dashboards keep one cancellation series.
        self._cancelled_counter = cancelled_counter

        #: Inner-backend dispatches per kind — the adapter aliases its
        #: ``batch_counts`` to this dict so serve stats keep working.
        self.dispatch_counts = {
            "generate": 0, "score": 0, "next_token": 0, "embed": 0,
            "score_matrix": 0,
        }
        #: Decode-window accounting: one "window" is one device dispatch
        #: that can retire up to ``decode_steps`` tokens per row (a legacy
        #: blocking generate counts as one window).  tokens/windows is the
        #: per-dispatch amortization the multi-token path exists to raise.
        self.decode_windows = 0
        self.decoded_tokens = 0

        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._gen_backlog: List[_Row] = []
        self._other: Dict[str, List[_Item]] = {
            "score": [], "next_token": [], "embed": [], "score_matrix": [],
        }
        self._slots: List[Optional[_Slot]] = [None] * self.n_slots
        #: Per-dp-shard page reservations (index = shard); the legacy
        #: single-pool figure is the sum.
        self._reserved: List[int] = [0] * self.mesh_dp
        self._stopped = False
        #: Latched when a dispatch raises BackendLostError: the device under
        #: this engine is gone for good (BackendLostError is sticky by
        #: contract).  Fleet replica health checks read this directly — a
        #: plain bool read, no lock — as the passive loss signal.
        self.backend_lost = False
        #: Hang watchdog (the one failure mode the fault taxonomy cannot
        #: raise its way out of): ``run_iteration`` stamps a heartbeat and
        #: marks the lock-free dispatch window busy; a monitor thread trips
        #: when a dispatch has been in flight for ``watchdog_timeout_s``
        #: without returning, latching ``backend_lost`` so the fleet health
        #: ladder (and ReplicaManager respawn) treat the wedge exactly like
        #: a device loss.  ``wedged`` records that the loss came from the
        #: watchdog, not an exception.
        self.watchdog_timeout_s = (
            float(watchdog_timeout_s) if watchdog_timeout_s else None
        )
        self.wedged = False
        self.watchdog_trips = 0
        self._busy_since: Optional[float] = None
        self._heartbeat = time.monotonic()
        self._watchdog_stop = threading.Event()
        self._watchdog_thread: Optional[threading.Thread] = None
        self.iterations = 0
        self._occ_sum = 0.0
        self._occ_iters = 0
        self._search_sessions = 0
        self._search_slots = 0
        #: Iteration ledger (ROADMAP-3 instrument): per-iteration wall time
        #: split into host phases / device dispatch / idle, aggregated into
        #: stats()["mfu_attribution"].  The accumulators below are touched
        #: only by the iteration thread (or the test thread stepping
        #: run_iteration) — no lock needed.
        self.ledger = IterationLedger()
        self._last_iter_end: Optional[float] = None
        #: Device-time split (ROADMAP-3 / PR 15): ``dispatch_s`` is host
        #: time spent ENQUEUEING device work (stream window launches),
        #: ``block_s`` is time spent WAITING on device results (collect /
        #: blocking inner calls).  On CPU backends the device runs
        #: host-synchronously, so block_s absorbs device compute — the
        #: caveat is stamped into ``mfu_attribution`` output itself.
        self._iter_dispatch_s = 0.0
        self._iter_block_s = 0.0
        self._iter_merge_s = 0.0
        self._iter_tokens = 0
        self._iter_spec_proposed = 0
        self._iter_spec_accepted = 0

        self._thread: Optional[threading.Thread] = None
        if auto_start:
            self._thread = threading.Thread(
                target=self._loop, name="decode-engine", daemon=True
            )
            self._thread.start()
        # The monitor runs whenever a timeout is configured — including
        # auto_start=False test engines stepped via run_iteration().
        if self.watchdog_timeout_s:
            self._watchdog_thread = threading.Thread(
                target=self._watchdog_loop, name="engine-watchdog",
                daemon=True,
            )
            self._watchdog_thread.start()

    # -- public ------------------------------------------------------------

    def submit(
        self, kind: str, requests: Sequence[Any], probe: Optional[Callable] = None
    ):
        """Enqueue one call and block until the loop retires it."""
        item = _Item(kind, list(requests), probe)
        active = trace_current()
        if active is not None:
            trace, parent = active
            item.trace = trace
            item.span = trace.begin(
                f"engine_{kind}", parent=parent, rows=len(item.requests))
        with self._work:
            if self._stopped:
                raise RuntimeError("decode engine is closed")
            if kind == "generate":
                for i, req in enumerate(item.requests):
                    row = _Row(item, i, req, self._prompt_token_ids(req))
                    if item.trace is not None:
                        row.trace = item.trace
                        row.span = item.trace.begin(
                            "engine_row", parent=item.span, row=i)
                    self._gen_backlog.append(row)
            else:
                self._other[kind].append(item)
            self._work.notify_all()
        item.event.wait()
        if item.trace is not None:
            item.trace.end(
                item.span,
                outcome="error" if item.error is not None else "ok")
        if item.error is not None:
            raise item.error
        return item.result

    @staticmethod
    def _trace_row_event(row: _Row, name: str, **attrs: Any) -> None:
        if row.trace is not None:
            row.trace.event(row.span, name, **attrs)

    @staticmethod
    def _trace_row_end(row: _Row, **attrs: Any) -> None:
        if row.trace is not None:
            row.trace.end(row.span, **attrs)

    def close(self) -> None:
        with self._work:
            self._stopped = True
            self._work.notify_all()
        self._watchdog_stop.set()
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=5.0)
        if (
            self._watchdog_thread is not None
            and self._watchdog_thread.is_alive()
        ):
            self._watchdog_thread.join(timeout=1.0)

    def track_session(self, session, spec):
        """Seam for ``open_token_search``: fused sessions bypass the request
        queue (their steps are already single fused programs), but their
        slot footprint still belongs on the engine's pressure surface —
        /healthz shows them next to slot occupancy."""
        with self._lock:
            self._search_sessions += 1
            self._search_slots += spec.n_slots
        orig_close = session.close

        def close():
            with self._lock:
                self._search_sessions -= 1
                self._search_slots -= spec.n_slots
            orig_close()

        session.close = close
        return session

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            occupied = sum(1 for s in self._slots if s is not None)
            pools = [pool.stats() for pool in self.pools]
            shard_occupied = [0] * self.mesh_dp
            for s in self._slots:
                if s is not None:
                    shard_occupied[s.shard] += 1
            if self.prefix_cache is not None:
                # Aggregate the per-shard caches into one legacy-shaped
                # block (counters sum; rates recompute from the sums).
                cache_stats = [c.stats() for c in self.prefix_caches]
                agg = {
                    key: sum(cs[key] for cs in cache_stats)
                    for key in (
                        "entries", "pages", "max_pages", "hits", "misses",
                        "evictions", "inserted_pages", "tokens_saved",
                    )
                }
                total = agg["hits"] + agg["misses"]
                agg["hit_rate"] = (agg["hits"] / total) if total else 0.0
                prefix_block: Dict[str, Any] = {"enabled": True, **agg}
            else:
                prefix_block = {"enabled": False}
            return {
                "slots": self.n_slots,
                "slots_occupied": occupied,
                "slot_occupancy": occupied / self.n_slots,
                "slot_occupancy_mean": (
                    self._occ_sum / self._occ_iters if self._occ_iters else 0.0
                ),
                "iterations": self.iterations,
                "queue_depth": len(self._gen_backlog)
                + sum(len(q) for q in self._other.values()),
                # Aggregates across every dp shard's pool (dp=1 == the
                # single legacy pool, unchanged numbers).
                "kv_pages": sum(p.num_pages for p in pools),
                "kv_page_size": pools[0].page_size,
                "kv_pages_in_use": sum(p.pages_in_use for p in pools),
                "kv_pages_reserved": sum(self._reserved),
                "kv_pages_high_water": sum(p.high_water for p in pools),
                # Fraction of the page pool not in use or reserved — the
                # capacity signal the kv_headroom SLO (obs/slo.py) watches.
                "kv_page_headroom": round(
                    max(
                        0.0,
                        1.0
                        - (
                            sum(p.pages_in_use for p in pools)
                            + sum(self._reserved)
                        )
                        / max(1, sum(p.num_pages for p in pools)),
                    ),
                    4,
                ),
                "fused_search_sessions": self._search_sessions,
                "fused_search_slots": self._search_slots,
                "decode_steps": self.decode_steps,
                "stream_active": self._stream is not None,
                "decode_windows": self.decode_windows,
                "decoded_tokens": self.decoded_tokens,
                "tokens_per_dispatch_mean": (
                    self.decoded_tokens / self.decode_windows
                    if self.decode_windows else 0.0
                ),
                "speculative": {
                    "enabled": self.speculative,
                    "proposed_tokens": self.spec_proposed_tokens,
                    "accepted_tokens": self.spec_accepted_tokens,
                    # Mean draft tokens accepted per device dispatch — each
                    # window emits 1 + accepted real tokens, so anything > 0
                    # is throughput past the fixed-K floor.
                    "accepted_tokens_per_dispatch": (
                        self.spec_accepted_tokens / self.decode_windows
                        if self.decode_windows else 0.0
                    ),
                    "draft_acceptance_rate": (
                        self.spec_accepted_tokens / self.spec_proposed_tokens
                        if self.spec_proposed_tokens else 0.0
                    ),
                },
                "backend_lost": self.backend_lost,
                "mfu_attribution": self.ledger.mfu_attribution(),
                "watchdog": {
                    "enabled": self.watchdog_timeout_s is not None,
                    "timeout_s": self.watchdog_timeout_s,
                    "heartbeat_age_s": round(
                        max(0.0, time.monotonic() - self._heartbeat), 4
                    ),
                    "wedged": self.wedged,
                    "trips": self.watchdog_trips,
                },
                "prefix_cache": prefix_block,
                "mesh": {
                    "dp": self.mesh_dp,
                    "tp": self.mesh_tp,
                    "per_shard": [
                        {
                            "slots_occupied": shard_occupied[i],
                            "kv_pages_in_use": pools[i].pages_in_use,
                            "kv_pages_reserved": self._reserved[i],
                        }
                        for i in range(self.mesh_dp)
                    ],
                },
            }

    # -- loop --------------------------------------------------------------

    def _loop(self) -> None:
        while True:
            with self._work:
                while not self._stopped and not self._has_work():
                    self._work.wait()
                if self._stopped:
                    self._fail_all(RuntimeError("decode engine closed"))
                    return
            try:
                self.run_iteration()
            except Exception as exc:  # pragma: no cover - loop must survive
                with self._work:
                    self._fail_all(exc)

    def _has_work(self) -> bool:
        return (
            bool(self._gen_backlog)
            or any(self._other.values())
            or any(s is not None for s in self._slots)
        )

    def _fail_all(self, exc: BaseException) -> None:
        """Stop-path cleanup (lock held): fail every queued/resident item."""
        if self._stream is not None:
            stream = self._stream
            self._stream, self._stream_slots = None, []
            close = getattr(stream, "close", None)
            if callable(close):
                try:
                    close()
                except Exception:
                    pass
        for row in self._gen_backlog:
            self._fail_item(row.item, exc)
        self._gen_backlog = []
        for slot in list(self._slots):
            if slot is not None:
                self._evict(slot, count=False)
                self._fail_item(slot.row.item, exc)
        for queue in self._other.values():
            for item in queue:
                self._fail_item(item, exc)
            queue.clear()

    def run_iteration(self) -> None:
        """One scheduler iteration.  Public so tests can step the engine
        deterministically (construct with ``auto_start=False``)."""
        self._heartbeat = time.monotonic()
        t_start = time.perf_counter()
        idle_s = (
            max(0.0, t_start - self._last_iter_end)
            if self._last_iter_end is not None else 0.0
        )
        self._iter_dispatch_s = 0.0
        self._iter_block_s = 0.0
        self._iter_merge_s = 0.0
        self._iter_tokens = 0
        self._iter_spec_proposed = 0
        self._iter_spec_accepted = 0
        with self._lock:
            t0 = time.perf_counter()
            self._process_cancellations()
            t1 = time.perf_counter()
            self._admit()
            t2 = time.perf_counter()
            self._advance_prefill()
            t3 = time.perf_counter()
            cohort = self._decode_cohort()
            t4 = time.perf_counter()
            occupied = sum(1 for s in self._slots if s is not None)
            occ = occupied / self.n_slots
            self._m_occupancy.set(occ)
            if occupied:
                self._occ_sum += occ
                self._occ_iters += 1
            self.iterations += 1
            queue_depth = len(self._gen_backlog) + sum(
                len(q) for q in self._other.values()
            )
            pages_in_use = sum(pool.in_use for pool in self.pools)
            others = {
                kind: queue[:] for kind, queue in self._other.items() if queue
            }
            for kind in others:
                self._other[kind] = []

        # Inner-backend calls run WITHOUT the lock: submitters keep
        # enqueueing while the device is busy, so the next iteration's
        # cohort and merged kind-batches widen for free (the same overlap
        # the legacy flush got from releasing its lock mid-dispatch).
        # The busy window brackets exactly the calls that can silently
        # wedge — a dispatch that never returns leaves ``_busy_since`` set
        # and the watchdog converts the stall into ``backend_lost``.
        # Stream mode: while a multi-token stream is in flight, the device
        # already holds a dispatched K-step window (launched LAST iteration,
        # after that iteration's host phases) — the sweep/admit/prefill
        # block above just ran CONCURRENTLY with it under jax async
        # dispatch.  ``_advance_stream`` now collects that window's tokens
        # (the only blocking point), retires finished rows, and launches
        # the next window before returning: D2H retirement and H2D
        # admission double-buffer against device compute.
        stream_active = self._stream is not None
        if cohort or others or stream_active:
            self._busy_since = time.monotonic()
        try:
            if stream_active:
                self._advance_stream()
            elif cohort:
                self._dispatch_decode(cohort)
            for kind, items in others.items():
                self._dispatch_other(kind, items)
        finally:
            self._busy_since = None
            self._heartbeat = time.monotonic()
            t_end = time.perf_counter()
            row = self.ledger.record(
                start_s=t_start,
                end_s=t_end,
                idle_s=idle_s,
                dispatch_s=self._iter_dispatch_s,
                block_s=self._iter_block_s,
                host={
                    "sweep": t1 - t0,
                    "admit": t2 - t1,
                    "prefill": t3 - t2,
                    "cohort": t4 - t3,
                    "merge": self._iter_merge_s,
                },
                tokens=self._iter_tokens,
                cohort=len(cohort),
                queue_depth=queue_depth,
                pages_in_use=pages_in_use,
                spec_proposed=self._iter_spec_proposed,
                spec_accepted=self._iter_spec_accepted,
            )
            self._last_iter_end = t_end
            get_flight_recorder().record_iteration(row)
            mfu = self.ledger.mfu_attribution()
            self._m_mfu_device.set(mfu["device_fraction"])
            self._m_mfu_host.set(mfu["host_fraction"])
            self._m_mfu_idle.set(mfu["idle_fraction"])
            if mfu["tokens"]:
                self._m_host_iter_per_token.set(
                    self.iterations / mfu["tokens"]
                )

    def _watchdog_loop(self) -> None:
        """Monitor thread: trip when a dispatched inner call has made no
        progress for ``watchdog_timeout_s``.  Idle engines never trip —
        staleness only counts while the busy window is open, so a quiet
        fleet replica is indistinguishable from a healthy one."""
        interval = max(0.01, self.watchdog_timeout_s / 4.0)
        while not self._watchdog_stop.wait(interval):
            now = time.monotonic()
            self._m_heartbeat_age.set(max(0.0, now - self._heartbeat))
            busy = self._busy_since
            if (
                not self.wedged
                and busy is not None
                and now - busy > self.watchdog_timeout_s
            ):
                self.wedged = True
                self.backend_lost = True
                self.watchdog_trips += 1
                self._m_watchdog_trips.inc()
                recorder = get_flight_recorder()
                recorder.record_event(
                    "watchdog_trip",
                    timeout_s=self.watchdog_timeout_s,
                    busy_s=round(now - busy, 3),
                    iterations=self.iterations,
                )
                recorder.dump("watchdog_trip")

    # -- iteration phases (lock held) ---------------------------------------

    def _process_cancellations(self) -> None:
        cancelled_items = set()
        keep: List[_Row] = []
        for row in self._gen_backlog:
            if row.item.failed or row.item in cancelled_items or row.item.cancelled():
                cancelled_items.add(row.item)
            else:
                keep.append(row)
        self._gen_backlog = keep
        for slot in list(self._slots):
            if slot is None:
                continue
            item = slot.row.item
            if item.failed or item in cancelled_items or item.cancelled():
                cancelled_items.add(item)
                self._evict(slot)
        for kind, queue in self._other.items():
            live: List[_Item] = []
            for item in queue:
                if item.cancelled():
                    if self._cancelled_counter is not None:
                        self._cancelled_counter.labels(kind).inc()
                    self._fail_item(
                        item,
                        RequestCancelled(
                            f"session cancelled before its {kind} call ran"
                        ),
                    )
                else:
                    live.append(item)
            self._other[kind] = live
        for item in cancelled_items:
            if self._cancelled_counter is not None and not item.failed:
                self._cancelled_counter.labels("generate").inc()
            self._fail_item(
                item,
                RequestCancelled(
                    "session cancelled; its resident rows were evicted and "
                    "their KV pages freed"
                ),
            )

    def _admit(self) -> None:
        free = [i for i, s in enumerate(self._slots) if s is None]
        occupied = [0] * self.mesh_dp
        for s in self._slots:
            if s is not None:
                occupied[s.shard] += 1
        while free and self._gen_backlog:
            row = self._gen_backlog[0]
            if row.item.failed:
                self._gen_backlog.pop(0)
                continue
            needed = self.pool.pages_for_tokens(
                row.prompt_tokens + int(getattr(row.request, "max_tokens", 0))
            )
            if needed > self.pool.num_pages:
                self._gen_backlog.pop(0)
                self._reject_oversized(row, needed)
                continue
            # Balanced admission: among free slots whose dp shard still has
            # reservation headroom, take the one on the least-loaded shard
            # (fewest resident rows, then fewest reserved pages, then lowest
            # slot index — which at dp=1 is exactly the legacy FIFO pick).
            best = None
            best_key = None
            for slot_idx in free:
                shard = slot_idx % self.mesh_dp
                if self._reserved[shard] + needed > self.pool.num_pages:
                    continue
                key = (occupied[shard], self._reserved[shard], slot_idx)
                if best_key is None or key < best_key:
                    best, best_key = slot_idx, key
            if best is None:
                # Fits a pool but not right now — hold FIFO order and wait
                # for resident rows to retire.
                break
            self._gen_backlog.pop(0)
            free.remove(best)
            shard = best % self.mesh_dp
            pool = self.pools[shard]
            cache = self.prefix_caches[shard]
            cached_pages: List[int] = []
            cached_tokens = 0
            if cache is not None:
                cached_pages, cached_tokens = cache.lookup(row.prompt_ids)
                if cached_tokens:
                    self._m_prefix_hits.inc()
                    self._m_prefix_saved.inc(cached_tokens)
                else:
                    self._m_prefix_misses.inc()
            # Shared pages come off the cache, not the free list — only the
            # private remainder counts against the reservation.
            slot = _Slot(
                best, row, reserved=needed - len(cached_pages), shard=shard
            )
            if cached_tokens:
                slot.table.adopt_shared(pool, cached_pages, cached_tokens)
                slot.prefilled = cached_tokens
                slot.cached_tokens = cached_tokens
                if slot.prefilled >= row.prompt_tokens:
                    slot.state = _READY
            self._slots[slot.idx] = slot
            self._reserved[shard] += slot.reserved
            occupied[shard] += 1
            self._m_admitted.inc()
            self._trace_row_event(
                row, "slot_admitted", slot=slot.idx, shard=shard,
                cached_tokens=cached_tokens)
            if slot.state == _READY:
                self._trace_row_event(row, "prefill_complete", cached=True)

    def _advance_prefill(self) -> None:
        for slot in self._slots:
            if slot is None or slot.state != _PREFILL:
                continue
            remaining = slot.row.prompt_tokens - slot.prefilled
            chunk = min(self.prefill_chunk, remaining)
            if chunk > 0:
                # Reservation guarantees the pool has room.
                slot.table.append_tokens(self.pools[slot.shard], chunk)
                slot.prefilled += chunk
                self._m_prefill_chunks.inc()
                self._m_prefill_tokens.inc(chunk)
                self._trace_row_event(slot.row, "prefill_chunk", tokens=chunk)
            if slot.prefilled >= slot.row.prompt_tokens:
                slot.state = _READY
                self._trace_row_event(slot.row, "prefill_complete")

    def _decode_cohort(self) -> List[_Slot]:
        # One multi-token stream in flight at a time: newly-ready slots
        # keep prefilling/waiting and form the NEXT cohort when the
        # current stream drains (admission still overlaps device decode —
        # that is the double-buffering, not a second stream).
        if self._stream is not None:
            return []
        ready = [s for s in self._slots if s is not None and s.state == _READY]
        prefilling = any(
            s is not None and s.state == _PREFILL for s in self._slots
        )
        if not ready or (prefilling and len(ready) < self.min_fill):
            return []
        for slot in ready:
            # Generated-token pages, allocated up front (the reservation
            # made at admission covers them); retired below with the slot.
            slot.table.append_tokens(
                self.pools[slot.shard],
                int(getattr(slot.row.request, "max_tokens", 0)),
            )
        self._m_pages.observe(sum(pool.in_use for pool in self.pools))
        return ready

    # -- dispatch (lock released) -------------------------------------------

    def _dispatch_decode(self, cohort: List[_Slot]) -> None:
        if self.decode_steps is not None and callable(
            getattr(self.inner, "generate_stream", None)
        ):
            self._open_stream(cohort)
            return
        requests = [slot.row.request for slot in cohort]
        self.dispatch_counts["generate"] += 1
        for slot in cohort:
            self._trace_row_event(
                slot.row, "decode_dispatch", cohort=len(cohort))
        results: Optional[List[Any]] = None
        row_errors: Dict[int, BaseException] = {}
        batch_error: Optional[BaseException] = None
        t_dev = time.perf_counter()
        try:
            results = self.inner.generate(requests)
        except PartialBatchError as exc:
            results = list(exc.results)
            row_errors = dict(exc.row_errors)
        except Exception as exc:
            batch_error = exc
            if isinstance(exc, BackendLostError):
                self.backend_lost = True
        # A blocking inner call IS a wait on device results.
        self._iter_block_s += time.perf_counter() - t_dev

        t_merge = time.perf_counter()
        with self._lock:
            tokens = 0
            for i, slot in enumerate(cohort):
                self._retire(slot)
                item = slot.row.item
                if batch_error is not None:
                    self._trace_row_end(slot.row, outcome="error")
                    self._fail_item(item, batch_error)
                elif i in row_errors:
                    self._trace_row_end(slot.row, outcome="error")
                    self._record_row(item, slot.row.index, None, row_errors[i])
                else:
                    result = results[i]
                    ids = getattr(result, "token_ids", None) or ()
                    row_tokens = len(ids) if ids else self._count_text_tokens(
                        getattr(result, "text", "") or ""
                    )
                    tokens += row_tokens
                    self._trace_row_end(
                        slot.row, outcome="retired", tokens=row_tokens)
                    self._record_row(item, slot.row.index, result, None)
            self._iter_tokens += tokens
            self._m_tokens_iter.observe(tokens)
            self._m_tokens_dispatch.observe(tokens)
            self.decode_windows += 1
            self.decoded_tokens += tokens
            self._work.notify_all()
        self._iter_merge_s += time.perf_counter() - t_merge

    # -- multi-token stream dispatch (lock released) --------------------------

    def _open_stream(self, cohort: List[_Slot]) -> None:
        """Start a K-step decode stream for this cohort: the inner backend
        prefills the cohort and launches the FIRST K-step window; the call
        returns as soon as the window is enqueued (jax async dispatch), so
        the next iteration's host phases run while the device decodes."""
        requests = [slot.row.request for slot in cohort]
        self.dispatch_counts["generate"] += 1
        for slot in cohort:
            self._trace_row_event(
                slot.row, "decode_dispatch", cohort=len(cohort),
                decode_steps=self.decode_steps)
        t_disp = time.perf_counter()
        try:
            if self.speculative:
                stream = self.inner.generate_stream(
                    requests, decode_steps=self.decode_steps,
                    speculative=True,
                )
            else:
                stream = self.inner.generate_stream(
                    requests, decode_steps=self.decode_steps
                )
            stream.dispatch()
        except Exception as exc:
            self._iter_dispatch_s += time.perf_counter() - t_disp
            if isinstance(exc, BackendLostError):
                self.backend_lost = True
            t_merge = time.perf_counter()
            with self._lock:
                for slot in cohort:
                    self._retire(slot)
                    self._trace_row_end(slot.row, outcome="error")
                    self._fail_item(slot.row.item, exc)
                self._work.notify_all()
            self._iter_merge_s += time.perf_counter() - t_merge
            return
        self._iter_dispatch_s += time.perf_counter() - t_disp
        self._stream = stream
        self._stream_slots = list(cohort)
        self._stream_spec_seen = (0, 0)

    def _advance_stream(self) -> None:
        """Collect the in-flight K-step window (the only point that blocks
        on the device), retire rows that finished inside it, then launch
        the next window — or drain the stream when every row is done."""
        stream = self._stream
        t_block = time.perf_counter()
        try:
            row_tokens, finished = stream.collect()
        except Exception as exc:
            self._iter_block_s += time.perf_counter() - t_block
            if isinstance(exc, BackendLostError):
                self.backend_lost = True
            self._close_stream(error=exc)
            return
        self._iter_block_s += time.perf_counter() - t_block

        # Draft accounting: the stream's cumulative counters advance at
        # dispatch (proposed) and collect (accepted); the delta since the
        # last read is this window's contribution.
        spec_proposed = int(getattr(stream, "spec_proposed", 0) or 0)
        spec_accepted = int(getattr(stream, "spec_accepted", 0) or 0)
        seen_p, seen_a = self._stream_spec_seen
        self._stream_spec_seen = (spec_proposed, spec_accepted)
        self._iter_spec_proposed += spec_proposed - seen_p
        self._iter_spec_accepted += spec_accepted - seen_a

        t_merge = time.perf_counter()
        with self._lock:
            tokens = sum(row_tokens)
            self._iter_tokens += tokens
            self._m_tokens_iter.observe(tokens)
            self._m_tokens_dispatch.observe(tokens)
            self.decode_windows += 1
            self.decoded_tokens += tokens
            self.spec_proposed_tokens += spec_proposed - seen_p
            self.spec_accepted_tokens += spec_accepted - seen_a
            for i, result in finished.items():
                slot = self._stream_slots[i]
                if slot is None:
                    continue
                self._stream_slots[i] = None
                if self._slots[slot.idx] is not slot:
                    # Evicted mid-stream (cancellation sweep); the stream
                    # kept masking the row on device — drop its result.
                    continue
                self._retire(slot)
                ids = getattr(result, "token_ids", None) or ()
                n_ids = len(ids) if ids else self._count_text_tokens(
                    getattr(result, "text", "") or ""
                )
                self._trace_row_end(
                    slot.row, outcome="retired", tokens=n_ids)
                self._record_row(slot.row.item, slot.row.index, result, None)
            self._work.notify_all()
        self._iter_merge_s += time.perf_counter() - t_merge

        if stream.finished:
            self._stream = None
            self._stream_slots = []
            close = getattr(stream, "close", None)
            if callable(close):
                close()
            return
        t_disp = time.perf_counter()
        try:
            stream.dispatch()
        except Exception as exc:
            self._iter_dispatch_s += time.perf_counter() - t_disp
            if isinstance(exc, BackendLostError):
                self.backend_lost = True
            self._close_stream(error=exc)
            return
        self._iter_dispatch_s += time.perf_counter() - t_disp

    def _close_stream(self, error: BaseException) -> None:
        """Tear down a failed stream: every row still riding it fails the
        way a legacy batch error fails its cohort."""
        stream, slots = self._stream, self._stream_slots
        self._stream, self._stream_slots = None, []
        close = getattr(stream, "close", None)
        if callable(close):
            try:
                close()
            except Exception:
                pass
        t_merge = time.perf_counter()
        with self._lock:
            for slot in slots:
                if slot is None or self._slots[slot.idx] is not slot:
                    continue
                self._retire(slot)
                self._trace_row_end(slot.row, outcome="error")
                self._fail_item(slot.row.item, error)
            self._work.notify_all()
        self._iter_merge_s += time.perf_counter() - t_merge

    def _dispatch_other(self, kind: str, items: List[_Item]) -> None:
        fn = {
            "score": self.inner.score,
            "next_token": self.inner.next_token_logprobs,
            "embed": self.inner.embed,
            "score_matrix": self._inner_score_matrix,
        }[kind]
        merged: List[Any] = []
        for item in items:
            merged.extend(item.requests)
        # Identical score rows in one merged dispatch compute once and fan
        # out (beam search re-scores shared prefixes every round; matrix
        # fallbacks repeat agent rows across co-batched sessions).
        mapping: Optional[List[int]] = None
        dispatch = merged
        if kind == "score":
            from consensus_tpu.backends.score_matrix import dedup_score_requests

            unique, mapping = dedup_score_requests(merged)
            if len(unique) < len(merged):
                self._m_score_dedup.inc(len(merged) - len(unique))
            dispatch = unique
        reserved = 0
        if kind == "score_matrix":
            reserved = self._reserve_matrix_pages(merged)
        self.dispatch_counts[kind] += 1
        for item in items:
            if item.trace is not None:
                item.trace.event(
                    item.span, "engine_dispatch", kind=kind,
                    batch=len(dispatch))
        try:
            t_dev = time.perf_counter()
            try:
                results = fn(dispatch)
            finally:
                self._iter_block_s += time.perf_counter() - t_dev
            if mapping is not None:
                from consensus_tpu.backends.score_matrix import expand_deduped

                results = expand_deduped(results, mapping)
            cursor = 0
            for item in items:
                n = len(item.requests)
                item.result = list(results[cursor : cursor + n])
                cursor += n
                item.event.set()
        except PartialBatchError as exc:
            if mapping is not None:
                from consensus_tpu.backends.score_matrix import (
                    expand_partial_error,
                )

                exc = expand_partial_error(exc, mapping)
            cursor = 0
            for item in items:
                n = len(item.requests)
                slice_errors = {
                    i - cursor: err
                    for i, err in exc.row_errors.items()
                    if cursor <= i < cursor + n
                }
                if not slice_errors:
                    item.result = list(exc.results[cursor : cursor + n])
                elif len(slice_errors) == n:
                    item.error = next(iter(slice_errors.values()))
                else:
                    item.error = PartialBatchError(
                        f"{len(slice_errors)}/{n} rows of this session's "
                        f"{kind} call failed inside an engine iteration",
                        results=list(exc.results[cursor : cursor + n]),
                        row_errors=slice_errors,
                    )
                cursor += n
                item.event.set()
        except Exception as exc:
            if isinstance(exc, BackendLostError):
                self.backend_lost = True
            for item in items:
                item.error = exc
                item.event.set()
        with self._lock:
            if reserved:
                self._reserved[0] -= reserved
            self._work.notify_all()

    def _inner_score_matrix(self, requests: List[Any]) -> List[Any]:
        """Route matrix requests to the inner backend's fused path when it
        has one, else the exact per-call fallback (one batched score)."""
        from consensus_tpu.backends.score_matrix import score_matrix_many

        return score_matrix_many(self.inner, requests)

    def _reserve_matrix_pages(self, requests: List[Any]) -> int:
        """Advisory page accounting for a matrix dispatch: the fused path
        allocates its own page pool on the same device, so reserving its
        estimated footprint against shard 0 makes generate admission back
        off instead of OOMing alongside it.  Estimates use the accounting
        tokenizer (never numerics); clamped so a huge matrix cannot wedge
        admission entirely."""
        ps = self.pool.page_size
        pages = 0
        for request in requests:
            cont = [self._count_text_tokens(c) for c in request.candidates]
            max_cont = max(cont, default=0)
            seen = set()
            for agent in request.agents:
                key = (agent.context, agent.system_prompt)
                if key in seen:
                    continue
                seen.add(key)
                n_ctx = self._count_text_tokens(agent.context)
                if agent.system_prompt:
                    n_ctx += self._count_text_tokens(agent.system_prompt)
                pages += n_ctx // ps
            rows = min(len(request.candidates) * len(request.agents), 64)
            pages += rows * ((ps + max_cont) // ps + 1)
        pages = min(pages, self.pool.num_pages // 2)
        if pages:
            with self._lock:
                self._reserved[0] += pages
        return pages

    # -- bookkeeping (lock held) --------------------------------------------

    def _retire(self, slot: _Slot) -> None:
        pool = self.pools[slot.shard]
        cache = self.prefix_caches[slot.shard]
        if cache is not None and slot.prefilled >= slot.row.prompt_tokens:
            # Donate the fully-prefilled, page-aligned prompt prefix before
            # releasing: the cache takes its own reference, so the pages
            # survive this slot's free below.  (Evicted mid-prefill slots
            # hold partial KV — never cacheable.)
            ps = pool.page_size
            n_pages = slot.row.prompt_tokens // ps
            if n_pages > 0:
                before = cache.evictions
                if cache.insert(
                    slot.row.prompt_ids[: n_pages * ps],
                    slot.table.pages[:n_pages],
                ):
                    self._m_prefix_inserted.inc(n_pages)
                self._m_prefix_evictions.inc(cache.evictions - before)
        slot.table.release(pool)
        self._reserved[slot.shard] -= slot.reserved
        self._slots[slot.idx] = None

    def _evict(self, slot: _Slot, count: bool = True) -> None:
        self._retire(slot)
        self._trace_row_end(slot.row, outcome="evicted")
        if count:
            self._m_evicted.inc()

    def _record_row(
        self, item: _Item, index: int, result, error: Optional[BaseException]
    ) -> None:
        if error is None:
            item.row_results[index] = result
        else:
            item.row_errors[index] = error
        item.rows_left -= 1
        if item.rows_left == 0 and not item.failed:
            self._finalize(item)

    def _finalize(self, item: _Item) -> None:
        if not item.row_errors:
            item.result = [
                item.row_results[i] for i in range(len(item.requests))
            ]
        elif len(item.row_errors) == len(item.requests):
            item.error = next(iter(item.row_errors.values()))
        else:
            item.error = PartialBatchError(
                f"{len(item.row_errors)}/{len(item.requests)} rows of this "
                "session's generate call failed inside an engine iteration",
                results=[
                    item.row_results.get(i) for i in range(len(item.requests))
                ],
                row_errors=dict(item.row_errors),
            )
        item.failed = item.error is not None
        item.event.set()

    def _fail_item(self, item: _Item, exc: BaseException) -> None:
        """Fail a whole item: queued rows are skipped on sight (``failed``),
        resident siblings get evicted by the cancellation sweep."""
        if item.failed or item.event.is_set():
            item.failed = True
            return
        item.failed = True
        item.error = exc
        item.event.set()

    def _reject_oversized(self, row: _Row, needed: int) -> None:
        # Lazy import: backends must not import the serving tier at module
        # load (serve imports batching), but the OOM contract is the
        # scheduler's typed admission signal.
        from consensus_tpu.serve.scheduler import SchedulerRejected

        self._fail_item(
            row.item,
            SchedulerRejected(
                "kv_oom",
                f"request needs {needed} KV pages; the pool holds only "
                f"{self.pool.num_pages} ({self.pool.page_size} tokens/page) "
                "— it can never be scheduled",
            ),
        )

    # -- token accounting ----------------------------------------------------

    def _prompt_token_ids(self, request) -> List[Any]:
        parts = [
            getattr(request, "system_prompt", None) or "",
            getattr(request, "user_prompt", "") or "",
        ]
        return self._tokenize_text(" ".join(p for p in parts if p))

    def _count_text_tokens(self, text: str) -> int:
        return len(self._tokenize_text(text))

    def _tokenize_text(self, text: str) -> List[Any]:
        """Tokens for PAGE accounting and prefix-cache CONTENT KEYS only —
        never for numerics.  Uses the inner backend's real tokenizer when
        it has one; the fake backend's whitespace pseudo-tokenizer
        otherwise."""
        tok = getattr(self.inner, "tokenizer", None)
        if tok is not None and hasattr(tok, "encode"):
            try:
                return list(tok.encode(text))
            except Exception:
                pass
        pseudo = getattr(self.inner, "_tokenize", None)
        if callable(pseudo):
            return list(pseudo(text))
        return text.split()
