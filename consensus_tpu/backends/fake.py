"""Deterministic fake backend for hardware- and network-free testing.

The reference has no fake/mock backend at all — its decoder logic is only
exercisable against the live Together API (SURVEY §4: "No mocks / fake
backends for the LLM").  This module supplies the missing piece: a fully
deterministic pseudo language model whose generations, logprobs, next-token
distributions and embeddings depend only on (text, seed) via a stable blake2b
hash.  Every decoder's search logic becomes unit-testable, bit-reproducibly.

Two instruction-following behaviours make the Habermas Machine pipeline
testable end-to-end:

* prompts asking for an Arrow-notation ranking (habermas_machine.py:586-654)
  get a valid ``<answer>...<sep>A > B ...</answer>`` response whose
  permutation is a deterministic function of (prompt, seed);
* prompts asking for the ``<answer>/<sep>`` statement envelope
  (habermas_machine.py:440-477, 1263-1305, 1344-1402) get a well-formed
  envelope wrapping pseudo-text.
"""

from __future__ import annotations

import hashlib
import math
import re
from typing import List, Optional, Sequence

import numpy as np

from consensus_tpu.backends.base import (
    GenerationRequest,
    GenerationResult,
    NextTokenRequest,
    ScoreRequest,
    ScoreResult,
    TokenCandidate,
)
from consensus_tpu.obs.backends import BackendInstruments

_WORDS = (
    "we believe support should public policy community fairness balance "
    "invest transport climate action change democracy voices people shared "
    "common ground improve protect ensure access education health funding "
    "local national future growth rights debate reform open equal trust "
    "together progress safety environment economy citizens representation"
).split()

_PUNCT = [".", ",", " and", " the", " of", " to", " in"]
_EOS_TOKENS = ["<|eot_id|>", "<end_of_turn>", ".\n\n"]

#: Fake vocabulary: words (with leading space), punctuation, EOS markers.
VOCAB: List[str] = [f" {w}" for w in _WORDS] + _PUNCT + _EOS_TOKENS

_RANK_PROMPT_MARKER = "Arrow notation"
_ENVELOPE_MARKER = "<answer>"
_STATEMENT_LINE_RE = re.compile(r"^([A-Z])\. ", re.MULTILINE)
_JUDGE_RANKING_MARKER = "method_ranking"
_JUDGE_SCORE_MARKER = "representation score"
_METHOD_LINE_RE = re.compile(r"^\d+\. \[([^\]]+)\]", re.MULTILINE)


def _digest(*parts) -> bytes:
    h = hashlib.blake2b(digest_size=16)
    for part in parts:
        h.update(str(part).encode("utf-8", "replace"))
        h.update(b"\x00")
    return h.digest()


def _hash_unit_float(*parts) -> float:
    """Deterministic float in [0, 1)."""
    return int.from_bytes(_digest(*parts)[:8], "big") / 2**64


def _rng(*parts) -> np.random.Generator:
    return np.random.default_rng(int.from_bytes(_digest(*parts)[:8], "big"))


def _pow2_bucket(n: int, minimum: int) -> int:
    """Next power of two >= max(n, minimum) — mirrors TPUBackend's row and
    width ladders so fake-run padding metrics have realistic shape."""
    bucket = minimum
    while bucket < n:
        bucket *= 2
    return bucket


class FakeBackend:
    """Deterministic pseudo-LM implementing the :class:`Backend` protocol."""

    name = "fake"
    #: The fake LM keys every response on (prompt, seed) and IGNORES
    #: temperature, so a temperature-0 retry with a new seed genuinely
    #: differs here — unlike TPUBackend's argmax path.  Keep False so the
    #: fake pipeline exercises the reference's full retry choreography.
    deterministic_greedy = False

    def __init__(
        self,
        embed_dim: int = 64,
        instruction_following: bool = True,
        registry=None,
    ):
        self.embed_dim = embed_dim
        self.instruction_following = instruction_following
        self.call_counts = {"generate": 0, "score": 0, "next_token": 0, "embed": 0}
        # Token-honest accounting mirroring TPUBackend (pseudo-tokens here).
        self.token_counts = {"generated": 0, "scored": 0}
        # obs: the fake backend records padding/launch events AS IF its
        # batches padded onto TPUBackend's pow2 grids, so the full metrics
        # path (registry -> metrics.json -> sweep aggregation) is testable
        # without hardware.  ``registry`` lets tests isolate from the
        # process-global registry.
        self.instruments = BackendInstruments("fake", registry=registry)

    # -- generation ---------------------------------------------------------

    def _full_prompt(self, request: GenerationRequest | NextTokenRequest) -> str:
        if request.system_prompt:
            if getattr(request, "chat", False):
                return f"[SYS]{request.system_prompt}[/SYS]\n{request.user_prompt}"
            return f"{request.system_prompt}\n\n{request.user_prompt}"
        return request.user_prompt

    def _pseudo_sentence(self, key: bytes, max_tokens: int) -> str:
        rng = np.random.default_rng(int.from_bytes(key[:8], "big"))
        length = int(rng.integers(6, max(7, min(max_tokens, 30))))
        words = [str(rng.choice(_WORDS)) for _ in range(length)]
        words[0] = words[0].capitalize()
        return " ".join(words) + "."

    def _ranking_response(self, prompt: str, seed) -> str:
        letters = sorted(set(_STATEMENT_LINE_RE.findall(prompt)))
        if not letters:
            letters = ["A", "B"]
        rng = _rng("rank", prompt, seed)
        order = list(rng.permutation(letters))
        ranking = " > ".join(order)
        return (
            "<answer>\nDeterministic fake reasoning about the participant's "
            f"opinion.\n<sep>\n{ranking}\n</answer>"
        )

    def _envelope_response(self, prompt: str, seed, max_tokens: int) -> str:
        body = self._pseudo_sentence(_digest("env", prompt, seed), max_tokens)
        return f"<answer>\nFake step-by-step reasoning.\n<sep>\n{body}\n</answer>"

    def _judge_ranking_response(self, prompt: str, seed) -> str:
        """Deterministic LLM-judge JSON: a permutation ranking of the
        ``N. [method] statement`` lines found in the prompt."""
        methods = _METHOD_LINE_RE.findall(prompt)
        if not methods:
            methods = ["unknown"]
        rng = _rng("judge-rank", prompt, seed)
        order = list(rng.permutation(len(methods)))
        ranking = {m: int(order[i]) + 1 for i, m in enumerate(methods)}
        import json as _json

        return _json.dumps(
            {
                "reasoning": "Deterministic fake comparative judgement.",
                "method_ranking": ranking,
            }
        )

    def _judge_score_response(self, prompt: str, seed) -> str:
        score = 1 + int(_hash_unit_float("judge-score", prompt, seed) * 5) % 5
        import json as _json

        return _json.dumps(
            {
                "representation score": score,
                "explanation": "Deterministic fake representation judgement.",
            }
        )

    def generate(self, requests: Sequence[GenerationRequest]) -> List[GenerationResult]:
        self.call_counts["generate"] += len(requests)
        if requests:
            rows = _pow2_bucket(len(requests), 8)
            width = _pow2_bucket(max(r.max_tokens for r in requests), 16)
            self.instruments.record_launch("generate", (rows, width))
        results = []
        for req in requests:
            prompt = self._full_prompt(req)
            if self.instruction_following and _JUDGE_RANKING_MARKER in prompt:
                text = self._judge_ranking_response(prompt, req.seed)
            elif self.instruction_following and _JUDGE_SCORE_MARKER in prompt:
                text = self._judge_score_response(prompt, req.seed)
            elif self.instruction_following and _RANK_PROMPT_MARKER in prompt:
                text = self._ranking_response(prompt, req.seed)
            elif self.instruction_following and _ENVELOPE_MARKER in prompt:
                text = self._envelope_response(prompt, req.seed, req.max_tokens)
            else:
                text = self._pseudo_sentence(_digest("gen", prompt, req.seed), req.max_tokens)
            for stop in req.stop:
                idx = text.find(stop)
                if idx >= 0:
                    text = text[:idx]
            self.token_counts["generated"] += len(self._tokenize(text))
            results.append(GenerationResult(text=text, finish_reason="stop"))
        if requests:
            self.instruments.record_padding(
                "generate_decode", rows, width,
                sum(len(self._tokenize(r.text)) for r in results),
            )
        return results

    def generate_stream(
        self,
        requests: Sequence[GenerationRequest],
        decode_steps: int = 1,
        speculative: bool = False,
    ) -> "_FakeGenerateStream":
        """Multi-token decode seam (engine ``decode_steps``): same bytes as
        ``generate`` — the full results are computed up front here, and each
        ``dispatch``/``collect`` window releases up to ``decode_steps``
        pseudo-tokens per unfinished row, so the engine's stream scheduling
        (windowed retirement, tokens-per-dispatch accounting) is exercised
        without a device in the loop.

        With ``speculative=True`` each window instead runs a REAL per-row
        ``NGramProposer`` self-draft against the precomputed pseudo-token
        stream and releases ``accepted + 1`` tokens — byte-identical by
        construction, with the same variable tokens-per-dispatch and
        draft-accounting surface (``spec_proposed`` / ``spec_accepted``)
        the TPU stream exposes."""
        prompt_rows = (
            [self._tokenize(self._full_prompt(r)) for r in requests]
            if speculative else None
        )
        return _FakeGenerateStream(
            list(self.generate(requests)), self._tokenize, decode_steps,
            prompt_rows=prompt_rows,
            registry=self.instruments.registry if speculative else None,
        )

    # -- scoring ------------------------------------------------------------

    def _tokenize(self, text: str) -> List[str]:
        """Whitespace-splitting pseudo-tokenizer that preserves spacing."""
        return re.findall(r"\s*\S+", text) or ([text] if text else [])

    def token_logprob(self, context: str, token: str) -> float:
        """Deterministic per-token logprob in [-6.0, -0.05]."""
        u = _hash_unit_float("lp", context, token)
        return -0.05 - 5.95 * u

    def score(self, requests: Sequence[ScoreRequest]) -> List[ScoreResult]:
        self.call_counts["score"] += len(requests)
        if requests:
            token_rows = [self._tokenize(r.continuation) for r in requests]
            rows = _pow2_bucket(len(requests), 8)
            width = _pow2_bucket(max(len(t) for t in token_rows), 64)
            self.instruments.record_launch("score", (rows, width))
            self.instruments.record_padding(
                "score", rows, width, sum(len(t) for t in token_rows)
            )
        results = []
        for req in requests:
            context = (
                f"{req.system_prompt}\n\n{req.context}" if req.system_prompt else req.context
            )
            tokens = self._tokenize(req.continuation)
            logprobs = []
            running = context
            for token in tokens:
                logprobs.append(self.token_logprob(running, token))
                running += token
            self.token_counts["scored"] += len(tokens)
            results.append(ScoreResult(tokens=tuple(tokens), logprobs=tuple(logprobs)))
        return results

    # -- next-token distribution -------------------------------------------

    def next_token_logprobs(
        self, requests: Sequence[NextTokenRequest]
    ) -> List[List[TokenCandidate]]:
        self.call_counts["next_token"] += len(requests)
        self.token_counts["scored"] += len(requests)
        if requests:
            rows = _pow2_bucket(len(requests), 8)
            self.instruments.record_launch("next_token", (rows, 1))
            self.instruments.record_padding("next_token", rows, 1, len(requests))
        out: List[List[TokenCandidate]] = []
        for req in requests:
            prompt = self._full_prompt(req)
            logits = np.array(
                [4.0 * _hash_unit_float("nt", prompt, tok) for tok in VOCAB]
            )
            for banned in req.bias_against_tokens:
                for idx, tok in enumerate(VOCAB):
                    if banned in tok:
                        logits[idx] += req.bias_value
            logprobs = logits - (
                np.max(logits) + math.log(np.sum(np.exp(logits - np.max(logits))))
            )
            k = min(req.k, len(VOCAB))
            if req.mode == "topk" or req.temperature <= 0:
                top = np.argsort(-logprobs)[:k]
            else:
                gumbel = _rng("gum", prompt, req.seed).gumbel(size=len(VOCAB))
                top = np.argsort(-(logprobs / req.temperature + gumbel))[:k]
                top = top[np.argsort(-logprobs[top])]
            out.append(
                [TokenCandidate(VOCAB[i], int(i), float(logprobs[i])) for i in top]
            )
        return out

    # -- embeddings ---------------------------------------------------------

    def embed(self, texts: Sequence[str]) -> np.ndarray:
        self.call_counts["embed"] += len(texts)
        if texts:
            rows = _pow2_bucket(len(texts), 8)
            self.instruments.record_launch("embed", (rows, 1))
            self.instruments.record_padding("embed", rows, 1, len(texts))
        vectors = np.stack(
            [_rng("emb", text).normal(size=self.embed_dim) for text in texts]
        )
        norms = np.linalg.norm(vectors, axis=1, keepdims=True)
        return vectors / np.maximum(norms, 1e-12)


class _FakeGenerateStream:
    """Windowed release of precomputed generate results.

    Mirrors the TPU backend's ``_PagedGenerateStream`` surface so the
    engine's multi-token scheduling is testable on the fake backend:
    ``dispatch()`` enqueues one K-step window, ``collect()`` returns
    ``(row_tokens, finished)`` where ``row_tokens[i]`` is the number of
    pseudo-tokens row i emitted in that window and ``finished`` maps row
    index -> GenerationResult for rows that completed inside it.
    """

    def __init__(
        self, results, tokenize, decode_steps: int,
        prompt_rows=None, registry=None,
    ):
        self._results = results
        self._token_rows = [tokenize(r.text) for r in results]
        self._cursors = [0] * len(results)
        self._done = [False] * len(results)
        self._decode_steps = max(1, int(decode_steps))
        self._pending = False
        #: Cumulative draft accounting the engine reads after collect().
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.speculative = prompt_rows is not None
        if self.speculative:
            from consensus_tpu.backends.speculative import NGramProposer

            # Pseudo-tokens are strings; the proposer wants int ids — map
            # them through a per-stream first-seen vocabulary.
            self._vocab: Dict[str, int] = {}
            self._id_rows = [
                [self._token_id(t) for t in toks]
                for toks in self._token_rows
            ]
            self._proposers = []
            self._ctx: List[List[int]] = []
            for prompt in prompt_rows:
                ids = [self._token_id(t) for t in prompt]
                proposer = NGramProposer()
                proposer.observe(ids)
                self._proposers.append(proposer)
                self._ctx.append(list(ids))
            self._obs_spec_proposed = registry.counter(
                "spec_draft_proposed_tokens_total",
                "Draft tokens proposed for speculative rollout verification",
                ("backend",),
            ).labels("fake")
            self._obs_spec_verified = registry.counter(
                "spec_draft_verified_tokens_total",
                "Draft tokens accepted by the parallel verify pass",
                ("backend",),
            ).labels("fake")

    def _token_id(self, token: str) -> int:
        return self._vocab.setdefault(token, len(self._vocab))

    @property
    def finished(self) -> bool:
        return all(self._done)

    def dispatch(self) -> None:
        self._pending = True

    def collect(self):
        if not self._pending:
            raise RuntimeError("collect() without a dispatched window")
        self._pending = False
        row_tokens = [0] * len(self._results)
        finished = {}
        for i, toks in enumerate(self._token_rows):
            if self._done[i]:
                continue
            if self.speculative:
                step = self._verify_window(i, len(toks))
            else:
                step = min(self._decode_steps, len(toks) - self._cursors[i])
            self._cursors[i] += step
            row_tokens[i] = step
            if self._cursors[i] >= len(toks):
                self._done[i] = True
                finished[i] = self._results[i]
        return row_tokens, finished

    def _verify_window(self, row: int, total: int) -> int:
        """Draft K ids, accept the longest matched prefix against the
        precomputed stream, release ``accepted + 1`` tokens (the exact
        device rejection rule — the '+1' is the correction/bonus token)."""
        k = self._decode_steps
        upcoming = self._id_rows[row][self._cursors[row]:]
        draft = self._proposers[row].draft(self._ctx[row], k)
        self.spec_proposed += k
        self._obs_spec_proposed.inc(k)
        matched = 0
        while matched < min(len(draft), len(upcoming)) \
                and draft[matched] == upcoming[matched]:
            matched += 1
        released = min(matched + 1, len(upcoming), total)
        accepted = min(matched, released)
        self.spec_accepted += accepted
        self._obs_spec_verified.inc(accepted)
        ids = upcoming[:released]
        self._proposers[row].observe(ids)
        self._ctx[row].extend(ids)
        return released

    def close(self) -> None:
        self._pending = False
