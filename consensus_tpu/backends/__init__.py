"""Pluggable model-execution backends.

``get_backend("fake" | "tpu" | "api")`` resolves the generation/scoring
engine used by all decoders — the single seam where the reference hard-wires
its Together client (src/utils.py:69-74).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from consensus_tpu.backends.base import (  # noqa: F401
    BAN_BIAS,
    Backend,
    BackendError,
    BackendIntegrityError,
    BackendLostError,
    GenerationRequest,
    GenerationResult,
    NextTokenRequest,
    PartialBatchError,
    ScoreRequest,
    ScoreResult,
    TokenCandidate,
    TransientBackendError,
    generate_one,
    score_one,
)
from consensus_tpu.backends.fake import FakeBackend  # noqa: F401

_BACKEND_CACHE: Dict[str, Backend] = {}


def get_backend(spec: Optional[Any] = None, *, fresh: bool = False,
                **kwargs) -> Backend:
    """Resolve a backend from a name, config dict, or pass through an instance.

    Accepted specs:
      * ``None`` / ``"fake"``  -> :class:`FakeBackend`
      * ``"tpu"``              -> :class:`~consensus_tpu.backends.tpu.TPUBackend`
      * ``"api"``              -> :class:`~consensus_tpu.backends.api.APIBackend`
      * ``"openai"``           -> :class:`~consensus_tpu.backends.api.OpenAIBackend` (LLM judge)
      * ``{"name": ..., ...}`` -> as above with constructor kwargs
      * an object already implementing :class:`Backend` -> returned unchanged

    ``fresh=True`` bypasses the cache in both directions: the caller gets
    its own instance and the cache is not polluted with it.  Fleet serving
    uses this — replicas must NOT alias one engine through the cache, or a
    single injected device loss would take down every "replica" at once.
    """
    if spec is None:
        spec = "fake"
    if isinstance(spec, dict):
        spec = dict(spec)
        name = spec.pop("name", "fake")
        kwargs = {**spec, **kwargs}
    elif isinstance(spec, str):
        name = spec
    else:
        return spec  # already a backend instance

    # Cache on (name, kwargs) so repeated resolutions — e.g. an in-process
    # config sweep — reuse one backend and its compiled device programs.
    try:
        cache_key = f"{name}:{sorted(kwargs.items())!r}"
    except TypeError:  # unhashable/unsortable kwargs: skip caching
        cache_key = None
    if fresh:
        cache_key = None
    if cache_key and cache_key in _BACKEND_CACHE:
        return _BACKEND_CACHE[cache_key]

    if name == "fake":
        backend: Backend = FakeBackend(**kwargs)
    elif name == "tpu":
        from consensus_tpu.backends.tpu import TPUBackend

        backend = TPUBackend(**kwargs)
    elif name == "api":
        from consensus_tpu.backends.api import APIBackend

        backend = APIBackend(**kwargs)
    elif name == "openai":
        from consensus_tpu.backends.api import OpenAIBackend

        backend = OpenAIBackend(**kwargs)
    else:
        raise ValueError(f"Unknown backend: {name!r}")

    if cache_key:
        _BACKEND_CACHE[cache_key] = backend
    return backend


def clear_backend_cache() -> None:
    _BACKEND_CACHE.clear()


def wrap_backend(
    backend: Backend,
    fault_plan=None,
    supervise=None,
    registry=None,
) -> Backend:
    """Layer the fault-tolerance wrappers onto a resolved backend.

    Order matters: faults are injected BELOW supervision so the supervisor
    has to handle them — ``supervisor(faults(engine))`` is the chaos-test
    stack.  Wrapped instances are never cached (``get_backend``'s cache
    holds only raw engines, so a faulted backend can't leak into a clean
    run).

    ``fault_plan``: a :class:`~consensus_tpu.backends.faults.FaultPlan`,
    dict, or JSON string; ``supervise``: ``True`` for defaults or a dict of
    :class:`~consensus_tpu.backends.supervisor.SupervisedBackend` kwargs.
    A fault plan without explicit ``supervise=False`` implies supervision —
    injecting faults nothing handles just breaks the run.
    """
    if fault_plan is not None:
        from consensus_tpu.backends.faults import FaultInjectingBackend

        backend = FaultInjectingBackend(backend, fault_plan, registry=registry)
        if supervise is None:
            supervise = True
    if supervise:
        from consensus_tpu.backends.supervisor import SupervisedBackend

        options = dict(supervise) if isinstance(supervise, dict) else {}
        backend = SupervisedBackend(backend, registry=registry, **options)
    return backend
