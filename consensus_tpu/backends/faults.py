"""Deterministic fault injection: the test substrate for every failure path.

Nothing in a failure path can be trusted until it has been exercised, and
real devices fail unreproducibly.  :class:`FaultInjectingBackend` wraps any
:class:`~consensus_tpu.backends.base.Backend` and injects faults from a
seeded :class:`FaultPlan` — the SAME plan against the same workload injects
the SAME faults at the same call indices, so chaos tests are as
reproducible as golden tests.

Fault kinds (``FaultSpec.kind``):

* ``transient_error`` / ``timeout_error`` — raise ``RuntimeError`` /
  ``TimeoutError`` BEFORE the inner call (the raw exception types flaky
  transports actually raise; the supervisor must classify them).
* ``nan_logprobs`` / ``inf_logprobs`` — poison one row (or all rows) of a
  ``score`` / ``next_token_logprobs`` result with NaN / +Inf.
* ``truncate`` — cut a generation's text in half and mark it
  ``finish_reason="length"``.
* ``latency`` — sleep ``latency_s`` before the inner call.
* ``device_lost`` — from the firing call onward, EVERY call raises
  :class:`~consensus_tpu.backends.base.BackendLostError` (a preempted TPU
  does not come back).
* ``hang`` — block the call FOREVER (until :meth:`release_hangs`): the one
  failure mode nothing above can classify, because nothing raises.  A hung
  XLA collective or wedged host runtime looks exactly like this — the call
  simply never returns — and it is what the decode engine's hang watchdog
  exists to convert into a recoverable ``backend_lost``.  Hung threads are
  daemon threads by serving convention; tests call ``release_hangs()`` at
  teardown to unstick them.

Transport-plane kinds (``drop``, ``duplicate``, ``reorder``, ``bit_flip``,
``partition``) share this spec/plan/seed machinery but are applied by
``serve.transport.FaultyTransport`` against the transport ops (``ship``,
``fetch``, ``probe``); the backend wrapper ignores them, so ONE seeded plan
can script a whole incident across both domains.

Firing is per-op and per-call-index: ``call_index`` pins a spec to the
N-th call of that op (exact), ``after_s`` pins it to the first matching
call at/after that much wall-clock time since backend construction (the
replica-loss spec fleet chaos runs arm per replica), and ``rate`` fires
pseudo-randomly via a seeded hash of
``(plan seed, spec index, op, call index)`` — deterministic given the
call order.  Injections are counted in ``faults_injected_total{kind,op}``.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from consensus_tpu.backends.base import (
    Backend,
    BackendLostError,
    GenerationRequest,
    GenerationResult,
    NextTokenRequest,
    ScoreRequest,
    ScoreResult,
    TokenCandidate,
)
from consensus_tpu.obs.metrics import Registry, get_registry

#: Backend protocol ops (the original injection surface).
BACKEND_OPS = ("generate", "score", "next_token", "embed")

#: Transport-plane ops (``serve/transport.py``): page-run shipping,
#: fetching, and the health probe.  One seeded plan can address both
#: domains — a spec with ``op="ship"`` simply never matches a backend
#: call, and a backend-only kind firing on a transport op is ignored by
#: the transport wrapper.
TRANSPORT_OPS = ("ship", "fetch", "probe")

#: Ops fault specs can target (``"*"`` matches all of them).
OPS = BACKEND_OPS + TRANSPORT_OPS

FAULT_KINDS = (
    "transient_error",
    "timeout_error",
    "nan_logprobs",
    "inf_logprobs",
    "truncate",
    "latency",
    "device_lost",
    "hang",
    # Transport-plane kinds (applied by ``serve.transport.FaultyTransport``;
    # ignored by the backend wrapper):
    "drop",
    "duplicate",
    "reorder",
    "bit_flip",
    "partition",
)

#: Kinds only the transport wrapper knows how to apply.
TRANSPORT_KINDS = ("drop", "duplicate", "reorder", "bit_flip", "partition")


def _hash_unit(*parts) -> float:
    """Deterministic float in [0, 1) from the fault plan's hash space."""
    h = hashlib.blake2b(digest_size=8)
    for part in parts:
        h.update(str(part).encode("utf-8", "replace"))
        h.update(b"\x1f")
    return int.from_bytes(h.digest(), "big") / 2**64


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One fault rule: what to inject, into which op, and when."""

    kind: str
    op: str = "*"  # generate | score | next_token | embed | *
    #: Exact per-op call index to fire at (0-based).  Mutually exclusive
    #: with ``rate`` in spirit; when set, ``rate`` is ignored.
    call_index: Optional[int] = None
    #: Fire on the first matching call at/after this many wall-clock
    #: seconds since the backend was constructed (checked after
    #: ``call_index``, before ``rate``).  With ``kind="device_lost"`` this
    #: is the "replica lost after N seconds" chaos spec: deterministic per
    #: replica given its own FaultInjectingBackend and clock.
    after_s: Optional[float] = None
    #: Seeded per-call firing probability when ``call_index`` is None.
    rate: float = 0.0
    #: Row to poison for nan/inf/truncate faults (None = every row).
    row_index: Optional[int] = None
    #: Added delay for ``latency`` faults.
    latency_s: float = 0.0
    #: Window length for ``partition`` faults: the peer is unreachable for
    #: ``[after_s, after_s + duration_s)`` on the transport's clock.
    duration_s: float = 0.0
    #: Peer name a ``partition`` fault isolates (None = partition the whole
    #: seam — every peer unreachable for the window).
    peer: Optional[str] = None

    def __post_init__(self):
        if self.kind not in FAULT_KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; expected one of "
                f"{FAULT_KINDS}"
            )
        if self.op != "*" and self.op not in OPS:
            raise ValueError(f"unknown op {self.op!r}; expected {OPS} or '*'")
        if not 0.0 <= self.rate <= 1.0:
            raise ValueError(f"rate must be in [0, 1], got {self.rate}")
        if self.after_s is not None and self.after_s < 0:
            raise ValueError(f"after_s must be >= 0, got {self.after_s}")
        if self.duration_s < 0:
            raise ValueError(
                f"duration_s must be >= 0, got {self.duration_s}")
        if self.kind == "partition" and self.after_s is None:
            raise ValueError("partition faults need after_s (window start)")

    def matches(self, op: str) -> bool:
        return self.op == "*" or self.op == op

    def fires(self, seed: int, spec_index: int, op: str, call_index: int,
              elapsed_s: float = 0.0) -> bool:
        if not self.matches(op):
            return False
        if self.call_index is not None:
            return call_index == self.call_index
        if self.after_s is not None:
            return elapsed_s >= self.after_s
        if self.rate <= 0.0:
            return False
        return _hash_unit(seed, spec_index, op, call_index) < self.rate


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """A seeded, ordered set of :class:`FaultSpec` rules."""

    seed: int = 0
    faults: Tuple[FaultSpec, ...] = ()

    @classmethod
    def from_spec(cls, spec: Union["FaultPlan", Dict[str, Any], str, None]
                  ) -> Optional["FaultPlan"]:
        """Coerce a plan from itself, a dict, or a JSON string (the
        ``--fault-plan`` CLI surface); ``None`` stays ``None``."""
        if spec is None or isinstance(spec, FaultPlan):
            return spec
        if isinstance(spec, str):
            spec = json.loads(spec)
        if not isinstance(spec, dict):
            raise ValueError(
                f"fault plan must be a dict or JSON object, got "
                f"{type(spec).__name__}"
            )
        faults = tuple(
            fault if isinstance(fault, FaultSpec) else FaultSpec(**fault)
            for fault in spec.get("faults", ())
        )
        return cls(seed=int(spec.get("seed", 0)), faults=faults)

    @classmethod
    def replica_lost(cls, after_s: Optional[float] = None,
                     call_index: Optional[int] = None,
                     op: str = "*", seed: int = 0) -> "FaultPlan":
        """A single sticky ``device_lost`` spec: the replica dies at the
        given wall-clock time OR per-op call index and never comes back —
        the deterministic kill fleet failover tests and ``BENCH_FLEET``
        chaos runs arm on one replica's backend."""
        if (after_s is None) == (call_index is None):
            raise ValueError(
                "replica_lost needs exactly one of after_s / call_index")
        return cls(seed=seed, faults=(FaultSpec(
            kind="device_lost", op=op, call_index=call_index,
            after_s=after_s,
        ),))

    def firing(self, op: str, call_index: int,
               elapsed_s: float = 0.0) -> List[FaultSpec]:
        """Specs that fire for this (op, per-op call index, elapsed time).

        ``partition`` specs are window-scheduled, not per-call — they are
        excluded here and consumed via :meth:`partition_windows`."""
        return [
            spec for i, spec in enumerate(self.faults)
            if spec.kind != "partition"
            and spec.fires(self.seed, i, op, call_index, elapsed_s)
        ]

    def partition_windows(self) -> List[Tuple[Optional[str], float, float]]:
        """Scheduled partitions as ``(peer, start_s, end_s)`` windows
        relative to the consuming wrapper's construction time.  ``peer``
        is None for a full-seam partition."""
        return [
            (spec.peer, float(spec.after_s),
             float(spec.after_s) + float(spec.duration_s))
            for spec in self.faults
            if spec.kind == "partition" and spec.after_s is not None
        ]


class FaultInjectingBackend:
    """Wrap ``inner`` and inject the plan's faults into its protocol calls.

    Deliberately does NOT expose ``open_fused_token_search``: fused
    sessions bypass the protocol seam, so they would bypass injection too —
    without the attribute, the session factory falls back to the
    full-prefix path whose every call crosses this wrapper.
    """

    name = "faults"

    def __init__(
        self,
        inner: Backend,
        plan: Union[FaultPlan, Dict[str, Any], str],
        registry: Optional[Registry] = None,
        sleep=time.sleep,
        clock=time.monotonic,
    ):
        self.inner = inner
        self.plan = FaultPlan.from_spec(plan) or FaultPlan()
        self._sleep = sleep
        self._clock = clock
        self._t0 = clock()  # ``after_s`` specs measure from construction
        self._lock = threading.Lock()
        self._call_index = {op: 0 for op in OPS}
        self._device_lost = False
        # ``hang`` faults park the calling thread on this event; it starts
        # unset (block forever) and ``release_hangs()`` sets it for good.
        self._hang_release = threading.Event()
        self.hangs_active = 0
        reg = registry if registry is not None else get_registry()
        self._injected = reg.counter(
            "faults_injected_total",
            "Faults injected by the fault-injection backend, by kind and op.",
            labels=("kind", "op"),
        )

    # -- passthrough surface -------------------------------------------------

    @property
    def deterministic_greedy(self) -> bool:
        return bool(getattr(self.inner, "deterministic_greedy", False))

    @property
    def token_counts(self):
        return getattr(self.inner, "token_counts", {})

    # -- injection core ------------------------------------------------------

    def _next_index(self, op: str) -> int:
        with self._lock:
            index = self._call_index[op]
            self._call_index[op] = index + 1
            return index

    def _pre_call(self, op: str) -> List[FaultSpec]:
        """Apply call-blocking faults; return result-mutating specs."""
        index = self._next_index(op)
        specs = self.plan.firing(op, index, self._clock() - self._t0)
        if self._device_lost or any(s.kind == "device_lost" for s in specs):
            if not self._device_lost:
                self._injected.labels("device_lost", op).inc()
            self._device_lost = True
            raise BackendLostError(
                f"injected device loss (op={op}, call={index})"
            )
        post = []
        for spec in specs:
            if spec.kind == "latency":
                self._injected.labels("latency", op).inc()
                self._sleep(spec.latency_s)
            elif spec.kind == "transient_error":
                self._injected.labels("transient_error", op).inc()
                raise RuntimeError(
                    f"injected transient fault (op={op}, call={index})"
                )
            elif spec.kind == "timeout_error":
                self._injected.labels("timeout_error", op).inc()
                raise TimeoutError(
                    f"injected timeout (op={op}, call={index})"
                )
            elif spec.kind == "hang":
                # Block until released — the silent-hang failure mode.  The
                # caller's thread parks here with no exception for anything
                # above to classify; only the decode engine's heartbeat
                # watchdog can observe the wedge.  After release the call
                # proceeds normally (the hang was transient from the
                # caller's perspective — but the watchdog has long since
                # declared the replica lost).
                self._injected.labels("hang", op).inc()
                with self._lock:
                    self.hangs_active += 1
                try:
                    self._hang_release.wait()
                finally:
                    with self._lock:
                        self.hangs_active -= 1
            else:
                post.append(spec)
        return post

    def release_hangs(self) -> None:
        """Unstick every thread parked (now or later) on a ``hang`` fault.

        Irreversible by design: tests call this at teardown so hung daemon
        threads do not outlive the test holding shared state."""
        self._hang_release.set()

    def _target_rows(self, spec: FaultSpec, n: int) -> List[int]:
        if spec.row_index is None:
            return list(range(n))
        return [spec.row_index] if 0 <= spec.row_index < n else []

    # -- protocol ------------------------------------------------------------

    def generate(self, requests: Sequence[GenerationRequest]) -> List[GenerationResult]:
        post = self._pre_call("generate")
        results = list(self.inner.generate(requests))
        for spec in post:
            if spec.kind != "truncate":
                continue
            for row in self._target_rows(spec, len(results)):
                res = results[row]
                cut = max(1, len(res.text) // 2)
                results[row] = dataclasses.replace(
                    res, text=res.text[:cut], finish_reason="length"
                )
                self._injected.labels("truncate", "generate").inc()
        return results

    def score(self, requests: Sequence[ScoreRequest]) -> List[ScoreResult]:
        post = self._pre_call("score")
        results = list(self.inner.score(requests))
        for spec in post:
            if spec.kind not in ("nan_logprobs", "inf_logprobs"):
                continue
            poison = float("nan") if spec.kind == "nan_logprobs" else float("inf")
            for row in self._target_rows(spec, len(results)):
                res = results[row]
                logprobs = list(res.logprobs) or [0.0]
                logprobs[0] = poison
                results[row] = dataclasses.replace(
                    res,
                    tokens=res.tokens or ("<poison>",),
                    logprobs=tuple(logprobs),
                )
                self._injected.labels(spec.kind, "score").inc()
        return results

    def next_token_logprobs(
        self, requests: Sequence[NextTokenRequest]
    ) -> List[List[TokenCandidate]]:
        post = self._pre_call("next_token")
        results = [list(cands) for cands in self.inner.next_token_logprobs(requests)]
        for spec in post:
            if spec.kind not in ("nan_logprobs", "inf_logprobs"):
                continue
            poison = float("nan") if spec.kind == "nan_logprobs" else float("inf")
            for row in self._target_rows(spec, len(results)):
                cands = results[row]
                if cands:
                    results[row] = [
                        dataclasses.replace(cands[0], logprob=poison)
                    ] + cands[1:]
                    self._injected.labels(spec.kind, "next_token").inc()
        return results

    def embed(self, texts: Sequence[str]) -> np.ndarray:
        post = self._pre_call("embed")
        vectors = np.array(self.inner.embed(texts), copy=True)
        for spec in post:
            if spec.kind not in ("nan_logprobs", "inf_logprobs"):
                continue
            poison = float("nan") if spec.kind == "nan_logprobs" else float("inf")
            for row in self._target_rows(spec, len(vectors)):
                vectors[row, 0] = poison
                self._injected.labels(spec.kind, "embed").inc()
        return vectors
