"""Remote-API backend: byte-compatible fallback to hosted models.

Preserves the reference's L1/L2 behaviour (src/utils.py): Together-style
chat/raw completions for ``generate``, echo'd-prompt logprobs for ``score``,
1-token completions for ``next_token_logprobs``, an embeddings endpoint for
``embed``, a token-bucket rate limiter (src/experiment.py:26-62) and error
sentinels instead of exceptions (src/utils.py:195-198, SURVEY §5.3).

This environment is zero-egress, so construction is lazy and failure-
tolerant: without the ``together``/``openai`` packages or keys every call
returns error sentinels — the framework's decoders and pipeline behave
exactly as the reference does when its client fails to initialize
(src/utils.py:69-74 sets ``client = None`` and call sites degrade).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import List, Optional, Sequence

import numpy as np

from consensus_tpu.backends.base import (
    GenerationRequest,
    GenerationResult,
    NextTokenRequest,
    ScoreRequest,
    ScoreResult,
    TokenCandidate,
)

logger = logging.getLogger(__name__)


class RateLimiter:
    """Token-bucket limiter (reference APIRateLimiter, src/experiment.py:26-62)."""

    def __init__(self, calls_per_second: float = 5.0):
        self.rate = calls_per_second
        self.capacity = max(1.0, calls_per_second)
        self.tokens = self.capacity
        self.updated = time.monotonic()
        self._lock = threading.RLock()

    def wait_for_token(self) -> None:
        while True:
            with self._lock:
                now = time.monotonic()
                self.tokens = min(
                    self.capacity, self.tokens + (now - self.updated) * self.rate
                )
                self.updated = now
                if self.tokens >= 1.0:
                    self.tokens -= 1.0
                    return
                needed = (1.0 - self.tokens) / self.rate
            time.sleep(needed)


class APIBackend:
    name = "api"

    def __init__(
        self,
        model: str = "google/gemma-2-9b-it",
        embedding_model: str = "BAAI/bge-large-en-v1.5",
        rate_limit: float = 5.0,
        embed_dim: int = 1024,
    ):
        self.model = model
        self.embedding_model = embedding_model
        self.embed_dim = embed_dim
        self.rate_limiter = RateLimiter(rate_limit)
        self._client = None
        try:  # pragma: no cover - zero-egress environment
            from together import Together  # type: ignore

            self._client = Together()
        except Exception as exc:
            logger.warning("APIBackend: client unavailable (%s); error sentinels", exc)

    # -- protocol -----------------------------------------------------------

    def generate(self, requests: Sequence[GenerationRequest]) -> List[GenerationResult]:
        return [self._generate_one(r) for r in requests]

    def _generate_one(self, request: GenerationRequest) -> GenerationResult:
        if self._client is None:
            return GenerationResult(
                text="[ERROR: API client not initialized]", finish_reason="error"
            )
        self.rate_limiter.wait_for_token()
        try:  # pragma: no cover
            if request.chat:
                messages = []
                if request.system_prompt:
                    messages.append({"role": "system", "content": request.system_prompt})
                messages.append({"role": "user", "content": request.user_prompt})
                response = self._client.chat.completions.create(
                    model=self.model,
                    messages=messages,
                    max_tokens=request.max_tokens,
                    temperature=request.temperature,
                    seed=request.seed,
                    stop=list(request.stop) or None,
                    repetition_penalty=request.repetition_penalty,
                )
                text = response.choices[0].message.content
            else:
                prompt = (
                    f"{request.system_prompt}\n\n{request.user_prompt}"
                    if request.system_prompt
                    else request.user_prompt
                )
                response = self._client.completions.create(
                    model=self.model,
                    prompt=prompt,
                    max_tokens=request.max_tokens,
                    temperature=request.temperature,
                    seed=request.seed,
                    stop=list(request.stop) or None,
                    repetition_penalty=request.repetition_penalty,
                )
                text = response.choices[0].text
            return GenerationResult(text=text or "", finish_reason="stop")
        except Exception as exc:
            return GenerationResult(text=f"[ERROR: {exc}]", finish_reason="error")

    def score(self, requests: Sequence[ScoreRequest]) -> List[ScoreResult]:
        return [self._score_one(r) for r in requests]

    def _score_one(self, request: ScoreRequest) -> ScoreResult:
        """Echo'd-prompt logprobs of the continuation span (the surface of
        reference get_prompt_logprobs, src/utils.py:201-281)."""
        if self._client is None:
            return ScoreResult(tokens=(), logprobs=())
        self.rate_limiter.wait_for_token()
        try:  # pragma: no cover
            prompt = (
                f"{request.system_prompt}\n\n{request.context}{request.continuation}"
                if request.system_prompt
                else f"{request.context}{request.continuation}"
            )
            response = self._client.completions.create(
                model=self.model,
                prompt=prompt,
                max_tokens=1,
                logprobs=1,
                echo=True,
            )
            tokens = response.prompt[0].logprobs.tokens
            logprobs = response.prompt[0].logprobs.token_logprobs
            # Keep only the continuation's trailing span by char budget.
            span: List[str] = []
            length = 0
            for token, lp in zip(reversed(tokens), reversed(logprobs)):
                if length >= len(request.continuation):
                    break
                span.append((token, lp))
                length += len(token)
            span.reverse()
            return ScoreResult(
                tokens=tuple(t for t, _ in span),
                logprobs=tuple(float(lp) for _, lp in span if lp is not None),
            )
        except Exception as exc:
            logger.warning("score failed: %s", exc)
            return ScoreResult(tokens=(), logprobs=())

    def next_token_logprobs(
        self, requests: Sequence[NextTokenRequest]
    ) -> List[List[TokenCandidate]]:
        out: List[List[TokenCandidate]] = []
        for request in requests:
            candidates: List[TokenCandidate] = []
            seen = set()
            attempts = 0
            # The reference's rejection-sampling pattern (beam_search.py:253-333):
            # repeated 1-token completions with varied seeds until k distinct.
            while len(candidates) < request.k and attempts < 3 * request.k:
                attempts += 1
                result = self._generate_one(
                    GenerationRequest(
                        user_prompt=request.user_prompt,
                        system_prompt=request.system_prompt,
                        max_tokens=1,
                        temperature=request.temperature,
                        seed=(request.seed or 0) + attempts,
                        chat=request.chat,
                    )
                )
                if not result.ok or not result.text:
                    break
                token = result.text
                if token not in seen:
                    seen.add(token)
                    candidates.append(
                        TokenCandidate(token=token, token_id=-1, logprob=0.0)
                    )
            out.append(candidates)
        return out

    def embed(self, texts: Sequence[str]) -> np.ndarray:
        if self._client is None:
            return np.zeros((len(texts), self.embed_dim), np.float32)
        vectors = []
        for text in texts:  # pragma: no cover
            self.rate_limiter.wait_for_token()
            try:
                response = self._client.embeddings.create(
                    model=self.embedding_model, input=text
                )
                vectors.append(np.asarray(response.data[0].embedding, np.float32))
            except Exception as exc:
                logger.warning("embed failed: %s", exc)
                vectors.append(np.zeros((self.embed_dim,), np.float32))
        stacked = np.stack(vectors)
        norms = np.linalg.norm(stacked, axis=1, keepdims=True)
        return stacked / np.maximum(norms, 1e-12)


#: Judge-model aliases the reference hardcodes: asking for "o3" actually
#: calls gpt-4.1 with temperature 0 and seed 42 (src/evaluation.py:447-462).
JUDGE_MODEL_ALIASES = {"o3": "gpt-4.1"}
JUDGE_SEED = 42


class OpenAIBackend:
    """OpenAI chat backend — the reference's LLM-judge path (L1 OpenAI leg,
    src/evaluation.py:23,456,714,744).

    Only ``generate`` is remote (judging is pure text-in/text-out); scoring,
    next-token and embeddings are not served by the judge API, so they
    return the same error sentinels the reference degrades to.  JSON mode is
    requested when the prompt asks for JSON (the judge prompts do).
    """

    name = "openai"

    def __init__(
        self,
        model: str = "o3",
        rate_limit: float = 5.0,
        json_mode: bool = True,
    ):
        self.requested_model = model
        self.model = JUDGE_MODEL_ALIASES.get(model, model)
        self.json_mode = json_mode
        self.rate_limiter = RateLimiter(rate_limit)
        self._client = None
        try:  # pragma: no cover - zero-egress environment
            from openai import OpenAI  # type: ignore

            self._client = OpenAI()
        except Exception as exc:
            logger.warning("OpenAIBackend: client unavailable (%s)", exc)

    def generate(self, requests: Sequence[GenerationRequest]) -> List[GenerationResult]:
        return [self._generate_one(r) for r in requests]

    def _generate_one(self, request: GenerationRequest) -> GenerationResult:
        if self._client is None:
            return GenerationResult(
                text="[ERROR: OpenAI client not initialized]", finish_reason="error"
            )
        self.rate_limiter.wait_for_token()
        try:  # pragma: no cover
            messages = []
            if request.system_prompt:
                messages.append({"role": "system", "content": request.system_prompt})
            messages.append({"role": "user", "content": request.user_prompt})
            kwargs = {}
            if self.json_mode and "json" in request.user_prompt.lower():
                kwargs["response_format"] = {"type": "json_object"}
            # Forward the request's sampling params (VERDICT r3): the judge
            # prompts ask for up to 1,000 tokens and would otherwise be
            # truncated at the server default; per-request seeds fall back
            # to the reference's fixed judge seed (src/evaluation.py:462).
            if request.max_tokens:
                kwargs["max_tokens"] = request.max_tokens
            response = self._client.chat.completions.create(
                model=self.model,
                messages=messages,
                temperature=request.temperature,
                seed=JUDGE_SEED if request.seed is None else request.seed,
                **kwargs,
            )
            return GenerationResult(
                text=response.choices[0].message.content or "", finish_reason="stop"
            )
        except Exception as exc:
            return GenerationResult(text=f"[ERROR: {exc}]", finish_reason="error")

    def score(self, requests: Sequence[ScoreRequest]) -> List[ScoreResult]:
        return [ScoreResult(tokens=(), logprobs=()) for _ in requests]

    def next_token_logprobs(
        self, requests: Sequence[NextTokenRequest]
    ) -> List[List[TokenCandidate]]:
        return [[] for _ in requests]

    def embed(self, texts: Sequence[str]) -> np.ndarray:
        return np.zeros((len(texts), 1024), np.float32)
