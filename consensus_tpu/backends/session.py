"""Token-search sessions: stateful propose-and-score for token-level decoders.

A session fixes the search context once — the reference-policy prompt and
the per-agent prompts (same model, different prefix: SURVEY §0) — and then
serves the decoders' per-step primitive:

    propose k next tokens per active slot from the reference policy, and
    score every proposal under every agent policy.

Two implementations:

* :class:`PrefixTokenSearchSession` — backend-agnostic fallback.  Each step
  re-submits full prefixes through ``Backend.next_token_logprobs`` +
  ``Backend.score`` (exactly round 1's beam-search data flow; works on
  fake/API backends).  O(T^2) total model work.
* :class:`TPUTokenSearchSession` (constructed by
  ``TPUBackend.open_token_search``) — persistent per-(slot x role) KV caches
  on device; each step is ONE fused program (models/stepper.py).  O(T).

Semantics note: the fallback re-tokenizes ``prompt + sequence_string`` every
step (the reference's behavior — its "sequence" is a string of API token
strings, beam_search.py:433-435), while the TPU session appends token *ids*
to persistent caches — the true token-level-MDP state.  The two coincide
except when a tokenizer would merge a sequence boundary on re-encoding.
"""

from __future__ import annotations

import dataclasses
from typing import List, NamedTuple, Optional, Sequence, Tuple

from consensus_tpu.backends.base import (
    BAN_BIAS,
    NextTokenRequest,
    ScoreRequest,
)


class FusedSessionUnavailable(Exception):
    """A backend's fused session implementation declined this spec (e.g. the
    KV caches would not fit in device memory) — use the generic fallback."""


class ScoredCandidate(NamedTuple):
    token: str
    token_id: int
    ref_logprob: float  # proposal logprob under the reference policy
    agent_logprobs: Tuple[float, ...]  # one per agent, search-order


@dataclasses.dataclass(frozen=True)
class SearchSpec:
    """Immutable description of one token search."""

    ref_system: Optional[str]
    ref_user: str
    agent_prompts: Tuple[Tuple[Optional[str], str], ...]  # (system, user) per agent
    n_slots: int
    k: int
    temperature: float = 1.0
    seed: Optional[int] = None
    sample: bool = True  # Gumbel-top-k vs deterministic top-k proposals
    bias_against_tokens: Tuple[str, ...] = ()
    bias_value: float = BAN_BIAS
    max_steps: int = 64
    failure_logprob: float = -10.0  # substituted when a backend scores nothing
    #: Speculative rollout verification (Leviathan et al.): an n-gram
    #: self-draft proposer emits ``spec_draft_len`` suffix tokens per leaf
    #: and the target model verifies the whole draft in one parallel
    #: forward (models/stepper.rollout_verify_many), with standard
    #: rejection keeping token streams byte-identical to the sequential
    #: scan.  TPU fused sessions only — the full-prefix fallback's rollout
    #: is already ONE batched generate call, so speculation is accepted
    #: and ignored there (trivially byte-identical).
    speculative: bool = False
    spec_draft_len: int = 8
    #: Route the fallback session's (prefix x candidate x agent) scoring
    #: through the utility-matrix seam (backends/score_matrix.py): fused
    #: on-device on backends that implement ``score_matrix``, byte-identical
    #: batched per-call fallback elsewhere.  Off restores the flat
    #: per-cell ``Backend.score`` batches.
    matrix_scoring: bool = True


class PrefixTokenSearchSession:
    """Fallback session: full-prefix batched calls per step (any backend)."""

    def __init__(self, backend, spec: SearchSpec):
        self.backend = backend
        self.spec = spec
        self._sequences = [""] * spec.n_slots
        self._step = 0
        #: Backend protocol calls actually submitted (the fallback's unit of
        #: host->device round trips).  Decoders read the delta per statement.
        self.dispatch_count = 0

    # -- protocol ------------------------------------------------------------

    def propose(self) -> List[List[ScoredCandidate]]:
        """Root proposals (every slot starts with the empty sequence)."""
        return self._propose_and_score()

    def close(self) -> None:
        """No device state to release in the full-prefix fallback."""

    def advance_and_propose(
        self, parents: Sequence[int], chosen: Sequence[ScoredCandidate]
    ) -> List[List[ScoredCandidate]]:
        """Advance slot i to ``parents[i]``'s sequence + ``chosen[i]``, then
        propose and score for the new state of every slot."""
        spec = self.spec
        if len(parents) != spec.n_slots or len(chosen) != spec.n_slots:
            raise ValueError(
                f"expected {spec.n_slots} (parent, token) pairs, got "
                f"{len(parents)}/{len(chosen)}"
            )
        self._sequences = [
            self._sequences[parent] + cand.token
            for parent, cand in zip(parents, chosen)
        ]
        self._step += 1
        return self._propose_and_score()

    def propose_suffixes(
        self, suffixes: Sequence[Sequence[ScoredCandidate]], salt: int
    ) -> List[List[ScoredCandidate]]:
        """Propose + score k candidates for each tree path hanging off the
        trunk (slot 0's sequence).  Full-prefix fallback: one batched
        next-token call over all paths plus one batched score call over
        (path x candidate x agent)."""
        spec = self.spec
        if spec.n_slots != 1:
            raise ValueError("propose_suffixes requires an n_slots=1 session")
        if not suffixes:
            return []
        trunk = self._sequences[0]
        prefixes = [
            trunk + "".join(c.token for c in suffix) for suffix in suffixes
        ]
        return self._proposals_for(prefixes, family=1, index=salt)

    def rollout_from(
        self, suffix: Sequence[ScoredCandidate], depth: int, salt: int
    ) -> Tuple[List[int], str, List[float], bool]:
        """Continue ``depth`` reference-policy tokens past trunk+suffix and
        return (rollout token ids, rollout text, per-agent total logprob of
        the rollout tokens, ok).  Delegates to :meth:`rollout_many` — one
        generate call + one batched score call either way, so results are
        bit-identical to the historical single-path implementation."""
        return self.rollout_many([suffix], depth, [salt])[0]

    def rollout_many(
        self,
        suffixes: Sequence[Sequence[ScoredCandidate]],
        depth: int,
        salts: Sequence[int],
    ) -> List[Tuple[List[int], str, List[float], bool]]:
        """Batched :meth:`rollout_from`: ONE generate call over all paths and
        ONE score call over (path x agent).  Row i uses ``salts[i]`` in the
        family-2 seed map, so each row's result is bit-identical to a
        sequential ``rollout_from(suffixes[i], depth, salts[i])`` call."""
        from consensus_tpu.backends.base import GenerationRequest

        spec = self.spec
        if spec.n_slots != 1:
            raise ValueError("rollout_many requires an n_slots=1 session")
        if len(salts) != len(suffixes):
            raise ValueError(
                f"expected {len(suffixes)} salts, got {len(salts)}"
            )
        if not suffixes:
            return []
        trunk = self._sequences[0]
        prefixes = [
            trunk + "".join(c.token for c in suffix) for suffix in suffixes
        ]
        seed = spec.seed
        results = self.backend.generate(
            [
                GenerationRequest(
                    user_prompt=spec.ref_user + prefix,
                    system_prompt=spec.ref_system,
                    max_tokens=depth,
                    temperature=spec.temperature,
                    # Family 2 = rollouts (0 = trunk steps, 1 = suffix
                    # proposals) in the injective (seed, family, index, row)
                    # seed map of _proposals_for.  The salt is the row-unique
                    # coordinate here, so batching preserves per-path streams.
                    seed=((seed * 3 + 2) * 1_000_000_000 + salt * 1000)
                    if seed is not None
                    else None,
                    chat=False,
                )
                for prefix, salt in zip(prefixes, salts)
            ]
        )
        self.dispatch_count += 1
        n_agents = len(spec.agent_prompts)
        if getattr(spec, "matrix_scoring", True):
            return self._rollout_totals_matrix(prefixes, results, n_agents)
        score_requests: List[ScoreRequest] = []
        starts: List[Optional[int]] = []
        for prefix, result in zip(prefixes, results):
            if result.ok and result.text:
                starts.append(len(score_requests))
                for a_system, a_user in spec.agent_prompts:
                    score_requests.append(
                        ScoreRequest(
                            context=a_user + prefix,
                            continuation=result.text,
                            system_prompt=a_system,
                            chat=False,
                        )
                    )
            else:
                starts.append(None)
        scores = self.backend.score(score_requests) if score_requests else []
        if score_requests:
            self.dispatch_count += 1
        out: List[Tuple[List[int], str, List[float], bool]] = []
        for result, start in zip(results, starts):
            if not result.ok:
                out.append(([], "", [], False))
            elif not result.text:
                out.append(([], "", [0.0] * n_agents, True))
            else:
                row = scores[start : start + n_agents]
                totals = [
                    (sum(s.logprobs) if s.ok else spec.failure_logprob)
                    for s in row
                ]
                out.append((list(result.token_ids), result.text, totals, True))
        return out

    def _rollout_totals_matrix(
        self, prefixes, results, n_agents: int
    ) -> List[Tuple[List[int], str, List[float], bool]]:
        """Rollout returns via the utility-matrix seam: one (1 x agents)
        matrix per successful rollout, all submitted in ONE backend call —
        the same dispatch count as the flat score batch it replaces, and
        byte-identical values over the per-call fallback (stat "sum" is
        the sequential Python sum the flat path used)."""
        from consensus_tpu.backends.score_matrix import (
            AgentContext,
            ScoreMatrixRequest,
            score_matrix_many,
        )

        spec = self.spec
        matrix_requests: List[ScoreMatrixRequest] = []
        rows: List[Optional[int]] = []
        for prefix, result in zip(prefixes, results):
            if result.ok and result.text:
                rows.append(len(matrix_requests))
                matrix_requests.append(
                    ScoreMatrixRequest(
                        agents=tuple(
                            AgentContext(
                                context=a_user + prefix,
                                system_prompt=a_system,
                                chat=False,
                            )
                            for a_system, a_user in spec.agent_prompts
                        ),
                        candidates=(result.text,),
                        stat="sum",
                        default=spec.failure_logprob,
                    )
                )
            else:
                rows.append(None)
        matrices = None
        if matrix_requests and n_agents:
            matrices = score_matrix_many(self.backend, matrix_requests)
            self.dispatch_count += 1
        out: List[Tuple[List[int], str, List[float], bool]] = []
        for result, row in zip(results, rows):
            if not result.ok:
                out.append(([], "", [], False))
            elif not result.text:
                out.append(([], "", [0.0] * n_agents, True))
            else:
                totals = (
                    [float(v) for v in matrices[row].utilities[0]]
                    if matrices is not None
                    else []
                )
                out.append((list(result.token_ids), result.text, totals, True))
        return out

    # -- internals -----------------------------------------------------------

    def _proposals_for(
        self, prefixes: Sequence[str], family: int, index: int
    ) -> List[List[ScoredCandidate]]:
        """One batched next-token call over ``prefixes`` + one batched score
        call over (prefix x candidate x agent).  ``(seed, family, index,
        row)`` tuples map injectively onto request seeds (index < 1e6 —
        generous for salts/steps; row < 1000 — far above any path fan-out),
        so no two seeded requests across a seed sweep ever collide."""
        spec = self.spec
        seed = spec.seed
        if not (0 <= index < 1_000_000 and len(prefixes) <= 1000):
            raise ValueError(
                f"seed-map bounds exceeded: index={index}, rows={len(prefixes)}"
            )
        requests = [
            NextTokenRequest(
                user_prompt=spec.ref_user + prefix,
                system_prompt=spec.ref_system,
                k=spec.k,
                temperature=spec.temperature,
                seed=(
                    (seed * 3 + family) * 1_000_000_000 + index * 1000 + row
                )
                if seed is not None
                else None,
                mode="sample" if spec.sample else "topk",
                bias_against_tokens=spec.bias_against_tokens,
                bias_value=spec.bias_value,
                chat=False,
            )
            for row, prefix in enumerate(prefixes)
        ]
        proposals = self.backend.next_token_logprobs(requests)
        self.dispatch_count += 1
        if getattr(spec, "matrix_scoring", True):
            return self._score_proposals_matrix(prefixes, proposals)

        score_requests = []
        for prefix, candidates in zip(prefixes, proposals):
            for candidate in candidates:
                for a_system, a_user in spec.agent_prompts:
                    score_requests.append(
                        ScoreRequest(
                            context=a_user + prefix,
                            continuation=candidate.token,
                            system_prompt=a_system,
                            chat=False,
                        )
                    )
        scores = self.backend.score(score_requests)
        if score_requests:
            self.dispatch_count += 1
        return self._zip_scores(proposals, scores)

    def _score_proposals_matrix(
        self, prefixes: Sequence[str], proposals
    ) -> List[List[ScoredCandidate]]:
        """Proposal scoring via the utility-matrix seam: one
        (candidates x agents) matrix per prefix — same cells, same order,
        ONE backend call for all prefixes (matching the flat batch's
        dispatch count).  Stat "last" is the per-call path's
        ``logprobs[-1]`` exactly, so fallback values are byte-identical."""
        from consensus_tpu.backends.score_matrix import (
            AgentContext,
            ScoreMatrixRequest,
            score_matrix_many,
        )

        spec = self.spec
        n_agents = len(spec.agent_prompts)
        matrix_requests = [
            ScoreMatrixRequest(
                agents=tuple(
                    AgentContext(
                        context=a_user + prefix,
                        system_prompt=a_system,
                        chat=False,
                    )
                    for a_system, a_user in spec.agent_prompts
                ),
                candidates=tuple(c.token for c in candidates),
                stat="last",
                default=spec.failure_logprob,
            )
            for prefix, candidates in zip(prefixes, proposals)
        ]
        total_cells = sum(len(c) for c in proposals) * n_agents
        matrices = None
        if total_cells:
            matrices = score_matrix_many(self.backend, matrix_requests)
            self.dispatch_count += 1
        out: List[List[ScoredCandidate]] = []
        for i, candidates in enumerate(proposals):
            slot_out = []
            for ci, candidate in enumerate(candidates):
                agent_lps = (
                    tuple(float(v) for v in matrices[i].utilities[ci])
                    if matrices is not None
                    else ()
                )
                slot_out.append(
                    ScoredCandidate(
                        token=candidate.token,
                        token_id=candidate.token_id,
                        ref_logprob=candidate.logprob,
                        agent_logprobs=agent_lps,
                    )
                )
            out.append(slot_out)
        return out

    def _propose_and_score(self) -> List[List[ScoredCandidate]]:
        # Seed family 0: trunk/beam steps (family 1 = suffix trees) — the
        # families must stay disjoint or a suffix level whose salt equals a
        # later trunk step would replay its exact proposal requests.
        return self._proposals_for(
            self._sequences, family=0, index=self._step
        )

    def _zip_scores(self, proposals, scores) -> List[List[ScoredCandidate]]:
        spec = self.spec
        n_agents = len(spec.agent_prompts)
        out: List[List[ScoredCandidate]] = []
        flat = 0
        for candidates in proposals:
            slot_out = []
            for candidate in candidates:
                agent_lps = tuple(
                    (s.logprobs[-1] if s.ok else spec.failure_logprob)
                    for s in scores[flat : flat + n_agents]
                )
                flat += n_agents
                slot_out.append(
                    ScoredCandidate(
                        token=candidate.token,
                        token_id=candidate.token_id,
                        ref_logprob=candidate.logprob,
                        agent_logprobs=agent_lps,
                    )
                )
            out.append(slot_out)
        return out


def open_token_search(backend, spec: SearchSpec):
    """Session factory: a backend offering ``open_fused_token_search`` (TPU,
    or the batching wrapper delegating to its inner TPU backend) gets first
    refusal; on :class:`FusedSessionUnavailable` — or with no fused
    implementation at all — the full-prefix fallback runs over ``backend``
    ITSELF, so e.g. a batching wrapper keeps merging the fallback's calls
    through its queue."""
    maker = getattr(backend, "open_fused_token_search", None)
    if maker is not None:
        try:
            return maker(spec)
        except FusedSessionUnavailable:
            pass
    session = PrefixTokenSearchSession(backend, spec)
    # Continuous-batching seam: over an engine-mode batching adapter the
    # fallback's per-step calls already land in the engine's iteration loop
    # as (prefill, decode-step, score) slot operations; registering the
    # session here additionally surfaces its slot footprint in the engine's
    # pressure stats (/healthz), same as fused sessions.
    engine = getattr(backend, "engine", None)
    if engine is not None and hasattr(engine, "track_session"):
        session = engine.track_session(session, spec)
    return session
