"""Backend supervision: retry, integrity guards, poison-row isolation,
and a circuit breaker — at the one seam every model call crosses.

:class:`SupervisedBackend` wraps any :class:`~consensus_tpu.backends.base.
Backend` and turns raw transport failures into the typed taxonomy of
``backends/base.py``:

* **Bounded retry with backoff.**  Raw transient exceptions
  (``RuntimeError``/``TimeoutError``/``ConnectionError``/``OSError``, and
  ``TransientBackendError`` from a nested supervisor) are retried up to
  ``max_retries`` times with exponential backoff; exhaustion raises
  :class:`TransientBackendError`.  Because backends are batch-composition
  invariant (per-request PRNG keys), a successful retry returns results
  bit-identical to a never-faulted call — chaos tests pin this.
* **Integrity guards.**  ``score`` / ``next_token_logprobs`` / ``embed``
  outputs are scanned for NaN/Inf.  A poisoned row is deterministic, so it
  is NEVER retried: with siblings present the call raises
  :class:`PartialBatchError` (valid rows ride along), alone it raises
  :class:`BackendIntegrityError`.  ``BatchingBackend`` unpacks the partial
  error so one bad row fails one waiter, not the whole device batch.
* **Batch bisection.**  When the inner call itself raises a DETERMINISTIC
  error on a multi-row batch, the supervisor bisects: halves re-execute
  until the failing row(s) are isolated, surviving rows return normally.
  (Safe because results are batch-composition invariant.)
* **Circuit breaker.**  ``failure_threshold`` consecutive transient/lost
  failures open the breaker; while open, calls fail fast with
  :class:`BackendLostError` instead of burning the retry budget.  After
  ``cooldown_s`` one probe call is let through (half-open): success closes
  the breaker, failure re-opens it.  State is exported as the
  ``supervisor_breaker_state`` gauge (0 closed / 1 half-open / 2 open) and
  surfaced by serve's ``/healthz``; the scheduler checks
  :meth:`CircuitBreaker.admission_allowed` to reject with
  ``SchedulerRejected(reason="breaker_open")`` → HTTP 503.

Obs families: ``supervisor_retries_total{op}``,
``supervisor_integrity_failures_total{op}``,
``supervisor_bisections_total{op}``, ``supervisor_breaker_state``,
``supervisor_breaker_opens_total``.
"""

from __future__ import annotations

import logging
import math
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from consensus_tpu.backends.base import (
    Backend,
    BackendError,
    BackendIntegrityError,
    BackendLostError,
    GenerationRequest,
    GenerationResult,
    NextTokenRequest,
    PartialBatchError,
    ScoreRequest,
    ScoreResult,
    TokenCandidate,
    TransientBackendError,
)
from consensus_tpu.obs.metrics import Registry, get_registry

logger = logging.getLogger(__name__)

#: Raw exception types the supervisor treats as transient.  Typed
#: BackendError subclasses other than TransientBackendError are excluded
#: even though device runtimes raise RuntimeError: integrity/lost failures
#: are deterministic by definition.
_RAW_TRANSIENT = (RuntimeError, TimeoutError, ConnectionError, OSError)

_STATE_VALUES = {"closed": 0.0, "half_open": 1.0, "open": 2.0}


def _is_transient(exc: BaseException) -> bool:
    if isinstance(exc, TransientBackendError):
        return True
    if isinstance(exc, BackendError):
        return False
    return isinstance(exc, _RAW_TRANSIENT)


class CircuitBreaker:
    """closed → open after N consecutive failures → half-open probe.

    Thread-safe; ``clock`` is injectable so tests drive the cooldown
    without sleeping.  Two consumer surfaces:

    * :meth:`allow_call` — the supervisor asks before every backend call.
      Open + cooldown elapsed transitions to half-open and admits the call
      as the probe; open otherwise refuses (fail fast).  Half-open admits
      (the probe request may issue several backend calls).
    * :meth:`admission_allowed` — the serving scheduler asks at admission.
      Half-open admits exactly ONE request per cooldown window so a wave
      of retries cannot stampede a recovering device.
    """

    def __init__(
        self,
        failure_threshold: int = 5,
        cooldown_s: float = 5.0,
        clock: Callable[[], float] = time.monotonic,
        registry: Optional[Registry] = None,
        name: str = "backend",
    ):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.failure_threshold = int(failure_threshold)
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._name = name
        self._lock = threading.Lock()
        self._state = "closed"
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probe_admitted_at: Optional[float] = None
        reg = registry if registry is not None else get_registry()
        self._m_state = reg.gauge(
            "supervisor_breaker_state",
            "Circuit breaker state: 0 closed, 1 half-open, 2 open.",
            labels=("name",),
        ).labels(name)
        self._m_opens = reg.counter(
            "supervisor_breaker_opens_total",
            "Transitions into the open state.",
            labels=("name",),
        ).labels(name)
        self._m_state.set(0.0)

    # -- state ---------------------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            return self._effective_state()

    def _effective_state(self) -> str:
        """Caller holds the lock.  ``open`` lazily decays to ``half_open``
        once the cooldown elapses (no background timer thread)."""
        if self._state == "open" and (
            self._clock() - self._opened_at >= self.cooldown_s
        ):
            self._to("half_open")
            self._probe_admitted_at = None
        return self._state

    def _to(self, state: str) -> None:
        if state == "open" and self._state != "open":
            # Flight-recorder breadcrumb: breaker opens are exactly the
            # fleet events a postmortem wants next to the iteration rows.
            from consensus_tpu.obs.trace import get_flight_recorder

            get_flight_recorder().record_event(
                "breaker_open", breaker=self._name,
                consecutive_failures=self._consecutive_failures)
        self._state = state
        self._m_state.set(_STATE_VALUES[state])

    def snapshot(self) -> Dict[str, Any]:
        """Live breaker facts for /healthz."""
        with self._lock:
            state = self._effective_state()
            remaining = 0.0
            if state == "open":
                remaining = max(
                    0.0, self._opened_at + self.cooldown_s - self._clock()
                )
            return {
                "state": state,
                "consecutive_failures": self._consecutive_failures,
                "failure_threshold": self.failure_threshold,
                "cooldown_remaining_s": round(remaining, 3),
            }

    def retry_after_s(self) -> float:
        """Suggested client backoff (the Retry-After header on 503s)."""
        return max(1.0, math.ceil(self.snapshot()["cooldown_remaining_s"]))

    # -- consumer surfaces ---------------------------------------------------

    def allow_call(self) -> bool:
        with self._lock:
            return self._effective_state() != "open"

    def admission_allowed(self) -> bool:
        with self._lock:
            state = self._effective_state()
            if state == "closed":
                return True
            if state == "open":
                return False
            # half-open: one probe per cooldown window.  A probe whose
            # request died before reporting back must not wedge the
            # breaker, so a stale probe slot reopens after cooldown_s.
            now = self._clock()
            if (
                self._probe_admitted_at is None
                or now - self._probe_admitted_at >= self.cooldown_s
            ):
                self._probe_admitted_at = now
                return True
            return False

    # -- outcome reporting ---------------------------------------------------

    def record_success(self) -> None:
        with self._lock:
            self._consecutive_failures = 0
            if self._state != "closed":
                self._to("closed")
            self._probe_admitted_at = None

    def record_failure(self) -> None:
        with self._lock:
            state = self._effective_state()
            self._consecutive_failures += 1
            if state == "half_open":
                # The probe failed: straight back to open, fresh cooldown.
                self._opened_at = self._clock()
                self._to("open")
                self._m_opens.inc()
                self._probe_admitted_at = None
            elif (
                state == "closed"
                and self._consecutive_failures >= self.failure_threshold
            ):
                self._opened_at = self._clock()
                self._to("open")
                self._m_opens.inc()


def _finite(value: float) -> bool:
    return math.isfinite(value)


def _check_score(result: ScoreResult) -> bool:
    return all(_finite(lp) for lp in result.logprobs)


def _check_next_token(candidates: List[TokenCandidate]) -> bool:
    return all(_finite(c.logprob) for c in candidates)


def _check_embed_row(row: np.ndarray) -> bool:
    return bool(np.isfinite(row).all())


class SupervisedBackend:
    """Wrap ``inner`` with retry, integrity guards, bisection, and the
    circuit breaker (module docstring for the full contract)."""

    name = "supervised"

    def __init__(
        self,
        inner: Backend,
        max_retries: int = 2,
        backoff_s: float = 0.05,
        failure_threshold: int = 5,
        cooldown_s: float = 5.0,
        guard_nonfinite: bool = True,
        breaker: Optional[CircuitBreaker] = None,
        registry: Optional[Registry] = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
    ):
        self.inner = inner
        self.max_retries = int(max_retries)
        self.backoff_s = float(backoff_s)
        self.guard_nonfinite = bool(guard_nonfinite)
        #: Latched when the INNER backend raises BackendLostError (not when
        #: the breaker refuses a call): the device under this supervisor is
        #: gone for good.  Fleet health checks read this as the passive
        #: "replica lost" signal without waiting for the breaker to trip.
        self.backend_lost = False
        self._sleep = sleep
        reg = registry if registry is not None else get_registry()
        self.circuit_breaker = breaker if breaker is not None else CircuitBreaker(
            failure_threshold=failure_threshold,
            cooldown_s=cooldown_s,
            clock=clock,
            registry=reg,
            name=getattr(inner, "name", "backend"),
        )
        self._m_retries = reg.counter(
            "supervisor_retries_total",
            "Transient backend failures retried at the supervision seam.",
            labels=("op",),
        )
        self._m_integrity = reg.counter(
            "supervisor_integrity_failures_total",
            "Rows failed by the NaN/Inf integrity guard or isolated by "
            "bisection.",
            labels=("op",),
        )
        self._m_bisections = reg.counter(
            "supervisor_bisections_total",
            "Batch bisection passes run to isolate deterministic poison rows.",
            labels=("op",),
        )

    # -- passthrough surface -------------------------------------------------

    @property
    def deterministic_greedy(self) -> bool:
        return bool(getattr(self.inner, "deterministic_greedy", False))

    @property
    def token_counts(self):
        return getattr(self.inner, "token_counts", {})

    # -- protocol ------------------------------------------------------------

    def generate(self, requests: Sequence[GenerationRequest]) -> List[GenerationResult]:
        return self._supervised("generate", list(requests), self.inner.generate)

    def score(self, requests: Sequence[ScoreRequest]) -> List[ScoreResult]:
        return self._supervised(
            "score", list(requests), self.inner.score, check=_check_score
        )

    def next_token_logprobs(
        self, requests: Sequence[NextTokenRequest]
    ) -> List[List[TokenCandidate]]:
        return self._supervised(
            "next_token", list(requests), self.inner.next_token_logprobs,
            check=_check_next_token,
        )

    def embed(self, texts: Sequence[str]) -> np.ndarray:
        out = self._supervised(
            "embed", list(texts), self.inner.embed, check=_check_embed_row
        )
        return np.asarray(out)

    # -- core ----------------------------------------------------------------

    def _supervised(
        self,
        op: str,
        requests: List[Any],
        fn: Callable,
        check: Optional[Callable[[Any], bool]] = None,
    ) -> Any:
        if not requests:
            return fn(requests)
        if not self.circuit_breaker.allow_call():
            raise BackendLostError(
                f"circuit breaker open: refusing {op} call "
                f"({self.circuit_breaker.snapshot()})"
            )
        attempt = 0
        while True:
            try:
                results = fn(requests)
            except (BackendLostError, BackendIntegrityError,
                    PartialBatchError) as exc:
                if isinstance(exc, BackendLostError):
                    self.backend_lost = True
                self.circuit_breaker.record_failure()
                raise
            except Exception as exc:
                if _is_transient(exc):
                    self.circuit_breaker.record_failure()
                    attempt += 1
                    if attempt > self.max_retries:
                        raise TransientBackendError(
                            f"{op} failed after {attempt} attempt(s): "
                            f"{type(exc).__name__}: {exc}"
                        ) from exc
                    if not self.circuit_breaker.allow_call():
                        raise BackendLostError(
                            f"circuit breaker opened while retrying {op}"
                        ) from exc
                    self._m_retries.labels(op).inc()
                    self._sleep(self.backoff_s * (2 ** (attempt - 1)))
                    continue
                # Deterministic failure: retrying reproduces it, but with
                # siblings in the batch we can still isolate the poison.
                if len(requests) > 1:
                    results, row_errors = self._bisect(op, fn, requests)
                    return self._resolve(op, requests, results, row_errors,
                                         check)
                raise BackendIntegrityError(
                    f"{op} row failed deterministically: "
                    f"{type(exc).__name__}: {exc}"
                ) from exc
            break
        self.circuit_breaker.record_success()
        return self._resolve(op, requests, results, {}, check)

    def _resolve(
        self,
        op: str,
        requests: List[Any],
        results: Any,
        row_errors: Dict[int, BaseException],
        check: Optional[Callable[[Any], bool]],
    ) -> Any:
        if check is not None and self.guard_nonfinite:
            for i in range(len(requests)):
                if i in row_errors:
                    continue
                row = results[i]
                if row is not None and not check(row):
                    row_errors[i] = BackendIntegrityError(
                        f"{op} row {i} returned non-finite values "
                        "(NaN/Inf); deterministic, not retried"
                    )
        if not row_errors:
            return results
        self._m_integrity.labels(op).inc(len(row_errors))
        if len(row_errors) == len(requests):
            raise BackendIntegrityError(
                f"every row of a {len(requests)}-row {op} batch failed: "
                f"{next(iter(row_errors.values()))}"
            )
        raise PartialBatchError(
            f"{len(row_errors)}/{len(requests)} rows of a {op} batch "
            f"failed; surviving rows ride along",
            results=results,
            row_errors=row_errors,
        )

    def _bisect(
        self, op: str, fn: Callable, requests: List[Any]
    ) -> tuple:
        """Isolate deterministically-failing rows by halving.  Safe because
        results are batch-composition invariant (per-request PRNG keys);
        costs O(bad_rows * log n) extra dispatches only on the failure
        path."""
        self._m_bisections.labels(op).inc()
        results: List[Any] = [None] * len(requests)
        row_errors: Dict[int, BaseException] = {}

        def solve(lo: int, hi: int) -> None:
            try:
                sub = fn(requests[lo:hi])
            except Exception as exc:
                if hi - lo == 1:
                    row_errors[lo] = (
                        exc if isinstance(exc, BackendError)
                        else BackendIntegrityError(
                            f"{op} row {lo} failed deterministically: "
                            f"{type(exc).__name__}: {exc}"
                        )
                    )
                    return
                mid = (lo + hi) // 2
                solve(lo, mid)
                solve(mid, hi)
                return
            for offset, row in enumerate(sub):
                results[lo + offset] = row
        solve(0, len(requests))
        return results, row_errors
