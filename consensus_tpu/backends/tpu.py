"""On-device model backend: the TPU replacement for the reference's HTTP client.

Where the reference sends every generate/score/sample/embed call to the
Together API one request at a time (src/utils.py:70-525), this backend owns
a resident JAX transformer (Gemma-2 / Llama-3 family or tiny test configs)
and executes each protocol call as ONE padded, jitted device batch:

* ``generate``  — left-padded batch prefill + ``lax.scan`` decode with
  temperature/top-k, per-request logit bias sets, EOS ids, host-side stop-
  string truncation (the ``generate_text`` surface, src/utils.py:77-198);
* ``score``     — right-padded teacher-forced forward with the streaming
  logsumexp scorer; returns continuation-token logprobs directly, replacing
  the echo'd-prompt span extraction (src/utils.py:201-373, SURVEY §7.3);
* ``next_token_logprobs`` — one forward for the exact next-token
  distribution; top-k or seeded Gumbel-top-k gives k DISTINCT candidates,
  replacing rejection-sampling-via-repeated-1-token-calls
  (beam_search.py:199-333, mcts.py:165-247);
* ``embed``     — masked mean-pooled final hidden states, L2-normalized
  (the reference calls a separate embeddings API, src/utils.py:376-407).

Shape discipline: prompts pad into power-of-two length buckets so XLA
compiles a small, reused set of programs.  Multi-device: params are placed
with the tensor-parallel layout and batches shard over ``data`` when a mesh
is configured (consensus_tpu.parallel).

Seed semantics (SURVEY §7.4): each request's seed folds into its OWN row
PRNG key, so a request's output is independent of which other requests
share its device batch (matching the reference's per-request determinism,
habermas_machine.py:91-95) — though not bitwise-comparable to the
reference's server-side seeds.
"""

from __future__ import annotations

import functools
import logging
import pathlib
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from consensus_tpu.backends.base import (
    GenerationRequest,
    GenerationResult,
    NextTokenRequest,
    ScoreRequest,
    ScoreResult,
    TokenCandidate,
)
from consensus_tpu.models.config import ModelConfig, get_model_config
from consensus_tpu.obs.backends import BackendInstruments
from consensus_tpu.models.generate import generate_tokens, next_token_topk
from consensus_tpu.models.tokenizer import get_tokenizer
from consensus_tpu.models.transformer import (
    forward,
    init_params,
    token_logprobs,
    token_logprobs_streamed,
)

logger = logging.getLogger(__name__)

#: Above this vocab size the streaming scorer replaces full-logit scoring.
_STREAMED_VOCAB_THRESHOLD = 32_768
#: Cap on the shared-scoring suffix attention's per-layer fp32 logits
#: transient (rows x heads x span x (ctx+span) x 4B) — it has no flash
#: kernel, so oversized groups fall back to the classic (flash) path.
_SHARED_SCORE_ATTN_BYTES_CAP = 1 << 31  # 2 GB

#: Below this many identical-prompt rows the shared-trunk generate path
#: isn't worth its own (1-row prefill + B-tail decode) program variant.
_SHARED_TRUNK_MIN_ROWS = 4

#: A small identical-prompt group inside a LARGER batch routes classic
#: instead: combined classic chunks amortize the per-step weight read over
#: every row in the chunk, which beats the shared path's 1-row prefill
#: once the group is this small (see _generate_impl docstring).
_SHARED_TRUNK_SOLO_ROWS = 16

#: Search-session KV caches above this (plus resident weights) risk HBM
#: exhaustion — fall back to the cacheless full-prefix session instead.
_SESSION_CACHE_BYTES_CAP = 8 * 1024**3

#: v5e HBM (15.75 GB usable) and the live-budget floor/reserve used to size
#: the concurrent-session budget against the resident weights.
_HBM_BYTES = 15 * 1024**3
_ACTIVATION_RESERVE_BYTES = 3 * 1024**3
_SESSION_MIN_BUDGET_BYTES = 1 * 1024**3


class _SessionBudget:
    """HBM budget for LIVE session caches.  Concurrent sweep cells each hold
    a session for a whole statement; unbounded, four wide-beam sessions plus
    resident weights exceed a v5e chip's 16 GB.  Opening a session blocks
    until its cache fits; closing releases the reservation."""

    def __init__(self, cap_bytes: int):
        self.cap = cap_bytes
        self.used = 0
        self._cond = threading.Condition()

    def acquire(self, nbytes: int) -> None:
        with self._cond:
            while self.used + nbytes > self.cap:
                self._cond.wait()
            self.used += nbytes

    def release(self, nbytes: int) -> None:
        with self._cond:
            self.used -= nbytes
            self._cond.notify_all()


def _bucket(n: int, minimum: int = 32) -> int:
    size = minimum
    while size < n:
        size *= 2
    return size


def _width_bucket(n: int, minimum: int = 128) -> int:
    """Sequence-length bucket on a {1, 1.5} x power-of-two ladder
    (128, 192, 256, 384, 512, ...).  Rows bucket to powers of two, but
    widths deserve the finer ladder: a 350-token scoring prompt padded to
    512 wastes 32% of a compute-bound forward, padded to 384 only 9%.
    Ladder steps stay multiples of the 128-lane TPU tile."""
    size = minimum
    while size < n:
        if size + size // 2 >= n:
            return size + size // 2
        size *= 2
    return size


class TPUBackend:
    name = "tpu"
    #: Temperature-0 generation is argmax (models/sampling.py): the request
    #: seed never enters the program, so re-issuing an identical greedy
    #: request is bitwise-identical.  Callers with seed-incrementing retry
    #: loops (habermas rankings) use this to elide provably-identical
    #: retries.  API backends stay False (server-side nondeterminism).
    deterministic_greedy = True

    def __init__(
        self,
        model: str = "tiny-gemma2",
        checkpoint: Optional[str] = None,
        tokenizer: Optional[str] = None,
        dtype: str = "bfloat16",
        max_context: int = 1024,
        base_seed: int = 0,
        tp: int = 1,
        dp: Optional[int] = None,
        params: Optional[Dict[str, Any]] = None,
        config: Optional[ModelConfig] = None,
        use_flash_attention: bool = False,
        use_decode_attention: bool = False,
        max_batch_rows: int = 64,
        quantization: Optional[str] = None,
        shared_context_scoring: bool = False,
        shared_trunk_generation: bool = True,
        pin_generation_budget: bool = False,
        segmented_decode: bool = True,
        decode_segment_len: int = 128,
        kv_quant: bool = True,
        quantize_frozen_kv: Optional[bool] = None,
        mesh: Optional[Any] = None,
    ):
        # ``mesh={'dp': N, 'tp': M}`` (or the "dp=4,tp=2" CLI string) is the
        # serving-path spelling of the tp/dp pair — create_server and the
        # sweep configs pass one opaque value straight through.  Explicit
        # tp=/dp= args win when both are given.
        if mesh is not None:
            from consensus_tpu.parallel import parse_mesh_spec

            parsed = parse_mesh_spec(mesh)
            if tp == 1:
                tp = parsed["tp"]
            if dp is None:
                dp = parsed["dp"]
        self.config = config if config is not None else get_model_config(model)
        if use_flash_attention and not self.config.use_flash_attention:
            import dataclasses

            self.config = dataclasses.replace(self.config, use_flash_attention=True)
        if use_decode_attention and not self.config.use_decode_attention:
            import dataclasses

            self.config = dataclasses.replace(self.config, use_decode_attention=True)
        self.model_name = model
        family = "llama" if "llama" in self.config.name else "gemma"
        self.tokenizer = get_tokenizer(tokenizer, family=family)
        # A tokenizer-sized vocab keeps random-weight runs self-consistent.
        if self.tokenizer.vocab_size != self.config.vocab_size and checkpoint is None:
            import dataclasses

            self.config = dataclasses.replace(
                self.config, vocab_size=self.tokenizer.vocab_size
            )
        self.max_context = max_context
        self.base_seed = base_seed
        # Device-batch cap: callers may hand over an arbitrarily large
        # request list (a whole sweep cell); slices bound peak activation
        # memory — a (B, H, S, S) einsum-path batch or (B, S, V) logit batch
        # must not scale with the sweep size.  Each public call processes
        # ceil(B / max_batch_rows) jitted slices and concatenates.
        self.max_batch_rows = max(1, max_batch_rows)
        self.shared_context_scoring = bool(shared_context_scoring)
        self.shared_trunk_generation = bool(shared_trunk_generation)
        # Segmented decode (models/generate.py): long-budget shared-trunk
        # generations carry only a decode_segment_len-column live KV tail
        # through the while_loop (the remote AOT compiler double-buffers the
        # carry every step); completed segments become read-only operands.
        # Kicks in at max_new >= 2*seg_len — short budgets keep the
        # monolithic single-dispatch program.
        self.segmented_decode = bool(segmented_decode)
        self.decode_segment_len = max(16, int(decode_segment_len))
        self._seg_len_fallbacks: set = set()  # budgets already logged
        # int8 generated-token KV for segmented decodes: the live tail is
        # WRITTEN int8+scale (halving the while_loop carry the remote AOT
        # compiler copies every step) and frozen segment blocks stay int8
        # (halving their read bytes and roughly doubling the segmented row
        # allowance).  ON by default — generation numerics are no longer
        # bit-identical to the bf16 KV path (teacher-forced scoring never
        # touches generated KV, so scores are unaffected); measured logit/
        # token deltas: reports/kv_quant_delta.md.  ``quantize_frozen_kv``
        # is the round-3 name for the frozen-only variant, kept as an
        # alias so older configs keep working.
        if quantize_frozen_kv is not None:
            kv_quant = bool(quantize_frozen_kv)
        self.kv_quant = bool(kv_quant)
        # Timing mode (VERDICT r2 #4): pin every generation to its full
        # max_tokens budget (no EOS early-exit, no stop-string truncation)
        # so random-weight timing runs can't flatter themselves with 1-token
        # degenerate statements.  Never use for quality runs.
        self.pin_generation_budget = bool(pin_generation_budget)

        if quantization not in (None, "none", "int8"):
            raise ValueError(f"unknown quantization mode: {quantization!r}")
        want_int8 = quantization == "int8" and params is None

        jax_dtype = {"bfloat16": jnp.bfloat16, "float32": jnp.float32}[dtype]
        # Weight-only int8 (models/quant.py) halves the HBM bytes every
        # decode step re-reads — and for gemma2-9b/llama3-8b it is the only
        # way onto one 16 GB v5e at all (their bf16 trees alone exceed HBM).
        # So the full-precision tree must NEVER land on the accelerator:
        # init/load on the host CPU backend, quantize there (threefry is
        # platform-deterministic, so host init == device init), and ship
        # only the int8+scale leaves across.
        import contextlib

        def host():
            # Fresh context per use: jax.default_device returns a
            # single-entry context manager, and the int8 path enters once
            # for init/load and again for the quantize pass.
            return (
                jax.default_device(jax.local_devices(backend="cpu")[0])
                if want_int8
                else contextlib.nullcontext()
            )
        if params is not None:
            self.params = params
        elif checkpoint and (pathlib.Path(checkpoint) / "ingest.json").exists():
            # Pre-converted orbax checkpoint (cli/ingest_checkpoint.py):
            # leaves restore straight to the default device in their stored
            # (possibly already-int8) form — no host conversion pass, no
            # 5-10 min quantize on every process start.
            import json as _json

            from consensus_tpu.models.quant import quantize_params
            from consensus_tpu.utils.checkpoint import restore_params

            meta = _json.loads(
                (pathlib.Path(checkpoint) / "ingest.json").read_text()
            )
            # The manifest must agree with this backend's settings: a
            # silently-mismatched restore either builds a wrong eval_shape
            # template (cryptic orbax failure) or — worse — lands an
            # unquantized 8-9B bf16 tree straight on a 16 GB chip.
            mismatches = []
            if meta.get("model") and meta["model"] != self.config.name:
                mismatches.append(
                    f"model: ingested {meta['model']!r} vs backend "
                    f"{self.config.name!r}"
                )
            if meta.get("dtype") and meta["dtype"] != dtype:
                mismatches.append(
                    f"dtype: ingested {meta['dtype']!r} vs backend {dtype!r}"
                )
            ingested_quant = meta.get("quantization") or None
            wanted_quant = quantization if quantization != "none" else None
            if ingested_quant != wanted_quant:
                mismatches.append(
                    f"quantization: ingested {ingested_quant!r} vs backend "
                    f"{wanted_quant!r} — re-run cli/ingest_checkpoint with "
                    "the matching --quantization"
                )
            if mismatches:
                raise ValueError(
                    f"ingested checkpoint {checkpoint} does not match this "
                    "backend: " + "; ".join(mismatches)
                )
            template = jax.eval_shape(
                lambda: quantize_params(
                    init_params(self.config, jax.random.PRNGKey(0), jax_dtype)
                )
                if meta.get("quantization") == "int8"
                else init_params(self.config, jax.random.PRNGKey(0), jax_dtype)
            )
            self.params = restore_params(
                str(pathlib.Path(checkpoint) / "params"), template
            )
        elif checkpoint:
            from consensus_tpu.models.loader import load_params

            with host():
                self.params = load_params(checkpoint, self.config, jax_dtype)
        else:
            logger.warning(
                "TPUBackend: no checkpoint given — using RANDOM weights (%s). "
                "Statements will be noise; timings/shapes are real.",
                self.config.name,
            )
            with host():
                self.params = init_params(
                    self.config, jax.random.PRNGKey(base_seed), jax_dtype
                )

        if quantization == "int8":
            # Weight-only int8 (models/quant.py): halves decode HBM traffic;
            # composes with tensor parallelism (mesh.py shards q like the
            # weight and replicates squeezed scale axes).  The train step
            # keeps full-precision pytrees.
            from consensus_tpu.models.quant import is_quantized, quantize_params

            if not is_quantized(self.params):  # shared params may already be
                if want_int8:  # host tree: quantize on host, then transfer
                    with host():
                        # jit on the host device so XLA fuses the f32 casts
                        # instead of materializing eager 2x-size temporaries;
                        # donation frees each full-precision leaf as it is
                        # consumed (nothing else references the host tree).
                        quantized = jax.jit(quantize_params, donate_argnums=0)(
                            self.params
                        )
                    if tp > 1:  # shard_params below places the int8 tree
                        self.params = quantized
                    else:
                        self.params = jax.device_put(quantized, jax.devices()[0])
                else:
                    # Caller-supplied device tree (assumed to fit): the
                    # caller may still hold references, so do NOT donate.
                    self.params = jax.jit(quantize_params)(self.params)
        self.quantization = quantization if quantization != "none" else None

        if tp > 1 or (dp is not None and dp > 1):
            # Pure DP (tp=1, dp>1) is the production multi-chip serving mode
            # (SURVEY §2.16 table / §5.8): params replicate over ``data`` —
            # the TP PartitionSpecs never name the data axis, so shard_params
            # on a (dp, 1) mesh replicates every leaf — and the protocol
            # batch rows shard over ``data`` (see _left_pad_batch /
            # _score_impl).  A sweep's co-batched rows then run dp-wide with
            # XLA inserting no per-layer collectives at all.
            from consensus_tpu.parallel import make_mesh, shard_params

            self.mesh_plan = make_mesh(tp=tp, dp=dp)
            self.params = shard_params(self.params, self.mesh_plan.mesh)
        else:
            self.mesh_plan = None

        self._bias_id_cache: Dict[str, Tuple[int, ...]] = {}
        # obs: padding efficiency per (kind, rows, width) bucket, compile-
        # cache events per padded program shape, H2D/D2H transfer timings —
        # recorded into the process registry (metrics.json / bench extra).
        self.instruments = BackendInstruments("tpu")
        self.call_counts = {
            "generate": 0, "score": 0, "next_token": 0, "embed": 0,
            "score_matrix": 0,
        }
        # Token-honest accounting (VERDICT r2 #4): "generated" counts
        # statement tokens actually emitted (what the API baseline bills as
        # output); "scored" counts teacher-forced positions whose logprob a
        # caller consumed (continuation tokens, next-token proposals,
        # session candidate x agent evaluations).  Cell-level deltas land in
        # each run dir's token_counts.json (experiment.py).
        self.token_counts = {"generated": 0, "scored": 0}
        # Fused utility-matrix accounting (score_matrix): device chunk
        # launches and per-call fallbacks — the chunked-under-budget tests
        # and BENCH_SCORE read these.
        self.matrix_stats = {"calls": 0, "chunks": 0, "fallbacks": 0}
        self._unseeded_calls = 0
        # Guards the unseeded-call nonce: concurrent sweep cells opening
        # sessions/batches must never derive the same "fresh" stream.
        self._nonce_lock = threading.Lock()
        # Live-session HBM budget: what a v5e chip holds after the resident
        # weights and a reserve for per-call activation transients (merged
        # score/generate batches run concurrently with session steps).
        # PER-CHIP accounting: weights and KV caches shard over ``model``
        # only — over ``data`` the weights replicate (each chip holds the
        # full tree at tp=1), so the divisor is tp, not the device count.
        # DP's capacity win shows up in _generate_rows_allowed instead:
        # batch rows spread over the data axis.
        self._shard_count = self.mesh_plan.tp if self.mesh_plan else 1
        self._dp = self.mesh_plan.dp if self.mesh_plan else 1
        self._params_bytes = sum(
            x.size * jnp.dtype(x.dtype).itemsize
            for x in jax.tree_util.tree_leaves(self.params)
        ) // self._shard_count
        budget = min(
            _SESSION_CACHE_BYTES_CAP,
            max(
                _SESSION_MIN_BUDGET_BYTES,
                _HBM_BYTES - self._params_bytes - _ACTIVATION_RESERVE_BYTES,
            ),
        )
        self._session_budget = _SessionBudget(budget)

    # -- helpers -------------------------------------------------------------

    def kv_cache_identity(self) -> tuple:
        """Content-key identity for cross-request prefix KV reuse: two
        backends may share cached prefix pages only when the model tier, the
        KV quantization mode AND the tensor-parallel width all match — the
        engine's PrefixCache folds this into every blake2b content key
        (ops/kv_pages.py).  tp enters because a tp=2 backend's pages hold
        each chip's half of the kv heads: byte-compatible only with another
        tp=2 mesh, never with tp=1.  (dp does NOT enter — pages replicate
        over data, so any dp width reads tp-compatible pages.)"""
        return (
            self.model_name,
            "int8" if self.kv_quant else "dense",
            ("tp", self._shard_count),
        )

    def suggest_kv_page_pool(self, page_size: int = 16) -> int:
        """Size the decode engine's KV page pool from the session HBM
        budget (backends/engine.py asks at construction).  One page holds
        ``page_size`` tokens of per-layer K+V; ``kv_quant`` halves the
        bytes (int8 + per-token scale ≈ half of bf16).  Half the session
        budget goes to pages — the rest stays for fused search sessions,
        which reserve through ``_SessionBudget`` as before.  The pool's
        page count INCLUDES the prefix cache's share: the engine's LRU
        budget (a quarter of the pool by default) bounds how many of these
        pages cached prefixes may pin, so cache + resident slots can never
        outgrow the reservation made here."""
        c = self.config
        kv_itemsize = (
            1.25
            if self.kv_quant
            else jnp.dtype(self.params["embed"].dtype).itemsize
        )
        bytes_per_token = int(
            2 * c.n_layers * c.n_kv_heads * c.head_dim * kv_itemsize
        ) // self._shard_count or 1
        page_bytes = bytes_per_token * page_size
        return max(64, (self._session_budget.cap // 2) // page_bytes)

    def _sliced(self, requests, fn, limit: Optional[int] = None):
        """Run ``fn`` over ``limit``-sized slices (default max_batch_rows)
        and concatenate.  Safe because per-request PRNG keys make results
        independent of batch composition."""
        limit = limit or self.max_batch_rows
        if len(requests) <= limit:
            return fn(requests)
        out = []
        for i in range(0, len(requests), limit):
            out.extend(fn(requests[i : i + limit]))
        return out


    def _render_prompt(self, request) -> str:
        if getattr(request, "chat", True):
            return self.tokenizer.chat_prompt(
                request.user_prompt, request.system_prompt
            )
        return self.tokenizer.raw_prompt(request.user_prompt, request.system_prompt)

    def _batch_width(self, token_lists: List[List[int]]) -> int:
        """The bucketed width _left_pad_batch will allocate for this batch —
        shared so HBM allowances are computed from the allocated width."""
        longest = min(max(len(t) for t in token_lists), self.max_context)
        return min(_width_bucket(longest), self.max_context)

    def _shared_cont_width(self, max_cont: int) -> int:
        """Continuation-width bucket used by _score_shared_group — a coarse
        pow2 ladder from 64 (fresh remote-AOT compile per variant, so the
        variant space stays small), capped at the context window."""
        width = 64
        while width < max_cont:
            width *= 2
        return min(width, self.max_context)

    def _place_batch(self, *arrays):
        """Commit batch-leading arrays to the mesh, rows sharded over
        ``data``.  Rows that don't divide dp (sessions with odd role counts)
        stay uncommitted — jit replicates them, still correct.  Single-device
        backends pass through."""
        with self.instruments.time_h2d():
            if self._dp > 1 and all(a.shape[0] % self._dp == 0 for a in arrays):
                from consensus_tpu.parallel.mesh import shard_batch

                placed = shard_batch(self.mesh_plan.mesh, *arrays)
                return placed if len(arrays) > 1 else (placed,)
            return tuple(jnp.asarray(a) for a in arrays)

    def _fetch(self, *arrays):
        """np.asarray with D2H timing.  Under async dispatch the fetch
        blocks on device work still in flight, so this reading is an upper
        bound that includes device execution, not pure transfer.  Arrays
        already on host (the segmented decode loop returns numpy) pass
        through without polluting the histogram with zero samples."""
        if all(isinstance(a, np.ndarray) for a in arrays):
            out = arrays
        else:
            with self.instruments.time_d2h():
                out = tuple(np.asarray(a) for a in arrays)
        return out if len(arrays) > 1 else out[0]

    def _left_pad_batch(
        self, token_lists: List[List[int]]
    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """(tokens, valid) left-padded into a shared length bucket."""
        width = self._batch_width(token_lists)
        pad = self.tokenizer.pad_id
        tokens = np.full((len(token_lists), width), pad, np.int32)
        valid = np.zeros((len(token_lists), width), bool)
        for row, ids in enumerate(token_lists):
            ids = ids[-width:]  # keep the most recent context
            tokens[row, width - len(ids):] = ids
            valid[row, width - len(ids):] = True
        tokens, valid = self._place_batch(tokens, valid)
        return tokens, valid

    def _bias_table(
        self, requests: Sequence
    ) -> Tuple[Optional[jnp.ndarray], Optional[jnp.ndarray]]:
        """Dedup per-request bias sets into a device table + row index.

        Batches share few distinct bias sets (usually one), so shipping a
        (U, V) table and gathering (B, V) rows ON DEVICE replaces a dense
        per-row host matrix (~1 MB/row at 256k vocab)."""
        if not any(r.bias_against_tokens for r in requests):
            return None, None
        unique: Dict[Tuple, int] = {}
        vectors: List[np.ndarray] = []
        index = np.zeros((len(requests),), np.int32)
        for row, request in enumerate(requests):
            key = (tuple(request.bias_against_tokens), request.bias_value)
            if key not in unique:
                vector = self._bias_vector(
                    request.bias_against_tokens, request.bias_value
                )
                if vector is None:
                    vector = np.zeros((self.config.vocab_size,), np.float32)
                unique[key] = len(vectors)
                vectors.append(vector)
            index[row] = unique[key]
        return jnp.asarray(np.stack(vectors)), jnp.asarray(index)

    def _bias_vector(
        self, bias_tokens: Sequence[str], bias_value: float
    ) -> Optional[np.ndarray]:
        if not bias_tokens:
            return None
        vector = np.zeros((self.config.vocab_size,), np.float32)
        for text in bias_tokens:
            key = text
            if key not in self._bias_id_cache:
                self._bias_id_cache[key] = tuple(
                    self.tokenizer.token_ids_containing(text)
                )
            for token_id in self._bias_id_cache[key]:
                vector[token_id] += bias_value
        return vector

    def _fold_seed(self, *parts) -> jax.Array:
        # Stable across processes (Python's hash() is salted per process).
        import hashlib

        digest = hashlib.blake2b(repr(parts).encode(), digest_size=4).digest()
        fold = int.from_bytes(digest, "big") % (2**31)
        return jax.random.fold_in(jax.random.PRNGKey(self.base_seed), fold)

    def _row_keys(self, kind: str, seeds: Sequence[Optional[int]]) -> jnp.ndarray:
        """Per-row PRNG keys. Seeded rows fold only their own seed (batch-
        composition independent, VERDICT r1 #7).  Unseeded rows must stay
        DIVERSE — identical unseeded prompts in one batch (best_of_n drafts,
        habermas candidates) each need a distinct stream — so they fold their
        row index plus a per-backend nonce instead."""
        keys = []
        for row, seed in enumerate(seeds):
            if seed is None:
                with self._nonce_lock:
                    self._unseeded_calls += 1
                    nonce = self._unseeded_calls
                keys.append(
                    self._fold_seed(kind, "unseeded", row, nonce)
                )
            else:
                keys.append(self._fold_seed(kind, seed))
        return jnp.stack(keys)

    # -- generate ------------------------------------------------------------

    def generate(self, requests: Sequence[GenerationRequest]) -> List[GenerationResult]:
        # The wide slice exists for the SHARED-TRUNK path: its prefill is 1
        # row and its per-step state is (B, V) logits + the KV tail, so a
        # co-batched sweep cell's hundreds of identical-prompt drafts ride
        # ONE decode dispatch instead of ceil(B/32) sequential ones (each
        # with its own tunneled-RTT + dispatch overhead).  The classic path
        # re-caps itself at max_batch_rows (its B-row prefill still
        # materializes per-layer (B, g, r, S, T) fp32 attention logits —
        # the transient max_batch_rows exists to bound).
        return self._sliced(requests, self._generate_impl, limit=256)

    def generate_stream(
        self,
        requests: Sequence[GenerationRequest],
        decode_steps: int = 1,
        speculative: bool = False,
    ) -> "_PagedGenerateStream":
        """Multi-token decode stream (engine ``decode_steps`` seam).

        Prefills the cohort into a private page pool and then serves it in
        K-step windows of ``models/stepper.py:paged_decode_steps``:
        ``dispatch()`` enqueues one window and returns without fetching
        (jax async dispatch — on TPU the host is free while the device
        decodes), ``collect()`` fetches the window's token/emitted arrays
        and finalizes rows that froze inside it with the exact
        ``_finish_generation`` semantics.  Sampling replays the sequential
        per-row key-split schedule, so emitted tokens are independent of K.

        With ``speculative=True`` each window instead drafts K tokens per
        row from an n-gram self-proposer and verifies them in ONE
        ``paged_verify_steps`` dispatch — ``1 + accepted`` real tokens per
        window instead of 1 per scan step, byte-identical token streams
        (exact sequential PRNG replay).
        """
        return _PagedGenerateStream(
            self, list(requests), decode_steps, speculative=speculative
        )

    def _seg_len_for(self, max_new: int) -> Optional[int]:
        """Segment length for a decode budget, or None for monolithic.

        Short budgets keep the monolithic single-dispatch program.  The
        fused pallas decode-attention kernel has no frozen-operand variant,
        so the two options are mutually exclusive — with use_decode_attention
        set, segmentation would silently drop the kernel for every segment
        after the first (code review r3).  The length must divide the
        bucketed budget: the {1,1.5}x-pow2 ladder makes 128 fit 256/384/
        512/768/1024 and 96 catch the 192 bucket (best_of_n's 150-token
        statements).

        Cold-compile cost, stated honestly: each frozen width (seg_len,
        2*seg_len, ... max_new - seg_len) is its own _decode_segment
        program — a 768 budget compiles ~6 decode programs per (rows, ctx)
        bucket where the monolithic path compiled 1.  The remote AOT cache
        keeps them permanently, so this is a one-time deployment cost;
        steady-state is where the 2.8x step-time win lives.
        """
        if not self.segmented_decode or self.config.use_decode_attention:
            return None
        for seg_len in (self.decode_segment_len, 96, 64):
            if max_new >= 2 * seg_len and max_new % seg_len == 0:
                if seg_len != self.decode_segment_len:
                    # Tell the operator (once per budget) their configured
                    # length was unusable for this bucket — tuning runs need
                    # to know which length actually served it (ADVICE r3).
                    if max_new not in self._seg_len_fallbacks:
                        self._seg_len_fallbacks.add(max_new)
                        logger.info(
                            "segmented decode: budget %d is not a multiple of "
                            "decode_segment_len=%d >= 2x; using seg_len=%d",
                            max_new, self.decode_segment_len, seg_len,
                        )
                return seg_len
        return None

    def _segmented_rows_allowed(
        self, prompt_width: int, max_new: int, seg_len: int
    ) -> int:
        """Row allowance for a SEGMENTED decode.

        Per-row KV columns at peak: the prompt trunk, the single-buffered
        frozen blocks (max_new − seg_len columns — blocks append to a LIST,
        so round 3's 2x concatenate transient is gone), the double-buffered
        seg_len live tail, and one seg_len of compaction-gather transient
        (old + gathered block rows coexist briefly).  With ``kv_quant``
        the frozen blocks AND the live tail are int8+scale — bytes halve,
        plus seg_len/8 of margin for the f32 scale planes (4 bytes per
        hd=256 int8 lane group ≈ 1.6%) — and the classic-layout prompt
        trunk is int8 too, so its decode-time cost halves; the binding
        moment for wide prompts becomes the prefill→quantize transient
        (bf16 + int8 trunks alive together, 1.5x the bf16 trunk).
        """
        gen_cols = (max_new - seg_len) + 2 * seg_len + seg_len
        if self.kv_quant:
            # seg_len//4 margin covers the f32 scale planes plus compiler
            # temps.  Hardware evidence at the 768/128 gemma2-2b shape: the
            # resulting 128-row allowance ran clean (decode_step_bench r4
            # arm, 19.4 ms/step) while a raw 192-row arm — above any
            # allowance this model can produce on a 16 GB chip — failed
            # remote compile on HLO temp space.
            q_cols = (gen_cols + 1) // 2 + seg_len // 4
            effective = max(
                prompt_width + prompt_width // 2 + 2 * seg_len,
                (prompt_width + 1) // 2 + prompt_width // 16 + q_cols,
            )
        else:
            effective = prompt_width + gen_cols
        return self._generate_rows_allowed(effective - 2 * seg_len, seg_len)

    def _generate_rows_allowed(self, prompt_width: int, max_new: int) -> int:
        """Largest decode batch whose KV cache fits HBM next to the weights.
        The prompt trunk is a scan closure constant (single-buffered); only
        the max_new-column tail rides the scan carry, which the remote AOT
        compiler DOUBLE-buffers (donation is not honored there)."""
        c = self.config
        itemsize = jnp.dtype(self.params["embed"].dtype).itemsize
        unit = (
            2 * c.n_layers * c.n_kv_heads * c.head_dim * itemsize
        ) // self._shard_count
        per_row = (prompt_width + 2 * max_new) * unit
        # Live search sessions hold real HBM reservations from the same
        # non-weight slice — generate batches must fit BESIDE them.
        budget = (
            _HBM_BYTES - self._params_bytes - _ACTIVATION_RESERVE_BYTES
            - self._session_budget.used
        )
        allowed = max(1, budget // per_row)
        # Round DOWN to the {1, 1.5} x pow2 ladder so chunk shapes stay
        # reusable — all the way to 1: returning a floor of 8 when only 2
        # rows fit would reintroduce the OOM this guard exists to prevent.
        # The ladder matters: long-generation decode is parameter-read
        # bound, so 24-row chunks beat a pow2 floor of 16 by 1.5x.
        bucket = 1
        while bucket * 2 <= allowed:
            bucket *= 2
        if bucket >= 2 and bucket + bucket // 2 <= allowed:
            bucket += bucket // 2
        # Pure DP: batch rows shard over ``data``, so dp chips hold dp x the
        # rows.  Scaling the per-chip ladder keeps every chunk size divisible
        # by dp (so _place_batch can actually shard it).
        return bucket * self._dp

    def _generate_impl(
        self,
        requests: Sequence[GenerationRequest],
        token_lists: Optional[List[List[int]]] = None,
    ) -> List[GenerationResult]:
        """Route: LARGE groups of identical prompts take the shared-trunk
        decode (prefill once, per-step KV reads drop from B·(ctx+t) to
        ctx+B·t — the shape of best_of_n's N drafts and the habermas
        candidate phase); everything else takes the classic per-row path.

        The size threshold matters because long decodes are weight-read
        bound: a B-row shared decode pays the full ~5 ms/step weight read
        over only B rows, while small groups COMBINED into one classic
        batch amortize it over the whole chunk (measured 0.35-0.41
        ms/row·step at B=32-48 classic vs ~1.4 ms/row·step at B=4 shared).
        The habermas revision phase is the canonical case: 30 concurrent
        statements × min(nc,4) rows of 30 DISTINCT prompts — as 4-row
        shared groups that is 30 serial small decodes; as classic chunks
        it is ~4 warm 32-row batches (round-4 fix).  A group that IS the
        whole batch still takes the shared path at >=_SHARED_TRUNK_MIN_ROWS
        (nothing else to amortize weights with, and the 1-row prefill
        wins)."""
        if not requests:
            return []

        if token_lists is None:
            token_lists = [
                self.tokenizer.encode(self._render_prompt(r), add_bos=True)
                for r in requests
            ]
        if self.shared_trunk_generation:
            groups: Dict[Tuple[int, ...], List[int]] = {}
            for i, ids in enumerate(token_lists):
                groups.setdefault(tuple(ids), []).append(i)

            def takes_shared_path(ids_t, idxs) -> bool:
                if not ids_t or len(idxs) < _SHARED_TRUNK_MIN_ROWS:
                    return False
                return (
                    len(idxs) >= _SHARED_TRUNK_SOLO_ROWS
                    or len(idxs) == len(requests)
                )

            if any(takes_shared_path(t, i) for t, i in groups.items()):
                results: List[Optional[GenerationResult]] = [None] * len(requests)
                classic: List[int] = []
                for ids_t, idxs in groups.items():
                    if takes_shared_path(ids_t, idxs):
                        sub = self._generate_shared(
                            [requests[i] for i in idxs], list(ids_t)
                        )
                        for i, result in zip(idxs, sub):
                            results[i] = result
                    else:
                        classic.extend(idxs)
                if classic:
                    sub = self._generate_classic(
                        [requests[i] for i in classic],
                        [token_lists[i] for i in classic],
                    )
                    for i, result in zip(classic, sub):
                        results[i] = result
                return results  # type: ignore[return-value]
        return self._generate_classic(requests, token_lists)

    def _prep_generation_rows(self, requests: Sequence[GenerationRequest], allowed: int):
        """Row bucketing + per-row sampling state shared by the classic and
        shared-trunk generate paths (they MUST stay in lockstep — a pad-row
        or eos-sentinel fix must hit both).

        Rows pad to a power-of-two bucket so XLA compiles a small, reused
        set of programs (decoders hand over varying candidate counts every
        step); dummy rows are never read.  The pad floor respects the HBM
        row allowance; dp-rounding keeps targets shardable.  The pinned-
        budget eos sentinel (-1: an id no tokenizer emits) disables the EOS
        early-exit in timing mode.
        """
        target = min(_bucket(len(requests), minimum=min(8, allowed)), allowed)
        if target % self._dp:  # dp > 8: pow-of-two buckets may undershoot
            target = min(-(-target // self._dp) * self._dp, allowed)
        pad_rows = target - len(requests)
        temperatures = jnp.asarray(
            [r.temperature for r in requests] + [1.0] * pad_rows, jnp.float32
        )
        # Repetition penalty: None (the overwhelmingly common case — no
        # paper config sets it) keeps the penalty-free decode programs; any
        # row >1 switches the batch to the presence-tracking variant.
        penalties = [getattr(r, "repetition_penalty", 1.0) for r in requests]
        rep_penalty = (
            jnp.asarray(penalties + [1.0] * pad_rows, jnp.float32)
            if any(abs(p - 1.0) > 1e-9 for p in penalties)
            else None
        )
        bias_table, bias_index = self._bias_table(requests)
        if bias_index is not None and pad_rows:
            bias_index = jnp.concatenate(
                [bias_index, jnp.zeros((pad_rows,), jnp.int32)]
            )
        keys = self._row_keys(
            "generate", [r.seed for r in requests] + [0] * pad_rows
        )
        eos_ids = (
            (-1,) if self.pin_generation_budget else self.tokenizer.eos_ids
        )
        return (target, pad_rows, temperatures, bias_table, bias_index,
                keys, eos_ids, rep_penalty)

    def _generate_shared(
        self, requests: Sequence[GenerationRequest], prompt_ids: List[int]
    ) -> List[GenerationResult]:
        """Decode all rows from ONE shared prompt trunk
        (models/generate.py:generate_tokens_shared_trunk)."""
        from consensus_tpu.models.generate import generate_tokens_shared_trunk

        max_new = _width_bucket(max(r.max_tokens for r in requests), minimum=16)
        # ONE trunk-width variant: the trunk is a single row, so padding its
        # prefill to max_context costs ~nothing — while letting its width
        # float over the {1,1.5}-pow2 ladder multiplies the remote-AOT
        # program space by every ladder step a scenario's prompts touch
        # (measured: scenario-3's new buckets alone cost ~50 min of serial
        # decode-loop compiles in the round-3 sweep).
        width = self.max_context
        prompt_ids = prompt_ids[-width:]
        seg_len = self._seg_len_for(max_new)
        segmented = seg_len is not None
        # Tail-only per-row HBM (the trunk is one row, a closure constant):
        # rows are ~(ctx+2·max_new)/(2·max_new) times cheaper than classic.
        if segmented:
            allowed = self._segmented_rows_allowed(0, max_new, seg_len)
        else:
            allowed = self._generate_rows_allowed(0, max_new)
        if len(requests) > allowed:
            out: List[GenerationResult] = []
            for i in range(0, len(requests), allowed):
                out.extend(
                    self._generate_shared(requests[i : i + allowed], prompt_ids)
                )
            return out

        self.call_counts["generate"] += len(requests)
        (target, pad_rows, temperatures, bias_table, bias_index, keys,
         eos_ids, rep_penalty) = self._prep_generation_rows(requests, allowed)
        self.instruments.record_padding(
            "generate_trunk", 1, width, len(prompt_ids)
        )
        self.instruments.record_launch(
            "generate_shared",
            (target, width, max_new, int(segmented), int(bias_table is not None)),
        )

        pad = self.tokenizer.pad_id
        tokens = np.full((1, width), pad, np.int32)
        valid = np.zeros((1, width), bool)
        tokens[0, width - len(prompt_ids):] = prompt_ids
        valid[0, width - len(prompt_ids):] = True

        # Bucket-pad rows start done (they'd otherwise sample real tokens
        # from the real prompt and pin the early exit at the full budget).
        init_done = np.zeros((target,), bool)
        init_done[len(requests):] = True
        kwargs = dict(
            max_new_tokens=max_new,
            temperature=temperatures,
            eos_ids=jnp.asarray(eos_ids, jnp.int32),
            bias_table=bias_table,
            bias_index=bias_index,
            pad_id=self.tokenizer.pad_id,
            init_done=jnp.asarray(init_done),
        )
        if rep_penalty is not None:
            kwargs["rep_penalty"] = rep_penalty
        if segmented:
            from consensus_tpu.models.generate import (
                generate_tokens_shared_trunk_segmented as fn,
            )

            kwargs["seg_len"] = seg_len
            kwargs["dp_align"] = self._dp  # compaction keeps dp-divisible rows
            kwargs["kv_quant"] = self.kv_quant
        else:
            fn = generate_tokens_shared_trunk
        out = fn(
            self.params, self.config,
            jnp.asarray(tokens), jnp.asarray(valid), target, keys, **kwargs,
        )
        return self._finish_generation(requests, out, rows=target, max_new=max_new)

    def _generate_classic(
        self,
        requests: Sequence[GenerationRequest],
        token_lists: List[List[int]],
    ) -> List[GenerationResult]:
        # Classic-path batches keep the max_batch_rows activation bound:
        # the B-row prefill materializes per-layer (B, g, r, S, T) fp32
        # attention logits that the KV-only HBM allowance below does not
        # model (the generate() slice limit is wider only for the 1-row-
        # prefill shared-trunk path).
        if len(requests) > self.max_batch_rows:
            out: List[GenerationResult] = []
            for i in range(0, len(requests), self.max_batch_rows):
                out.extend(
                    self._generate_classic(
                        requests[i : i + self.max_batch_rows],
                        token_lists[i : i + self.max_batch_rows],
                    )
                )
            return out
        width = self._batch_width(token_lists)
        max_new = _width_bucket(max(r.max_tokens for r in requests), minimum=16)
        seg_len = self._seg_len_for(max_new)
        segmented = seg_len is not None
        if segmented:
            allowed = self._segmented_rows_allowed(width, max_new, seg_len)
        else:
            allowed = self._generate_rows_allowed(width, max_new)
        if len(requests) > allowed:
            # Long-generation batches re-chunk so the KV cache stays inside
            # the HBM budget (a 32-row x 2048-column cache double-buffered
            # is 13 GB — the habermas candidate phase OOM).  Token lists ride
            # along so chunks don't re-render/re-tokenize their prompts.
            out: List[GenerationResult] = []
            for i in range(0, len(requests), allowed):
                out.extend(
                    self._generate_classic(
                        requests[i : i + allowed],
                        token_lists[i : i + allowed],
                    )
                )
            return out

        self.call_counts["generate"] += len(requests)
        (target, pad_rows, temperatures, bias_table, bias_index, keys,
         eos_ids, rep_penalty) = self._prep_generation_rows(requests, allowed)
        self.instruments.record_padding(
            "generate_prompt", target, width,
            sum(min(len(t), width) for t in token_lists),
        )
        self.instruments.record_launch(
            "generate",
            (target, width, max_new, int(segmented), int(bias_table is not None)),
        )
        token_lists = list(token_lists) + [[]] * pad_rows
        tokens, valid = self._left_pad_batch(token_lists)
        kwargs = dict(
            max_new_tokens=max_new,
            temperature=temperatures,
            eos_ids=jnp.asarray(eos_ids, jnp.int32),
            bias_table=bias_table,
            bias_index=bias_index,
            pad_id=self.tokenizer.pad_id,
        )
        if rep_penalty is not None:
            kwargs["rep_penalty"] = rep_penalty
        if segmented:
            from consensus_tpu.models.generate import (
                generate_tokens_segmented as fn,
            )

            kwargs["seg_len"] = seg_len
            kwargs["dp_align"] = self._dp  # compaction keeps dp-divisible rows
            kwargs["kv_quant"] = self.kv_quant
        else:
            fn = generate_tokens
        out = fn(self.params, self.config, tokens, valid, keys, **kwargs)
        return self._finish_generation(requests, out, rows=target, max_new=max_new)

    def _finish_generation(
        self,
        requests: Sequence[GenerationRequest],
        out,
        rows: int,
        max_new: int,
    ) -> List[GenerationResult]:
        """Shared host-side post-processing: decode, EOS/stop semantics,
        token accounting."""
        generated, counts, hit_eos = self._fetch(
            out.tokens, out.num_generated, out.hit_eos
        )
        # Decode-grid padding efficiency from the tokens actually emitted:
        # EOS early exits and bucket-pad rows both show up as empty slots.
        self.instruments.record_padding(
            "generate_decode", rows, max_new, int(counts[: len(requests)].sum())
        )

        results = []
        for row, request in enumerate(requests):
            emitted = int(counts[row])
            ids = [int(t) for t in generated[row, :emitted]]
            ids = ids[: request.max_tokens]
            text = self.tokenizer.decode(ids)
            # "stop" only if EOS arrived within the request's OWN cap; an EOS
            # beyond max_tokens means the cap truncated the text ("length"),
            # even though the bucketed decode window saw an EOS later.
            finish = "stop" if (hit_eos[row] and emitted <= request.max_tokens) else "length"
            truncated = False
            if not self.pin_generation_budget:
                for stop in request.stop:
                    idx = text.find(stop)
                    if idx >= 0:
                        text = text[:idx]
                        finish = "stop"
                        truncated = True
            if truncated:
                # Keep token_ids consistent with the truncated text so token
                # counts/ids downstream match what the caller sees.
                ids = self.tokenizer.encode(text)
            self.token_counts["generated"] += len(ids)
            results.append(
                GenerationResult(text=text, token_ids=tuple(ids), finish_reason=finish)
            )
        return results

    # -- score ---------------------------------------------------------------

    def _score_prefix(self, request: ScoreRequest) -> str:
        prefix = (
            f"{request.system_prompt}\n\n{request.context}"
            if request.system_prompt
            else request.context
        )
        if request.chat and request.role == "user":
            # Reference evaluation semantics (src/evaluation.py:182-193):
            # the eval template sits in the system slot and the statement
            # is scored INSIDE the user turn.
            parts = [p for p in (request.system_prompt, request.context) if p]
            prefix = self.tokenizer.user_turn_prefix("\n\n".join(parts) or None)
        elif request.chat:
            prefix = self.tokenizer.chat_prompt(request.context, request.system_prompt)
        return prefix

    def score(self, requests: Sequence[ScoreRequest]) -> List[ScoreResult]:
        """Teacher-forced scoring; requests sharing a context prefill it ONCE.

        best_of_n / evaluation score many candidates under the same agent
        context (reference best_of_n.py:266-321) — re-running the ~1k-token
        context forward per candidate is O(P·(C+L)).  With
        ``shared_context_scoring`` enabled, requests are grouped by rendered
        prefix; groups of >=4 that fit the window go through
        ``shared_context_token_logprobs`` (O(C + P·L), trunk broadcast) —
        measured 3.4x faster than the classic path on a bon-shaped batch
        (445 reqs, 1k ctx, Gemma-2B int8, one v5e: 5.8s vs 19.8s warm).
        Default OFF: on the tunneled shared chip the in-situ sweep numbers
        were too noisy to certify an end-to-end win this round.
        """
        if not requests:
            return []
        if not self.shared_context_scoring:
            return self._sliced(requests, self._score_impl)
        prepared = []
        # Memoize the prefix encoding: a P-candidate group shares one
        # identical ~1k-token context — the workload this path dedupes —
        # so tokenize it once, not P times (ADVICE r2).
        prefix_ids: Dict[str, List[int]] = {}
        for request in requests:
            prefix = self._score_prefix(request)
            if prefix not in prefix_ids:
                prefix_ids[prefix] = self.tokenizer.encode(prefix, add_bos=True)
            prepared.append(
                (
                    prefix,
                    prefix_ids[prefix],
                    self.tokenizer.encode(request.continuation),
                )
            )
        by_prefix: Dict[str, List[int]] = {}
        for i, (prefix, _, _) in enumerate(prepared):
            by_prefix.setdefault(prefix, []).append(i)

        results: List[Optional[ScoreResult]] = [None] * len(requests)
        legacy: List[int] = []
        for prefix, idxs in by_prefix.items():
            ctx_ids = prepared[idxs[0]][1]
            conts = [prepared[i][2] for i in idxs]
            max_cont = max((len(c) for c in conts), default=0)
            # The suffix attention materializes per-layer fp32 logits of
            # (rows, heads, span, ctx+span) — unlike the classic path it has
            # no flash kernel, so bound that transient explicitly, and from
            # the widths _score_shared_group will actually ALLOCATE (pow2
            # continuation bucket, {1,1.5}-pow2 context bucket — up to ~2x
            # the unpadded sizes the guard previously used, ADVICE r2).
            # Chunk rows start at 4x max_batch_rows (suffix-only rows carry
            # no (B, S, S) transient — a co-batched cell's 256-candidate
            # group rides 2 dispatches instead of 8) and halve until the
            # transient fits.
            cont_width = self._shared_cont_width(max_cont)
            ctx_width = self.max_context  # matches _shared_prefill's padding
            rows_cap = max(self.max_batch_rows, 128)
            while rows_cap >= 8:
                attn_bytes = (
                    rows_cap * self.config.n_heads
                    * cont_width * (ctx_width + cont_width) * 4
                )
                if attn_bytes <= _SHARED_SCORE_ATTN_BYTES_CAP:
                    break
                rows_cap //= 2
            fits = (
                # >=4 rows: below that the single-row prefill + padded
                # suffix costs more than riding a wide legacy batch.
                len(idxs) >= 4
                and all(conts)
                and ctx_ids
                and len(ctx_ids) + max_cont <= self.max_context
                and attn_bytes <= _SHARED_SCORE_ATTN_BYTES_CAP
            )
            if not fits:
                legacy.extend(idxs)
                continue
            # Prefill the shared context ONCE for the whole group; every
            # row chunk scores against the same resident trunk (round 2
            # re-prefilled per 32-row chunk — VERDICT r2 #5).
            trunk_state = None
            for start in range(0, len(idxs), rows_cap):
                chunk = idxs[start : start + rows_cap]
                if len(chunk) < 4:  # sub-threshold tail: ride the wide batch
                    legacy.extend(chunk)
                    continue
                if trunk_state is None:
                    trunk_state = self._shared_prefill(ctx_ids)
                self._score_shared_group(trunk_state, chunk, prepared, results, rows_cap)
        if legacy:
            for start in range(0, len(legacy), self.max_batch_rows):
                chunk = legacy[start : start + self.max_batch_rows]
                chunk_results = self._score_impl(
                    [requests[i] for i in chunk],
                    prepared=[(prepared[i][1], prepared[i][2]) for i in chunk],
                )
                for i, result in zip(chunk, chunk_results):
                    results[i] = result
        return results  # type: ignore[return-value]

    def _shared_prefill(self, ctx_ids: List[int]):
        """Prefill one shared scoring context into a resident trunk.

        ONE width variant: the context is a single row, so padding to
        max_context is ~free, and the trunk's width is baked into every
        downstream suffix-scorer program shape — a floating width would
        multiply the remote-AOT compile space per scenario."""
        from consensus_tpu.models.transformer import shared_context_prefill

        ctx_width = self.max_context
        self.instruments.record_padding("score_trunk", 1, ctx_width, len(ctx_ids))
        self.instruments.record_launch("score_trunk", (1, ctx_width))
        pad = self.tokenizer.pad_id
        ctx_tokens = np.full((1, ctx_width), pad, np.int32)
        ctx_tokens[0, : len(ctx_ids)] = ctx_ids
        ctx_valid = np.zeros((1, ctx_width), bool)
        ctx_valid[0, : len(ctx_ids)] = True
        return shared_context_prefill(
            self.params, self.config, jnp.asarray(ctx_tokens), jnp.asarray(ctx_valid)
        )

    def _score_shared_group(
        self,
        trunk_state,
        idxs: List[int],
        prepared,
        results,
        rows_cap: Optional[int] = None,
    ) -> None:
        from consensus_tpu.models.transformer import shared_context_cont_logprobs

        self.call_counts["score"] += len(idxs)
        conts = [prepared[i][2] for i in idxs]
        # Shape discipline: every program here is a fresh remote-AOT compile,
        # so the variant space must stay SMALL: rows bucket on a coarse pow2
        # ladder from 32 up to rows_cap (a 5-candidate habermas group must
        # not pad 4x to a 128-row bucket), continuation width likewise.
        n_rows = min(
            rows_cap or max(self.max_batch_rows, 128),
            _bucket(len(idxs), minimum=32),
        )
        width = self._shared_cont_width(max(len(c) for c in conts))
        self.instruments.record_padding(
            "score_shared", n_rows, width, sum(len(c) for c in conts)
        )
        self.instruments.record_launch("score_shared", (n_rows, width))
        pad = self.tokenizer.pad_id
        cont_tokens = np.full((n_rows, width), pad, np.int32)
        cont_valid = np.zeros((n_rows, width), bool)
        for row, ids in enumerate(conts):
            cont_tokens[row, : len(ids)] = ids
            cont_valid[row, : len(ids)] = True
        cont_tokens_dev, cont_valid_dev = self._place_batch(cont_tokens, cont_valid)
        trunk, ctx_len, last_hidden = trunk_state
        logprobs = self._fetch(
            shared_context_cont_logprobs(
                self.params,
                self.config,
                trunk,
                ctx_len,
                last_hidden,
                cont_tokens_dev,
                cont_valid_dev,
            )
        )
        for row, i in enumerate(idxs):
            ids = conts[row]
            self.token_counts["scored"] += len(ids)
            results[i] = ScoreResult(
                tokens=tuple(self.tokenizer.token_str(t) for t in ids),
                logprobs=tuple(float(v) for v in logprobs[row, : len(ids)]),
            )

    def _score_impl(
        self,
        requests: Sequence[ScoreRequest],
        prepared: Optional[Sequence[Tuple[List[int], List[int]]]] = None,
    ) -> List[ScoreResult]:
        """Classic full-sequence batch scorer.  ``prepared`` carries
        already-encoded (context_ids, continuation_ids) so the shared-path
        router does not pay tokenization twice for its legacy fallbacks."""
        self.call_counts["score"] += len(requests)
        if not requests:
            return []

        rows = []
        spans = []  # (context_len, continuation_len) per row
        for i, request in enumerate(requests):
            if prepared is not None:
                context_ids, continuation_ids = prepared[i]
            else:
                prefix = self._score_prefix(request)
                context_ids = self.tokenizer.encode(prefix, add_bos=True)
                continuation_ids = self.tokenizer.encode(request.continuation)
            rows.append(context_ids + continuation_ids)
            spans.append((len(context_ids), len(continuation_ids)))

        # Row bucketing (see _generate_impl): dummy all-pad rows are skipped
        # by the result loop below.
        rows += [[]] * (_bucket(len(rows), minimum=8) - len(rows))
        longest = min(max(len(r) for r in rows), self.max_context)
        width = min(_width_bucket(longest), self.max_context)
        pad = self.tokenizer.pad_id
        tokens = np.full((len(rows), width), pad, np.int32)
        valid = np.zeros((len(rows), width), bool)
        for i, ids in enumerate(rows):
            if len(ids) > width:
                # Drop the OLDEST context so the scored continuation (at the
                # end) survives; record how much context was cut.  If the cut
                # eats past the context into the continuation, shrink the
                # continuation span too so the returned logprobs cover only
                # the surviving continuation tokens.
                cut = len(ids) - width
                ids = ids[cut:]
                ctx_len, cont_len = spans[i]
                new_ctx = max(ctx_len - cut, 0)
                new_cont = cont_len - max(cut - ctx_len, 0)
                if new_ctx == 0:
                    # Position 0 carries no conditioning — its token_logprobs
                    # slot is a padded 0.0, which would report probability 1
                    # for a real token.  Drop it from the scored span.
                    new_ctx, new_cont = 1, new_cont - 1
                spans[i] = (new_ctx, new_cont)
                if cut >= ctx_len:
                    logger.warning(
                        "score(): continuation truncated to %d tokens "
                        "(context window %d)", new_cont, width,
                    )
            tokens[i, : len(ids)] = ids  # RIGHT-padded for scoring
            valid[i, : len(ids)] = True

        scorer = (
            token_logprobs_streamed
            if self.config.vocab_size > _STREAMED_VOCAB_THRESHOLD
            else token_logprobs
        )
        self.instruments.record_padding(
            "score", len(rows), width,
            sum(min(len(r), width) for r in rows[: len(requests)]),
        )
        self.instruments.record_launch("score", (len(rows), width))
        tokens_dev, valid_dev = self._place_batch(tokens, valid)
        logprobs = self._fetch(
            scorer(self.params, self.config, tokens_dev, valid_dev)
        )

        results = []
        for i, (request, (ctx_len, cont_len)) in enumerate(zip(requests, spans)):
            end = min(ctx_len + cont_len, width)
            span_lp = logprobs[i, ctx_len:end]
            span_ids = tokens[i, ctx_len:end]
            self.token_counts["scored"] += len(span_lp)
            results.append(
                ScoreResult(
                    tokens=tuple(self.tokenizer.token_str(t) for t in span_ids),
                    logprobs=tuple(float(v) for v in span_lp),
                )
            )
        return results

    # -- fused (candidates x agents) utility matrix ---------------------------

    #: KV page width of the fused scoring pool.  Small pages keep the
    #: shared/private split fine-grained: everything up to the last full
    #: page of an agent context is shared read-only across all candidate
    #: rows; only the <=15-token tail plus the candidate re-runs per row.
    _SCORE_PAGE_SIZE = 16

    def score_matrix(self, requests) -> List:
        """Evaluate whole (candidates x agents) utility matrices on device.

        Each matrix runs as ONE logical program: per-agent context pages
        are prefilled once (deduped across agents sharing a rendered
        prefix) and shared READ-ONLY by every candidate row via block
        tables; the flattened candidate-major row batch is chunked under
        the live-session HBM budget and sharded over the dp mesh; per-row
        logprob reductions and the welfare fold happen on device
        (models/stepper.py: paged_score_chunk / utility_matrix).  Only the
        (C, A) utilities, the (C,) welfare vector, and the moments aux
        cross D2H — never a per-token logprob vector.  Requests whose
        rows would need the per-call scorer's truncation semantics fall
        back to it wholesale, keeping truncation behavior in one place.
        """
        from consensus_tpu.backends.score_matrix import (
            fallback_score_matrix_many,
            record_matrix,
            reduce_matrix,
        )

        out = []
        for request in requests:
            self.call_counts["score_matrix"] += 1
            self.matrix_stats["calls"] += 1
            if not request.candidates or not request.agents:
                out.append(reduce_matrix(request, [], path="fused"))
                continue
            result = self._score_matrix_fused(request)
            if result is None:  # needs per-call truncation semantics
                self.matrix_stats["fallbacks"] += 1
                result = fallback_score_matrix_many(self, [request])[0]
            else:
                record_matrix(
                    result,
                    len(request.agents),
                    welfare_rule=request.welfare_rule,
                )
            out.append(result)
        return out

    def _score_matrix_fused(self, request):
        from consensus_tpu.backends.score_matrix import ScoreMatrixResult
        from consensus_tpu.models.stepper import (
            make_page_state,
            paged_prefill_chunk,
            paged_score_chunk,
            utility_matrix,
        )

        ps = self._SCORE_PAGE_SIZE
        mesh = self.mesh_plan.mesh if self.mesh_plan is not None else None
        n_candidates = len(request.candidates)
        n_agents = len(request.agents)

        # Tokenize once per unique rendered agent prefix (agents routinely
        # share the issue framing) and once per candidate.
        prefix_ids: Dict[str, List[int]] = {}
        agent_prefixes: List[str] = []
        for agent in request.agents:
            prefix = self._score_prefix(agent.to_score_request(""))
            if prefix not in prefix_ids:
                prefix_ids[prefix] = self.tokenizer.encode(prefix, add_bos=True)
            agent_prefixes.append(prefix)
        cont_ids = [self.tokenizer.encode(c) for c in request.candidates]
        max_cont = max(len(c) for c in cont_ids)
        if any(
            len(ids) + max_cont > self.max_context
            for ids in prefix_ids.values()
        ):
            return None  # per-call scorer owns truncation semantics

        # Shared page layout: each unique context owns the pages below its
        # last full page boundary; the remaining 1..ps-token tail is
        # re-fed per row so the hidden state at the final context position
        # exists to teacher-force the first candidate token.
        shared: Dict[str, Tuple[int, int, int]] = {}  # prefix -> (first, npg, n0)
        next_page = 0
        for prefix, ids in prefix_ids.items():
            n0 = ((len(ids) - 1) // ps) * ps
            shared[prefix] = (next_page, n0 // ps, n0)
            next_page += n0 // ps
        shared_total = next_page

        # Flattened candidate-major rows; q block = context tail + all but
        # the last candidate token (targets are the NEXT stream token).
        rows = []  # (prefix, cont, q_len, n_private)
        max_q = 1
        max_private = 1
        max_blocks = 1
        for cont in cont_ids:
            for prefix in agent_prefixes:
                ids = prefix_ids[prefix]
                _, npg, n0 = shared[prefix]
                q_len = (len(ids) - n0) + max(len(cont) - 1, 0)
                n_private = (n0 + q_len - 1) // ps - n0 // ps + 1
                rows.append((prefix, cont, q_len, n_private))
                max_q = max(max_q, q_len)
                max_private = max(max_private, n_private)
                max_blocks = max(max_blocks, npg + n_private)

        # Chunk the row batch under the live-session HBM budget: pow2 row
        # buckets so the compiled-variant space stays small, halved until
        # the page pool (shared + per-row private + sink) fits.
        dtype = jnp.dtype(self.params["embed"].dtype)
        page_bytes = (
            self.config.n_layers * ps * self.config.n_kv_heads
            * self.config.head_dim * dtype.itemsize * 2
        )

        def pool_bytes(n_rows: int) -> int:
            return (shared_total + n_rows * max_private + 1) * page_bytes

        total_rows = len(rows)
        chunk_rows = min(
            _bucket(total_rows, minimum=8),
            _bucket(max(self.max_batch_rows, 64), minimum=8),
        )
        budget = self._session_budget.cap
        while chunk_rows > 1 and pool_bytes(chunk_rows) > budget:
            chunk_rows //= 2
        if pool_bytes(chunk_rows) > budget:
            return None  # even one row over-commits; per-call path chunks finer
        chunk_rows = max(chunk_rows, self._dp)
        width = _bucket(max_q, minimum=ps)
        num_pages = shared_total + chunk_rows * max_private
        sink = num_pages

        nbytes = pool_bytes(chunk_rows)
        self._session_budget.acquire(nbytes)
        try:
            state = make_page_state(
                self.config, num_pages, ps, dtype=dtype, mesh=mesh
            )
            state = self._prefill_shared_pages(state, prefix_ids, shared, sink, mesh)
            chunk_stats = []
            for start in range(0, total_rows, chunk_rows):
                chunk = rows[start : start + chunk_rows]
                stats, state = self._score_matrix_chunk(
                    state, chunk, shared, prefix_ids, chunk_rows, width,
                    max_blocks, shared_total, max_private, sink, mesh,
                )
                chunk_stats.append(tuple(s[: len(chunk)] for s in stats))
                self.matrix_stats["chunks"] += 1
            stats = tuple(
                jnp.concatenate([cs[i] for cs in chunk_stats])
                for i in range(4)
            )
            utilities, welfare_vals, aux = utility_matrix(
                stats, n_candidates, n_agents,
                stat=request.stat, rule=request.welfare_rule,
                default=request.default,
            )
            fetched = self._fetch(
                *([utilities, welfare_vals] + ([aux] if aux is not None else []))
            )
        finally:
            self._session_budget.release(nbytes)
        utilities_np, welfare_np = fetched[0], fetched[1]
        aux_np = fetched[2] if aux is not None else None
        self.token_counts["scored"] += n_agents * sum(len(c) for c in cont_ids)
        d2h = utilities_np.nbytes + welfare_np.nbytes + (
            aux_np.nbytes if aux_np is not None else 0
        )
        return ScoreMatrixResult(
            utilities=utilities_np,
            welfare=welfare_np,
            best=int(np.argmax(welfare_np)) if welfare_np.size else 0,
            aux=aux_np,
            cells=n_candidates * n_agents,
            d2h_bytes=d2h,
            path="fused",
        )

    def _prefill_shared_pages(self, state, prefix_ids, shared, sink, mesh):
        """Ingest every unique agent context's full pages (one row per
        unique prefix, chunked along the sequence).  Rows padding the pow2
        batch bucket duplicate row 0 with writes routed to the sink."""
        from consensus_tpu.models.stepper import paged_prefill_chunk

        ps = self._SCORE_PAGE_SIZE
        pre = [p for p in prefix_ids if shared[p][1] > 0]
        if not pre:
            return state
        n_rows = _bucket(len(pre), minimum=8)
        max_n0 = max(shared[p][2] for p in pre)
        chunk = min(256, _bucket(max_n0, minimum=ps))
        n_blocks = max(shared[p][1] for p in pre)
        tables = np.full((n_rows, n_blocks), -1, np.int32)
        for r, p in enumerate(pre):
            first, npg, _ = shared[p]
            tables[r, :npg] = np.arange(first, first + npg, dtype=np.int32)
        tables[len(pre):] = tables[0]
        pad_id = self.tokenizer.pad_id
        for k in range(0, max_n0, chunk):
            tokens = np.full((n_rows, chunk), pad_id, np.int32)
            valid = np.zeros((n_rows, chunk), bool)
            lengths = np.zeros((n_rows,), np.int32)
            write_pages = np.full((n_rows, chunk), sink, np.int32)
            write_offsets = np.zeros((n_rows, chunk), np.int32)
            for r, p in enumerate(pre):
                ids = prefix_ids[p]
                first, _, n0 = shared[p]
                hi = min(n0, k + chunk)
                lengths[r] = hi  # == n0 once the row is complete
                if hi <= k:
                    continue
                span = ids[k:hi]
                valid[r, : len(span)] = True
                tokens[r, : len(span)] = span
                for j in range(len(span)):
                    write_pages[r, j] = first + (k + j) // ps
                    write_offsets[r, j] = (k + j) % ps
            # Pad rows ride row 0's shape (valid positions, table) but
            # write only to the sink — never a real page.
            tokens[len(pre):] = tokens[0]
            valid[len(pre):] = valid[0]
            lengths[len(pre):] = lengths[0]
            self.instruments.record_launch("score_matrix_prefill", (n_rows, chunk))
            # lengths is rank-1: jit's in-program constraint shards it.
            placed = self._place_batch(
                tokens, valid, tables, write_pages, write_offsets
            )
            _, state = paged_prefill_chunk(
                self.params, self.config, placed[0], placed[1], state,
                placed[2], jnp.asarray(lengths), placed[3], placed[4],
                mesh=mesh,
            )
        return state

    def _score_matrix_chunk(
        self, state, chunk, shared, prefix_ids, n_rows, width,
        max_blocks, shared_total, max_private, sink, mesh,
    ):
        """One fused teacher-forced pass over a chunk of matrix rows."""
        from consensus_tpu.models.stepper import paged_score_chunk

        ps = self._SCORE_PAGE_SIZE
        pad_id = self.tokenizer.pad_id
        tokens = np.full((n_rows, width), pad_id, np.int32)
        targets = np.zeros((n_rows, width), np.int32)
        score_mask = np.zeros((n_rows, width), bool)
        chunk_valid = np.zeros((n_rows, width), bool)
        tables = np.full((n_rows, max_blocks), -1, np.int32)
        lengths = np.zeros((n_rows,), np.int32)
        write_pages = np.full((n_rows, width), sink, np.int32)
        write_offsets = np.zeros((n_rows, width), np.int32)
        for r, (prefix, cont, q_len, n_private) in enumerate(chunk):
            ids = prefix_ids[prefix]
            first, npg, n0 = shared[prefix]
            stream = ids + cont
            block = stream[n0 : n0 + q_len]
            tokens[r, : q_len] = block
            chunk_valid[r, : q_len] = True
            lengths[r] = n0 + q_len
            tables[r, :npg] = np.arange(first, first + npg, dtype=np.int32)
            base = shared_total + r * max_private
            tables[r, npg : npg + n_private] = np.arange(
                base, base + n_private, dtype=np.int32
            )
            for j in range(q_len):
                pos = n0 + j
                write_pages[r, j] = base + pos // ps - n0 // ps
                write_offsets[r, j] = pos % ps
                if pos + 1 < len(stream):
                    targets[r, j] = stream[pos + 1]
            lo = len(ids) - 1 - n0
            score_mask[r, lo : lo + len(cont)] = bool(cont)
        # Pad rows duplicate row 0 (well-defined positions/attention) but
        # write to the sink and score nothing.
        n_real = len(chunk)
        tokens[n_real:] = tokens[0]
        targets[n_real:] = targets[0]
        chunk_valid[n_real:] = chunk_valid[0]
        lengths[n_real:] = lengths[0]
        tables[n_real:] = tables[0]
        self.instruments.record_padding(
            "score_matrix", n_rows, width,
            sum(q for (_, _, q, _) in chunk),
        )
        self.instruments.record_launch("score_matrix", (n_rows, width))
        # lengths is rank-1: jit's in-program constraint shards it.
        placed = self._place_batch(
            tokens, targets, score_mask, chunk_valid, tables,
            write_pages, write_offsets,
        )
        return paged_score_chunk(
            self.params, self.config, placed[0], placed[1], placed[2],
            placed[3], state, placed[4], jnp.asarray(lengths), placed[5],
            placed[6], mesh=mesh,
        )

    # -- next-token distribution ----------------------------------------------

    def next_token_logprobs(
        self, requests: Sequence[NextTokenRequest]
    ) -> List[List[TokenCandidate]]:
        return self._sliced(requests, self._next_token_impl)

    def _next_token_impl(
        self, requests: Sequence[NextTokenRequest]
    ) -> List[List[TokenCandidate]]:
        self.call_counts["next_token"] += len(requests)
        if not requests:
            return []
        self.token_counts["scored"] += len(requests)

        token_lists = [
            self.tokenizer.encode(self._render_prompt(r), add_bos=True)
            for r in requests
        ]
        # Row bucketing (see _generate_impl): beam/MCTS candidate counts
        # vary per step; dummy rows keep compiled shapes stable.
        pad_rows = _bucket(len(requests), minimum=8) - len(requests)
        token_lists += [[]] * pad_rows
        tokens, valid = self._left_pad_batch(token_lists)

        bias_table, bias_index = self._bias_table(requests)
        if bias_index is not None and pad_rows:
            bias_index = jnp.concatenate(
                [bias_index, jnp.zeros((pad_rows,), jnp.int32)]
            )
        # k buckets too (widths vary little; candidates slice their own k).
        k = _bucket(
            max(min(r.k, self.config.vocab_size) for r in requests), minimum=4
        )
        k = min(k, self.config.vocab_size)
        temperatures = jnp.asarray(
            [r.temperature for r in requests] + [1.0] * pad_rows, jnp.float32
        )
        gumbel_rows = [
            r.mode != "topk" and r.temperature > 0 for r in requests
        ] + [False] * pad_rows
        if any(gumbel_rows):
            keys = self._row_keys(
                "next_token", [r.seed for r in requests] + [0] * pad_rows
            )
        else:
            # Pure-topk batches are deterministic: don't burn the unseeded
            # nonce (keeps unrelated unseeded generate() calls reproducible).
            keys = jnp.zeros((len(requests) + pad_rows, 2), jnp.uint32)
        width = int(tokens.shape[1])
        self.instruments.record_padding(
            "next_token", len(token_lists), width,
            sum(min(len(t), width) for t in token_lists[: len(requests)]),
        )
        self.instruments.record_launch(
            "next_token",
            (len(token_lists), width, k, int(bias_table is not None)),
        )
        # Device-side selection: only (B, k) ids+logprobs cross the wire
        # (VERDICT r1 #6) — never the (B, 256k) logit matrix.
        ids, logprobs = next_token_topk(
            self.params, self.config, tokens, valid, keys,
            k, temperatures, jnp.asarray(gumbel_rows, bool),
            bias_table, bias_index, with_gumbel=any(gumbel_rows),
        )
        ids, logprobs = self._fetch(ids, logprobs)

        out: List[List[TokenCandidate]] = []
        for row, request in enumerate(requests):
            # Take this request's k in score order (the without-replacement
            # sample), then present best-first by true logprob (reference
            # orders candidates by -logprob).
            row_k = min(request.k, self.config.vocab_size)
            pairs = sorted(
                zip(ids[row, :row_k], logprobs[row, :row_k]),
                key=lambda p: -p[1],
            )
            out.append(
                [
                    TokenCandidate(
                        token=self.tokenizer.token_str(int(t)),
                        token_id=int(t),
                        logprob=float(lp),
                    )
                    for t, lp in pairs
                ]
            )
        return out

    # -- token-search sessions -------------------------------------------------

    def open_fused_token_search(self, spec):
        """Incremental KV-cache search session (models/stepper.py): one fused
        device program per emitted token instead of re-running every prefix.
        Raises FusedSessionUnavailable when the persistent caches wouldn't
        fit alongside the weights (the session sizes its cache from the
        ACTUAL tokenized prefix width, so the check happens in its
        constructor, not on a pessimistic pre-tokenize bound) — the factory
        then builds the full-prefix fallback over the CALLING backend."""
        return TPUTokenSearchSession(self, spec)

    # -- embeddings ------------------------------------------------------------

    def embed(self, texts: Sequence[str]) -> np.ndarray:
        pieces = [
            self._embed_impl(texts[i : i + self.max_batch_rows])
            for i in range(0, len(texts), self.max_batch_rows)
        ] or [np.zeros((0, self.config.d_model), np.float32)]
        return np.vstack(pieces)

    def _embed_impl(self, texts: Sequence[str]) -> np.ndarray:
        self.call_counts["embed"] += len(texts)
        token_lists = [self.tokenizer.encode(t, add_bos=True) for t in texts]
        pad_rows = _bucket(len(texts), minimum=8) - len(texts)
        token_lists += [[]] * pad_rows
        tokens, valid = self._left_pad_batch(token_lists)
        width = int(tokens.shape[1])
        self.instruments.record_padding(
            "embed", len(token_lists), width,
            sum(min(len(t), width) for t in token_lists[: len(texts)]),
        )
        self.instruments.record_launch("embed", (len(token_lists), width))
        hidden = self._fetch(
            _embed_forward(self.params, self.config, tokens, valid)
        )[: len(texts)]
        norms = np.linalg.norm(hidden, axis=1, keepdims=True)
        return hidden / np.maximum(norms, 1e-12)


@functools.partial(jax.jit, static_argnames=("config",))
def _embed_forward(params, config: ModelConfig, tokens, valid):
    """Masked mean-pool of final hidden states -> (B, D) float32."""
    positions = jnp.maximum(jnp.cumsum(valid.astype(jnp.int32), axis=1) - 1, 0)
    hidden, _ = forward(params, config, tokens, positions, valid, return_hidden=True)
    mask = valid[..., None].astype(jnp.float32)
    pooled = (hidden.astype(jnp.float32) * mask).sum(1) / jnp.maximum(
        mask.sum(1), 1.0
    )
    return pooled


#: Page size of the multi-token decode stream's private pool.  16 keeps the
#: per-cohort page count fine-grained enough that short requests don't
#: strand KV while staying a multiple of common TPU sublane tiles.
_STREAM_PAGE_SIZE = 16
#: Fixed prefill chunk width — ONE prefill program per (rows, pages) shape
#: instead of one per prompt-length bucket.
_STREAM_PREFILL_CHUNK = 64


@functools.partial(jax.jit, static_argnames=("config",))
def _stream_logits(params, config: ModelConfig, hidden):
    from consensus_tpu.models.transformer import project_logits

    return project_logits(params, config, hidden)


class _PagedGenerateStream:
    """One generate cohort served as K-step decode windows.

    Construction prefills every prompt into a PRIVATE page pool (contiguous
    block tables, fixed-width chunks) and projects the first sampling
    logits.  After that the protocol is the engine's stream seam:

    - ``dispatch()`` enqueues ONE ``paged_decode_steps`` window and returns
      without fetching anything — under jax async dispatch the host gets
      control back while the device runs, so the engine overlaps its
      sweep/admit/prefill phases with decode.
    - ``collect()`` fetches the pending window's small host-facing arrays
      (tokens / emitted / done / hit_eos), extends per-row ids, and returns
      ``(per_row_token_counts, {row: GenerationResult})`` for rows that
      froze inside the window, finalized with the exact
      ``_finish_generation`` semantics (max_tokens truncation, stop
      strings, token accounting).
    - ``finished`` / ``close()`` manage drain and teardown.

    Sampling state (keys, budgets, presence) comes from the SAME
    ``_prep_generation_rows`` the dense paths use, and the in-scan sampler
    replays the sequential key-split schedule — so emitted tokens match the
    dense paths for any ``decode_steps``, up to paged-vs-dense forward
    numerics.  Rows/pages/blocks are bucketed so cohort shape variety maps
    to a small reused program set.
    """

    def __init__(
        self,
        backend: "TPUBackend",
        requests: List[GenerationRequest],
        decode_steps: int,
        speculative: bool = False,
    ):
        from consensus_tpu.models import stepper
        from consensus_tpu.models.generate import _prompt_presence

        self._stepper = stepper
        be = backend
        self.backend = be
        self.requests = requests
        self.decode_steps = max(1, int(decode_steps))
        self.speculative = bool(speculative)
        self._mesh = be.mesh_plan.mesh if be.mesh_plan is not None else None
        self._pending = None
        self._closed = False
        self._finished_rows: set = set()
        self._results: Dict[int, GenerationResult] = {}

        be.call_counts["generate"] += len(requests)
        tok = be.tokenizer
        prompt_ids = [
            tok.encode(be._render_prompt(r), add_bos=True)[-be.max_context :]
            for r in requests
        ]
        (target, pad_rows, temperatures, bias_table, bias_index, keys,
         eos_ids, rep_penalty) = be._prep_generation_rows(
            requests, allowed=_bucket(len(requests), minimum=8)
        )
        self._n_rows = len(requests)
        self._ids: List[List[int]] = [[] for _ in requests]

        # Contiguous block tables over a bucketed private pool: each row
        # reserves ceil((prompt + max_tokens) / page) pages AT DISPATCH TIME
        # — every page the in-scan cursor can reach exists before the first
        # window runs.  The eos-check token never needs one (sink).
        ps = _STREAM_PAGE_SIZE
        pages_per = [
            -(-(len(ids) + r.max_tokens) // ps)
            for ids, r in zip(prompt_ids, requests)
        ] + [1] * pad_rows
        max_blocks = _bucket(max(pages_per), minimum=8)
        num_pages = min(
            _width_bucket(sum(pages_per), minimum=16),
            target * max_blocks,
        )
        tables = np.full((target, max_blocks), -1, np.int32)
        off = 0
        for row, n in enumerate(pages_per):
            tables[row, :n] = np.arange(off, off + n)
            off += n
        be.instruments.record_launch(
            "generate_stream",
            (target, num_pages, max_blocks, self.decode_steps),
        )

        state = stepper.make_page_state(
            be.config, num_pages, ps,
            dtype=jnp.dtype(be.params["embed"].dtype), mesh=self._mesh,
        )
        sink = num_pages
        tables_j = jnp.asarray(tables)

        # Fixed-width chunked prefill; per-row final-prompt hidden is
        # accumulated with a last-chunk mask so ragged prompts share the
        # same program.
        chunk = _STREAM_PREFILL_CHUNK
        maxlen = max(len(ids) for ids in prompt_ids)
        lengths = np.zeros(target, np.int32)
        final_hidden = None
        for start in range(0, maxlen, chunk):
            ctok = np.zeros((target, chunk), np.int32)
            cval = np.zeros((target, chunk), bool)
            wp = np.full((target, chunk), sink, np.int32)
            wo = np.zeros((target, chunk), np.int32)
            is_last = np.zeros(target, bool)
            for row, ids in enumerate(prompt_ids):
                piece = ids[start : start + chunk]
                if not piece:
                    continue
                ctok[row, : len(piece)] = piece
                cval[row, : len(piece)] = True
                pos = start + np.arange(len(piece))
                wp[row, : len(piece)] = tables[row, pos // ps]
                wo[row, : len(piece)] = pos % ps
                lengths[row] = start + len(piece)
                is_last[row] = start + len(piece) >= len(ids)
            hid, state = stepper.paged_prefill_chunk(
                be.params, be.config, *be._place_batch(ctok, cval), state,
                tables_j, jnp.asarray(lengths),
                *be._place_batch(wp, wo), mesh=self._mesh,
            )
            mask = jnp.asarray(is_last)[:, None]
            final_hidden = (
                jnp.where(mask, hid, final_hidden)
                if final_hidden is not None
                else hid
            )
        be.instruments.record_padding(
            "generate_trunk", target, -(-maxlen // chunk) * chunk,
            int(sum(len(ids) for ids in prompt_ids)),
        )

        self._logits = _stream_logits(
            be.params, be.config, final_hidden.astype(jnp.float32)
        )
        self._state = state
        self._tables = tables_j
        self._lengths = jnp.asarray(lengths)
        self._keys = keys
        # Bucket-pad rows start done with budget 0: they sample pad ids into
        # the sink forever and never show up in collect().
        row_pad = np.zeros(target, bool)
        row_pad[len(requests) :] = True
        self._done = jnp.asarray(row_pad)
        self._budgets = jnp.asarray(
            [r.max_tokens for r in requests] + [0] * pad_rows, jnp.int32
        )
        self._hit_eos = jnp.zeros(target, bool)
        self._temperatures = temperatures
        self._eos_ids = jnp.asarray(eos_ids, jnp.int32)
        self._bias_table = bias_table
        self._bias_index = bias_index
        self._rep_penalty = rep_penalty
        if rep_penalty is not None:
            width = max(maxlen, 1)
            ptok = np.full((target, width), tok.pad_id, np.int32)
            pval = np.zeros((target, width), bool)
            for row, ids in enumerate(prompt_ids):
                ptok[row, width - len(ids) :] = ids
                pval[row, width - len(ids) :] = True
            self._presence = _prompt_presence(
                jnp.asarray(ptok), jnp.asarray(pval), be.config.vocab_size
            )
        else:
            self._presence = None

        #: Cumulative draft accounting the engine reads after collect().
        self.spec_proposed = 0
        self.spec_accepted = 0
        if self.speculative:
            from consensus_tpu.backends.speculative import NGramProposer

            # One n-gram self-draft table per row, seeded from the row's
            # OWN prompt; emitted tokens feed it at collect() — the
            # lookup-decoding seam generate traffic was missing.
            self._proposers = [NGramProposer() for _ in requests]
            self._ctx: List[List[int]] = []
            for proposer, ids in zip(self._proposers, prompt_ids):
                proposer.observe(ids)
                self._ctx.append(list(ids))
            self._target = target
            self._pending_tok = jnp.zeros(target, jnp.int32)
            self._has_pending = False
            reg = be.instruments.registry
            self._obs_spec_proposed = reg.counter(
                "spec_draft_proposed_tokens_total",
                "Draft tokens proposed for speculative rollout verification",
                ("backend",),
            ).labels(be.name)
            self._obs_spec_verified = reg.counter(
                "spec_draft_verified_tokens_total",
                "Draft tokens accepted by the parallel verify pass",
                ("backend",),
            ).labels(be.name)

    @property
    def finished(self) -> bool:
        return self._closed or len(self._finished_rows) >= self._n_rows

    def dispatch(self) -> None:
        """Enqueue one K-step window.  Returns without fetching — the
        device arrays stay in flight until ``collect()``."""
        if self._closed or self._pending is not None or self.finished:
            return
        if self.speculative:
            self._dispatch_verify()
            return
        (tokens, emitted, self._logits, self._state, self._lengths,
         self._keys, self._done, self._budgets, self._hit_eos,
         self._presence) = self._stepper.paged_decode_steps(
            self.backend.params, self.backend.config, self._logits,
            self._state, self._tables, self._lengths, self._keys,
            self._done, self._budgets, self._hit_eos,
            temperature=self._temperatures, eos_ids=self._eos_ids,
            num_steps=self.decode_steps,
            bias_table=self._bias_table, bias_index=self._bias_index,
            pad_id=self.backend.tokenizer.pad_id,
            presence=self._presence, rep_penalty=self._rep_penalty,
            mesh=self._mesh,
        )
        self._pending = (tokens, emitted, None, self._done, self._hit_eos)

    def _dispatch_verify(self) -> None:
        """Speculative window: draft K tokens per live row on the host,
        verify them in ONE ``paged_verify_steps`` dispatch.  The drafts
        ride the same async-dispatch seam — drafting happens between
        collect() and dispatch(), so the double-buffer overlap of the
        plain stream is preserved."""
        k = self.decode_steps
        drafts = np.zeros((self._target, k), np.int32)
        live = 0
        for row in range(self._n_rows):
            if row in self._finished_rows:
                continue
            drafts[row] = self._proposers[row].draft(self._ctx[row], k)
            live += 1
        self.spec_proposed += live * k
        self._obs_spec_proposed.inc(live * k)
        (tokens, emitted, accepted, self._pending_tok, self._state,
         self._lengths, self._keys, self._done, self._budgets,
         self._hit_eos, self._presence) = self._stepper.paged_verify_steps(
            self.backend.params, self.backend.config, self._logits,
            self._state, self._tables, self._lengths, self._keys,
            self._done, self._budgets, self._hit_eos,
            temperature=self._temperatures,
            draft_tokens=jnp.asarray(drafts), pending=self._pending_tok,
            eos_ids=self._eos_ids, num_steps=k,
            bias_table=self._bias_table, bias_index=self._bias_index,
            pad_id=self.backend.tokenizer.pad_id,
            presence=self._presence, rep_penalty=self._rep_penalty,
            has_pending=self._has_pending, mesh=self._mesh,
        )
        # The carried prefill logits are consumed by the FIRST window;
        # every later first-decision sample re-derives its logits from the
        # pending column's hidden on device.
        self._logits = None
        self._has_pending = True
        self._pending = (tokens, emitted, accepted, self._done,
                         self._hit_eos)

    def collect(self) -> Tuple[List[int], Dict[int, GenerationResult]]:
        """Block on the pending window; return (per-row emitted counts,
        {row: result}) for rows that froze inside it."""
        if self._pending is None:
            raise RuntimeError("collect() before dispatch()")
        be = self.backend
        tokens, emitted, accepted = self._pending[:3]
        if accepted is None:
            tokens, emitted, done, hit = be._fetch(*self._pending[:2],
                                                   *self._pending[3:])
        else:
            tokens, emitted, accepted, done, hit = be._fetch(*self._pending)
        self._pending = None
        row_tokens = [0] * self._n_rows
        newly_finished: Dict[int, GenerationResult] = {}
        for row in range(self._n_rows):
            if row in self._finished_rows:
                continue
            ids = [int(t) for t, e in zip(tokens[row], emitted[row]) if e]
            self._ids[row].extend(ids)
            row_tokens[row] = len(ids)
            if accepted is not None and ids:
                self._proposers[row].observe(ids)
                self._ctx[row].extend(ids)
            if bool(done[row]):
                self._finished_rows.add(row)
                result = self._finish_row(row, bool(hit[row]))
                self._results[row] = result
                newly_finished[row] = result
        if accepted is not None:
            window_accepted = int(
                sum(int(accepted[row]) for row in range(self._n_rows))
            )
            self.spec_accepted += window_accepted
            self._obs_spec_verified.inc(window_accepted)
        if self.finished:
            be.instruments.record_padding(
                "generate_decode", self._n_rows,
                max((r.max_tokens for r in self.requests), default=0),
                sum(len(ids) for ids in self._ids),
            )
        return row_tokens, newly_finished

    def _finish_row(self, row: int, hit_eos: bool) -> GenerationResult:
        """Per-row ``_finish_generation``: same truncation, stop-string,
        finish-reason, and token-accounting semantics."""
        be = self.backend
        request = self.requests[row]
        emitted = len(self._ids[row])
        ids = self._ids[row][: request.max_tokens]
        text = be.tokenizer.decode(ids)
        finish = (
            "stop" if (hit_eos and emitted <= request.max_tokens) else "length"
        )
        truncated = False
        if not be.pin_generation_budget:
            for stop in request.stop:
                idx = text.find(stop)
                if idx >= 0:
                    text = text[:idx]
                    finish = "stop"
                    truncated = True
        if truncated:
            ids = be.tokenizer.encode(text)
        be.token_counts["generated"] += len(ids)
        return GenerationResult(
            text=text, token_ids=tuple(ids), finish_reason=finish
        )

    def results(self) -> List[GenerationResult]:
        """All results in request order (valid once ``finished``)."""
        return [self._results[row] for row in range(self._n_rows)]

    def close(self) -> None:
        self._closed = True
        self._pending = None
        self._state = None
        self._logits = None
        if self.speculative:
            self._pending_tok = None


class TPUTokenSearchSession:
    """Incremental token search over persistent per-(slot x role) KV caches.

    Rows are beam-major: slot b occupies rows [b*(1+A), (b+1)*(1+A)) with
    role 0 = reference policy and roles 1..A = agent policies.  Each
    ``advance_and_propose`` is ONE fused device call (models/stepper.py):
    gather surviving parents' cache rows, append the chosen token id,
    forward one position, Gumbel-top-k the reference rows, and gather the
    proposal ids from the agents' log-softmax — O(T) total model work where
    the full-prefix data flow is O(T^2).

    State is token *ids* (the true token-level-MDP state); the decoded
    strings in returned candidates are for host-side semantics (EOS sets,
    dedup, display).
    """

    def __init__(self, backend: "TPUBackend", spec):
        self.backend = backend
        self.spec = spec
        tok = backend.tokenizer
        prefixes = [tok.raw_prompt(spec.ref_user, spec.ref_system)] + [
            tok.raw_prompt(a_user, a_system)
            for a_system, a_user in spec.agent_prompts
        ]
        token_lists = [tok.encode(p, add_bos=True) for p in prefixes]
        max_prefix = backend.max_context - spec.max_steps
        if max_prefix < 16:
            # A negative/zero budget would flip the slice below into keeping
            # the WRONG end (and silently lose the generation-slot reserve).
            raise ValueError(
                f"max_steps={spec.max_steps} leaves no prefix room inside "
                f"max_context={backend.max_context}"
            )
        token_lists = [ids[-max_prefix:] for ids in token_lists]
        self._tokens, self._valid = backend._left_pad_batch(token_lists)
        self._w0 = int(self._tokens.shape[1])
        self.n_roles = len(prefixes)
        c = backend.config
        n_rows = spec.n_slots * self.n_roles
        itemsize = jnp.dtype(backend.params["embed"].dtype).itemsize
        # Trunk once per role + per-(slot x role) tails — the prefix is
        # SHARED, never replicated per slot (models/stepper.py).  Per-chip
        # bytes (caches shard with the weights under tensor parallelism).
        # Trunk sessions (n_slots=1) reserve 2x: every tree expansion and
        # rollout materializes one transient trunk+tail scratch copy
        # (stepper._scratch_cache).
        cache_bytes = (
            2 * c.n_layers
            * (self.n_roles * self._w0 + n_rows * spec.max_steps)
            * c.n_kv_heads * c.head_dim * itemsize
        ) // backend._shard_count
        if spec.n_slots == 1:
            cache_bytes *= 2
        # Compare against the backend's LIVE budget (HBM minus weights and
        # activation reserve) — a session bigger than the whole budget would
        # otherwise block in acquire() forever.
        if cache_bytes > backend._session_budget.cap:
            from consensus_tpu.backends.session import FusedSessionUnavailable

            logger.warning(
                "fused session unavailable: %d-row x %d-wide cache "
                "(~%.1f GB) over the %.1f GB session budget",
                n_rows, self._w0 + spec.max_steps, cache_bytes / 1e9,
                backend._session_budget.cap / 1e9,
            )
            raise FusedSessionUnavailable(
                f"{n_rows}-row x {self._w0 + spec.max_steps}-wide session "
                f"cache (~{cache_bytes / 1e9:.1f} GB) over budget"
            )
        # Reserve HBM for the lifetime of the session (blocks while other
        # threads' sessions hold the budget); close() releases it.  The
        # reservation is recorded only AFTER acquire succeeds: an exception
        # inside a blocked acquire must not let __del__ release bytes that
        # were never granted.
        backend._session_budget.acquire(cache_bytes)
        self._budget_bytes = cache_bytes
        self._step = 0
        self._state = None
        #: Fused device programs launched by this session (each one is one
        #: host->device round trip over the tunneled relay).  Decoders read
        #: the delta per statement for the obs dispatch counters.
        self.dispatch_count = 0
        bias = backend._bias_vector(spec.bias_against_tokens, spec.bias_value)
        self._ref_bias = jnp.asarray(bias) if bias is not None else None
        # One base key per session; per-(step, slot) keys fold in-device so a
        # step ships no key material.  Unseeded sessions draw a fresh nonce
        # (each session serves exactly one statement).
        if spec.seed is None:
            with backend._nonce_lock:
                backend._unseeded_calls += 1
                nonce = backend._unseeded_calls
            self._base_key = backend._fold_seed(
                "search", "unseeded", nonce
            )
        else:
            self._base_key = backend._fold_seed("search", spec.seed)
        self._temperature = jnp.asarray(spec.temperature, jnp.float32)
        #: Speculative rollout verification (backends/speculative.py +
        #: models/stepper.rollout_verify_many): an n-gram self-draft
        #: proposer seeded from the reference prompt + trunk advances.
        self._proposer = None
        if getattr(spec, "speculative", False):
            from consensus_tpu.backends.speculative import NGramProposer

            self._proposer = NGramProposer()
            self._proposer.observe(token_lists[0])
            #: Trunk token ids (ref-role prompt + advances) — the drafting
            #: context every rollout continues from.
            self._trunk_ids = list(token_lists[0])
            reg = backend.instruments.registry
            label = backend.name
            self._obs_spec_proposed = reg.counter(
                "spec_draft_proposed_tokens_total",
                "Draft tokens proposed for speculative rollout verification",
                ("backend",),
            ).labels(label)
            self._obs_spec_verified = reg.counter(
                "spec_draft_verified_tokens_total",
                "Draft tokens accepted by the parallel verify pass",
                ("backend",),
            ).labels(label)

    # -- protocol ------------------------------------------------------------

    def propose(self) -> List[List["ScoredCandidate"]]:
        from consensus_tpu.models.stepper import search_prefill

        self._check_open()
        spec = self.spec
        # k candidates x (n_roles - 1) agent evaluations per slot.
        self.backend.token_counts["scored"] += (
            spec.n_slots * spec.k * (self.n_roles - 1)
        )
        self.dispatch_count += 1
        out = search_prefill(
            self.backend.params, self.backend.config,
            self._tokens, self._valid,
            spec.n_slots, self.n_roles,
            self._base_key, self._temperature,
            spec.k, spec.sample, spec.max_steps,
            ref_bias=self._ref_bias,
        )
        return self._finish(out)

    def advance_and_propose(
        self, parents: Sequence[int], chosen: Sequence
    ) -> List[List["ScoredCandidate"]]:
        from consensus_tpu.models.stepper import search_step

        self._check_open()
        spec = self.spec
        if len(parents) != spec.n_slots or len(chosen) != spec.n_slots:
            raise ValueError(
                f"expected {spec.n_slots} (parent, token) pairs, got "
                f"{len(parents)}/{len(chosen)}"
            )
        if self._step >= spec.max_steps:
            raise ValueError(f"session exhausted its {spec.max_steps} steps")
        self._step += 1
        self.backend.token_counts["generated"] += spec.n_slots
        self.backend.token_counts["scored"] += (
            spec.n_slots * spec.k * (self.n_roles - 1)
        )
        # One packed H2D array and one packed D2H fetch per step: every
        # host<->device round-trip rides a tunneled relay (~90 ms RTT), so
        # scalar-by-scalar shipping would dominate the whole search.
        advance = np.stack(
            [
                np.asarray(list(parents), np.int32),
                np.asarray([c.token_id for c in chosen], np.int32),
            ]
        )
        step_meta = np.asarray([self._step, self._step - 1], np.int32)
        if self._proposer is not None:
            self._proposer.observe([c.token_id for c in chosen])
            self._trunk_ids.extend(c.token_id for c in chosen)
        self.dispatch_count += 1
        out = search_step(
            self.backend.params, self.backend.config,
            self._state,
            jnp.asarray(advance), jnp.asarray(step_meta),
            spec.n_slots, self.n_roles,
            self._base_key, self._temperature,
            spec.k, spec.sample,
            ref_bias=self._ref_bias,
        )
        return self._finish(out)

    def propose_suffixes(
        self, suffixes: Sequence[Sequence], salt: int
    ) -> List[List["ScoredCandidate"]]:
        """Propose + score k candidates for each tree path (a suffix of
        candidates hanging off the trunk), sharing the trunk cache across
        all paths (models/stepper.py:suffix_propose).  Trunk sessions only
        (n_slots == 1); the trunk itself advances via advance_and_propose."""
        self._check_open()
        spec = self.spec
        if spec.n_slots != 1:
            raise ValueError("propose_suffixes requires an n_slots=1 session")
        if self._state is None:
            raise ValueError("call propose() before propose_suffixes()")
        if not suffixes:
            return []
        if any(len(s) == 0 for s in suffixes):
            raise ValueError("suffixes must be non-empty")
        # The fused kernel wants one uniform suffix length per call (the
        # shared-prefill shapes are static) — mixed-length callers (wave
        # MCTS selects leaves at different depths) are grouped by span,
        # one device call per distinct span, results re-ordered.
        groups: Dict[int, List[int]] = {}
        for i, suffix in enumerate(suffixes):
            groups.setdefault(len(suffix), []).append(i)
        multi = len(groups) > 1
        results: List[Optional[List["ScoredCandidate"]]] = [None] * len(suffixes)
        for span, idxs in groups.items():
            # Single-span calls keep the caller's salt verbatim (the only
            # historically legal shape — existing PRNG streams must not
            # move).  With several spans, each group folds its span into
            # the salt so no two groups replay identical per-row keys.
            group_salt = (salt ^ (span << 20)) if multi else salt
            rows = self._propose_suffix_group(
                [suffixes[i] for i in idxs], span, group_salt
            )
            for i, row in zip(idxs, rows):
                results[i] = row
        return results

    def _propose_suffix_group(
        self, suffixes: Sequence[Sequence], span: int, salt: int
    ) -> List[List["ScoredCandidate"]]:
        """One fused suffix_propose call over equal-length suffixes."""
        from consensus_tpu.models.stepper import suffix_propose

        spec = self.spec
        # Pad the path count to a bucket (repeating row 0) so XLA reuses a
        # small set of compiled (P, L) shapes across tree levels.
        # Each path re-evaluates its span under every agent and proposes k
        # scored candidates.
        self.backend.token_counts["scored"] += (
            len(suffixes) * (span + spec.k) * (self.n_roles - 1)
        )
        n_paths = _bucket(len(suffixes), minimum=4)
        tokens = np.zeros((n_paths, span), np.int32)
        for i, suffix in enumerate(suffixes):
            tokens[i] = [c.token_id for c in suffix]
        tokens[len(suffixes):] = tokens[0]

        self.dispatch_count += 1
        packed = np.asarray(
            suffix_propose(
                self.backend.params, self.backend.config,
                self._state, jnp.asarray(self._step, jnp.int32),
                jnp.asarray(tokens), jnp.asarray(salt, jnp.int32),
                self.n_roles, self._base_key, self._temperature,
                spec.k, spec.sample,
                ref_bias=self._ref_bias,
            )
        )[: len(suffixes)]
        return self._unpack(packed)

    def rollout_from(
        self, suffix: Sequence, depth: int, salt: int
    ) -> Tuple[List[int], str, List[float], bool]:
        """Continue ``depth`` reference-policy tokens past trunk+suffix and
        return (rollout token ids, rollout text, per-agent total logprob of
        the rollout tokens, ok) — the MCTS rollout + evaluation as ONE
        device call (models/stepper.py:rollout_scored).  Trunk sessions
        only.  The ids are authoritative (arbitrary sampled bytes need not
        survive a decode/encode round trip); the text is for display."""
        from consensus_tpu.models.stepper import rollout_scored

        self._check_open()
        spec = self.spec
        if spec.n_slots != 1:
            raise ValueError("rollout_from requires an n_slots=1 session")
        if self._state is None:
            raise ValueError("call propose() before rollout_from()")
        if not suffix:
            raise ValueError("rollout_from needs a non-empty suffix")
        self.dispatch_count += 1
        rows = np.asarray(
            rollout_scored(
                self.backend.params, self.backend.config,
                self._state, jnp.asarray(self._step, jnp.int32),
                jnp.asarray([c.token_id for c in suffix], jnp.int32),
                jnp.asarray(salt, jnp.int32),
                self.n_roles, len(suffix), depth,
                self._base_key, self._temperature,
                jnp.asarray(self.backend.tokenizer.eos_ids, jnp.int32),
            )
        )  # (depth, 2 + A)
        return self._rollout_result(rows, depth)

    def rollout_many(
        self, suffixes: Sequence[Sequence], depth: int, salts: Sequence[int]
    ) -> List[Tuple[List[int], str, List[float], bool]]:
        """Batched :meth:`rollout_from` over a wave of tree paths.  Paths
        are grouped by suffix length (the fused kernel's shared-prefill
        shapes are static per span); a singleton group delegates to
        ``rollout_from`` — bit-identical to the sequential path — while a
        multi-path group runs ONE ``rollout_scored_many`` program per HBM
        chunk (the wave width is capped by :meth:`_rollout_chunk_cap` so
        the per-(path x role) decode tails stay inside the session's
        reservation slack)."""
        from consensus_tpu.models.stepper import rollout_scored_many

        self._check_open()
        spec = self.spec
        if spec.n_slots != 1:
            raise ValueError("rollout_many requires an n_slots=1 session")
        if self._state is None:
            raise ValueError("call propose() before rollout_many()")
        if len(salts) != len(suffixes):
            raise ValueError(
                f"expected {len(suffixes)} salts, got {len(salts)}"
            )
        if not suffixes:
            return []
        if any(not s for s in suffixes):
            raise ValueError("rollout_many needs non-empty suffixes")
        if self._proposer is not None:
            return self._rollout_many_spec(suffixes, depth, salts)
        groups: Dict[int, List[int]] = {}
        for i, suffix in enumerate(suffixes):
            groups.setdefault(len(suffix), []).append(i)
        results: List[Optional[Tuple[List[int], str, List[float], bool]]] = (
            [None] * len(suffixes)
        )
        for span, idxs in groups.items():
            cap = self._rollout_chunk_cap(span, depth)
            for lo in range(0, len(idxs), cap):
                chunk = idxs[lo : lo + cap]
                if len(chunk) == 1:
                    i = chunk[0]
                    results[i] = self.rollout_from(
                        suffixes[i], depth, salts[i]
                    )
                    continue
                # Bucket the path count (padding repeats row 0 with its own
                # salt — identical compute, sliced away) for shape reuse.
                n_paths = _bucket(len(chunk), minimum=2)
                tokens = np.zeros((n_paths, span), np.int32)
                salt_arr = np.zeros((n_paths,), np.int32)
                for j, i in enumerate(chunk):
                    tokens[j] = [c.token_id for c in suffixes[i]]
                    salt_arr[j] = salts[i]
                tokens[len(chunk):] = tokens[0]
                salt_arr[len(chunk):] = salt_arr[0]
                self.dispatch_count += 1
                rows = np.asarray(
                    rollout_scored_many(
                        self.backend.params, self.backend.config,
                        self._state, jnp.asarray(self._step, jnp.int32),
                        jnp.asarray(tokens), jnp.asarray(salt_arr),
                        self.n_roles, span, depth,
                        self._base_key, self._temperature,
                        jnp.asarray(
                            self.backend.tokenizer.eos_ids, jnp.int32
                        ),
                    )
                )  # (n_paths, depth, 2 + A)
                for j, i in enumerate(chunk):
                    results[i] = self._rollout_result(rows[j], depth)
        return results

    def _rollout_many_spec(
        self, suffixes: Sequence[Sequence], depth: int, salts: Sequence[int]
    ) -> List[Tuple[List[int], str, List[float], bool]]:
        """Speculative rollout_many: draft each path's whole remaining
        rollout from the n-gram proposer and verify it in ONE parallel
        ``rollout_verify_many`` forward per round (all active paths ride
        the same dispatch).  Each round accepts every path's longest
        draft-matched prefix plus the first corrected token — standard
        rejection, so accepted token streams replay the sequential scan
        exactly, with agent totals agreeing to float tolerance (pinned in
        tests/test_speculative.py) — and a perfect draft finishes a
        depth-``d`` rollout in one round instead of ``d`` sequential
        decode steps."""
        from consensus_tpu.models.stepper import rollout_verify_many

        spec = self.spec
        results: List[Optional[Tuple[List[int], str, List[float], bool]]] = (
            [None] * len(suffixes)
        )
        groups: Dict[int, List[int]] = {}
        for i, suffix in enumerate(suffixes):
            groups.setdefault(len(suffix), []).append(i)
        n_agents = self.n_roles - 1
        for span, idxs in groups.items():
            cap = max(1, self._rollout_chunk_cap(span, depth))
            for lo in range(0, len(idxs), cap):
                chunk = idxs[lo : lo + cap]
                #: Per path: accepted rows [(token, counted, lps...)], and
                #: whether an EOS ended the counted stream.
                emitted: Dict[int, List[List[float]]] = {i: [] for i in chunk}
                finished: Dict[int, bool] = {i: False for i in chunk}
                contexts = {
                    i: self._trunk_ids + [c.token_id for c in suffixes[i]]
                    for i in chunk
                }
                while True:
                    active = [
                        i for i in chunk
                        if not finished[i] and len(emitted[i]) < depth
                    ]
                    if not active:
                        break
                    drafts: Dict[int, List[int]] = {}
                    for i in active:
                        accepted = [int(r[0]) for r in emitted[i]]
                        fresh = self._proposer.draft(
                            contexts[i] + accepted, depth - len(accepted)
                        )
                        self._obs_spec_proposed.inc(len(fresh))
                        drafts[i] = accepted + fresh
                    n_paths = _bucket(len(active), minimum=2)
                    tokens = np.zeros((n_paths, span), np.int32)
                    draft_arr = np.zeros((n_paths, depth), np.int32)
                    salt_arr = np.zeros((n_paths,), np.int32)
                    for j, i in enumerate(active):
                        tokens[j] = [c.token_id for c in suffixes[i]]
                        draft_arr[j] = drafts[i]
                        salt_arr[j] = salts[i]
                    tokens[len(active):] = tokens[0]
                    draft_arr[len(active):] = draft_arr[0]
                    salt_arr[len(active):] = salt_arr[0]
                    self.dispatch_count += 1
                    rows = np.asarray(
                        rollout_verify_many(
                            self.backend.params, self.backend.config,
                            self._state, jnp.asarray(self._step, jnp.int32),
                            jnp.asarray(tokens), jnp.asarray(draft_arr),
                            jnp.asarray(salt_arr),
                            self.n_roles, span, depth,
                            self._base_key, self._temperature,
                            jnp.asarray(
                                self.backend.tokenizer.eos_ids, jnp.int32
                            ),
                        )
                    )  # (n_paths, depth, 2 + A)
                    for j, i in enumerate(active):
                        t = len(emitted[i])
                        while t < depth:
                            chosen = int(rows[j, t, 0])
                            is_eos = rows[j, t, 1] > 0.5
                            counted = 0.0 if is_eos else 1.0
                            emitted[i].append(
                                [float(chosen), counted]
                                + [
                                    float(v) * counted
                                    for v in rows[j, t, 2:]
                                ]
                            )
                            matched = chosen == int(drafts[i][t])
                            if matched:
                                self._obs_spec_verified.inc()
                            t += 1
                            if is_eos:
                                # Post-EOS tokens are uncounted in the
                                # sequential scan and filtered from the
                                # result — stop generating them at all.
                                finished[i] = True
                                break
                            if not matched:
                                # chosen is the valid correction; rows past
                                # it were conditioned on the wrong draft.
                                break
                for i in chunk:
                    out = np.zeros((depth, 2 + n_agents), np.float32)
                    if emitted[i]:
                        got = np.asarray(emitted[i], np.float32)
                        out[: got.shape[0]] = got
                    results[i] = self._rollout_result(out, depth)
        return results

    def _rollout_chunk_cap(self, span: int, depth: int) -> int:
        """How many wave paths one rollout_scored_many call may carry: each
        path adds a (n_layers x n_roles x (span + depth)) decode tail on
        top of the scratch trunk copy, and the session's 2x reservation
        (constructor) only pre-books the scratch — cap the tails at 1/8 of
        the reservation so a wide wave degrades into chunks instead of
        blowing the budget."""
        c = self.backend.config
        itemsize = jnp.dtype(self.backend.params["embed"].dtype).itemsize
        per_path = (
            2 * c.n_layers * self.n_roles * (span + depth)
            * c.n_kv_heads * c.head_dim * itemsize
        ) // self.backend._shard_count
        allowance = self._budget_bytes // 8
        return max(1, int(allowance // max(per_path, 1)))

    def _rollout_result(
        self, rows: np.ndarray, depth: int
    ) -> Tuple[List[int], str, List[float], bool]:
        """Unpack one path's (depth, 2 + A) rollout rows + token accounting."""
        counted = rows[:, 1] > 0.5
        tok = self.backend.tokenizer
        ids = [int(rows[t, 0]) for t in range(depth) if counted[t]]
        self.backend.token_counts["generated"] += len(ids)
        self.backend.token_counts["scored"] += len(ids) * (self.n_roles - 1)
        text = "".join(tok.token_str(i) for i in ids)
        totals = [float(v) for v in rows[counted, 2:].sum(axis=0)]
        return ids, text, totals, True

    def close(self) -> None:
        """Drop the device caches and release the session's HBM reservation.
        Idempotent; also runs at garbage collection as a safety net."""
        # getattr: the constructor may raise before the reservation exists,
        # and __del__ still runs.
        if getattr(self, "_budget_bytes", 0):
            self._state = None
            self.backend._session_budget.release(self._budget_bytes)
            self._budget_bytes = 0

    def __del__(self):
        self.close()

    # -- internals -----------------------------------------------------------

    def _check_open(self) -> None:
        if not getattr(self, "_budget_bytes", 0):
            raise ValueError("session is closed")

    def _finish(self, out) -> List[List["ScoredCandidate"]]:
        self._state = out.state
        return self._unpack(np.asarray(out.packed))

    def _unpack(self, packed: np.ndarray) -> List[List["ScoredCandidate"]]:
        from consensus_tpu.backends.session import ScoredCandidate

        tok = self.backend.tokenizer
        results = []
        for row in range(packed.shape[0]):
            row_out = []
            for j in range(self.spec.k):
                token_id = int(packed[row, j, 0])
                row_out.append(
                    ScoredCandidate(
                        token=tok.token_str(token_id),
                        token_id=token_id,
                        ref_logprob=float(packed[row, j, 1]),
                        agent_logprobs=tuple(
                            float(v) for v in packed[row, j, 2:]
                        ),
                    )
                )
            results.append(row_out)
        return results
