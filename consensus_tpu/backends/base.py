"""Backend protocol: the single seam between decoders and model execution.

The reference funnels every model interaction through a module-global HTTP
client (``src/utils.py:69-74``) with four call shapes: chat/raw text
generation (``generate_text``, src/utils.py:77-198), prompt-span logprob
scoring (``get_prompt_logprobs``, src/utils.py:201-281), repeated 1-token
completions used as a sampler (``beam_search.py:199-333``), and embeddings
(``get_embedding``, src/utils.py:376-407).

Here those four shapes become an explicit, batch-first protocol.  Every call
takes a *sequence* of requests so a backend can execute them as one padded,
sharded device batch — the (candidates x agents) scoring loops of the
reference collapse into a single ``score()`` call.  ``next_token_logprobs``
returns the top-k of the true next-token distribution in one forward pass,
replacing the reference's rejection-sampling-via-repeated-API-calls
(beam_search.py:253-333, mcts.py:188-247) with an exact, cheaper primitive.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Protocol, Sequence, Tuple, runtime_checkable

import numpy as np

#: Logit bias value used to effectively ban tokens (reference src/utils.py:86,
#: beam_search.py:56 use -1_000_000 through the API's logit_bias map).
BAN_BIAS = -1.0e6


# -- error taxonomy ----------------------------------------------------------
#
# Raw backends raise whatever their transport raises (RuntimeError from XLA,
# TimeoutError/OSError from sockets).  The supervision layer
# (backends/supervisor.py) classifies those into this typed hierarchy so
# every caller above the backend seam — batching, the experiment harness,
# the serving scheduler — can decide retry-vs-fail-vs-isolate by type
# instead of by string matching.


class BackendError(Exception):
    """Base of the typed backend failure taxonomy (docs/ARCHITECTURE.md
    §Fault tolerance)."""


class TransientBackendError(BackendError):
    """A retryable failure (flaky dispatch, timeout, dropped connection):
    the same call MAY succeed if reissued.  Raised by the supervisor after
    its own bounded retry budget is exhausted — seeing this type means
    retrying already happened below you."""


class BackendIntegrityError(BackendError):
    """The backend returned, but the payload is poisoned (NaN/Inf logprobs,
    a deterministically-failing row).  Never retryable: the same input
    produces the same poison."""


class BackendLostError(BackendError):
    """The device/backend is gone for good (or fenced off by an open
    circuit breaker).  Not retryable within this process."""


class RequestCancelled(BackendError):
    """The caller abandoned this request before its batch dispatched
    (serving ticket cancelled / deadline passed), so the batching layer
    dropped it at the flush snapshot instead of spending device time on it.
    Not a backend failure and never retryable: the work was withdrawn, not
    lost.  Deliberately NOT in the scheduler's TRANSIENT_EXCEPTIONS — a
    cancelled request must not be resurrected by the retry loop."""


class PartialBatchError(BackendError):
    """Some rows of a batched call failed and the rest succeeded.

    ``results`` is the full-length result list (or array) with valid
    entries at surviving indices; ``row_errors`` maps failing row index →
    the typed error for that row.  ``BatchingBackend`` unpacks this so one
    poisoned row fails only the session that submitted it; direct callers
    can either treat it as a whole-call failure or pick out ``results``.
    """

    def __init__(self, message: str, results, row_errors):
        super().__init__(message)
        self.results = results
        self.row_errors = dict(row_errors)


@dataclasses.dataclass(frozen=True)
class GenerationRequest:
    """One text-generation work item.

    ``chat=True`` renders the backend's chat template (the reference's
    ``use_chat_completions=True`` path); ``chat=False`` concatenates
    ``"{system}\n\n{user}"`` exactly as the raw-completions call sites do
    (beam_search.py:231-234, mcts.py:184-186, finite_lookahead.py:310-334).
    """

    user_prompt: str
    system_prompt: Optional[str] = None
    max_tokens: int = 128
    temperature: float = 1.0
    seed: Optional[int] = None
    stop: Tuple[str, ...] = ()
    bias_against_tokens: Tuple[str, ...] = ()
    bias_value: float = BAN_BIAS
    chat: bool = True
    #: HF/Together-style repetition penalty (>1 discourages repeats; the
    #: reference forwards the same-named param, src/utils.py:88).  1.0 = off.
    repetition_penalty: float = 1.0


@dataclasses.dataclass(frozen=True)
class GenerationResult:
    text: str
    token_ids: Tuple[int, ...] = ()
    finish_reason: str = "stop"  # "stop" | "length" | "error"

    @property
    def ok(self) -> bool:
        return self.finish_reason != "error"


@dataclasses.dataclass(frozen=True)
class ScoreRequest:
    """Teacher-forced scoring of ``continuation`` given ``context``.

    The backend returns per-token logprobs for the continuation tokens only.
    This replaces the reference's echo'd-prompt span extraction
    (``extract_user_prompt_logprobs``, src/utils.py:284-373, including its
    zero-width-space marker hack) — on-device we simply tokenize context and
    continuation and gather the continuation logprobs directly
    (SURVEY §7.3 "logprob-extraction semantics").

    ``role`` selects where the continuation sits in the chat template:
    ``"assistant"`` (default) scores it as a model reply after the user
    turn; ``"user"`` scores it INSIDE the user turn with ``context`` in the
    system slot — the reference's evaluation semantics (its scorer echoes
    the statement as the *user prompt* with the eval template as system,
    src/evaluation.py:182-193).  Only meaningful with ``chat=True``.
    """

    context: str
    continuation: str
    system_prompt: Optional[str] = None
    chat: bool = True
    role: str = "assistant"  # "assistant" | "user"


@dataclasses.dataclass(frozen=True)
class ScoreResult:
    tokens: Tuple[str, ...]
    logprobs: Tuple[float, ...]

    @property
    def ok(self) -> bool:
        return len(self.logprobs) > 0

    def mean(self, default: float = -10.0) -> float:
        """Mean continuation logprob (best_of_n / finite_lookahead utility)."""
        if not self.logprobs:
            return default
        return float(np.mean(self.logprobs))

    def total(self, default: float = -10.0) -> float:
        """Summed continuation logprob (beam_search / MCTS utility)."""
        if not self.logprobs:
            return default
        return float(np.sum(self.logprobs))


@dataclasses.dataclass(frozen=True)
class NextTokenRequest:
    """Ask for k candidate next tokens after a prompt, in one forward pass.

    ``mode="topk"`` returns the exact top-k of the next-token distribution;
    ``mode="sample"`` draws k *distinct* tokens by seeded Gumbel-top-k at the
    given temperature, preserving the stochastic-search character of the
    reference's repeated 1-token sampling while staying single-forward.
    """

    user_prompt: str
    system_prompt: Optional[str] = None
    k: int = 4
    temperature: float = 1.0
    seed: Optional[int] = None
    mode: str = "sample"  # "topk" | "sample"
    bias_against_tokens: Tuple[str, ...] = ()
    bias_value: float = BAN_BIAS
    chat: bool = False


@dataclasses.dataclass(frozen=True)
class TokenCandidate:
    token: str
    token_id: int
    logprob: float


@runtime_checkable
class Backend(Protocol):
    """Batch-first model-execution protocol (see module docstring)."""

    name: str

    def generate(self, requests: Sequence[GenerationRequest]) -> List[GenerationResult]:
        ...

    def score(self, requests: Sequence[ScoreRequest]) -> List[ScoreResult]:
        ...

    def next_token_logprobs(
        self, requests: Sequence[NextTokenRequest]
    ) -> List[List[TokenCandidate]]:
        ...

    def embed(self, texts: Sequence[str]) -> np.ndarray:
        """Return an (len(texts), dim) float array of unit-normalized embeddings."""
        ...


def generate_one(backend: Backend, request: GenerationRequest) -> GenerationResult:
    return backend.generate([request])[0]


def score_one(backend: Backend, request: ScoreRequest) -> ScoreResult:
    return backend.score([request])[0]
