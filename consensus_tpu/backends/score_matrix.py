"""The (candidates x agents) utility-matrix scoring seam.

The paper's objective is intrinsically a matrix: every candidate statement
is scored under every agent's opinion context and a welfare rule reduces
the agent axis.  Before this seam existed, each (candidate, agent) cell
was a separate :class:`~consensus_tpu.backends.base.ScoreRequest` whose
full per-token logprob vector crossed D2H before host Python reduced it.
This module defines the batch-first protocol that lets backends evaluate
the whole matrix in one device program (``TPUBackend.score_matrix``) and
provides an exact host-side fallback for backends that cannot
(:func:`fallback_score_matrix_many`).

Identity contract
-----------------

The fallback builds *precisely* the per-call ``ScoreRequest`` rows that
today's consumers (best-of-N, beam sessions, the evaluator) build, issues
ONE batched ``backend.score`` call, and reduces each cell with the same
expressions the consumers used (``ScoreResult.mean``, ``sum(logprobs)``,
``logprobs[-1]``, the evaluator's float64 moments) — so switching a
consumer to the matrix seam over a fallback backend is byte-identical,
and the fused device path agrees to float tolerance with the same argmax
under pinned (numpy first-max) tie-breaking.

Per-cell statistics (``stat``):

* ``"mean"``    — ``ScoreResult.mean(default)`` (best-of-N, evaluator's
  scalar utility).
* ``"sum"``     — ``float(sum(logprobs))`` — the *sequential* Python sum
  the search sessions use for rollout returns (NOT ``np.sum``; pairwise
  summation rounds differently on long sequences).
* ``"last"``    — ``logprobs[-1]`` (token-search proposal scoring).
* ``"moments"`` — ``(mean logprob, mean prob)`` in float64, the
  evaluator's perplexity accounting; ``aux`` carries the mean prob.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from consensus_tpu.backends.base import (
    PartialBatchError,
    ScoreRequest,
    ScoreResult,
)
from consensus_tpu.obs.welfare import get_welfare_sink
from consensus_tpu.ops.welfare import (
    DEFAULT_REWARD,
    WELFARE_RULES,
    sanitize_utilities,
)

_STATS = ("mean", "sum", "last", "moments")


@dataclasses.dataclass(frozen=True)
class AgentContext:
    """One agent's scoring context — the ScoreRequest fields minus the
    continuation, so a matrix request can cross A contexts with C
    candidates without materializing C*A strings."""

    context: str
    system_prompt: Optional[str] = None
    chat: bool = True
    role: str = "assistant"

    def to_score_request(self, continuation: str) -> ScoreRequest:
        return ScoreRequest(
            context=self.context,
            continuation=continuation,
            system_prompt=self.system_prompt,
            chat=self.chat,
            role=self.role,
        )


@dataclasses.dataclass(frozen=True)
class ScoreMatrixRequest:
    """Score every candidate under every agent context in one call."""

    agents: Tuple[AgentContext, ...]
    candidates: Tuple[str, ...]
    stat: str = "mean"
    welfare_rule: str = "egalitarian"
    default: float = DEFAULT_REWARD

    def __post_init__(self) -> None:
        if self.stat not in _STATS:
            raise ValueError(f"unknown stat {self.stat!r}; want one of {_STATS}")
        if self.welfare_rule not in WELFARE_RULES:
            raise ValueError(
                f"unknown welfare rule {self.welfare_rule!r}; "
                f"want one of {tuple(WELFARE_RULES)}"
            )

    def cell_requests(self) -> List[ScoreRequest]:
        """The per-call rows this matrix replaces, in (candidate-major,
        agent-minor) order — the order every adopting consumer used."""
        return [
            agent.to_score_request(candidate)
            for candidate in self.candidates
            for agent in self.agents
        ]


@dataclasses.dataclass(frozen=True, eq=False)
class ScoreMatrixResult:
    """(C, A) utilities + the on-device welfare reduction.

    ``utilities`` is float64 on the fallback path (exact per-call floats)
    and f32 from the fused device path; consumers that historically cast
    to f32 (best-of-N) keep doing so and see identical values either way.
    ``aux`` is the second moment for ``stat="moments"`` (mean prob per
    cell), else ``None``.  ``best`` is ``int(np.argmax(welfare))`` — numpy
    first-max is the pinned tie-break.  ``d2h_bytes`` is what actually
    crossed device-to-host for this matrix (the fused path ships only the
    reductions; the fallback ships every per-token logprob and reports
    that honestly).  ``path`` is ``"fused"`` or ``"fallback"``.
    """

    utilities: np.ndarray
    welfare: np.ndarray
    best: int
    aux: Optional[np.ndarray] = None
    cells: int = 0
    d2h_bytes: int = 0
    path: str = "fallback"


def _cell_stat(result: ScoreResult, stat: str, default: float):
    """Reduce one ScoreResult with the exact host expression the per-call
    consumers used (see module docstring)."""
    if stat == "mean":
        return result.mean(default=default)
    if stat == "sum":
        return float(sum(result.logprobs)) if result.ok else default
    if stat == "last":
        return float(result.logprobs[-1]) if result.ok else default
    # moments: the evaluator's float64 accounting (empty -> (default, 0.0))
    lps = np.asarray(result.logprobs, dtype=np.float64)
    avg_lp = float(lps.mean()) if lps.size else default
    avg_p = float(np.exp(lps).mean()) if lps.size else 0.0
    return avg_lp, avg_p


def reduce_matrix(
    request: ScoreMatrixRequest, results: Sequence[ScoreResult], *, path: str
) -> ScoreMatrixResult:
    """Fold per-cell ScoreResults into a ScoreMatrixResult (fallback path)."""
    n_candidates = len(request.candidates)
    n_agents = len(request.agents)
    values: List[float] = []
    aux_values: List[float] = []
    d2h = 0
    for result in results:
        d2h += len(result.logprobs) * 8  # f64 logprobs actually shipped
        cell = _cell_stat(result, request.stat, request.default)
        if request.stat == "moments":
            values.append(cell[0])
            aux_values.append(cell[1])
        else:
            values.append(cell)
    utilities = np.asarray(values, dtype=np.float64).reshape(
        n_candidates, n_agents
    )
    aux = (
        np.asarray(aux_values, dtype=np.float64).reshape(n_candidates, n_agents)
        if request.stat == "moments"
        else None
    )
    welfare_vals, best = welfare_argmax(utilities, request.welfare_rule)
    return ScoreMatrixResult(
        utilities=utilities,
        welfare=welfare_vals,
        best=best,
        aux=aux,
        cells=n_candidates * n_agents,
        d2h_bytes=d2h,
        path=path,
    )


def welfare_argmax(utilities: np.ndarray, rule: str) -> Tuple[np.ndarray, int]:
    """sanitize -> welfare over the agent axis -> pinned first-max argmax.

    Matches best-of-N's selection statement exactly: welfare is computed
    on the f32-sanitized matrix and numpy's first-max breaks ties."""
    if utilities.size == 0:
        return np.zeros((utilities.shape[0],), dtype=np.float32), 0
    welfare_vals = np.asarray(
        WELFARE_RULES[rule](sanitize_utilities(utilities), axis=1)
    )
    return welfare_vals, int(np.argmax(welfare_vals))


# ---------------------------------------------------------------------------
# Score-row dedup (engine + legacy flush; satellite: beam search re-scores
# shared prefixes every round, and matrices repeat agent rows).


def _score_key(request: ScoreRequest):
    return (
        request.context,
        request.continuation,
        request.system_prompt,
        request.chat,
        request.role,
    )


def dedup_score_requests(
    requests: Sequence[ScoreRequest],
) -> Tuple[List[ScoreRequest], List[int]]:
    """-> (unique, mapping) with ``requests[i] == unique[mapping[i]]``.

    Model identity is per-backend (one inner model per dispatch loop), so
    the key is the full request tuple; two textually identical rows score
    identically on any deterministic backend."""
    seen: Dict[tuple, int] = {}
    unique: List[ScoreRequest] = []
    mapping: List[int] = []
    for request in requests:
        key = _score_key(request)
        index = seen.get(key)
        if index is None:
            index = len(unique)
            seen[key] = index
            unique.append(request)
        mapping.append(index)
    return unique, mapping


def expand_deduped(values: Sequence, mapping: Sequence[int]) -> List:
    return [values[j] for j in mapping]


def expand_partial_error(
    error: PartialBatchError, mapping: Sequence[int]
) -> PartialBatchError:
    """Re-shape a PartialBatchError over unique rows back to caller rows:
    every caller row sharing a failed unique row fails the same way."""
    results = (
        expand_deduped(error.results, mapping)
        if error.results is not None
        else None
    )
    row_errors = {
        i: error.row_errors[j]
        for i, j in enumerate(mapping)
        if j in error.row_errors
    }
    return PartialBatchError(
        str(error) or "partial batch failure", results, row_errors
    )


# ---------------------------------------------------------------------------
# Observability (families are idempotent by name across backends).


def matrix_metrics(registry=None):
    from consensus_tpu.obs.metrics import DEFAULT_COUNT_BUCKETS, get_registry

    reg = registry if registry is not None else get_registry()
    cells = reg.counter(
        "score_matrix_cells_total",
        "(candidate, agent) utility cells evaluated via the matrix seam",
    )
    d2h = reg.counter(
        "score_matrix_d2h_bytes_total",
        "bytes fetched device-to-host for matrix scoring results",
    )
    agents_hist = reg.histogram(
        "score_agents_per_call",
        "agent-axis width of score_matrix calls",
        buckets=DEFAULT_COUNT_BUCKETS,
    )
    return cells, d2h, agents_hist


def record_matrix(
    result: ScoreMatrixResult,
    n_agents: int,
    registry=None,
    welfare_rule: Optional[str] = None,
):
    cells, d2h, agents_hist = matrix_metrics(registry)
    cells.inc(result.cells)
    d2h.inc(result.d2h_bytes)
    agents_hist.observe(n_agents)
    # Welfare telemetry plane (PR 16): when a server installed a sink, the
    # chosen candidate's welfare + worst-agent utility feed the
    # score-path sketches.  Off (the default) this is one global read.
    sink = get_welfare_sink()
    if sink is not None:
        sink.record_matrix(result, welfare_rule)


# ---------------------------------------------------------------------------
# Fallback + dispatch.


def fallback_score_matrix_many(
    backend, requests: Sequence[ScoreMatrixRequest]
) -> List[ScoreMatrixResult]:
    """Evaluate matrices through the per-call score seam: dedup identical
    rows across ALL matrices, issue ONE batched ``backend.score`` call
    (so session dispatch accounting is unchanged vs the per-call code it
    replaces), fan results back out, and reduce with the exact host
    semantics."""
    all_rows: List[ScoreRequest] = []
    spans: List[Tuple[int, int]] = []
    for request in requests:
        rows = request.cell_requests()
        spans.append((len(all_rows), len(all_rows) + len(rows)))
        all_rows.extend(rows)
    if not all_rows:
        return [
            reduce_matrix(request, [], path="fallback") for request in requests
        ]
    unique, mapping = dedup_score_requests(all_rows)
    try:
        unique_results = backend.score(unique)
    except PartialBatchError as exc:
        raise expand_partial_error(exc, mapping) from None
    results = expand_deduped(unique_results, mapping)
    out = []
    for request, (lo, hi) in zip(requests, spans):
        matrix = reduce_matrix(request, results[lo:hi], path="fallback")
        record_matrix(
            matrix, len(request.agents), welfare_rule=request.welfare_rule
        )
        out.append(matrix)
    return out


def score_matrix_many(
    backend, requests: Sequence[ScoreMatrixRequest]
) -> List[ScoreMatrixResult]:
    """Route to ``backend.score_matrix`` when the backend has one (fused
    device path / engine seam), else the exact per-call fallback."""
    fn = getattr(backend, "score_matrix", None)
    if callable(fn):
        return list(fn(list(requests)))
    return fallback_score_matrix_many(backend, requests)
