"""Host-side draft proposal for speculative rollout verification.

Score-only rollouts (methods/finite_lookahead.py, methods/mcts.py) pay one
sequential decode step per rollout token even though the statement under
search is highly repetitive — the prompt restates the issue and opinions,
and MCTS re-rolls near-identical continuations from sibling leaves.  An
n-gram SELF-DRAFT proposer (Leviathan et al., speculative decoding;
lookup-decoding flavour: the draft model is the request's own token
history, so there is no second model to load) guesses the next
``draft_len`` tokens from the longest recent n-gram match, and the target
model verifies the whole draft in ONE parallel forward
(models/stepper.rollout_verify_many).  Standard rejection — accept the
matched prefix plus the first corrected token — keeps accepted token
streams identical to the sequential scan (totals agree to float
tolerance); a bad draft costs nothing but the width of one
already-parallel verify.

Deterministic by construction: the table is built from the observed token
stream only (insertion order resolves ties toward the MOST RECENT
occurrence), so identical requests draft identically.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple


class NGramProposer:
    """Longest-suffix n-gram table over an observed token-id stream."""

    def __init__(self, max_order: int = 3):
        self.max_order = max(1, int(max_order))
        #: Per order: suffix tuple -> next token id (latest occurrence wins).
        self._tables: List[Dict[Tuple[int, ...], int]] = [
            {} for _ in range(self.max_order)
        ]
        self._history: List[int] = []

    def observe(self, tokens: Sequence[int]) -> None:
        """Extend the history (prompt, trunk advances, accepted rollouts)."""
        for t in tokens:
            t = int(t)
            h = self._history
            for order in range(1, self.max_order + 1):
                if len(h) >= order:
                    self._tables[order - 1][tuple(h[-order:])] = t
            h.append(t)

    def _next(self, context: Sequence[int]) -> int:
        for order in range(min(self.max_order, len(context)), 0, -1):
            hit = self._tables[order - 1].get(tuple(context[-order:]))
            if hit is not None:
                return hit
        # No match anywhere: repeat the last token — a guess that is free
        # to be wrong (rejection discards it) but right surprisingly often
        # in list-ish consensus statements.
        return int(context[-1]) if context else 0

    def draft(self, context: Sequence[int], k: int) -> List[int]:
        """Propose ``k`` tokens continuing ``context`` (not yet observed
        tokens included by the caller).  Drafted tokens chain: token j is
        looked up against context + draft[:j]."""
        ctx = [int(t) for t in context]
        out: List[int] = []
        for _ in range(max(0, int(k))):
            nxt = self._next(ctx)
            out.append(nxt)
            ctx.append(nxt)
        return out
