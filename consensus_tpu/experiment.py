"""Experiment engine: seeds × methods × parameter grids → results.csv.

Reference: ``src/experiment.py`` (SURVEY §2.9).  Behaviour-compatible
artifact contract:

* timestamped run directory ``{output_dir}/{experiment_name}_{YYYYmmdd_HHMMSS}``
  with a ``config.yaml`` snapshot (reference :119-133);
* per seed ``base_seed + i`` for ``num_seeds`` (reference :224-226);
* list-valued method parameters expand to the Cartesian product of run
  configs (reference :241-267);
* each run records ``method, statement, generation_time_s, seed,
  error_message, evaluation_status="pending"`` plus ``param_*`` columns and
  ``pre_brushup_statement`` when a decoder retains one (reference :135-201);
  evaluation is deliberately post-hoc (:190-192);
* results ordered and saved to ``results.csv`` (:334-380).

Architectural change: no rate limiter, and the thread pool serves a
DIFFERENT purpose.  The reference fans method×param combos across a
``ThreadPoolExecutor`` to hide HTTP latency behind a token-bucket
``APIRateLimiter`` (:26-62, 283-322).  Here ``concurrent_execution: true``
(the same config key, default true like the reference :105-110) runs
independent (seed × method × param) combos on worker threads whose backend
calls MERGE into shared device batches via
:class:`consensus_tpu.backends.batching.BatchingBackend` — the sweep's
parallelism axis becomes device batch width (SURVEY §2.16).  Per-request
PRNG keys keep results bit-identical to sequential execution.
``api_rate_limit`` is accepted and recorded but unused on-device.
"""

from __future__ import annotations

import collections
import datetime
import hashlib
import itertools
import json
import logging
import pathlib
import time
from typing import Any, Dict, List, Optional, Tuple

import pandas as pd
import yaml

from consensus_tpu.backends import get_backend, wrap_backend
from consensus_tpu.backends.base import Backend
from consensus_tpu.methods import get_method_generator
from consensus_tpu.obs import (
    bucket_recompiles,
    diff_snapshots,
    diff_span_paths,
    get_registry,
    padding_efficiency,
)
from consensus_tpu.utils.io_atomic import (
    JournalWriter,
    atomic_write_json,
    atomic_write_text,
    read_journal,
    sanitize_frame_for_csv,
)
from consensus_tpu.utils.tracing import device_trace, get_tracer

logger = logging.getLogger(__name__)

#: Result-row column order (reference src/experiment.py:334-367).
_LEAD_COLUMNS = [
    "method",
    "statement",
    "pre_brushup_statement",
    "generation_time_s",
    "seed",
    "error_message",
    "evaluation_status",
]

#: ``on_error`` policies for a failed (method, config, seed) run.
ON_ERROR_POLICIES = ("skip", "fail", "retry")


def run_config_hash(run_config: Dict[str, Any]) -> str:
    """Stable short hash of a run config (seed excluded — the journal key
    carries the seed separately, so the same grid point across seeds shares
    one hash)."""
    payload = {k: v for k, v in run_config.items() if k != "seed"}
    blob = json.dumps(payload, sort_keys=True, default=str)
    return hashlib.blake2b(blob.encode("utf-8"), digest_size=8).hexdigest()


class Experiment:
    def __init__(self, config: Dict[str, Any], backend: Optional[Backend] = None):
        self.config = config
        self.base_seed = int(config.get("seed", 42))
        self.num_seeds = int(config.get("num_seeds", 1))

        # ``scenario`` is inline issue/opinions (the historical form), a
        # registry ref string ("corpus:v2:polarized-0004", "aamas:3"), or
        # a dict with a ``ref`` key plus overriding fields — so sweep
        # configs can name corpus scenarios instead of inlining text.
        scenario_cfg = config.get("scenario", {})
        if isinstance(scenario_cfg, str) or (
            isinstance(scenario_cfg, dict) and "ref" in scenario_cfg
        ):
            from consensus_tpu.data.scenarios.registry import (
                maybe_resolve_scenario,
            )

            scenario = maybe_resolve_scenario(scenario_cfg)
        else:
            scenario = scenario_cfg
        self.issue: str = scenario.get("issue", "")
        self.agent_opinions: Dict[str, str] = dict(scenario.get("agent_opinions", {}))

        models = config.get("models", {})
        self.generation_model: str = models.get("generation_model", "")
        # Singular back-compat key (reference :90-100).
        eval_models = models.get("evaluation_models")
        if eval_models is None:
            single = models.get("evaluation_model")
            eval_models = [single] if single else []
        self.evaluation_models: List[str] = list(eval_models)

        self.methods_to_run: List[str] = list(config.get("methods_to_run", []))

        if backend is not None:
            self.backend = backend
        else:
            # get_backend caches by name so an in-process sweep (run_sweep)
            # reuses one backend — and its compiled programs — across configs.
            options = dict(config.get("backend_options") or {})
            if config.get("timing_pin_budget") and config.get("backend") == "tpu":
                options["pin_generation_budget"] = True
            self.backend = get_backend(config.get("backend", "fake"), **options)

        # Fault-tolerance stack: supervisor(faults(engine)).  ``fault_plan``
        # (chaos runs) implies supervision unless explicitly disabled.
        fault_plan = config.get("fault_plan")
        supervise = config.get("supervisor")
        if fault_plan is not None or supervise:
            self.backend = wrap_backend(
                self.backend, fault_plan=fault_plan, supervise=supervise
            )

        self.on_error = str(config.get("on_error", "skip"))
        if self.on_error not in ON_ERROR_POLICIES:
            raise ValueError(
                f"on_error must be one of {ON_ERROR_POLICIES}, "
                f"got {self.on_error!r}"
            )
        self.error_retries = max(0, int(config.get("error_retries", 1)))
        #: Zero wall-clock columns so chaos/resume proofs can assert
        #: byte-identical results.csv across runs.
        self.deterministic_artifacts = bool(
            config.get("deterministic_artifacts", False)
        )

        output_dir = pathlib.Path(config.get("output_dir", "results"))
        name = config.get("experiment_name", "experiment")
        self.resume = bool(config.get("resume", False))
        run_dir: Optional[pathlib.Path] = None
        if self.resume:
            # Reuse the newest journaled run dir for this experiment name;
            # timestamped names sort chronologically.
            candidates = sorted(
                p for p in output_dir.glob(f"{name}_*")
                if (p / "journal.jsonl").exists()
            )
            if candidates:
                run_dir = candidates[-1]
                logger.info("Resuming from %s", run_dir)
        self.resumed = run_dir is not None
        if run_dir is None:
            stamp = datetime.datetime.now().strftime("%Y%m%d_%H%M%S")
            run_dir = output_dir / f"{name}_{stamp}"
        self.run_dir = run_dir
        self.run_dir.mkdir(parents=True, exist_ok=True)
        atomic_write_text(
            self.run_dir / "config.yaml",
            yaml.safe_dump(config, sort_keys=False),
        )
        logger.info("Run directory: %s", self.run_dir)

    # -- run configs ---------------------------------------------------------

    @staticmethod
    def expand_param_grid(method_config: Dict[str, Any]) -> List[Dict[str, Any]]:
        """List-valued params → Cartesian product (reference :241-267)."""
        listed = {k: v for k, v in method_config.items() if isinstance(v, list)}
        if not listed:
            return [dict(method_config)]
        fixed = {k: v for k, v in method_config.items() if k not in listed}
        configs = []
        keys = sorted(listed)
        for combo in itertools.product(*(listed[k] for k in keys)):
            run_config = dict(fixed)
            run_config.update(dict(zip(keys, combo)))
            configs.append(run_config)
        return configs

    def _run_configs(self, seed: int) -> List[Dict[str, Any]]:
        runs = []
        for method in self.methods_to_run:
            method_config = dict(self.config.get(method, {}) or {})
            method_config["seed"] = seed
            if self.config.get("timing_pin_budget"):
                # Timing mode (VERDICT r2 #4): decoders must not terminate a
                # statement early on EOS strings/terminators, so random-weight
                # timing runs measure the full-budget workload real weights
                # would run.  The backend-side half is the
                # pin_generation_budget backend option.
                method_config["pin_budget"] = True
            for run_config in self.expand_param_grid(method_config):
                runs.append({"method": method, "config": run_config, "seed": seed})
        return runs

    # -- execution -----------------------------------------------------------

    def _run_one(
        self,
        method: str,
        run_config: Dict[str, Any],
        seed: int,
        backend: Optional[Backend] = None,
    ) -> Dict:
        attempts = 1 + (self.error_retries if self.on_error == "retry" else 0)
        start = time.perf_counter()
        for attempt in range(attempts):
            row: Dict[str, Any] = {
                "method": method,
                "seed": seed,
                "error_message": "",
                "evaluation_status": "pending",
            }
            for key, value in run_config.items():
                if key != "seed":
                    row[f"param_{key}"] = value
            try:
                generator = get_method_generator(
                    method, backend or self.backend, run_config,
                    self.generation_model,
                )
                with get_tracer().span(f"generate/{method}"):
                    statement = generator.generate_statement(
                        self.issue, self.agent_opinions
                    )
                row["statement"] = statement
                if generator.degraded:
                    # Anytime early exit / scaled budget (budget_s or
                    # budget_scale in the run config).  Keys appear ONLY on
                    # degraded rows so full-budget sweeps keep their exact
                    # historical CSV schema (tests/golden/).
                    row["degraded"] = True
                    row["degraded_reason"] = generator.degraded_reason
                    row["budget_spent"] = json.dumps(
                        generator.budget_spent, sort_keys=True
                    )
                if generator.pre_brushup_statement is not None and run_config.get(
                    "brushup", False
                ):
                    row["pre_brushup_statement"] = generator.pre_brushup_statement
                break
            except Exception as exc:
                if self.on_error == "fail":
                    raise
                if attempt + 1 < attempts:
                    logger.warning(
                        "Method %s failed (%s: %s); retry %d/%d",
                        method, type(exc).__name__, exc,
                        attempt + 1, attempts - 1,
                    )
                    continue
                # Structured error row, sweep continues (reference :194-201).
                logger.exception("Method %s failed", method)
                row["statement"] = ""
                row["error_message"] = f"{type(exc).__name__}: {exc}"
        row["generation_time_s"] = (
            0.0 if self.deterministic_artifacts
            else round(time.perf_counter() - start, 3)
        )
        return row

    @staticmethod
    def _journal_key(run: Dict[str, Any]) -> Tuple[str, str, int]:
        return (
            str(run["method"]),
            run_config_hash(run["config"]),
            int(run["seed"]),
        )

    def _load_journal(
        self, runs: List[Dict[str, Any]]
    ) -> Dict[int, Dict[str, Any]]:
        """Map journaled rows back onto the deterministic run list.

        Keys ``(method, config_hash, seed)`` can repeat (identical grid
        points are legal), so matching is by multiplicity: the K-th
        journaled row for a key fills the K-th run with that key."""
        if not self.resumed:
            return {}
        journaled: Dict[Tuple[str, str, int], collections.deque] = {}
        for record in read_journal(self.run_dir / "journal.jsonl"):
            key_info = record.get("key") or {}
            row = record.get("row")
            if not isinstance(row, dict):
                continue
            key = (
                str(key_info.get("method", "")),
                str(key_info.get("config_hash", "")),
                int(key_info.get("seed", -1)),
            )
            journaled.setdefault(key, collections.deque()).append(row)
        done: Dict[int, Dict[str, Any]] = {}
        for index, run in enumerate(runs):
            queue = journaled.get(self._journal_key(run))
            if queue:
                done[index] = queue.popleft()
        return done

    def run(self) -> pd.DataFrame:
        runs: List[Dict[str, Any]] = []
        for i in range(self.num_seeds):
            seed = self.base_seed + i
            runs.extend(self._run_configs(seed))

        rows_by_index = self._load_journal(runs)
        pending = [
            (index, run) for index, run in enumerate(runs)
            if index not in rows_by_index
        ]
        if rows_by_index:
            logger.info(
                "Resume: %d/%d rows journaled; executing %d",
                len(rows_by_index), len(runs), len(pending),
            )

        # Token-honest cell accounting: the backend may be shared across an
        # in-process sweep, so record deltas around this experiment's runs.
        # Metrics and spans follow the same delta discipline: the registry
        # and tracer are process-global, so this cell's metrics.json records
        # (after - before), which run_sweep can sum back together exactly.
        tokens_before = dict(getattr(self.backend, "token_counts", {}) or {})
        wall_start = time.perf_counter()
        tracer = get_tracer()
        metrics_before = get_registry().snapshot()
        spans_before = tracer.snapshot_paths()

        concurrent = bool(self.config.get("concurrent_execution", True))
        max_workers = int(self.config.get("max_concurrent_methods", 4))

        # --profile-dir: a TensorBoard-loadable device profile per cell,
        # namespaced by run-dir name so sweep cells don't clobber each other.
        profile_dir = self.config.get("profile_dir") or None
        if profile_dir:
            profile_dir = str(pathlib.Path(profile_dir) / self.run_dir.name)

        # Each completed row is journaled (append + fsync) BEFORE the sweep
        # moves on, so a kill at any point loses at most the in-flight rows;
        # --resume replays the journal and executes only what's missing.
        journal = JournalWriter(self.run_dir / "journal.jsonl")

        def finish(index: int, run: Dict[str, Any], row: Dict[str, Any]) -> Dict[str, Any]:
            method, config_hash, seed = self._journal_key(run)
            journal.append({
                "key": {
                    "method": method,
                    "config_hash": config_hash,
                    "seed": seed,
                },
                "run_index": index,
                "row": row,
            })
            return row

        try:
            with tracer.span("experiment"), device_trace(profile_dir):
                # Worker threads adopt this path so their generate/<method>
                # spans nest under this experiment in the span tree.
                parent_path = tracer.current_path()
                if concurrent and len(pending) > 1 and max_workers > 1:
                    # Independent combos (all seeds flattened) share device
                    # batches through the BatchingBackend; results stay
                    # bit-identical to sequential execution (per-request PRNG
                    # keys).
                    from concurrent.futures import ThreadPoolExecutor

                    from consensus_tpu.backends.batching import BatchingBackend

                    # ``engine: true`` routes the workers' calls through the
                    # continuous-batching decode engine instead of the
                    # legacy flush-snapshot path (results byte-identical;
                    # tests/test_engine.py pins all seven methods).
                    batching = BatchingBackend(
                        self.backend,
                        flush_ms=float(self.config.get("batch_flush_ms", 10.0)),
                        expected_sessions=min(max_workers, len(pending)),
                        engine=bool(self.config.get("engine", False)),
                        engine_options=self.config.get("engine_options"),
                    )

                    def worker(item):
                        index, run = item
                        with tracer.adopt(parent_path), batching.session():
                            logger.info(
                                "Running %s with %s", run["method"], run["config"]
                            )
                            row = self._run_one(
                                run["method"], run["config"], run["seed"],
                                backend=batching,
                            )
                        return index, finish(index, run, row)

                    try:
                        with ThreadPoolExecutor(max_workers=max_workers) as pool:
                            for index, row in pool.map(worker, pending):
                                rows_by_index[index] = row
                    finally:
                        batching.close()
                    self.last_batch_counts = dict(batching.batch_counts)
                    logger.info(
                        "Device batches issued: %s (%d runs, %d workers)",
                        batching.batch_counts, len(pending), max_workers,
                    )
                else:
                    for index, run in pending:
                        logger.info(
                            "Running %s with %s", run["method"], run["config"]
                        )
                        row = self._run_one(
                            run["method"], run["config"], run["seed"]
                        )
                        rows_by_index[index] = finish(index, run, row)
        finally:
            journal.close()

        rows = [rows_by_index[index] for index in range(len(runs))]
        frame = pd.DataFrame(rows)
        lead = [c for c in _LEAD_COLUMNS if c in frame.columns]
        rest = sorted(c for c in frame.columns if c not in lead)
        frame = frame[lead + rest]
        atomic_write_text(
            self.run_dir / "results.csv",
            sanitize_frame_for_csv(frame).to_csv(index=False),
        )
        get_tracer().write(self.run_dir / "timing.json")
        self._write_metrics(metrics_before, spans_before)
        self._write_token_counts(tokens_before, wall_start, len(frame))
        logger.info("Saved %d rows to %s", len(frame), self.run_dir / "results.csv")
        return frame

    def _write_metrics(self, metrics_before, spans_before) -> None:
        """This cell's observability artifacts.

        ``metrics.json`` (schema ``consensus_tpu.metrics.v1``) holds the
        registry DELTA for this cell plus the nested span tree and the two
        derived headline numbers; ``metrics.prom`` is the cumulative
        process registry in Prometheus text exposition (what a scrape
        endpoint would serve)."""
        registry = get_registry()
        delta = diff_snapshots(metrics_before, registry.snapshot())
        span_delta = diff_span_paths(
            spans_before, get_tracer().snapshot_paths()
        )
        payload = {
            "schema": "consensus_tpu.metrics.v1",
            "spans": get_tracer().tree(span_delta),
            "metrics": delta,
            "derived": {
                "padding_efficiency": padding_efficiency(delta),
                "bucket_recompiles": bucket_recompiles(delta),
            },
            "mesh": self._mesh_labels(),
        }
        atomic_write_json(self.run_dir / "metrics.json", payload)
        atomic_write_text(
            self.run_dir / "metrics.prom", registry.to_prometheus()
        )

    def _mesh_labels(self) -> Dict[str, int]:
        """The device-mesh layout this cell ran on, so sweep readers can
        tell a dp=4,tp=2 run from single-chip without re-deriving it from
        throughput.  Unwraps batching/supervision decorators to find the
        device backend; no mesh -> dp=1, tp=1."""
        backend = self.backend
        seen = set()
        while backend is not None and id(backend) not in seen:
            seen.add(id(backend))
            plan = getattr(backend, "mesh_plan", None)
            if plan is not None:
                return {"dp": int(plan.dp), "tp": int(plan.tp)}
            backend = getattr(backend, "inner", None)
        return {"dp": 1, "tp": 1}

    def _write_token_counts(
        self, before: Dict[str, int], wall_start: float, statements: int
    ) -> None:
        """Cell-level token accounting -> run_dir/token_counts.json
        (VERDICT r2 #4: s/stmt numbers must be accompanied by how many
        tokens were actually generated/scored, so degenerate short
        statements can't flatter a speedup)."""
        after = getattr(self.backend, "token_counts", None)
        if not after:
            return
        wall = time.perf_counter() - wall_start
        generated = int(after.get("generated", 0) - (before.get("generated") or 0))
        scored = int(after.get("scored", 0) - (before.get("scored") or 0))
        total = generated + scored
        payload = {
            "statements": statements,
            "wall_s": round(wall, 3),
            "tokens_generated": generated,
            "tokens_scored": scored,
            "tokens_generated_per_statement": round(generated / max(statements, 1), 1),
            "s_per_1k_tokens": round(wall / max(total / 1000.0, 1e-9), 3)
            if total
            else None,
            "pinned_budget": bool(self.config.get("timing_pin_budget", False)),
        }
        atomic_write_json(self.run_dir / "token_counts.json", payload)
