"""Parsers for LLM deliberation responses.

Covers the two structured formats the Habermas Machine pipeline relies on:

1. ``<answer> reasoning <sep> payload </answer>`` chain-of-thought envelopes
   (statements, critiques, revisions) — reference
   ``src/methods/habermas_machine.py:480-527``.
2. Arrow-notation preference rankings like ``"B > A = D > C"`` — reference
   ``src/methods/habermas_machine.py:657-918``, with the exact error-code
   strings (``INCORRECT_TEMPLATE`` / ``INCORRECT_ARROW_RANKING`` /
   ``INTERNAL_PARSING_ERROR``) pinned by golden tests.

Rank convention: lower is better, 0 is best; ties share a rank and the next
preference level increments by one (``"B>A=D>C" -> [1, 0, 2, 1]``).
"""

from __future__ import annotations

import re
from typing import Optional, Tuple

import numpy as np

_ANSWER_RE = re.compile(r"<answer>(.*?)<sep>(.*?)(?:</answer>|\Z)", re.DOTALL | re.IGNORECASE)
_ARROW_RE = re.compile(r"\b[A-Z](?: *[>=] *[A-Z])*\b")
_ARROW_FULL_RE = re.compile(r"^[A-Z](?: *[>=] *[A-Z])*$")
_SEP_RE = re.compile(r"<sep>", re.IGNORECASE)
_ANSWER_OPEN_RE = re.compile(r"<answer>", re.IGNORECASE)
_ANSWER_CLOSE_RE = re.compile(r"</answer>", re.IGNORECASE)
_FINAL_RANKING_RE = re.compile(r"final ranking:", re.IGNORECASE)


def extract_statement(response: str) -> Optional[str]:
    """Pull the payload after ``<sep>`` out of an ``<answer>`` envelope.

    Tolerates a truncated ``</answer>`` (stop sequences may eat it) and
    rejects payloads of 5 characters or fewer, matching reference
    ``_process_llm_response`` (habermas_machine.py:480-527).
    """
    if not response:
        return None
    match = _ANSWER_RE.search(response)
    if not match:
        return None
    statement = match.group(2).strip()
    if statement and len(statement) > 5:
        return statement
    return None


def check_response_format(response: str) -> bool:
    """Strict check that all three envelope tags are present (reference :657-666)."""
    return bool(
        _ANSWER_OPEN_RE.search(response)
        and _SEP_RE.search(response)
        and _ANSWER_CLOSE_RE.search(response)
    )


def check_arrow_format(ranking_str: str, num_statements: int) -> bool:
    """Validate an arrow/equality ranking string (reference :669-713).

    Requires: only ``>``/``=`` separators, the letter set exactly
    {A..} for ``num_statements`` statements, and no duplicate letters.
    """
    if not ranking_str:
        return False
    if not _ARROW_FULL_RE.fullmatch(ranking_str):
        return False
    letters = [c for c in ranking_str if c.isalpha()]
    expected = {chr(ord("A") + i) for i in range(num_statements)}
    if set(letters) != expected:
        return False
    if len(letters) != len(set(letters)):
        return False
    return True


def extract_arrow_ranking(text: str) -> Optional[str]:
    """Find the first arrow-ranking substring and strip internal spaces.

    ``'Explanation\\nA > B < C > D' -> 'A>B'`` (first maximal match only),
    reference :716-749.
    """
    if not text:
        return None
    match = _ARROW_RE.search(text)
    if not match:
        return None
    return re.sub(r" *([>=]) *", r"\1", match.group(0)).strip()


def parse_arrow_ranking(arrow_ranking: str, num_statements: int) -> Optional[np.ndarray]:
    """Parse a validated arrow ranking to a 0-based rank array with ties.

    ``"B>A=D>C", 4 -> [1, 0, 2, 1]``; each ``>`` level increments the rank by
    exactly one regardless of tie-group size (reference :752-832).
    """
    if not arrow_ranking:
        return None

    ranking = np.full(num_statements, -1, dtype=int)
    seen = set()
    for rank, group in enumerate(arrow_ranking.split(">")):
        group = group.strip()
        if not group:
            continue
        for item in group.split("="):
            letter = item.strip()
            if len(letter) != 1 or not ("A" <= letter <= "Z"):
                return None
            if letter in seen:
                return None
            idx = ord(letter) - ord("A")
            if not 0 <= idx < num_statements:
                return None
            ranking[idx] = rank
            seen.add(letter)

    expected = {chr(ord("A") + i) for i in range(num_statements)}
    if seen != expected or -1 in ranking:
        return None
    return ranking


def _ranking_from_text(text: str, num_statements: int) -> Optional[np.ndarray]:
    arrow = extract_arrow_ranking(text)
    if arrow and check_arrow_format(arrow, num_statements):
        return parse_arrow_ranking(arrow, num_statements)
    return None


def process_ranking_response(
    response: str, num_statements: int
) -> Tuple[Optional[np.ndarray], str]:
    """Full response -> (rank array | None, explanation-or-error string).

    Error-string contract (reference :835-918):
      * valid envelope but bad/missing ranking -> ``"INCORRECT_ARROW_RANKING: <response>"``
      * bad envelope with a parsable ``final ranking:`` fallback -> rank array
      * bad envelope otherwise -> ``"INCORRECT_TEMPLATE: <response>"``
      * post-validation parse failure -> ``"INTERNAL_PARSING_ERROR: <response>"``
    On success the explanation is the raw response itself.
    """
    if check_response_format(response):
        sep_match = _SEP_RE.search(response)
        close_match = _ANSWER_CLOSE_RE.search(response)
        start = sep_match.end()
        end = close_match.start() if close_match else len(response)
        candidate_text = response[start:end].strip()

        arrow = extract_arrow_ranking(candidate_text)
        if arrow and check_arrow_format(arrow, num_statements):
            ranking = parse_arrow_ranking(arrow, num_statements)
            if ranking is None:
                return None, f"INTERNAL_PARSING_ERROR: {response}"
            return ranking, response
        return None, f"INCORRECT_ARROW_RANKING: {response}"

    final_match = _FINAL_RANKING_RE.search(response)
    if final_match:
        start = final_match.end()
        newline = response.find("\n", start)
        end = newline if newline != -1 else len(response)
        candidate_text = response[start:end].strip()

        arrow = extract_arrow_ranking(candidate_text)
        if arrow and check_arrow_format(arrow, num_statements):
            ranking = parse_arrow_ranking(arrow, num_statements)
            if ranking is None:
                return None, f"INTERNAL_PARSING_ERROR: {response}"
            return ranking, response
        return None, f"INCORRECT_TEMPLATE: {response}"

    return None, f"INCORRECT_TEMPLATE: {response}"
