"""Schulze-method preference aggregation (vectorized numpy).

Behaviour parity with the reference implementation in
``src/methods/habermas_machine.py:985-1260`` (itself adapted from Google's
Habermas Machine code), but written as vectorized array programs rather than
quadruple Python loops: pairwise defeats are one broadcast comparison, the
Floyd–Warshall widest-path sweep is vectorized per intermediate candidate.
Semantics (including tie handling, dominance-count ranking, and seeded
random-ballot tie-breaking) are identical and pinned by the electowiki golden
tests in ``tests/test_social_choice.py``.

Rank convention throughout: lower is better, 0 is best, ties allowed.
"""

from __future__ import annotations

from typing import Dict, Mapping, Optional

import numpy as np


def validate_rankings(rankings: np.ndarray) -> None:
    """Shape/dtype/range checks (reference habermas_machine.py:1030-1045)."""
    if rankings.ndim != 2:
        raise ValueError(
            f"Rankings should be 2D [num_voters, num_candidates], got shape {rankings.shape}"
        )
    if not np.issubdtype(rankings.dtype, np.integer):
        raise ValueError(f"Rankings should be integers, got {rankings.dtype}")
    num_candidates = rankings.shape[1]
    bad = (rankings < 0) | (rankings >= num_candidates)
    if np.any(bad):
        raise ValueError(
            f"Ranks must be between 0 and {num_candidates - 1}. "
            f"Found invalid rank: {rankings[bad][0]}"
        )


def compute_pairwise_defeats(rankings: np.ndarray) -> np.ndarray:
    """d[i, j] = #voters preferring candidate i to candidate j.

    Reference habermas_machine.py:1048-1069, vectorized: a single broadcast
    ``rank_i < rank_j`` comparison summed over the voter axis.
    """
    rankings = np.asarray(rankings)
    # (voters, cand, 1) < (voters, 1, cand) -> (voters, cand, cand)
    prefers = rankings[:, :, None] < rankings[:, None, :]
    return prefers.sum(axis=0).astype(np.int32)


def _check_square_zero_diag(matrix: np.ndarray, name: str) -> None:
    if matrix.ndim != 2 or matrix.shape[0] != matrix.shape[1]:
        raise ValueError(f"{name} should be a square array, got shape {matrix.shape}")
    if np.any(np.diag(matrix) != 0):
        raise ValueError(f"{name} should have an all zero diagonal.")


def compute_strongest_paths(pairwise_defeats: np.ndarray) -> np.ndarray:
    """Widest-path strengths p[i, j] via Floyd–Warshall.

    Reference habermas_machine.py:1072-1120.  Initial strength is d[i, j]
    where i beats j head-to-head, else 0; the relaxation
    ``p[j,k] = max(p[j,k], min(p[j,i], p[i,k]))`` runs vectorized over (j, k)
    for each intermediate i (p[i,i] = 0 makes self-loops inert).
    """
    _check_square_zero_diag(pairwise_defeats, "pairwise_defeats")
    d = np.asarray(pairwise_defeats)
    p = np.where(d > d.T, d, 0).astype(d.dtype)
    np.fill_diagonal(p, 0)

    n = p.shape[0]
    for via in range(n):
        np.maximum(p, np.minimum(p[:, via : via + 1], p[via : via + 1, :]), out=p)
    np.fill_diagonal(p, 0)
    return p


def rank_from_path_strengths(path_strengths: np.ndarray) -> np.ndarray:
    """Dominance-count social ranking with ties (reference :1123-1160).

    Candidate i is at least as good as j iff p[i, j] >= p[j, i]; candidates
    are ranked by how many others they weakly dominate (more is better).
    """
    _check_square_zero_diag(path_strengths, "path_strengths")
    p = np.asarray(path_strengths)
    dominance_count = (p >= p.T).sum(axis=1)
    _, social_ranking = np.unique(-dominance_count, return_inverse=True)
    return social_ranking


def schulze_social_ranking(rankings: np.ndarray) -> np.ndarray:
    """End-to-end Schulze aggregation, ties allowed (reference :1163-1178)."""
    rankings = np.asarray(rankings)
    validate_rankings(rankings)
    return rank_from_path_strengths(
        compute_strongest_paths(compute_pairwise_defeats(rankings))
    )


# --- Tie handling helpers (reference habermas_machine.py:992-1024) ---


def normalize_ranking(ranking: np.ndarray) -> np.ndarray:
    """Compress ranks to consecutive integers: [0, 2, 5, 5] -> [0, 1, 2, 2]."""
    ranking = np.asarray(ranking)
    if ranking.ndim != 1:
        raise ValueError("The input array should be a single ranking so `ndim=1`")
    _, normalized = np.unique(ranking, return_inverse=True)
    return normalized


def is_untied(ranking: np.ndarray) -> bool:
    ranking = np.asarray(ranking)
    if ranking.ndim != 1:
        raise ValueError("The input array should be a single ranking so `ndim=1`")
    return np.unique(ranking).size == ranking.size


def untie_with_ballot(ranking: np.ndarray, ballot: np.ndarray) -> np.ndarray:
    """Break ties with an auxiliary ballot, preserving the existing order.

    Scaling the normalized ranking by the candidate count guarantees the
    ballot only reorders within tie groups (reference :1007-1024).
    """
    ranking = np.asarray(ranking)
    ballot = np.asarray(ballot)
    if ranking.ndim != 1:
        raise ValueError("The input array should be a single ranking so `ndim=1`")
    if ranking.shape != ballot.shape:
        raise ValueError("The ranking and ballot should have the same shape.")
    combined = normalize_ranking(ranking) * len(ranking) + normalize_ranking(ballot)
    return normalize_ranking(combined)


def aggregate_schulze(
    agent_rankings: Mapping[str, Optional[np.ndarray]],
    num_candidates: int,
    seed: Optional[int] = None,
    tie_breaking_method: str = "random",
) -> Optional[np.ndarray]:
    """Aggregate per-agent rank arrays; optionally break ties with a seeded
    random ballot (reference habermas_machine.py:1181-1260).

    Agents whose ranking failed (``None``) are dropped; returns ``None`` when
    no valid ranking remains or shapes are inconsistent.
    """
    valid = [np.asarray(r) for r in agent_rankings.values() if r is not None]
    if not valid:
        return None

    try:
        stacked = np.stack(valid, axis=0)
    except ValueError:
        return None
    if stacked.shape[1] != num_candidates:
        return None

    try:
        tied = schulze_social_ranking(stacked)
    except ValueError:
        return None

    if tie_breaking_method == "ties_allowed" or is_untied(tied):
        return tied
    if tie_breaking_method == "random":
        rng = np.random.default_rng(seed)
        ballot = rng.permutation(num_candidates).astype(np.int32)
        return untie_with_ballot(tied, ballot)
    # Unknown tie-breaking method: return the tied ranking unchanged.
    return tied
