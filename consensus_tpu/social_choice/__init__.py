from consensus_tpu.social_choice.schulze import (  # noqa: F401
    aggregate_schulze,
    compute_pairwise_defeats,
    compute_strongest_paths,
    is_untied,
    normalize_ranking,
    rank_from_path_strengths,
    schulze_social_ranking,
    untie_with_ballot,
    validate_rankings,
)
from consensus_tpu.social_choice.parsing import (  # noqa: F401
    check_arrow_format,
    check_response_format,
    extract_arrow_ranking,
    extract_statement,
    parse_arrow_ranking,
    process_ranking_response,
)
