"""Segmented shared-trunk decode: identical results, bounded carry.

Long-budget decodes (habermas' 700-token CoT envelopes) dominate the
north-star sweep, and the while_loop carry holding the full-budget KV tail
is copied every step by the remote AOT compiler (no aliasing): measured
44.6 ms/step at B=64 x T=768 against a ~6 ms roofline
(scripts/decode_step_bench.py).  ``generate_tokens_shared_trunk_segmented``
decodes in seg_len-column slices, moving completed segments into read-only
frozen operands (transformer.forward_trunk_tail ``frozen_*``).

It must be a PURE optimization: same tokens, counts, and EOS flags as the
monolithic ``generate_tokens_shared_trunk`` for identical inputs — the
per-step sampling math and PRNG stream are shared, and attention sees the
same chronological key set [trunk, frozen, tail].
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from consensus_tpu.backends.base import GenerationRequest
from consensus_tpu.backends.tpu import TPUBackend
from consensus_tpu.models.config import get_model_config
from consensus_tpu.models.generate import (
    generate_tokens,
    generate_tokens_segmented,
    generate_tokens_shared_trunk,
    generate_tokens_shared_trunk_segmented,
)
from consensus_tpu.models.transformer import init_params

BATCH = 4
CTX = 32
MAX_NEW = 64
SEG = 16


@pytest.fixture(scope="module")
def setup():
    config = get_model_config("tiny-gemma2", vocab_size=128)
    params = init_params(config, jax.random.PRNGKey(0), dtype=jnp.float32)
    prompt = jax.random.randint(
        jax.random.PRNGKey(1), (1, CTX), 1, config.vocab_size, jnp.int32
    )
    valid = jnp.ones((1, CTX), bool)
    keys = jax.vmap(lambda i: jax.random.fold_in(jax.random.PRNGKey(7), i))(
        jnp.arange(BATCH)
    )
    return config, params, prompt, valid, keys


def run_both(setup, **kw):
    config, params, prompt, valid, keys = setup
    common = dict(
        batch=BATCH, key=keys, max_new_tokens=MAX_NEW, pad_id=0,
    )
    common.update(kw)
    mono = generate_tokens_shared_trunk(params, config, prompt, valid, **common)
    seg = generate_tokens_shared_trunk_segmented(
        params, config, prompt, valid, seg_len=SEG, **common
    )
    return mono, seg


def assert_equal_outputs(mono, seg):
    np.testing.assert_array_equal(np.asarray(mono.tokens), np.asarray(seg.tokens))
    np.testing.assert_array_equal(
        np.asarray(mono.num_generated), np.asarray(seg.num_generated)
    )
    np.testing.assert_array_equal(
        np.asarray(mono.hit_eos), np.asarray(seg.hit_eos)
    )


def test_greedy_matches_monolithic(setup):
    mono, seg = run_both(setup, temperature=jnp.zeros((BATCH,), jnp.float32))
    assert_equal_outputs(mono, seg)
    assert int(np.asarray(seg.num_generated).min()) == MAX_NEW  # no EOS ids


def test_sampled_matches_monolithic(setup):
    """Per-row PRNG streams are identical across the segment boundary."""
    mono, seg = run_both(setup, temperature=jnp.ones((BATCH,), jnp.float32))
    assert_equal_outputs(mono, seg)


def test_eos_rows_stop_and_match(setup):
    """Rows hitting EOS mid-segment stay done across later segments."""
    config, params, prompt, valid, keys = setup
    # Use a likely token id as EOS so rows finish at different steps.
    probe = generate_tokens_shared_trunk(
        params, config, prompt, valid, batch=BATCH, key=keys,
        max_new_tokens=MAX_NEW, temperature=jnp.ones((BATCH,), jnp.float32),
        pad_id=0,
    )
    common_token = int(np.bincount(np.asarray(probe.tokens).ravel()[1:]).argmax())
    eos = jnp.asarray([common_token], jnp.int32)
    mono, seg = run_both(
        setup, temperature=jnp.ones((BATCH,), jnp.float32), eos_ids=eos
    )
    assert_equal_outputs(mono, seg)
    assert bool(np.asarray(seg.hit_eos).any())


def test_init_done_rows_stay_empty(setup):
    init_done = jnp.asarray([False, True, False, True])
    mono, seg = run_both(
        setup,
        temperature=jnp.ones((BATCH,), jnp.float32),
        init_done=init_done,
    )
    assert_equal_outputs(mono, seg)
    counts = np.asarray(seg.num_generated)
    assert counts[1] == 0 and counts[3] == 0


def test_rejects_non_multiple_budget(setup):
    config, params, prompt, valid, keys = setup
    with pytest.raises(ValueError):
        generate_tokens_shared_trunk_segmented(
            params, config, prompt, valid, batch=BATCH, key=keys,
            max_new_tokens=MAX_NEW + 3, seg_len=SEG,
        )


def run_both_classic(setup, **kw):
    """Classic layout: per-row prompts (left-padded to different lengths)."""
    config, params, _, _, keys = setup
    prompts = np.zeros((BATCH, CTX), np.int32)
    valid = np.zeros((BATCH, CTX), bool)
    rng = np.random.default_rng(3)
    for row in range(BATCH):
        n = CTX - 3 * row  # varying prompt lengths exercise per-row positions
        prompts[row, CTX - n:] = rng.integers(1, config.vocab_size, n)
        valid[row, CTX - n:] = True
    common = dict(key=keys, max_new_tokens=MAX_NEW, pad_id=0)
    common.update(kw)
    mono = generate_tokens(
        params, config, jnp.asarray(prompts), jnp.asarray(valid), **common
    )
    seg = generate_tokens_segmented(
        params, config, jnp.asarray(prompts), jnp.asarray(valid),
        seg_len=SEG, **common
    )
    return mono, seg


def test_classic_greedy_matches_monolithic(setup):
    mono, seg = run_both_classic(
        setup, temperature=jnp.zeros((BATCH,), jnp.float32)
    )
    assert_equal_outputs(mono, seg)


def test_classic_sampled_matches_monolithic(setup):
    mono, seg = run_both_classic(
        setup, temperature=jnp.ones((BATCH,), jnp.float32)
    )
    assert_equal_outputs(mono, seg)


def test_classic_pad_rows_stay_done(setup):
    """All-pad prompt rows (bucket padding) generate nothing in both paths."""
    config, params, _, _, keys = setup
    prompts = np.zeros((BATCH, CTX), np.int32)
    valid = np.zeros((BATCH, CTX), bool)
    prompts[0, CTX - 5:] = 7
    valid[0, CTX - 5:] = True  # only row 0 is real
    mono = generate_tokens(
        params, config, jnp.asarray(prompts), jnp.asarray(valid), keys,
        max_new_tokens=MAX_NEW, temperature=jnp.ones((BATCH,), jnp.float32),
        pad_id=0,
    )
    seg = generate_tokens_segmented(
        params, config, jnp.asarray(prompts), jnp.asarray(valid), keys,
        max_new_tokens=MAX_NEW, seg_len=SEG,
        temperature=jnp.ones((BATCH,), jnp.float32), pad_id=0,
    )
    assert_equal_outputs(mono, seg)
    assert np.asarray(seg.num_generated)[1:].sum() == 0


def test_compaction_preserves_results(setup, monkeypatch):
    """Rows hitting EOS compact away at segment boundaries (batch 16
    halves); per-row streams are batch-independent, so the output must
    equal the monolithic full-batch decode row for row — AND compaction
    must actually fire (a silently-disabled optimization would still pass
    the equality check)."""
    import consensus_tpu.models.generate as gen_mod

    config, params, prompt, valid, _ = setup
    batch = 16
    keys = jax.vmap(lambda i: jax.random.fold_in(jax.random.PRNGKey(5), i))(
        jnp.arange(batch)
    )
    common = dict(
        batch=batch, key=keys, max_new_tokens=MAX_NEW, pad_id=0,
        temperature=jnp.ones((batch,), jnp.float32),
    )
    probe = generate_tokens_shared_trunk(params, config, prompt, valid, **common)
    common_token = int(np.bincount(np.asarray(probe.tokens).ravel()[1:]).argmax())
    eos = jnp.asarray([common_token], jnp.int32)
    mono = generate_tokens_shared_trunk(
        params, config, prompt, valid, eos_ids=eos, **common
    )
    seen_batches = []
    orig_segment = gen_mod._decode_segment

    def recording(*args, **kwargs):
        seen_batches.append(kwargs["n_slots"] * kwargs["n_roles"])
        return orig_segment(*args, **kwargs)

    monkeypatch.setattr(gen_mod, "_decode_segment", recording)
    seg = generate_tokens_shared_trunk_segmented(
        params, config, prompt, valid, seg_len=SEG, eos_ids=eos, **common
    )
    assert_equal_outputs(mono, seg)
    # Rows finish at different times AND the batch actually halved.
    counts = np.asarray(seg.num_generated)
    assert counts.min() < MAX_NEW and len(set(counts.tolist())) > 1
    assert min(seen_batches) < batch, seen_batches


def test_quantize_kv_roundtrip_error_bounded():
    """_quantize_kv: symmetric absmax int8 over hd — relative reconstruction
    error is bounded by half a quantization step (~0.4% of the row max)."""
    from consensus_tpu.models.generate import _quantize_kv

    arr = jax.random.normal(jax.random.PRNGKey(0), (2, 3, 5, 2, 16))
    q, scale = _quantize_kv(arr)
    assert q.dtype == jnp.int8 and scale.shape == (2, 3, 5, 2, 1)
    recon = q.astype(jnp.float32) * scale
    err = np.abs(np.asarray(recon) - np.asarray(arr))
    bound = np.asarray(scale) * 0.5 + 1e-7
    assert (err <= bound).all()


def test_kv_quant_close_to_bf16(setup):
    """int8 generated-token KV (the production default): not bit-identical,
    but the decode must stay statistically faithful — most greedy tokens
    agree with the exact path, and every row still produces a full-budget
    generation."""
    config, params, prompt, valid, keys = setup
    common = dict(
        batch=BATCH, key=keys, max_new_tokens=MAX_NEW, pad_id=0,
        temperature=jnp.zeros((BATCH,), jnp.float32),  # greedy
    )
    exact = generate_tokens_shared_trunk_segmented(
        params, config, prompt, valid, seg_len=SEG, **common
    )
    quant = generate_tokens_shared_trunk_segmented(
        params, config, prompt, valid, seg_len=SEG, kv_quant=True,
        **common
    )
    a, b = np.asarray(exact.tokens), np.asarray(quant.tokens)
    agreement = (a == b).mean()
    assert agreement > 0.8, f"token agreement {agreement:.2%}"
    assert int(np.asarray(quant.num_generated).min()) == MAX_NEW


def test_kv_quant_classic_trunk_close_to_bf16(setup):
    """Classic layout under kv_quant additionally quantizes the per-row
    prompt trunk (the dominant per-step read at production widths); the
    decode must stay statistically faithful to the exact path."""
    config, params, prompt, valid, keys = setup
    prompts = jnp.tile(prompt, (BATCH, 1))
    valids = jnp.tile(valid, (BATCH, 1))
    common = dict(
        key=keys, max_new_tokens=MAX_NEW, pad_id=0,
        temperature=jnp.zeros((BATCH,), jnp.float32),  # greedy
    )
    exact = generate_tokens_segmented(
        params, config, prompts, valids, seg_len=SEG, **common
    )
    quant = generate_tokens_segmented(
        params, config, prompts, valids, seg_len=SEG, kv_quant=True, **common
    )
    a, b = np.asarray(exact.tokens), np.asarray(quant.tokens)
    agreement = (a == b).mean()
    assert agreement > 0.8, f"token agreement {agreement:.2%}"
    assert int(np.asarray(quant.num_generated).min()) == MAX_NEW


def test_backend_kv_quant_option():
    """TPUBackend(kv_quant=True), the default, serves long budgets
    end-to-end; the round-3 ``quantize_frozen_kv`` name still works as an
    alias."""
    backend = TPUBackend(
        model="tiny-gemma2",
        max_context=64,
        base_seed=0,
        dtype="float32",
        decode_segment_len=32,
    )
    assert backend.kv_quant  # production default is ON
    requests = [
        GenerationRequest(
            user_prompt="Shared long-budget prompt.",
            max_tokens=70,
            seed=50 + i,
            temperature=1.0,
        )
        for i in range(4)
    ]
    results = backend.generate(requests)
    assert all(r.ok for r in results)
    # Strict >: the int8-KV allowance branch must actually raise capacity
    # (96 -> 192 rows at the 768 budget on production HBM).
    assert backend._segmented_rows_allowed(0, 768, 128) > TPUBackend(
        model="tiny-gemma2", max_context=64, dtype="float32", kv_quant=False
    )._segmented_rows_allowed(0, 768, 128)
    # The deprecated alias maps onto the same switch, both ways.
    assert not TPUBackend(
        model="tiny-gemma2", max_context=64, dtype="float32",
        quantize_frozen_kv=False,
    ).kv_quant


def test_backend_routes_long_budgets_through_segments(monkeypatch):
    """TPUBackend: budgets >= 2*seg_len take the segmented path and produce
    the same results as the monolithic path (kv_quant off — the int8-KV
    default is deliberately not token-exact vs monolithic)."""
    def build(segmented):
        return TPUBackend(
            model="tiny-gemma2",
            max_context=64,
            base_seed=0,
            dtype="float32",
            segmented_decode=segmented,
            decode_segment_len=32,
            kv_quant=False,
        )

    requests = [
        GenerationRequest(
            user_prompt="Shared draft prompt.",
            max_tokens=70,  # buckets to 96... below 2*32? widths: 96 -> yes
            seed=11 + i,
            temperature=1.0,
        )
        for i in range(4)
    ]
    import consensus_tpu.models.generate as gen_mod

    seg_backend = build(True)
    calls = {"segmented": 0}
    orig = gen_mod.generate_tokens_shared_trunk_segmented

    def counting(*a, **k):
        calls["segmented"] += 1
        return orig(*a, **k)

    monkeypatch.setattr(
        gen_mod, "generate_tokens_shared_trunk_segmented", counting
    )
    seg_results = seg_backend.generate(requests)
    mono_backend = build(False)
    mono_results = mono_backend.generate(requests)
    assert [r.token_ids for r in seg_results] == [
        r.token_ids for r in mono_results
    ]
    assert calls["segmented"] == 1
