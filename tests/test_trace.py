"""Request-scoped tracing, iteration ledger, flight recorder (ISSUE 14).

The acceptance proofs:

* **Critical-path partition**: the phase decomposition sums to the root
  span's duration exactly on a synthetic tree, and to within 5% of the
  measured request latency end-to-end through the HTTP server.
* **Failover span tree**: a 3-replica fleet with the serving replica
  killed mid-flight yields ONE trace holding both dispatch spans (tagged
  primary / failover reason); the final span's replica matches the
  response's ``served_by``; the tree is retrievable via ``GET
  /v1/trace/<id>``.
* **Server-minted request ids**: a client that omits ``request_id`` gets
  a deterministic ``srv-`` id echoed in success AND rejection bodies.
* **MFU attribution**: the engine's iteration ledger accounts for >=95%
  of engine wall time, split device / host / idle.
* **Flight recorder**: a watchdog trip dumps a parseable blackbox JSON
  with the trip in the event ring.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from consensus_tpu.backends import FakeBackend, GenerationRequest
from consensus_tpu.backends.batching import BatchingBackend
from consensus_tpu.backends.engine import DecodeEngine
from consensus_tpu.backends.faults import (
    FaultInjectingBackend,
    FaultPlan,
    FaultSpec,
)
from consensus_tpu.obs.metrics import Registry
from consensus_tpu.obs.trace import (
    MAX_SPANS_PER_TRACE,
    FlightRecorder,
    IterationLedger,
    RollingWindow,
    TraceContext,
    TraceStore,
    get_flight_recorder,
    get_trace_store,
    trace_current,
    use_trace,
)
from consensus_tpu.serve import (
    ConsensusServer,
    FleetRouter,
    Replica,
    SchedulerRejected,
    create_server,
    parse_request,
)

ISSUE = "Should we invest in public transport?"
OPINIONS = {
    "Agent 1": "Yes, buses are vital.",
    "Agent 2": "Only with congestion pricing.",
}


def _payload(seed=7, **overrides):
    payload = {
        "issue": ISSUE,
        "agent_opinions": dict(OPINIONS),
        "method": "best_of_n",
        "params": {"n": 2, "max_tokens": 16},
        "seed": seed,
        "request_id": f"req-{seed}",
    }
    payload.update(overrides)
    return payload


def _post(base_url, payload, timeout=30.0):
    request = urllib.request.Request(
        base_url + "/v1/consensus",
        data=json.dumps(payload).encode("utf-8"),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(request, timeout=timeout) as response:
            return response.status, json.loads(response.read().decode())
    except urllib.error.HTTPError as exc:
        return exc.code, json.loads(exc.read().decode())


def _get(base_url, path, timeout=10.0):
    try:
        with urllib.request.urlopen(
            base_url + path, timeout=timeout
        ) as response:
            return response.status, json.loads(response.read().decode())
    except urllib.error.HTTPError as exc:
        return exc.code, json.loads(exc.read().decode())


def _wait_for(predicate, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.01)
    return predicate()


# ---------------------------------------------------------------------------
# TraceContext unit behaviour
# ---------------------------------------------------------------------------


class TestTraceContext:
    def test_span_tree_export(self):
        trace = TraceContext("t-1")
        root = trace.begin("http_request", method="best_of_n")
        child = trace.begin("queue_wait", parent=root, replica="r0")
        trace.event(child, "probe", detail=1)
        trace.end(child)
        trace.end(root, status=200)
        exported = trace.to_dict()
        assert exported["trace_id"] == "t-1"
        by_name = {s["name"]: s for s in exported["spans"]}
        assert by_name["queue_wait"]["parent"] == root
        assert by_name["http_request"]["attrs"]["status"] == 200
        assert not by_name["http_request"]["in_flight"]
        assert by_name["queue_wait"]["events"][0]["name"] == "probe"

    def test_end_is_idempotent_first_wins(self):
        trace = TraceContext("t-2")
        span = trace.begin("handler")
        trace.end(span, outcome="ok")
        first = trace.to_dict()["spans"][0]["duration_s"]
        time.sleep(0.02)
        trace.end(span, outcome="late")  # attrs update, duration does not
        again = trace.to_dict()["spans"][0]
        assert again["duration_s"] == first
        assert again["attrs"]["outcome"] == "late"

    def test_span_cap_returns_noop_sentinel(self):
        trace = TraceContext("t-3")
        ids = [trace.begin(f"s{i}") for i in range(MAX_SPANS_PER_TRACE + 5)]
        assert ids[-1] == 0
        assert trace.dropped_spans == 5
        trace.end(0, outcome="ignored")  # must not raise
        trace.event(0, "ignored")
        assert len(trace.to_dict()["spans"]) == MAX_SPANS_PER_TRACE

    def test_critical_path_partitions_root_exactly(self):
        trace = TraceContext("t-4")
        root = trace.begin("http_request")
        queue = trace.begin("queue_wait", parent=root)
        time.sleep(0.01)
        trace.end(queue)
        row = trace.begin("engine_row", parent=root)
        time.sleep(0.005)
        trace.event(row, "slot_admitted")
        time.sleep(0.005)
        trace.event(row, "prefill_complete")
        time.sleep(0.01)
        trace.end(row, outcome="retired")
        score = trace.begin("engine_score", parent=root)
        time.sleep(0.005)
        trace.end(score)
        trace.end(root)
        path = trace.critical_path()
        phases = path["phases"]
        assert abs(sum(phases.values()) - path["total_s"]) < 1e-4
        for name in ("queue_wait", "admission_wait", "prefill", "decode",
                     "score"):
            assert phases[name] > 0.0, name
        assert phases["failover_overhead"] == 0.0


class TestUseTrace:
    def test_thread_local_carrier_nests_and_restores(self):
        trace = TraceContext("t-5")
        assert trace_current() is None
        with use_trace(trace, 1):
            assert trace_current() == (trace, 1)
            with use_trace(trace, 2):
                assert trace_current() == (trace, 2)
            assert trace_current() == (trace, 1)
        assert trace_current() is None

    def test_none_trace_is_passthrough(self):
        with use_trace(None, 7):
            assert trace_current() is None


class TestTraceStore:
    def test_lru_bound_and_recency(self):
        store = TraceStore(capacity=3)
        for i in range(5):
            store.put(TraceContext(f"t{i}"))
        assert len(store) == 3
        assert store.get("t0") is None and store.get("t1") is None
        assert store.get("t2") is not None
        # touching t2 makes t3 the eviction victim
        store.put(TraceContext("t5"))
        assert store.get("t3") is None
        assert store.get("t2") is not None


# ---------------------------------------------------------------------------
# IterationLedger / RollingWindow / FlightRecorder units
# ---------------------------------------------------------------------------


class TestIterationLedger:
    def test_residual_is_attributed_and_coverage_full(self):
        ledger = IterationLedger()
        ledger.record(
            start_s=10.0, end_s=10.1, idle_s=0.0, device_s=0.06,
            host={"sweep": 0.01, "admit": 0.005, "prefill": 0.0,
                  "cohort": 0.005, "merge": 0.01},
            tokens=32, cohort=4, queue_depth=2, pages_in_use=16,
        )
        ledger.record(
            start_s=10.15, end_s=10.25, idle_s=0.05, device_s=0.08,
            host={"sweep": 0.005, "admit": 0.0, "prefill": 0.0,
                  "cohort": 0.0, "merge": 0.005},
            tokens=16, cohort=2, queue_depth=0, pages_in_use=8,
        )
        report = ledger.mfu_attribution()
        assert report["iterations"] == 2
        assert report["tokens"] == 48
        assert report["coverage"] >= 0.95
        # residual host time (0.1 - 0.06 - 0.03 = 0.01) lands in "other"
        assert report["host_breakdown"]["other"] == pytest.approx(
            0.02, abs=1e-6)
        fractions = (report["device_fraction"] + report["host_fraction"]
                     + report["idle_fraction"])
        assert fractions == pytest.approx(1.0, abs=0.02)
        assert ledger.recent(1)[0]["iteration"] == 2


class TestRollingWindow:
    def test_buckets_availability_and_p95(self):
        window = RollingWindow(bucket_s=1.0)
        for t in (0.1, 0.5, 0.9):
            window.observe(t, ok=True, latency_s=0.010)
        window.observe(1.2, ok=False)
        window.observe(1.8, ok=True, latency_s=0.100)
        curve = window.curve()
        assert [row["t_s"] for row in curve] == [0.0, 1.0]
        assert curve[0]["offered"] == 3 and curve[0]["availability"] == 1.0
        assert curve[1]["availability"] == 0.5
        assert curve[1]["p95_ms"] == pytest.approx(100.0)
        assert curve[0]["rps"] == pytest.approx(3.0)


class TestFlightRecorderUnit:
    def test_dump_without_path_is_noop(self):
        recorder = FlightRecorder()
        recorder.record_event("replica_lost", replica="r0")
        assert recorder.dump("test") is None
        assert recorder.dumps == 0

    def test_dump_writes_parseable_blackbox(self, tmp_path):
        path = str(tmp_path / "blackbox.json")
        recorder = FlightRecorder(path=path)
        recorder.record_event("breaker_open", breaker="fake")
        recorder.record_iteration({"iteration": 1, "total_s": 0.01})
        assert recorder.dump("unit_test") == path
        with open(path, encoding="utf-8") as handle:
            blackbox = json.load(handle)
        assert blackbox["schema"] == FlightRecorder.SCHEMA
        assert blackbox["reason"] == "unit_test"
        assert blackbox["events"][0]["kind"] == "breaker_open"
        assert blackbox["iterations"][0]["iteration"] == 1
        assert recorder.dumps == 1

    def test_rings_are_bounded(self):
        recorder = FlightRecorder(max_events=4, max_iterations=2)
        for i in range(10):
            recorder.record_event("scale_up", replica=f"r{i}")
            recorder.record_iteration({"iteration": i})
        snapshot = recorder.snapshot()
        assert len(snapshot["events"]) == 4
        assert len(snapshot["iterations"]) == 2
        assert snapshot["events"][-1]["replica"] == "r9"


# ---------------------------------------------------------------------------
# End-to-end: HTTP -> scheduler -> engine span tree
# ---------------------------------------------------------------------------


class TestEndToEndTrace:
    def test_trace_block_endpoint_and_critical_path_sum(self):
        server = create_server(
            backend=FakeBackend(), port=0, registry=Registry()).start()
        try:
            # warm the stack (connection setup, lazy imports, first-flush
            # compile) so the measured request's latency is the span's
            _post(server.base_url, _payload(seed=30))
            start = time.perf_counter()
            status, body = _post(server.base_url, _payload(
                seed=31, request_id="trace-e2e-1", trace=True))
            latency_s = time.perf_counter() - start
            assert status == 200
            trace_block = body["trace"]
            assert trace_block["trace_id"] == "trace-e2e-1"
            names = {s["name"] for s in trace_block["spans"]}
            assert {"http_request", "queue_wait", "handler"} <= names
            assert "engine_row" in names  # slot lifecycle reached
            path = trace_block["critical_path"]
            total = path["total_s"]
            assert abs(sum(path["phases"].values()) - total) < 1e-4
            # the root span's wall is the request latency (within 5%, the
            # acceptance bar; the HTTP hop outside the span is the slack)
            assert total <= latency_s
            assert total >= 0.95 * latency_s - 0.010

            status, exported = _get(server.base_url, "/v1/trace/trace-e2e-1")
            assert status == 200
            assert exported["trace_id"] == "trace-e2e-1"
            assert {s["name"] for s in exported["spans"]} >= {
                "http_request", "handler"}
            assert "critical_path" in exported

            status, error = _get(server.base_url, "/v1/trace/never-existed")
            assert status == 404
            assert error["error"]["type"] == "trace_not_found"
        finally:
            server.stop(drain=False, timeout=5.0)

    def test_trace_off_responses_have_no_trace_block(self):
        server = create_server(
            backend=FakeBackend(), port=0, registry=Registry()).start()
        try:
            status, body = _post(server.base_url, _payload(seed=32))
            assert status == 200
            assert "trace" not in body
        finally:
            server.stop(drain=False, timeout=5.0)

    def test_server_mints_request_id_and_echoes_in_success(self):
        server = create_server(
            backend=FakeBackend(), port=0, registry=Registry()).start()
        try:
            payload = _payload(seed=33)
            del payload["request_id"]
            status, body = _post(server.base_url, payload)
            assert status == 200
            assert body["request_id"].startswith("srv-")
            # deterministic digest: same payload -> same digest suffix
            status2, body2 = _post(server.base_url, payload)
            assert body["request_id"].split("-")[2] == \
                body2["request_id"].split("-")[2]
            assert body["request_id"] != body2["request_id"]  # seq differs
        finally:
            server.stop(drain=False, timeout=5.0)

    def test_minted_request_id_echoed_in_rejection_body(self):
        class SlowGen:
            name = "slow"

            def __init__(self):
                self.inner = FakeBackend()

            def __getattr__(self, attr):
                return getattr(self.inner, attr)

            def generate(self, requests):
                time.sleep(0.2)
                return self.inner.generate(requests)

        server = create_server(
            backend=SlowGen(), port=0, registry=Registry(),
            max_inflight=1, max_queue_depth=1).start()
        try:
            results = []

            def fire(seed):
                payload = _payload(seed=seed)
                del payload["request_id"]
                results.append(_post(server.base_url, payload))

            threads = [threading.Thread(target=fire, args=(40 + i,),
                                        daemon=True)
                       for i in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30.0)
            rejected = [b for s, b in results if s == 429]
            assert rejected, "capacity 1+1 under 8 concurrent posts must 429"
            for body in rejected:
                assert body["error"]["request_id"].startswith("srv-")
        finally:
            server.stop(drain=False, timeout=5.0)

    def _rejection_response(self, server, exc):
        """POST (no client request_id) with submit forced to reject."""
        scheduler = server.scheduler

        def rejecting_submit(request):
            raise exc

        scheduler.submit = rejecting_submit
        payload = _payload(seed=60)
        del payload["request_id"]
        request = urllib.request.Request(
            server.base_url + "/v1/consensus",
            data=json.dumps(payload).encode("utf-8"),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(request, timeout=10.0):
                raise AssertionError("rejection expected")
        except urllib.error.HTTPError as err:
            return err.code, json.loads(err.read().decode()), err.headers

    def test_breaker_open_503_carries_request_id(self):
        server = create_server(
            backend=FakeBackend(), port=0, registry=Registry()).start()
        try:
            status, body, headers = self._rejection_response(
                server,
                SchedulerRejected("breaker_open",
                                  "circuit breaker open to backend",
                                  retry_after_s=3.0),
            )
            assert status == 503
            error = body["error"]
            assert error["type"] == "rejected"
            assert error["reason"] == "breaker_open"
            assert error["request_id"].startswith("srv-")
            assert headers["Retry-After"] is not None
        finally:
            server.stop(drain=False, timeout=5.0)

    def test_kv_oom_413_carries_request_id(self):
        server = create_server(
            backend=FakeBackend(), port=0, registry=Registry()).start()
        try:
            status, body, headers = self._rejection_response(
                server,
                SchedulerRejected("kv_oom",
                                  "request KV footprint exceeds pool"),
            )
            assert status == 413
            error = body["error"]
            assert error["type"] == "rejected"
            assert error["reason"] == "kv_oom"
            assert error["request_id"].startswith("srv-")
            # Oversized requests don't shrink on retry: no Retry-After.
            assert headers["Retry-After"] is None
        finally:
            server.stop(drain=False, timeout=5.0)


# ---------------------------------------------------------------------------
# Failover span tree (mid-flight replica kill)
# ---------------------------------------------------------------------------


class _SlowBackend:
    """FakeBackend with a per-dispatch delay so kills land mid-flight."""

    name = "slow-fake"

    def __init__(self, delay_s=0.05):
        self.inner = FakeBackend()
        self.delay_s = delay_s

    def __getattr__(self, attr):
        return getattr(self.inner, attr)

    def generate(self, requests):
        time.sleep(self.delay_s)
        return self.inner.generate(requests)

    def score(self, requests):
        time.sleep(self.delay_s)
        return self.inner.score(requests)


@pytest.mark.chaos
class TestFailoverTrace:
    def test_span_tree_holds_both_dispatches_across_kill(self):
        registry = Registry()
        replicas = [
            Replica(f"r{i}", _SlowBackend(), registry=registry,
                    scheduler_options={"max_inflight": 2,
                                       "max_queue_depth": 6,
                                       "default_timeout_s": 30.0})
            for i in range(3)
        ]
        router = FleetRouter(replicas, registry=registry)
        server = ConsensusServer(router, port=0, registry=registry).start()
        try:
            payload = _payload(seed=51, request_id="trace-failover-1",
                               trace=True)
            doomed = router.route_for(parse_request(payload))
            outbox = {}

            def fire():
                outbox["result"] = _post(server.base_url, payload)

            thread = threading.Thread(target=fire, daemon=True)
            thread.start()
            assert _wait_for(
                lambda: doomed.scheduler.stats()["inflight"] > 0)
            router.kill_replica(doomed.name)
            thread.join(timeout=30.0)

            status, body = outbox["result"]
            assert status == 200
            assert body["served_by"] and body["served_by"] != doomed.name

            trace = get_trace_store().get("trace-failover-1")
            assert trace is not None
            spans = trace.to_dict()["spans"]
            dispatches = [s for s in spans if s["name"] == "dispatch"]
            assert len(dispatches) >= 2
            reasons = [s["attrs"]["reason"] for s in dispatches]
            assert reasons[0] == "primary"
            assert any(r != "primary" for r in reasons[1:])
            assert dispatches[0]["attrs"]["replica"] == doomed.name
            finals = [s for s in dispatches if s["attrs"].get("final")]
            assert len(finals) == 1
            assert finals[0]["attrs"]["replica"] == body["served_by"]
            # failover time shows up as an explicit critical-path phase
            path = trace.critical_path()
            assert path["phases"]["failover_overhead"] > 0.0
            assert abs(sum(path["phases"].values())
                       - path["total_s"]) < 1e-4

            # and the whole tree is retrievable over HTTP
            status, exported = _get(
                server.base_url, "/v1/trace/trace-failover-1")
            assert status == 200
            assert len([s for s in exported["spans"]
                        if s["name"] == "dispatch"]) >= 2
        finally:
            server.stop(drain=False, timeout=5.0)


# ---------------------------------------------------------------------------
# Engine iteration ledger: MFU attribution coverage
# ---------------------------------------------------------------------------


class TestEngineMfuAttribution:
    def test_ledger_covers_engine_wall_time(self):
        engine = DecodeEngine(
            FakeBackend(), slots=8, num_pages=512, auto_start=False,
        )
        outboxes = []
        threads = []
        try:
            for i in range(4):
                out = {}

                def worker(i=i, out=out):
                    out["result"] = engine.submit("generate", [
                        GenerationRequest(
                            user_prompt=f"prompt {i} with extra words",
                            max_tokens=8, seed=i,
                        )])

                thread = threading.Thread(target=worker, daemon=True)
                thread.start()
                threads.append(thread)
                outboxes.append(out)
            assert _wait_for(
                lambda: engine.stats()["queue_depth"] == 4)
            for _ in range(3):
                engine.run_iteration()
            for thread in threads:
                thread.join(timeout=10.0)
            assert all("result" in out for out in outboxes)
            report = engine.stats()["mfu_attribution"]
            assert report["iterations"] >= 3
            assert report["tokens"] > 0
            assert report["device_s"] > 0.0
            assert report["coverage"] >= 0.95  # the acceptance bar
            fractions = (report["device_fraction"] + report["host_fraction"]
                         + report["idle_fraction"])
            assert fractions == pytest.approx(1.0, abs=0.05)
            assert set(report["host_breakdown"]) == set(
                IterationLedger.HOST_PHASES)
        finally:
            engine.close()


# ---------------------------------------------------------------------------
# Watchdog trip -> blackbox dump (integration)
# ---------------------------------------------------------------------------


@pytest.mark.chaos
class TestWatchdogBlackbox:
    def test_watchdog_trip_dumps_blackbox(self, tmp_path):
        path = str(tmp_path / "blackbox.json")
        recorder = get_flight_recorder()
        recorder.configure(path)
        plan = FaultPlan(seed=1, faults=[
            FaultSpec(kind="hang", op="generate", call_index=0)])
        faulty = FaultInjectingBackend(FakeBackend(), plan)
        batching = BatchingBackend(
            faulty, engine=True,
            engine_options={"watchdog_timeout_s": 0.2},
        )
        try:
            thread = threading.Thread(
                target=lambda: batching.generate(
                    [GenerationRequest(user_prompt="hello", max_tokens=4)]),
                daemon=True,
            )
            thread.start()
            assert _wait_for(lambda: faulty.hangs_active == 1, timeout=5.0)
            assert _wait_for(
                lambda: batching.engine.watchdog_trips >= 1, timeout=5.0)
            assert _wait_for(lambda: recorder.dumps >= 1, timeout=5.0)
            with open(path, encoding="utf-8") as handle:
                blackbox = json.load(handle)
            assert blackbox["schema"] == FlightRecorder.SCHEMA
            assert blackbox["reason"] == "watchdog_trip"
            kinds = [e["kind"] for e in blackbox["events"]]
            assert "watchdog_trip" in kinds
        finally:
            faulty.release_hangs()
            batching.close()
            recorder.configure(None)
