"""Artifact-contract fixture: a COMPLETE committed run directory.

``tests/golden/fake_smoke_run/`` is a full ``run_experiment_with_eval``
pass (fake backend, 2 seeds, all phases incl. the LLM-judge comparative
ranking) committed to git (VERDICT r2 #9).  The reference documents this
exact tree in its readme (readme.md:192-215); these tests pin that a fresh
run still produces the same tree, the same results.csv schema, and — the
fake backend being deterministic — the same statements.
"""

import pathlib

import pandas as pd
import pytest
import yaml

GOLDEN = pathlib.Path(__file__).parent / "golden" / "fake_smoke_run"


def relative_files(root: pathlib.Path):
    return sorted(str(p.relative_to(root)) for p in root.rglob("*") if p.is_file())


def test_golden_tree_is_complete():
    files = relative_files(GOLDEN)
    for expected in [
        "config.yaml",
        "results.csv",
        "journal.jsonl",
        "timing.json",
        "token_counts.json",
        "metrics.json",
        "metrics.prom",
        "evaluation/improved_aggregate/aggregated_metrics.csv",
        "evaluation/improved_aggregate/aggregated_metrics_raw.csv",
        "evaluation/fake-lm/seed_0/evaluation_results.csv",
        "evaluation/llm_judge/seed_0/ranking_results.csv",
        "evaluation/llm_judge/seed_0/comparative_ranking_matrix.json",
    ]:
        assert expected in files, f"golden run dir missing {expected}"


def test_golden_results_schema():
    frame = pd.read_csv(GOLDEN / "results.csv")
    for column in [
        "method",
        "statement",
        "generation_time_s",
        "seed",
        "error_message",
        "evaluation_status",
    ]:
        assert column in frame.columns
    assert (frame["evaluation_status"] == "pending").all()
    assert len(frame) > 0


@pytest.fixture(scope="module")
def fresh_run(tmp_path_factory):
    """Re-run the committed config through the full pipeline."""
    from consensus_tpu.cli.run_experiment_with_eval import run_pipeline

    config = yaml.safe_load((GOLDEN / "config.yaml").read_text())
    config["output_dir"] = str(tmp_path_factory.mktemp("rerun"))
    config_path = tmp_path_factory.mktemp("cfg") / "config.yaml"
    config_path.write_text(yaml.safe_dump(config))
    return pathlib.Path(run_pipeline(str(config_path)))


def test_fresh_run_reproduces_golden_tree(fresh_run):
    assert relative_files(fresh_run) == relative_files(GOLDEN)


def test_fresh_run_reproduces_golden_statements(fresh_run):
    golden = pd.read_csv(GOLDEN / "results.csv")
    fresh = pd.read_csv(fresh_run / "results.csv")
    assert list(fresh.columns) == list(golden.columns)
    pd.testing.assert_frame_equal(
        fresh[["method", "statement", "seed"]],
        golden[["method", "statement", "seed"]],
    )


def test_fresh_run_reproduces_aggregate_metrics(fresh_run):
    golden = pd.read_csv(GOLDEN / "evaluation/improved_aggregate/aggregated_metrics.csv")
    fresh = pd.read_csv(fresh_run / "evaluation/improved_aggregate/aggregated_metrics.csv")
    assert list(fresh.columns) == list(golden.columns)
    metric_cols = [c for c in golden.columns if c.endswith(("_mean", "_std"))]
    pd.testing.assert_frame_equal(
        fresh[metric_cols].round(6), golden[metric_cols].round(6)
    )
