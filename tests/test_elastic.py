"""Elastic fleet: replica lifecycle manager, warm prefix-KV handoff,
pressure-driven autoscaler, and the engine hang watchdog.

Covers the PR-13 acceptance claims:

* ``hang`` fault spec blocks an op forever; ``release_hangs`` unsticks it.
* The DecodeEngine watchdog converts a wedged device dispatch into
  ``backend_lost`` — the existing health ladder then does the rest.
* Rendezvous hashing gives minimal disruption on replica JOIN (only keys
  the new name wins move), and a same-name respawn restores the mapping
  exactly (affinity recovers after the kill/respawn cycle).
* The ReplicaManager's ladder: loss -> backoff respawn under the old
  name -> warm PageStore pre-seed -> rejoin; flapping names quarantine.
* Warm handoff is byte-identical: statements served from migrated pages
  equal cold-cache statements, and the respawned replica's prefix cache
  hits immediately instead of re-prefilling.
* The Autoscaler's control law composes with the brownout tiers without
  oscillation (capacity lever fires before the quality levers, pinned
  against the brownout thresholds).
"""

import threading
import time

import pytest

from consensus_tpu.backends import FakeBackend
from consensus_tpu.backends.batching import BatchingBackend
from consensus_tpu.backends.faults import (
    FaultInjectingBackend,
    FaultPlan,
    FaultSpec,
)
from consensus_tpu.obs.metrics import Registry
from consensus_tpu.serve import (
    Autoscaler,
    ConsensusService,
    FleetRouter,
    PageStore,
    Replica,
    ReplicaManager,
    RequestScheduler,
    parse_request,
)
from consensus_tpu.serve.router import _rendezvous_weight

ISSUE = "Should we invest in public transport?"
OPINIONS = {
    "Agent 1": "Yes, buses are vital.",
    "Agent 2": "Only with congestion pricing.",
}


def _payload(seed=7, issue=ISSUE, **overrides):
    payload = {
        "issue": issue,
        "agent_opinions": dict(OPINIONS),
        "method": "best_of_n",
        "params": {"n": 2, "max_tokens": 16},
        "seed": seed,
        "request_id": f"req-{seed}",
    }
    payload.update(overrides)
    return payload


def _wait_for(predicate, timeout=10.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


# ---------------------------------------------------------------------------
# hang fault + engine watchdog
# ---------------------------------------------------------------------------


@pytest.mark.chaos
class TestHangFault:
    def test_hang_blocks_until_released(self):
        plan = FaultPlan(seed=1, faults=[
            FaultSpec(kind="hang", op="score", call_index=0)])
        faulty = FaultInjectingBackend(FakeBackend(), plan,
                                       registry=Registry())
        from consensus_tpu.backends import ScoreRequest

        done = threading.Event()

        def call():
            faulty.score([ScoreRequest(context="p", continuation="c")])
            done.set()

        thread = threading.Thread(target=call, daemon=True)
        thread.start()
        assert _wait_for(lambda: faulty.hangs_active == 1, timeout=5.0)
        assert not done.is_set()
        faulty.release_hangs()
        thread.join(timeout=5.0)
        assert done.is_set()
        assert faulty.hangs_active == 0

    def test_hang_rejects_unknown_op(self):
        with pytest.raises(ValueError):
            FaultSpec(kind="hang", op="definitely-not-an-op")


@pytest.mark.chaos
class TestEngineWatchdog:
    def _wedged_stack(self, registry, timeout_s=0.2):
        plan = FaultPlan(seed=1, faults=[
            FaultSpec(kind="hang", op="generate", call_index=0)])
        faulty = FaultInjectingBackend(FakeBackend(), plan,
                                       registry=registry)
        batching = BatchingBackend(
            faulty, registry=registry, engine=True,
            engine_options={"watchdog_timeout_s": timeout_s},
        )
        return faulty, batching

    def test_watchdog_trips_on_wedged_dispatch(self):
        registry = Registry()
        faulty, batching = self._wedged_stack(registry)
        engine = batching.engine
        try:
            from consensus_tpu.backends import GenerationRequest

            thread = threading.Thread(
                target=lambda: batching.generate(
                    [GenerationRequest(user_prompt="hello", max_tokens=4)]),
                daemon=True,
            )
            thread.start()
            assert _wait_for(lambda: faulty.hangs_active == 1, timeout=5.0)
            # The engine loop is parked inside the hang; nobody advances
            # decode until the watchdog converts that into a loss.
            assert _wait_for(lambda: engine.backend_lost, timeout=5.0)
            assert engine.wedged
            assert engine.watchdog_trips >= 1
            # stats() stays readable with the loop thread wedged — the
            # monitor/healthz path must not depend on the engine lock the
            # dispatcher holds.
            watchdog = engine.stats()["watchdog"]
            assert watchdog["enabled"] and watchdog["wedged"]
            metrics = registry.to_prometheus()
            assert "engine_watchdog_trips_total" in metrics
        finally:
            faulty.release_hangs()
            batching.close()

    def test_idle_engine_never_trips(self):
        registry = Registry()
        batching = BatchingBackend(
            FakeBackend(), registry=registry, engine=True,
            engine_options={"watchdog_timeout_s": 0.05},
        )
        try:
            time.sleep(0.3)  # several watchdog intervals, zero dispatches
            assert not batching.engine.wedged
            assert batching.engine.watchdog_trips == 0
        finally:
            batching.close()


# ---------------------------------------------------------------------------
# rendezvous: minimal disruption on JOIN
# ---------------------------------------------------------------------------


class TestRendezvousJoin:
    def test_join_moves_only_keys_the_new_name_wins(self):
        names = ["r0", "r1", "r2"]
        keys = [f"scenario-{i}" for i in range(200)]

        def winner(pool, key):
            return max(pool, key=lambda n: _rendezvous_weight(key, n))

        before = {k: winner(names, k) for k in keys}
        after = {k: winner(names + ["r3"], k) for k in keys}
        moved = {k for k in keys if before[k] != after[k]}
        # Every moved key moved TO the joiner; nothing reshuffled between
        # surviving names — the minimal-disruption property.
        assert moved, "a 200-key universe should hand the joiner some keys"
        assert all(after[k] == "r3" for k in moved)
        # And the joiner's share is roughly fair (1/4 of keys +/- slack).
        assert 20 <= len(moved) <= 90

    def test_same_name_rejoin_restores_the_exact_mapping(self):
        names = ["r0", "r1", "r2"]
        keys = [f"scenario-{i}" for i in range(100)]

        def winner(pool, key):
            return max(pool, key=lambda n: _rendezvous_weight(key, n))

        before = {k: winner(names, k) for k in keys}
        survivors = ["r1", "r2"]
        rejoined = {k: winner(survivors + ["r0"], k) for k in keys}
        assert rejoined == before


# ---------------------------------------------------------------------------
# elastic fleet harness
# ---------------------------------------------------------------------------


def _elastic_fleet(
    n=3,
    *,
    registry=None,
    fault_plans=None,
    watchdog_timeout_s=None,
    manager_kwargs=None,
    clock=None,
):
    """A FleetRouter over FakeBackend engine replicas plus a fast-knob
    ReplicaManager.  ``fault_plans`` arms a name's FIRST life only, like
    the production factory — a deterministic kill must not respawn-loop
    into quarantine."""
    registry = registry if registry is not None else Registry()
    engine_options = {"prefix_cache": True}
    if watchdog_timeout_s is not None:
        engine_options["watchdog_timeout_s"] = watchdog_timeout_s
    scheduler_options = {
        "max_inflight": 2, "max_queue_depth": 16,
        "default_timeout_s": 30.0, "retry_backoff_s": 0.001,
        "engine": True, "engine_options": engine_options,
    }
    built = set()
    injectors = []

    def factory(name, tier=None):
        plan = None
        if fault_plans and name in fault_plans and name not in built:
            plan = fault_plans[name]
        built.add(name)
        backend = FakeBackend()
        if plan is not None:
            backend = FaultInjectingBackend(backend, plan,
                                            registry=registry)
            injectors.append(backend)
        return Replica(
            name, backend, tier=tier or "full", registry=registry,
            scheduler_options=dict(scheduler_options),
        )

    replicas = [factory(f"r{i}") for i in range(n)]
    router = FleetRouter(replicas, registry=registry).start()
    kwargs = {
        "respawn_backoff_s": 0.05,
        "respawn_backoff_max_s": 0.4,
        "check_interval_s": 0.05,
        "harvest_interval_s": 0.1,
        "retire_timeout_s": 1.0,
        "flap_window_s": 30.0,
        "flap_threshold": 3,
    }
    kwargs.update(manager_kwargs or {})
    if clock is not None:
        kwargs["clock"] = clock
    manager = ReplicaManager(
        router, factory, page_store=PageStore(registry=registry),
        registry=registry, **kwargs,
    )
    return router, manager, injectors


def _shutdown(router, injectors=()):
    for injector in injectors:
        injector.release_hangs()
    router.shutdown(drain=False, timeout=10.0)


# ---------------------------------------------------------------------------
# lifecycle ladder: kill -> respawn -> rejoin (same name, warm pages)
# ---------------------------------------------------------------------------


class TestReplicaManagerRespawn:
    def test_kill_respawns_under_the_same_name(self):
        registry = Registry()
        router, manager, _ = _elastic_fleet(3, registry=registry)
        try:
            assert router.manager is manager
            router.kill_replica("r0")
            assert _wait_for(
                lambda: manager.snapshot()["respawns"] >= 1
                and router.stats()["fleet"]["healthy"] == 3,
                timeout=10.0,
            )
            names = sorted(r.name for r in router.replicas)
            assert names == ["r0", "r1", "r2"]
            fresh = router._replica("r0")
            assert not fresh.lost
            snap = manager.snapshot()
            assert snap["losses"] == 1
            assert snap["quarantined"] == {}
            assert "fleet_respawns_total 1" in registry.to_prometheus()
        finally:
            _shutdown(router)

    def test_affinity_recovers_after_same_name_respawn(self):
        router, manager, _ = _elastic_fleet(3)
        try:
            requests = [parse_request(_payload(seed=i, issue=f"issue {i}"))
                        for i in range(30)]
            before = {req.request_id: router.route_for(req).name
                      for req in requests}
            victim = before[requests[0].request_id]
            router.kill_replica(victim)
            assert _wait_for(
                lambda: router.stats()["fleet"]["healthy"] == 3,
                timeout=10.0,
            )
            after = {req.request_id: router.route_for(req).name
                     for req in requests}
            # Same names back in the pool => identical rendezvous winners:
            # every scenario lands exactly where it did pre-kill, so warm
            # prefix pages and client affinity line up again.
            assert after == before
        finally:
            _shutdown(router)

    def test_set_target_scales_up_and_down(self):
        router, manager, _ = _elastic_fleet(3)
        try:
            manager.set_target(4)
            assert _wait_for(
                lambda: len(router.replicas) == 4
                and router.stats()["fleet"]["healthy"] == 4,
                timeout=10.0,
            )
            # Fresh capacity joins under a fresh name, never a corpse's.
            assert sorted(r.name for r in router.replicas) == [
                "r0", "r1", "r2", "r3"]
            manager.set_target(3)
            assert _wait_for(lambda: len(router.replicas) == 3, timeout=10.0)
            # Scale-down retires the newest member, keeping the seed names.
            assert sorted(r.name for r in router.replicas) == [
                "r0", "r1", "r2"]
        finally:
            _shutdown(router)


# ---------------------------------------------------------------------------
# flap detector -> quarantine (fake clock, deterministic ticks)
# ---------------------------------------------------------------------------


class TestFlapQuarantine:
    def test_flapping_name_quarantines_and_operator_clears(self):
        now = [0.0]
        registry = Registry()
        router, manager, _ = _elastic_fleet(
            3, registry=registry, clock=lambda: now[0],
            manager_kwargs={"auto_start": False, "flap_threshold": 3,
                            "flap_window_s": 30.0,
                            "respawn_backoff_s": 0.05},
        )
        try:
            for cycle in range(3):
                router.kill_replica("r0")
                manager.tick()  # detect the loss
                now[0] += 1.0
                manager.tick()  # respawn when due (backoff < 1s)
                if cycle < 2:
                    assert any(r.name == "r0" for r in router.replicas), (
                        f"cycle {cycle}: r0 should have respawned")
            snap = manager.snapshot()
            assert "r0" in snap["quarantined"]
            assert snap["effective_target"] == 2
            assert not any(r.name == "r0" for r in router.replicas)
            assert snap["pending_respawns"] == []
            # Quarantine does NOT backfill with a fresh name: the flap is
            # a signal a fresh stack would not outrun.
            assert sorted(r.name for r in router.replicas) == ["r1", "r2"]
            assert "fleet_quarantined_total 1" in registry.to_prometheus()

            assert manager.clear_quarantine("r0")
            manager.tick()
            assert any(r.name == "r0" for r in router.replicas)
            assert manager.snapshot()["quarantined"] == {}
        finally:
            _shutdown(router)

    def test_respawn_backoff_doubles_and_caps(self):
        now = [0.0]
        router, manager, _ = _elastic_fleet(
            3, clock=lambda: now[0],
            manager_kwargs={"auto_start": False, "flap_threshold": 10,
                            "respawn_backoff_s": 0.2,
                            "respawn_backoff_max_s": 0.5},
        )
        try:
            router.kill_replica("r0")
            manager.tick()
            assert "r0" in manager.snapshot()["pending_respawns"]
            # Not due yet: the first backoff is 0.2s of fake time.
            now[0] += 0.1
            manager.tick()
            assert not any(r.name == "r0" for r in router.replicas)
            now[0] += 0.15
            manager.tick()
            assert any(r.name == "r0" for r in router.replicas)
        finally:
            _shutdown(router)


# ---------------------------------------------------------------------------
# warm handoff: PageStore capture -> seed -> byte-identity
# ---------------------------------------------------------------------------


class TestWarmHandoff:
    def _engine_scheduler(self, registry):
        backend = FakeBackend()
        service = ConsensusService(backend)
        scheduler = RequestScheduler(
            service.run, backend, registry=registry,
            max_inflight=2, max_queue_depth=16, default_timeout_s=30.0,
            engine=True, engine_options={"prefix_cache": True},
        )
        return scheduler.start()

    def _run(self, scheduler, payloads):
        tickets = [scheduler.submit(parse_request(p)) for p in payloads]
        for ticket in tickets:
            assert ticket.wait(30.0)
            assert ticket.outcome == "ok"
        return [t.result()["statement"] for t in tickets]

    def test_seeded_engine_serves_byte_identical_statements_warm(self):
        registry = Registry()
        donor = self._engine_scheduler(registry)
        payloads = [_payload(seed=100 + i) for i in range(4)]
        try:
            cold_statements = self._run(donor, payloads)
            store = PageStore(registry=registry)
            captured = store.capture_engine(donor.batching.engine)
            assert captured > 0
            assert len(store) > 0
        finally:
            donor.shutdown(drain=False, timeout=10.0)

        joiner = self._engine_scheduler(registry)
        try:
            adopted = store.seed_engine(joiner.batching.engine)
            assert adopted > 0
            cache = joiner.batching.engine.prefix_cache
            assert cache.hits == 0  # seeding itself is not a hit
            warm_statements = self._run(joiner, payloads)
            # Byte-identity: migrated pages change WHERE prefill comes
            # from, never what the model computes.
            assert warm_statements == cold_statements
            # And the pages were actually used: the joiner's FIRST pass
            # over these scenarios hits, where a cold replica would miss.
            assert cache.hits > 0
            assert joiner.batching.engine.stats()[
                "prefix_cache"]["tokens_saved"] > 0
        finally:
            joiner.shutdown(drain=False, timeout=10.0)

    def test_identity_mismatch_refuses_adoption(self):
        from consensus_tpu.ops.kv_pages import PagePool, PrefixCache

        registry = Registry()
        donor_pool = PagePool(num_pages=32, page_size=4)
        donor = PrefixCache(donor_pool, max_pages=32,
                            identity=("tier-a", "tp1"))
        tokens = tuple(range(8))
        pages = donor_pool.alloc(2)
        assert donor.insert(tokens, pages)
        donor_pool.free(pages)

        store = PageStore(registry=registry)
        assert store.capture_cache(donor) == 1

        class OneCacheEngine:
            def __init__(self, cache):
                self.prefix_caches = [cache]
                self.inner = None

        mismatched = PrefixCache(PagePool(num_pages=32, page_size=4),
                                 max_pages=32, identity=("tier-b", "tp1"))
        assert store.seed_engine(OneCacheEngine(mismatched)) == 0
        assert len(mismatched._entries) == 0
        assert "pagestore_identity_rejects_total 1" in (
            registry.to_prometheus())

        matched = PrefixCache(PagePool(num_pages=32, page_size=4),
                              max_pages=32, identity=("tier-a", "tp1"))
        assert store.seed_engine(OneCacheEngine(matched)) == 1
        found, n_tokens = matched.lookup(tokens)
        assert n_tokens == 8 and len(found) == 2

    def test_page_size_mismatch_refuses_adoption(self):
        from consensus_tpu.ops.kv_pages import PagePool, PrefixCache

        donor_pool = PagePool(num_pages=32, page_size=4)
        donor = PrefixCache(donor_pool, max_pages=32, identity=("m",))
        pages = donor_pool.alloc(1)
        assert donor.insert(tuple(range(4)), pages)
        donor_pool.free(pages)
        store = PageStore()
        store.capture_cache(donor)

        class OneCacheEngine:
            def __init__(self, cache):
                self.prefix_caches = [cache]
                self.inner = None

        other = PrefixCache(PagePool(num_pages=32, page_size=8),
                            max_pages=32, identity=("m",))
        assert store.seed_engine(OneCacheEngine(other)) == 0

    def test_store_is_lru_bounded(self):
        from consensus_tpu.ops.kv_pages import PagePool, PrefixCache

        pool = PagePool(num_pages=64, page_size=2)
        cache = PrefixCache(pool, max_pages=64, identity=("m",))
        for i in range(6):
            pages = pool.alloc(1)
            assert cache.insert((100 + i, 200 + i), pages)
            pool.free(pages)
        store = PageStore(max_runs=4)
        store.capture_cache(cache)
        assert len(store) == 4
        stats = store.stats()
        assert stats["runs"] == 4 and stats["max_runs"] == 4

    def test_respawned_replica_rejoins_warm(self):
        """The full ladder claim: after kill -> respawn, the fresh r0's
        prefix cache is pre-seeded from the fleet store, so its first
        requests over known scenarios hit instead of re-prefilling."""
        router, manager, _ = _elastic_fleet(3)
        try:
            payloads = [_payload(seed=200 + i, issue=f"warm issue {i}")
                        for i in range(12)]
            requests = [parse_request(p) for p in payloads]
            expected = {}
            owners = {}
            for req in requests:
                owners[req.request_id] = router.route_for(req).name
            tickets = [router.submit(req) for req in requests]
            for req, ticket in zip(requests, tickets):
                assert ticket.wait(30.0)
                assert ticket.outcome == "ok"
                expected[req.request_id] = ticket.result()["statement"]
            victim = owners[requests[0].request_id]
            # Let the harvest cadence capture the victim's cache.
            assert _wait_for(
                lambda: len(manager.page_store) > 0, timeout=10.0)
            router.kill_replica(victim)
            assert _wait_for(
                lambda: router.stats()["fleet"]["healthy"] == 3,
                timeout=10.0,
            )
            fresh = router._replica(victim)
            cache = fresh.scheduler.batching.engine.prefix_cache
            baseline_hits = cache.hits
            # Replay the victim's scenarios: same name => same rendezvous
            # owner, and the seeded cache must hit on the FIRST pass.
            replay = [req for req in requests
                      if owners[req.request_id] == victim]
            assert replay, "victim should have owned at least one scenario"
            tickets = [router.submit(req) for req in replay]
            for req, ticket in zip(replay, tickets):
                assert ticket.wait(30.0)
                assert ticket.outcome == "ok"
                assert ticket.result()["statement"] == (
                    expected[req.request_id])
            assert cache.hits > baseline_hits
        finally:
            _shutdown(router)


# ---------------------------------------------------------------------------
# watchdog -> ladder -> respawn (no human intervention)
# ---------------------------------------------------------------------------


@pytest.mark.chaos
class TestWatchdogRecovery:
    def test_wedged_engine_is_respawned_automatically(self):
        registry = Registry()
        plan = FaultPlan(seed=3, faults=[
            FaultSpec(kind="hang", op="generate", call_index=0)])
        router, manager, injectors = _elastic_fleet(
            3, registry=registry, fault_plans={"r0": plan},
            watchdog_timeout_s=0.2,
        )
        try:
            doomed = router._replica("r0")
            request = parse_request(_payload(seed=1))
            # Aim one request straight at the armed replica: its first
            # generate wedges the engine loop forever.
            ticket = doomed.scheduler.submit(request)
            assert _wait_for(
                lambda: injectors and injectors[0].hangs_active >= 1,
                timeout=10.0,
            )
            # Watchdog -> backend_lost -> health ladder -> manager respawn,
            # all without any human or test intervention.
            assert _wait_for(
                lambda: manager.snapshot()["respawns"] >= 1
                and router.stats()["fleet"]["healthy"] == 3
                and not router._replica("r0").lost,
                timeout=15.0,
            )
            assert "engine_watchdog_trips_total 1" in (
                registry.to_prometheus())
            # The fresh r0 serves: second life is unarmed by the factory.
            fresh_ticket = router.submit(parse_request(_payload(seed=2)))
            assert fresh_ticket.wait(30.0)
            assert fresh_ticket.outcome == "ok"
            ticket.cancel()
        finally:
            _shutdown(router, injectors)


# ---------------------------------------------------------------------------
# autoscaler control law + brownout composition
# ---------------------------------------------------------------------------


class _StubRouter:
    def __init__(self):
        self.autoscaler = None
        self.replicas = []

    def _pressure(self):
        return 0.0


class _StubManager:
    def __init__(self, target=3):
        self.target = target
        self.router = _StubRouter()
        self.targets_seen = []

    def set_target(self, n):
        self.target = max(1, int(n))
        self.targets_seen.append(self.target)
        return self.target


class TestAutoscaler:
    def _scaler(self, manager, pressure, now, **kwargs):
        kwargs.setdefault("min_replicas", 1)
        kwargs.setdefault("max_replicas", 6)
        kwargs.setdefault("up_dwell_s", 0.5)
        kwargs.setdefault("down_dwell_s", 3.0)
        kwargs.setdefault("cooldown_s", 2.0)
        return Autoscaler(
            manager, pressure_fn=lambda: pressure[0],
            clock=lambda: now[0], registry=Registry(),
            auto_start=False, **kwargs,
        )

    def test_scale_up_needs_dwell_not_a_spike(self):
        manager = _StubManager(target=3)
        pressure, now = [0.95], [0.0]
        scaler = self._scaler(manager, pressure, now)
        scaler.tick()
        assert manager.target == 3  # spike: above threshold, no dwell yet
        now[0] = 0.3
        pressure[0] = 0.5  # dead band visit resets the dwell clock
        scaler.tick()
        pressure[0] = 0.95
        now[0] = 0.6
        scaler.tick()
        now[0] = 0.9
        scaler.tick()
        assert manager.target == 3  # dwell restarted at t=0.6
        now[0] = 1.2
        scaler.tick()
        assert manager.target == 4
        assert scaler.scale_ups == 1

    def test_scale_down_is_slow_and_cooled(self):
        manager = _StubManager(target=4)
        pressure, now = [0.1], [0.0]
        scaler = self._scaler(manager, pressure, now)
        scaler.tick()  # dwell clock starts at t=0
        now[0] = 2.0
        scaler.tick()
        assert manager.target == 4  # below threshold but short of dwell
        now[0] = 3.1
        scaler.tick()
        assert manager.target == 3
        # A change resets the dwell clock AND starts the cooldown: the
        # next step down needs a fresh 3s dwell, not just the cooldown.
        now[0] = 4.0
        scaler.tick()  # fresh dwell starts here
        now[0] = 6.0
        scaler.tick()
        assert manager.target == 3
        now[0] = 7.2
        scaler.tick()
        assert manager.target == 2
        assert scaler.scale_downs == 2

    def test_dead_band_hover_never_oscillates(self):
        manager = _StubManager(target=3)
        pressure, now = [0.5], [0.0]
        scaler = self._scaler(manager, pressure, now)
        for i in range(200):
            now[0] = i * 0.25
            pressure[0] = 0.45 + 0.2 * (i % 2)  # hover inside the band
            scaler.tick()
        assert manager.targets_seen == []
        assert scaler.scale_ups == 0 and scaler.scale_downs == 0

    def test_bounds_and_validation(self):
        manager = _StubManager(target=1)
        pressure, now = [0.95], [0.0]
        scaler = self._scaler(manager, pressure, now, max_replicas=2)
        now[0] = 1.0
        scaler.tick()
        now[0] = 2.0
        scaler.tick()
        now[0] = 10.0
        scaler.tick()
        now[0] = 11.0
        scaler.tick()
        assert manager.target == 2  # clamped at max_replicas
        with pytest.raises(ValueError):
            self._scaler(_StubManager(), [0.0], [0.0],
                         scale_up_pressure=0.3, scale_down_pressure=0.4)
        with pytest.raises(ValueError):
            self._scaler(_StubManager(), [0.0], [0.0],
                         min_replicas=4, max_replicas=2)

    def test_capacity_lever_fires_before_quality_levers(self):
        """The composition contract, pinned: the autoscaler's default
        scale-up threshold sits BELOW the brownout tier-2 enter pressure
        and the router's tier-lever enter pressure, so under rising load
        the fleet adds capacity before it degrades answer quality.  The
        brownout tier-1 overlap (light budget trim while capacity spins
        up) is intended — tier 1 is reversible and cheap; tier 2 is the
        quality cliff the scaler must pre-empt."""
        from consensus_tpu.serve.autoscale import (
            DEFAULT_SCALE_DOWN_PRESSURE,
            DEFAULT_SCALE_UP_PRESSURE,
        )
        from consensus_tpu.serve.brownout import BrownoutController

        controller = BrownoutController(registry=Registry())
        tier2_enter = controller.enter_thresholds[1]
        assert DEFAULT_SCALE_UP_PRESSURE < tier2_enter

        import inspect

        from consensus_tpu.serve.router import _TierLever

        lever_enter = inspect.signature(
            _TierLever.__init__).parameters["enter"].default
        assert DEFAULT_SCALE_UP_PRESSURE < lever_enter
        # And the scaler's own hysteresis band is non-degenerate.
        assert DEFAULT_SCALE_DOWN_PRESSURE < DEFAULT_SCALE_UP_PRESSURE

    def test_fleet_pressure_is_max_over_live_replicas(self):
        class _Brownout:
            def __init__(self, p):
                self._p = p

            def snapshot(self):
                return {"pressure": self._p}

        class _R:
            def __init__(self, p, lost=False):
                self.brownout = _Brownout(p)
                self.lost = lost

        manager = _StubManager(target=2)
        manager.router.replicas = [
            _R(0.2), _R(0.9), _R(5.0, lost=True)]
        scaler = Autoscaler(manager, clock=lambda: 0.0,
                            registry=Registry(), auto_start=False)
        # One saturated replica is a capacity problem even when the mean
        # looks fine; a lost replica's stale pressure must not count.
        assert scaler._fleet_pressure() == 0.9


# ---------------------------------------------------------------------------
# full elasticity cycle through create_server (the acceptance claim)
# ---------------------------------------------------------------------------


class TestElasticServerAcceptance:
    def test_full_cycle_kill_respawn_scale_up_scale_down(self):
        from consensus_tpu.serve import create_server

        registry = Registry()
        server = create_server(
            backend="fake", port=0, registry=registry,
            max_inflight=2, max_queue_depth=16,
            fleet_size=3,
            fleet_options={
                "elastic": True,
                "elastic_options": {"check_interval_s": 0.05,
                                    "respawn_backoff_s": 0.05,
                                    "harvest_interval_s": 0.1},
            },
            engine=True,
            engine_options={"prefix_cache": True},
        ).start()
        router = server.scheduler
        manager = router.manager
        try:
            assert manager is not None
            # Phase 1: kill -> respawn, replica count back to 3.
            router.kill_replica("r0")
            assert _wait_for(
                lambda: manager.snapshot()["respawns"] >= 1
                and router.stats()["fleet"]["availability"] == 1.0,
                timeout=10.0,
            )
            # Phase 2: scale up to 4 (fresh name), then back down to 3.
            manager.set_target(4)
            assert _wait_for(
                lambda: len(router.replicas) == 4
                and router.stats()["fleet"]["healthy"] == 4,
                timeout=10.0,
            )
            manager.set_target(3)
            assert _wait_for(lambda: len(router.replicas) == 3, timeout=10.0)
            # The manager/pagestore surface in /healthz-shaped stats.
            fleet = router.stats()["fleet"]
            assert fleet["manager"]["respawns"] >= 1
            assert fleet["manager"]["page_store"] is not None
        finally:
            server.stop(drain=False)

    def test_autoscale_option_attaches_the_scaler(self):
        from consensus_tpu.serve import create_server

        server = create_server(
            backend="fake", port=0, registry=Registry(),
            fleet_size=2,
            fleet_options={"autoscale": {"auto_start": False,
                                         "max_replicas": 5}},
        ).start()
        try:
            router = server.scheduler
            assert router.manager is not None
            assert router.autoscaler is not None
            assert router.autoscaler.max_replicas == 5
            snap = router.stats()["fleet"]["autoscaler"]
            assert snap["target"] == 2
        finally:
            server.stop(drain=False)
