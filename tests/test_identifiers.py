"""Method-identifier round-trip tests (reference src/utils.py:19-62 semantics)."""

from consensus_tpu.utils.identifiers import (
    create_method_identifier,
    normalize_method_name,
    parse_method_identifier,
)


def test_create_basic():
    assert create_method_identifier("zero_shot") == "zero_shot"


def test_create_filters_unimportant_params_and_sorts():
    key = create_method_identifier(
        "best_of_n",
        {"param_n": 10, "max_tokens": 50, "beta": 1.0, "num_rounds": 2},
    )
    # max_tokens/beta are not in IMPORTANT_PARAMETERS; sorted order n < num_rounds
    assert key == "best_of_n (n=10, num_rounds=2)"


def test_create_with_seed():
    key = create_method_identifier("beam_search", {"beam_width": 4}, True, 42)
    assert key == "beam_search (beam_width=4) [seed=42]"


def test_parse_round_trip():
    base, params, seed = parse_method_identifier("beam_search (beam_width=4) [seed=42]")
    assert base == "beam_search"
    assert params == {"beam_width": 4}
    assert seed == 42


def test_parse_no_params():
    base, params, seed = parse_method_identifier("habermas_machine")
    assert base == "habermas_machine" and params == {} and seed is None


def test_parse_float_param():
    _, params, _ = parse_method_identifier("m (beta=0.5)")
    assert params == {"beta": 0.5}


def test_normalize_strips_seed():
    assert normalize_method_name("best_of_n (n=3) [seed=7]") == "best_of_n (n=3)"
    assert normalize_method_name("") == "unknown"
