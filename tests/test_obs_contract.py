"""End-to-end observability contract on the FakeBackend pipeline.

The unit tests (test_obs_metrics.py) pin the primitives; this file pins
the *artifacts*: a concurrent fake-backend ``Experiment`` must leave a
schema-valid ``metrics.json`` whose derived numbers are nonzero (padding
efficiency, recompiles) and whose registry delta shows the batching
backend actually merged sessions (batch-fill, queue-wait), plus a
``metrics.prom`` scrape file; the sweep CLI must roll per-cell deltas
into one aggregate via ``--metrics-out``; and ``bench.py`` (slow, real
stack) must keep emitting exactly one parseable JSON line with the new
``padding_efficiency`` / ``bucket_recompiles`` keys.
"""

import json
import os
import pathlib
import subprocess
import sys

import pytest
import yaml

ISSUE = "Should the library extend weekend hours?"
OPINIONS = {
    "Agent 1": "Weekend mornings are the only time I can visit.",
    "Agent 2": "Extended hours cost money we do not have.",
    "Agent 3": "Students need quiet space on Sundays.",
}


@pytest.fixture(scope="module")
def fake_run(tmp_path_factory):
    """One concurrent fake-backend experiment; all assertions share it."""
    from consensus_tpu.experiment import Experiment

    config = {
        "experiment_name": "obs_contract",
        "seed": 11,
        "num_seeds": 2,
        "backend": "fake",
        # A non-default option gives get_backend a distinct cache key -> a
        # COLD FakeBackend whose first launches count as compiles, however
        # many fake-backend tests ran earlier in this process.
        "backend_options": {"embed_dim": 48},
        "models": {"generation_model": "fake-lm", "evaluation_models": ["fake-lm"]},
        "scenario": {"issue": ISSUE, "agent_opinions": dict(OPINIONS)},
        "methods_to_run": ["zero_shot", "best_of_n"],
        "best_of_n": {"n": 2, "max_tokens": 16},
        "zero_shot": {"max_tokens": 16},
        "concurrent_execution": True,
        "output_dir": str(tmp_path_factory.mktemp("obs_contract")),
    }
    experiment = Experiment(config)
    experiment.run()
    payload = json.loads((experiment.run_dir / "metrics.json").read_text())
    return experiment.run_dir, payload


def _series(metrics, name):
    assert name in metrics["families"], (
        f"metrics.json missing {name}; has {sorted(metrics['families'])}"
    )
    return metrics["families"][name]["series"]


class TestMetricsJson:
    def test_schema_and_derived_values(self, fake_run):
        _, payload = fake_run
        assert payload["schema"] == "consensus_tpu.metrics.v1"
        derived = payload["derived"]
        assert 0.0 < derived["padding_efficiency"] < 1.0
        assert derived["bucket_recompiles"] >= 1

    def test_padding_series_nonzero(self, fake_run):
        _, payload = fake_run
        useful = _series(payload["metrics"], "backend_padding_useful_tokens_total")
        allocated = _series(
            payload["metrics"], "backend_padding_allocated_tokens_total"
        )
        assert sum(s["value"] for s in useful) > 0
        assert sum(s["value"] for s in allocated) >= sum(
            s["value"] for s in useful
        )
        assert all(s["labels"]["backend"] == "fake" for s in useful)

    def test_batching_merged_sessions(self, fake_run):
        """Concurrent methods must actually co-batch: at least one flush
        carried >1 session, and every merged call has a queue-wait sample."""
        _, payload = fake_run
        fill = _series(payload["metrics"], "batching_batch_fill_sessions")
        assert sum(s["count"] for s in fill) >= 1
        assert max(s["max"] for s in fill) > 1
        wait = _series(payload["metrics"], "batching_queue_wait_seconds")
        assert sum(s["count"] for s in wait) >= 2
        assert all(s["sum"] >= 0 for s in wait)

    def test_span_tree_is_nested(self, fake_run):
        _, payload = fake_run
        roots = {node["name"]: node for node in payload["spans"]}
        assert "experiment" in roots
        experiment = roots["experiment"]
        assert experiment["count"] == 1
        children = {c["name"] for c in experiment["children"]}
        assert any(name.startswith("generate/") for name in children), children
        # Children are concurrent pool workers, so their summed elapsed may
        # exceed the parent's wall time — only existence/counts are pinned.
        assert all(c["count"] >= 1 for c in experiment["children"])

    def test_prometheus_file_written(self, fake_run):
        run_dir, _ = fake_run
        text = (run_dir / "metrics.prom").read_text()
        assert "# TYPE backend_padding_useful_tokens_total counter" in text
        assert "# TYPE batching_queue_wait_seconds histogram" in text
        assert text.endswith("\n")

    def test_timing_json_contract_untouched(self, fake_run):
        """The pre-obs artifact keeps its flat name -> totals shape."""
        run_dir, _ = fake_run
        timing = json.loads((run_dir / "timing.json").read_text())
        for entry in timing.values():
            assert {"total_s", "count", "mean_s"} <= set(entry)


class TestSweepAggregate:
    def test_metrics_out_merges_cells(self, tmp_path, monkeypatch):
        from consensus_tpu.cli.run_sweep import main

        for idx, method in enumerate(("quick_bon", "quick_zero")):
            section = (
                {"best_of_n": {"n": 2, "max_tokens": 8, "seed": 1}}
                if method == "quick_bon"
                else {"zero_shot": {"max_tokens": 8, "seed": 1}}
            )
            cfg = {
                "experiment_name": f"obs_sweep_{method}",
                "seed": 7,
                "num_seeds": 1,
                "backend": "fake",
                "models": {
                    "generation_model": "fake",
                    "evaluation_models": ["fake"],
                },
                "scenario": {"issue": ISSUE, "agent_opinions": dict(OPINIONS)},
                "methods_to_run": list(section),
                "output_dir": str(tmp_path / "out"),
                **section,
            }
            path = tmp_path / "gemma" / "scenario_1" / f"{method}.yaml"
            path.parent.mkdir(parents=True, exist_ok=True)
            path.write_text(yaml.safe_dump(cfg))

        monkeypatch.chdir(tmp_path)
        out = tmp_path / "sweep_metrics.json"
        rc = main(
            [
                "--configs-root", str(tmp_path),
                "--skip-comparative-ranking",
                "--metrics-out", str(out),
                "--quiet",
            ]
        )
        assert rc == 0
        aggregate = json.loads(out.read_text())
        assert aggregate["schema"] == "consensus_tpu.metrics.sweep.v1"
        assert len(aggregate["cells"]) == 2
        assert set(aggregate["spans_by_cell"]) == set(aggregate["cells"])
        useful = _series(
            aggregate["metrics"], "backend_padding_useful_tokens_total"
        )
        # The aggregate is the SUM over cells: at least as much useful
        # work as either cell alone reported.
        per_cell = []
        for cell_dir in (tmp_path / "out").iterdir():
            cell = json.loads((cell_dir / "metrics.json").read_text())
            series = cell["metrics"]["families"][
                "backend_padding_useful_tokens_total"
            ]["series"]
            per_cell.append(sum(s["value"] for s in series))
        assert sum(s["value"] for s in useful) == pytest.approx(sum(per_cell))
        assert aggregate["derived"]["padding_efficiency"] is not None


@pytest.mark.slow
def test_bench_emits_one_parseable_json_line_with_obs_keys():
    """Real-stack bench contract (~3 min on CPU with the tiny model):
    stdout's final line is the ONLY json payload, and it now carries the
    observability-derived keys alongside the throughput headline."""
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        BENCH_MODEL="tiny-gemma2",
        BENCH_N="2",
        BENCH_TOKENS="8",
        BENCH_CONCURRENT="2",
        BENCH_TRIALS="1",
        BENCH_QUANT="none",
        BENCH_MCTS_SIMS="6",  # keep the MCTS extra's CPU cost bounded
    )
    proc = subprocess.run(
        [sys.executable, "bench.py"],
        capture_output=True,
        text=True,
        env=env,
        cwd=str(pathlib.Path(__file__).resolve().parent.parent),
        timeout=900,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.strip().splitlines() if l.strip()]
    payloads = []
    for line in lines:
        try:
            payloads.append(json.loads(line))
        except json.JSONDecodeError:
            continue
    assert len(payloads) == 1, f"expected exactly one JSON line, got {len(payloads)}"
    (payload,) = payloads
    assert payload["metric"] == "best_of_n_statements_per_sec"
    extra = payload["extra"]
    assert 0.0 < extra["padding_efficiency"] <= 1.0
    assert extra["bucket_recompiles"] >= 1
    assert extra["tokens_per_sec"] > 0
    assert "bon_throughput_tokens_all_trials" in extra
    assert "bon_throughput_walls_sum_s" in extra
    assert extra["mcts_seconds_per_statement"] > 0
    assert extra["mcts_device_dispatches_per_statement"] > 0
    assert extra["mcts_wave_size"] == 8
