"""dp=8 FULL-pipeline pin (VERDICT r3 #7).

tests/test_dp_serving.py pins protocol-level dp=8 == dp=1; this extends
the proof to the SWEEP surface: one north-star config (its real structure
— habermas + best_of_n Cartesian grids, tpu backend, shared scoring —
shrunk to test scale and pointed at the tiny model so 8 virtual CPU
devices finish in test time) runs through the full
``run_experiment_with_eval`` pipeline at dp=8 and dp=1, and every
artifact CSV must agree: results.csv statements byte-identical, every
evaluation metric column equal to float precision.  With this pinned, the
"~N/8 wall at dp=8" projection rests on an executed end-to-end path.
"""

import pathlib

import pandas as pd
import yaml

NORTH_STAR = pathlib.Path("configs/north_star/gemma/scenario_1/habermas_vs_best_of_n.yaml")


def _run(tmp_path, dp: int) -> pathlib.Path:
    from consensus_tpu.cli.run_experiment_with_eval import run_pipeline

    config = yaml.safe_load(NORTH_STAR.read_text())
    # Test-scale: tiny model on the virtual CPU mesh; the STRUCTURE (both
    # methods, list-valued grids, shared scoring, seeds) is the config's.
    config["num_seeds"] = 2
    config["backend_options"].update(
        {"model": "tiny-gemma2", "dtype": "float32", "max_context": 256,
         "quantization": None, "dp": dp}
    )
    config["models"] = {
        "generation_model": "tiny-gemma2",
        "evaluation_models": ["tiny-gemma2"],
    }
    config["best_of_n"].update({"n": [1, 3], "max_tokens": 24})
    config["habermas_machine"].update(
        {"num_candidates": [1, 2], "max_tokens": 48}
    )
    config["experiment_name"] = f"dp_pipeline_dp{dp}"
    config["output_dir"] = str(tmp_path / f"dp{dp}")
    cfg_path = tmp_path / f"dp{dp}.yaml"
    cfg_path.write_text(yaml.safe_dump(config))
    return pathlib.Path(run_pipeline(str(cfg_path), skip_comparative_ranking=True))


def test_dp8_pipeline_artifacts_match_dp1(tmp_path):
    run_dp1 = _run(tmp_path, 1)
    run_dp8 = _run(tmp_path, 8)

    a = pd.read_csv(run_dp1 / "results.csv")
    b = pd.read_csv(run_dp8 / "results.csv")
    pd.testing.assert_frame_equal(
        a.drop(columns=["generation_time_s"]),
        b.drop(columns=["generation_time_s"]),
    )

    for seed_dir in sorted((run_dp1 / "evaluation" / "tiny-gemma2").iterdir()):
        eval_a = pd.read_csv(seed_dir / "evaluation_results.csv")
        eval_b = pd.read_csv(
            run_dp8 / "evaluation" / "tiny-gemma2" / seed_dir.name
            / "evaluation_results.csv"
        )
        drop = [c for c in eval_a.columns if c.endswith("_time_s")]
        pd.testing.assert_frame_equal(
            eval_a.drop(columns=drop), eval_b.drop(columns=drop),
            check_exact=False, atol=1e-6, rtol=1e-6,
        )

    agg_a = pd.read_csv(
        run_dp1 / "evaluation" / "improved_aggregate" / "aggregated_metrics.csv"
    )
    agg_b = pd.read_csv(
        run_dp8 / "evaluation" / "improved_aggregate" / "aggregated_metrics.csv"
    )
    drop = [c for c in agg_a.columns if "time" in c]
    pd.testing.assert_frame_equal(
        agg_a.drop(columns=drop), agg_b.drop(columns=drop),
        check_exact=False, atol=1e-6, rtol=1e-6,
    )
