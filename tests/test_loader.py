"""Checkpoint-loader and HF-tokenizer path tests (VERDICT r1 #2).

No real checkpoint exists in this zero-egress environment, so the loader is
proven with synthetic HF-format safetensors fixtures: a random runtime
pytree is exported under HuggingFace parameter names (the exact inverse of
the loader's mapping — transposed projections, per-layer norms) and read
back with ``load_params``; tree equality then validates every transpose,
layer-stack placement, and norm-routing rule for both families:

* Gemma-2 layout — tied LM head, all four per-layer norms
  (input / post_attention / pre_feedforward / post_feedforward);
* Llama-3 layout — untied ``lm_head.weight``, pre-norms only
  (input / post_attention -> ffn_norm).

Reference model usage these layouts serve:
configs/appendix/gemma/scenario_1/beam_search.yaml:4-12 (Gemma-2-9b-it) and
configs/main_body (Llama-3.1 evaluation models).
"""

import json
import pathlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from safetensors.numpy import save_file

from consensus_tpu.models.config import get_model_config
from consensus_tpu.models.loader import infer_config_name, load_params
from consensus_tpu.models.transformer import init_params, token_logprobs


def _export_hf(params, config, out_dir: pathlib.Path, shards: int = 1):
    """Write a runtime pytree as HF-named safetensors (loader's inverse)."""
    c = config
    tensors = {
        "model.embed_tokens.weight": np.asarray(params["embed"], np.float32),
        "model.norm.weight": np.asarray(params["final_norm"], np.float32),
    }
    if not c.tie_lm_head:
        tensors["lm_head.weight"] = np.asarray(params["lm_head"], np.float32)
    layers = params["layers"]
    for i in range(c.n_layers):
        prefix = f"model.layers.{i}."
        for ours, hf, transpose in (
            ("wq", "self_attn.q_proj.weight", True),
            ("wk", "self_attn.k_proj.weight", True),
            ("wv", "self_attn.v_proj.weight", True),
            ("wo", "self_attn.o_proj.weight", True),
            ("w_gate", "mlp.gate_proj.weight", True),
            ("w_up", "mlp.up_proj.weight", True),
            ("w_down", "mlp.down_proj.weight", True),
        ):
            mat = np.asarray(layers[ours][i], np.float32)
            # safetensors dumps the raw buffer: transposed views MUST be
            # materialized contiguous or the file is silently garbage.
            tensors[prefix + hf] = np.ascontiguousarray(mat.T) if transpose else mat
        tensors[prefix + "input_layernorm.weight"] = np.asarray(
            layers["attn_norm"][i], np.float32
        )
        if c.use_post_norms:
            tensors[prefix + "post_attention_layernorm.weight"] = np.asarray(
                layers["post_attn_norm"][i], np.float32
            )
            tensors[prefix + "pre_feedforward_layernorm.weight"] = np.asarray(
                layers["ffn_norm"][i], np.float32
            )
            tensors[prefix + "post_feedforward_layernorm.weight"] = np.asarray(
                layers["post_ffn_norm"][i], np.float32
            )
        else:
            tensors[prefix + "post_attention_layernorm.weight"] = np.asarray(
                layers["ffn_norm"][i], np.float32
            )
    out_dir.mkdir(parents=True, exist_ok=True)
    names = sorted(tensors)
    chunk = -(-len(names) // shards)
    for s in range(shards):
        piece = {n: tensors[n] for n in names[s * chunk : (s + 1) * chunk]}
        suffix = f"-{s:05d}-of-{shards:05d}" if shards > 1 else ""
        save_file(piece, str(out_dir / f"model{suffix}.safetensors"))


def _assert_tree_equal(a, b):
    flat_a, _ = jax.tree_util.tree_flatten_with_path(a)
    flat_b = dict(jax.tree_util.tree_flatten_with_path(b)[0])
    assert len(flat_a) == len(flat_b)
    for path, leaf in flat_a:
        np.testing.assert_allclose(
            np.asarray(leaf, np.float32),
            np.asarray(dict(flat_b)[path], np.float32),
            atol=1e-6,
            err_msg=str(path),
        )


@pytest.mark.parametrize("model", ["tiny-gemma2", "tiny-llama3"])
def test_roundtrip_hf_layout(model, tmp_path):
    config = get_model_config(model)
    params = init_params(config, jax.random.PRNGKey(0))
    _export_hf(params, config, tmp_path / model)
    loaded = load_params(str(tmp_path / model), config, jnp.float32)
    _assert_tree_equal(params, loaded)


def test_roundtrip_sharded_checkpoint(tmp_path):
    """Multi-shard safetensors (the production layout) merge correctly."""
    config = get_model_config("tiny-gemma2")
    params = init_params(config, jax.random.PRNGKey(1))
    _export_hf(params, config, tmp_path / "sharded", shards=3)
    loaded = load_params(str(tmp_path / "sharded"), config, jnp.float32)
    _assert_tree_equal(params, loaded)


def test_loaded_params_run_forward(tmp_path):
    """Loaded checkpoints produce the same logprobs as the source pytree."""
    config = get_model_config("tiny-llama3")
    params = init_params(config, jax.random.PRNGKey(2))
    _export_hf(params, config, tmp_path / "fwd")
    loaded = load_params(str(tmp_path / "fwd"), config, jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(3), (2, 16), 0, 512, jnp.int32)
    valid = jnp.ones((2, 16), bool)
    np.testing.assert_allclose(
        np.asarray(token_logprobs(params, config, tokens, valid)),
        np.asarray(token_logprobs(loaded, config, tokens, valid)),
        atol=1e-5,
    )


def test_missing_embed_raises(tmp_path):
    config = get_model_config("tiny-gemma2")
    save_file(
        {"model.norm.weight": np.zeros((config.d_model,), np.float32)},
        str(tmp_path / "model.safetensors"),
    )
    with pytest.raises(ValueError, match="embed_tokens"):
        load_params(str(tmp_path), config)


def test_untied_head_required(tmp_path):
    config = get_model_config("tiny-llama3")
    params = init_params(config, jax.random.PRNGKey(4))
    _export_hf(params, config, tmp_path)
    (tmp_path / "model.safetensors").unlink()
    tensors = {
        "model.embed_tokens.weight": np.asarray(params["embed"], np.float32),
        "model.norm.weight": np.asarray(params["final_norm"], np.float32),
    }
    save_file(tensors, str(tmp_path / "model.safetensors"))
    with pytest.raises(ValueError, match="lm_head"):
        load_params(str(tmp_path), config)


@pytest.mark.parametrize(
    "hf_config,expected",
    [
        ({"model_type": "gemma2", "hidden_size": 2304}, "gemma2-2b"),
        ({"model_type": "gemma2", "hidden_size": 3584}, "gemma2-9b"),
        ({"model_type": "llama", "hidden_size": 4096}, "llama3-8b"),
        ({"model_type": "mistral", "hidden_size": 4096}, None),
    ],
)
def test_infer_config_name(hf_config, expected, tmp_path):
    (tmp_path / "config.json").write_text(json.dumps(hf_config))
    assert infer_config_name(str(tmp_path)) == expected


def test_infer_config_name_no_file(tmp_path):
    assert infer_config_name(str(tmp_path)) is None
