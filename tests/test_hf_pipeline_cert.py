"""Whole-pipeline cross-certification against HuggingFace ``transformers``.

``tests/test_hf_numerics.py`` certifies raw forwards/decodes; VERDICT r3 #2
asks for the next link: identical weights through BOTH full stacks — a
torch ``Gemma2ForCausalLM`` reference backend and this runtime — driving
the same best_of_n cell greedily, asserting the chosen STATEMENTS are
byte-identical and every evaluation metric column agrees within tolerance.
With this link tested, quality parity reduces to mounting a real
checkpoint: every step above the weight files is exercised.

The torch side implements the backend protocol directly on HF primitives
(greedy ``model.generate``, teacher-forced log-softmax gather, mean-pooled
hidden-state embeddings) while borrowing the SAME tokenizer and prompt
rendering as the production backend, so any disagreement isolates to model
numerics — already certified to <=2e-4 — or to pipeline logic, which is
what this test pins.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax.numpy as jnp  # noqa: E402

from consensus_tpu.backends.base import (  # noqa: E402
    GenerationRequest,
    GenerationResult,
    ScoreRequest,
    ScoreResult,
    TokenCandidate,
)
from consensus_tpu.backends.tpu import TPUBackend  # noqa: E402
from consensus_tpu.evaluation import StatementEvaluator  # noqa: E402
from consensus_tpu.methods.best_of_n import BestOfNGenerator  # noqa: E402
from consensus_tpu.models.tokenizer import get_tokenizer  # noqa: E402

ISSUE = "Should the library extend its opening hours?"
OPINIONS = {
    "Agent 1": "Students need late-night study space.",
    "Agent 2": "Staff costs must stay within the current budget.",
}


class TorchRefBackend:
    """Backend protocol on HF torch primitives (CPU, float32, eager)."""

    name = "torch-ref"

    def __init__(self, model):
        self.model = model
        self.tokenizer = get_tokenizer(None, family="gemma")

    # Prompt/score rendering is BORROWED from the production backend so the
    # two stacks tokenize byte-identical strings.
    _render_prompt = TPUBackend._render_prompt
    _score_prefix = TPUBackend._score_prefix

    def generate(self, requests):
        results = []
        for request in requests:
            ids = self.tokenizer.encode(self._render_prompt(request), add_bos=True)
            with torch.no_grad():
                out = self.model.generate(
                    torch.tensor([ids]),
                    max_new_tokens=request.max_tokens,
                    do_sample=False,
                    eos_token_id=list(self.tokenizer.eos_ids),
                    pad_token_id=self.tokenizer.pad_id,
                )
            new_ids = out[0, len(ids):].tolist()
            if new_ids and new_ids[-1] in self.tokenizer.eos_ids:
                new_ids = new_ids[:-1]
                finish = "stop"
            else:
                finish = "length"
            text = self.tokenizer.decode(new_ids)
            results.append(
                GenerationResult(
                    text=text, token_ids=tuple(new_ids), finish_reason=finish
                )
            )
        return results

    def score(self, requests):
        results = []
        for request in requests:
            ctx = self.tokenizer.encode(self._score_prefix(request), add_bos=True)
            cont = self.tokenizer.encode(request.continuation)
            ids = torch.tensor([ctx + cont])
            with torch.no_grad():
                logits = self.model(input_ids=ids).logits.float()
            logprobs = torch.log_softmax(logits[0], dim=-1)
            span = []
            for j, token in enumerate(cont):
                span.append(float(logprobs[len(ctx) + j - 1, token]))
            results.append(
                ScoreResult(
                    tokens=tuple(
                        self.tokenizer.decode([t]) for t in cont
                    ),
                    logprobs=tuple(span),
                )
            )
        return results

    def embed(self, texts):
        vectors = []
        for text in texts:
            ids = self.tokenizer.encode(text, add_bos=True)
            with torch.no_grad():
                hidden = self.model.model(
                    input_ids=torch.tensor([ids])
                ).last_hidden_state[0].float()
            pooled = hidden.mean(dim=0).numpy()
            vectors.append(pooled / max(np.linalg.norm(pooled), 1e-12))
        return np.stack(vectors)

    def next_token_logprobs(self, requests):
        """Deterministic top-k proposals, mirroring the production backend's
        semantics for ``mode=="topk"`` or ``temperature<=0`` rows (the only
        rows whose Gumbel term is zeroed there, generate.py:next_token_topk):
        bias added to LOGITS over every token id containing each banned
        string, then top-k of the biased log-softmax."""
        results = []
        for request in requests:
            if request.mode != "topk" and request.temperature > 0:
                raise NotImplementedError(
                    "torch reference implements deterministic proposals only"
                )
            ids = self.tokenizer.encode(
                self._render_prompt(request), add_bos=True
            )
            with torch.no_grad():
                logits = self.model(
                    input_ids=torch.tensor([ids])
                ).logits[0, -1].float()
            for text in request.bias_against_tokens:
                for token_id in self.tokenizer.token_ids_containing(text):
                    logits[token_id] += request.bias_value
            logprobs = torch.log_softmax(logits, dim=-1)
            top = torch.topk(logprobs, min(request.k, logprobs.shape[-1]))
            results.append(
                [
                    TokenCandidate(
                        token=self.tokenizer.decode([int(i)]),
                        token_id=int(i),
                        logprob=float(v),
                    )
                    for v, i in zip(top.values, top.indices)
                ]
            )
        return results


def _hf_tiny_gemma2_long():
    """tiny-gemma2's exact structure, but with a 1024-position window —
    the reference prompt templates alone are ~500 byte-tokens."""
    cfg = transformers.Gemma2Config(
        vocab_size=512,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=4,
        num_attention_heads=4,
        num_key_value_heads=2,
        head_dim=16,
        query_pre_attn_scalar=16,
        sliding_window=16,
        attn_logit_softcapping=50.0,
        final_logit_softcapping=30.0,
        rope_theta=10_000.0,
        rms_norm_eps=1e-6,
        hidden_activation="gelu_pytorch_tanh",
        max_position_embeddings=1024,
        tie_word_embeddings=True,
        attention_dropout=0.0,
    )
    cfg._attn_implementation = "eager"
    torch.manual_seed(0)
    model = transformers.Gemma2ForCausalLM(cfg)
    model.eval()
    return model


@pytest.fixture(scope="module")
def stacks(tmp_path_factory):
    from tests.test_hf_numerics import _save_hf_model

    model = _hf_tiny_gemma2_long()
    ckpt = _save_hf_model(model, tmp_path_factory.mktemp("ckpt"))
    torch_backend = TorchRefBackend(model)
    # vocab 512 (checkpoint) exceeds the byte tokenizer's id range, so both
    # stacks index the same rows of the same embedding matrix.
    jax_backend = TPUBackend(
        model="tiny-gemma2", checkpoint=ckpt, dtype="float32", max_context=1024
    )
    return torch_backend, jax_backend


def run_cell(backend):
    generator = BestOfNGenerator(
        backend=backend,
        config={"n": 2, "max_tokens": 16, "temperature": 0.0, "seed": 3},
    )
    return generator.generate_statement(ISSUE, OPINIONS)


def test_same_statement_through_both_stacks(stacks):
    torch_backend, jax_backend = stacks
    assert run_cell(torch_backend) == run_cell(jax_backend)


def test_metric_columns_agree(stacks):
    torch_backend, jax_backend = stacks
    statement = run_cell(jax_backend)
    metrics = {}
    for name, backend in (("torch", torch_backend), ("jax", jax_backend)):
        evaluator = StatementEvaluator(backend=backend)
        metrics[name] = evaluator.evaluate_statement(statement, ISSUE, OPINIONS)
    keys_t = {k for k, v in metrics["torch"].items() if isinstance(v, (int, float))}
    keys_j = {k for k, v in metrics["jax"].items() if isinstance(v, (int, float))}
    assert keys_t == keys_j and keys_t
    for key in sorted(keys_t):
        a, b = metrics["torch"][key], metrics["jax"][key]
        assert a == pytest.approx(b, rel=2e-3, abs=2e-3), key


def test_mcts_cell_through_both_stacks(stacks):
    """Session-driven search through both stacks: torch runs MCTS over the
    full-prefix fallback session (next_token_logprobs + score + generate),
    jax over the fused TPU session (persistent KV caches, batched wave
    rollouts) — same weights, same statement.  temperature=0 keeps both
    proposal paths on deterministic top-k, so any divergence isolates to
    session/search logic rather than sampling streams."""
    from consensus_tpu.methods.mcts import MCTSGenerator

    torch_backend, jax_backend = stacks
    cfg = {
        "num_simulations": 2,
        "expansion_sample_width": 2,
        "max_tokens": 3,
        "rollout_depth": 2,
        "temperature": 0.0,
        "seed": 5,
        "mcts_wave_size": 2,
    }
    statements = {}
    for name, backend in (("torch", torch_backend), ("jax", jax_backend)):
        gen = MCTSGenerator(backend, dict(cfg))
        statements[name] = gen.generate_statement(ISSUE, OPINIONS)
        assert gen.search_stats["device_dispatches"] > 0
    assert statements["torch"] == statements["jax"]


def test_greedy_generation_token_identical(stacks):
    """The raw greedy decode paths agree token-for-token for a plain
    request (no search logic in the loop)."""
    torch_backend, jax_backend = stacks
    request = GenerationRequest(
        user_prompt=f"Issue: {ISSUE}", max_tokens=24, temperature=0.0, seed=1
    )
    a = torch_backend.generate([request])[0]
    b = jax_backend.generate([request])[0]
    assert a.token_ids == b.token_ids
    assert a.text == b.text
