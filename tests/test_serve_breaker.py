"""Circuit breaker through the serving stack: 503s, probe, drain."""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from consensus_tpu.backends import FakeBackend
from consensus_tpu.backends.base import BackendLostError
from consensus_tpu.backends.supervisor import SupervisedBackend
from consensus_tpu.obs.metrics import Registry
from consensus_tpu.serve import SchedulerRejected, create_server
from consensus_tpu.serve.scheduler import RequestScheduler

pytestmark = pytest.mark.chaos

BODY = {
    "issue": "Should the town build a new park?",
    "agent_opinions": {"a": "yes", "b": "no"},
    "method": "zero_shot",
    "params": {"max_tokens": 8},
    "seed": 1,
}


def post(base_url, payload=None):
    data = json.dumps(payload or BODY).encode("utf-8")
    request = urllib.request.Request(
        base_url + "/v1/consensus", data=data,
        headers={"Content-Type": "application/json"}, method="POST",
    )
    try:
        with urllib.request.urlopen(request, timeout=30) as response:
            return response.status, dict(response.headers), \
                json.loads(response.read())
    except urllib.error.HTTPError as exc:
        return exc.code, dict(exc.headers), json.loads(exc.read())


def get_json(url):
    with urllib.request.urlopen(url, timeout=10) as response:
        return json.loads(response.read())


class TestBreakerOverHTTP:
    def test_breaker_open_rejects_503_with_retry_after(self):
        server = create_server(
            backend="fake", port=0, max_inflight=2,
            fault_plan={"faults": [
                {"kind": "device_lost", "op": "*", "call_index": 0}]},
            supervise={"failure_threshold": 1, "cooldown_s": 60.0},
        ).start()
        try:
            status, _, body = post(server.base_url)
            assert status == 500
            assert body["error"]["exception"] == "BackendLostError"
            status, headers, body = post(server.base_url)
            assert status == 503
            assert body["error"]["reason"] == "breaker_open"
            assert int(headers["Retry-After"]) >= 1
            health = get_json(server.base_url + "/healthz")
            breaker = health["circuit_breaker"]
            assert breaker["state"] == "open"
            assert breaker["cooldown_remaining_s"] > 0
        finally:
            server.stop()

    def test_healthy_server_has_closed_breaker_in_healthz(self):
        server = create_server(
            backend="fake", port=0, max_inflight=2, supervise=True,
        ).start()
        try:
            status, _, body = post(server.base_url)
            assert status == 200 and body["statement"]
            health = get_json(server.base_url + "/healthz")
            assert health["circuit_breaker"]["state"] == "closed"
        finally:
            server.stop()


class TestBreakerAdmission:
    def make_scheduler(self, handler, breaker_kwargs=None, **kwargs):
        registry = Registry()
        backend = SupervisedBackend(
            FakeBackend(), registry=registry, sleep=lambda _s: None,
            **(breaker_kwargs or {}),
        )
        kwargs.setdefault("max_inflight", 1)
        kwargs.setdefault("max_retries", 0)
        scheduler = RequestScheduler(
            handler=handler, backend=backend, registry=registry, **kwargs
        )
        return scheduler, backend.circuit_breaker

    def test_submit_rejects_when_breaker_open(self):
        scheduler, breaker = self.make_scheduler(
            handler=lambda request, backend: {"ok": True},
            breaker_kwargs={"failure_threshold": 1, "cooldown_s": 60.0},
        )
        scheduler.start()
        try:
            breaker.record_failure()
            with pytest.raises(SchedulerRejected) as excinfo:
                scheduler.submit(object())
            assert excinfo.value.reason == "breaker_open"
            assert excinfo.value.retry_after_s >= 1
            assert scheduler.stats()["circuit_breaker"]["state"] == "open"
        finally:
            scheduler.shutdown(drain=True, timeout=5)

    def test_half_open_admits_exactly_one_probe(self):
        now = [0.0]
        registry = Registry()
        backend = SupervisedBackend(
            FakeBackend(), registry=registry, failure_threshold=1,
            cooldown_s=10.0, clock=lambda: now[0], sleep=lambda _s: None,
        )
        done = threading.Event()

        def handler(request, _backend):
            done.wait(5)  # hold the probe in flight
            return {"ok": True}

        scheduler = RequestScheduler(
            handler=handler, backend=backend, registry=registry,
            max_inflight=2,
        ).start()
        try:
            breaker = scheduler.circuit_breaker
            breaker.record_failure()
            assert breaker.state == "open"
            now[0] += 10.0  # cooldown elapses -> half-open
            probe = scheduler.submit(BODY)
            with pytest.raises(SchedulerRejected) as excinfo:
                scheduler.submit(BODY)  # second request: probe slot taken
            assert excinfo.value.reason == "breaker_open"
            done.set()
            assert probe.wait(timeout=10)
            assert probe.result()["ok"]
            # The probe's backend-free handler never reported an outcome;
            # a real success (record_success) reopens admission fully.
            breaker.record_success()
            assert breaker.state == "closed"
            ticket = scheduler.submit(BODY)
            assert ticket.wait(timeout=10)
        finally:
            done.set()
            scheduler.shutdown(drain=True, timeout=5)

    def test_drain_with_breaker_open_resolves_every_ticket(self):
        release = threading.Event()

        def handler(request, _backend):
            release.wait(10)
            raise BackendLostError("device gone")

        scheduler, breaker = self.make_scheduler(
            handler=handler,
            breaker_kwargs={"failure_threshold": 1, "cooldown_s": 60.0},
        )
        scheduler.start()
        try:
            # Admit three tickets while the breaker is still closed; the
            # single worker serializes them behind the first.
            tickets = [scheduler.submit(object()) for _ in range(3)]
            breaker.record_failure()  # breaker opens while work is queued
            assert breaker.state == "open"
            release.set()
            scheduler.shutdown(drain=True, timeout=15)
            for ticket in tickets:
                assert ticket.done()  # drain resolved every ticket
                with pytest.raises(BackendLostError):
                    ticket.result()
        finally:
            release.set()
            scheduler.shutdown(drain=True, timeout=5)

    def test_no_breaker_backend_keeps_legacy_admission(self):
        scheduler = RequestScheduler(
            handler=lambda request, backend: {"ok": True},
            backend=FakeBackend(), registry=Registry(),
        )
        assert scheduler.circuit_breaker is None
        assert "circuit_breaker" not in scheduler.stats()
