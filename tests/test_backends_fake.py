"""FakeBackend determinism and protocol contract tests."""

import numpy as np

from consensus_tpu.backends import (
    Backend,
    FakeBackend,
    GenerationRequest,
    NextTokenRequest,
    ScoreRequest,
    get_backend,
)


def test_protocol_conformance():
    assert isinstance(FakeBackend(), Backend)


def test_get_backend_resolution():
    backend = get_backend("fake")
    assert backend.name == "fake"
    assert get_backend("fake") is backend  # cached
    assert get_backend(backend) is backend  # pass-through
    assert get_backend({"name": "fake", "embed_dim": 16}).embed_dim == 16


def test_generation_deterministic_and_seed_sensitive():
    backend = FakeBackend()
    req = GenerationRequest(user_prompt="Issue: transit", seed=1, max_tokens=30)
    a = backend.generate([req])[0]
    b = backend.generate([req])[0]
    assert a.text == b.text and a.text
    c = backend.generate([GenerationRequest(user_prompt="Issue: transit", seed=2)])[0]
    assert c.text != a.text


def test_generation_respects_stop_sequences():
    backend = FakeBackend()
    req = GenerationRequest(user_prompt="p", seed=0, stop=(".",))
    text = backend.generate([req])[0].text
    assert "." not in text


def test_score_deterministic_and_context_sensitive():
    backend = FakeBackend()
    req = ScoreRequest(context="ctx A", continuation="the shared future")
    r1, r2 = backend.score([req, req])
    assert r1.logprobs == r2.logprobs
    assert len(r1.tokens) == 3
    assert all(-6.0 <= lp <= -0.05 for lp in r1.logprobs)
    other = backend.score([ScoreRequest(context="ctx B", continuation="the shared future")])[0]
    assert other.logprobs != r1.logprobs
    assert r1.mean() != r1.total()
    assert np.isclose(r1.total(), sum(r1.logprobs))


def test_score_empty_continuation_uses_default():
    backend = FakeBackend()
    result = backend.score([ScoreRequest(context="c", continuation="")])[0]
    assert not result.ok
    assert result.mean() == -10.0
    assert result.total(default=-3.0) == -3.0


def test_next_token_topk_sorted_unique():
    backend = FakeBackend()
    req = NextTokenRequest(user_prompt="prompt", k=5, mode="topk")
    cands = backend.next_token_logprobs([req])[0]
    assert len(cands) == 5
    lps = [c.logprob for c in cands]
    assert lps == sorted(lps, reverse=True)
    assert len({c.token for c in cands}) == 5


def test_next_token_sampling_seeded_and_biased():
    backend = FakeBackend()
    a = backend.next_token_logprobs(
        [NextTokenRequest(user_prompt="p", k=4, mode="sample", seed=0)]
    )[0]
    b = backend.next_token_logprobs(
        [NextTokenRequest(user_prompt="p", k=4, mode="sample", seed=0)]
    )[0]
    assert [c.token for c in a] == [c.token for c in b]
    # Banning ":"-like junk tokens keeps them out of the top-k.
    banned = backend.next_token_logprobs(
        [
            NextTokenRequest(
                user_prompt="p", k=10, mode="topk", bias_against_tokens=("<|eot_id|>", ",")
            )
        ]
    )[0]
    assert all("," not in c.token and "<|eot_id|>" not in c.token for c in banned)


def test_instruction_following_ranking():
    backend = FakeBackend()
    prompt = (
        "Use Arrow notation for the ranking.\n\nStatements to rank:\n"
        "A. first statement\nB. second statement\nC. third statement\n"
    )
    text = backend.generate([GenerationRequest(user_prompt=prompt, seed=3)])[0].text
    assert "<answer>" in text and "<sep>" in text and "</answer>" in text
    from consensus_tpu.social_choice import process_ranking_response

    ranking, _ = process_ranking_response(text, 3)
    assert ranking is not None and set(ranking) == {0, 1, 2}


def test_instruction_following_envelope():
    backend = FakeBackend()
    prompt = "Provide your answer in the following format:\n<answer>\n...\n<sep>\n..."
    text = backend.generate([GenerationRequest(user_prompt=prompt, seed=3)])[0].text
    from consensus_tpu.social_choice import extract_statement

    assert extract_statement(text)


def test_embeddings_unit_norm_deterministic():
    backend = FakeBackend(embed_dim=32)
    vecs = backend.embed(["alpha", "beta", "alpha"])
    assert vecs.shape == (3, 32)
    np.testing.assert_allclose(np.linalg.norm(vecs, axis=1), 1.0, atol=1e-9)
    np.testing.assert_allclose(vecs[0], vecs[2])
    assert not np.allclose(vecs[0], vecs[1])
