"""Mergeable quantile sketch algebra (ISSUE 16 tentpole layer 1).

The property the federated /metrics view rests on: a sketch is integer
bucket counts, so merge is key-wise addition — associative, commutative,
and lossless.  Pinned here:

* **Relative-error bound**: every quantile estimate is within
  ``relative_accuracy`` of the exact order statistic
  ``sorted(values)[floor(q*(n-1))]``, across magnitudes, signs, and
  accuracies.
* **Merge algebra**: associativity ``(a+b)+c == a+(b+c)`` and
  commutativity ``a+b == b+a`` as full store equality (dyadic-rational
  inputs keep the float ``sum`` exact too), and merge == pooled: merging
  N sketches equals one sketch fed the concatenated stream.
* **Edges**: empty sketches, zero/near-zero collapse, NaN dropped,
  single-value, huge-magnitude saturation.
* **Exemplars**: bounded retention from the configured extreme tail,
  surviving merge.
* **Snapshot algebra**: ``diff_sketch_series`` is exact store
  subtraction (None when idle); ``federate_snapshot`` adds an exact
  ``replica="fleet"`` merge per family, sums counters, and skips gauges.
"""

import math
import random

import pytest

from consensus_tpu.obs.metrics import Registry
from consensus_tpu.obs.sketch import (
    DEFAULT_MAX_EXEMPLARS,
    MIN_TRACKABLE,
    QuantileSketch,
    diff_sketch_series,
    federate_snapshot,
    merge_sketch_series,
    quantile_from_series,
)


def exact_quantile(values, q):
    ordered = sorted(values)
    return ordered[int(math.floor(q * (len(ordered) - 1)))]


def assert_within_relative(estimate, exact, alpha):
    assert estimate is not None
    assert abs(estimate - exact) <= alpha * abs(exact) + MIN_TRACKABLE, (
        f"estimate {estimate} vs exact {exact} exceeds alpha={alpha}"
    )


# ---------------------------------------------------------------------------
# Relative-error bound
# ---------------------------------------------------------------------------


class TestRelativeErrorBound:
    @pytest.mark.parametrize("alpha", [0.01, 0.05])
    def test_lognormal_positive_stream(self, alpha):
        rng = random.Random(7)
        values = [math.exp(rng.gauss(0.0, 2.0)) for _ in range(2000)]
        sketch = QuantileSketch(relative_accuracy=alpha)
        for v in values:
            sketch.observe(v)
        for q in (0.01, 0.1, 0.25, 0.5, 0.75, 0.9, 0.95, 0.99):
            assert_within_relative(
                sketch.quantile(q), exact_quantile(values, q), alpha
            )

    def test_signed_welfare_like_stream(self):
        # Welfare values: signed, clustered near zero, negative log-Nash
        # tail — the regime the three-store design exists for.
        rng = random.Random(11)
        values = [rng.uniform(-1.0, 1.0) for _ in range(500)]
        values += [-math.exp(rng.gauss(1.0, 1.0)) for _ in range(500)]
        sketch = QuantileSketch(relative_accuracy=0.01, extreme="low")
        for v in values:
            sketch.observe(v)
        for q in (0.05, 0.1, 0.5, 0.9, 0.95):
            assert_within_relative(
                sketch.quantile(q), exact_quantile(values, q), 0.01
            )

    def test_magnitudes_across_decades(self):
        values = [10.0 ** e for e in range(-9, 10)]
        sketch = QuantileSketch(relative_accuracy=0.01)
        for v in values:
            sketch.observe(v)
        for q in (0.1, 0.5, 0.9):
            assert_within_relative(
                sketch.quantile(q), exact_quantile(values, q), 0.01
            )

    def test_q0_and_q1_are_exact_min_max(self):
        sketch = QuantileSketch()
        for v in (3.7, -2.2, 9.9, 0.0):
            sketch.observe(v)
        assert sketch.quantile(0.0) == -2.2
        assert sketch.quantile(1.0) == 9.9

    def test_count_sum_track_observations(self):
        sketch = QuantileSketch()
        for v in (1.0, 2.0, 3.5):
            sketch.observe(v)
        assert sketch.count == 3
        assert sketch.sum == 6.5


# ---------------------------------------------------------------------------
# Merge algebra
# ---------------------------------------------------------------------------


def _dyadic_stream(seed, n):
    # Dyadic rationals with a narrow exponent range: float addition over
    # them is exact, so store equality can include `sum`.
    rng = random.Random(seed)
    return [rng.randrange(-1024, 1025) / 64.0 for _ in range(n)]


def _sketch_of(values, **kwargs):
    sketch = QuantileSketch(**kwargs)
    for v in values:
        sketch.observe(v)
    return sketch


class TestMergeAlgebra:
    def test_merge_equals_pooled_stream(self):
        streams = [_dyadic_stream(s, 300) for s in (1, 2, 3)]
        merged = _sketch_of(streams[0])
        merged.merge(_sketch_of(streams[1]))
        merged.merge(_sketch_of(streams[2]))
        pooled = _sketch_of([v for s in streams for v in s])
        assert merged.series_view() == pooled.series_view()
        for q in (0.05, 0.5, 0.95, 0.99):
            assert merged.quantile(q) == pooled.quantile(q)

    def test_associativity(self):
        a1, b1, c1 = (_sketch_of(_dyadic_stream(s, 200)) for s in (4, 5, 6))
        a2, b2, c2 = (_sketch_of(_dyadic_stream(s, 200)) for s in (4, 5, 6))
        left = a1.merge(b1).merge(c1)  # (a+b)+c
        right = a2.merge(b2.merge(c2))  # a+(b+c)
        assert left.series_view() == right.series_view()

    def test_commutativity(self):
        a1, b1 = _sketch_of(_dyadic_stream(7, 200)), _sketch_of(
            _dyadic_stream(8, 200))
        a2, b2 = _sketch_of(_dyadic_stream(7, 200)), _sketch_of(
            _dyadic_stream(8, 200))
        assert a1.merge(b1).series_view() == b2.merge(a2).series_view()

    def test_merge_rejects_mismatched_accuracy(self):
        with pytest.raises(ValueError, match="relative accuracy"):
            QuantileSketch(relative_accuracy=0.01).merge(
                QuantileSketch(relative_accuracy=0.02))

    def test_merge_with_empty_is_identity(self):
        full = _sketch_of(_dyadic_stream(9, 100))
        before = full.series_view()
        full.merge(QuantileSketch())
        assert full.series_view() == before
        empty = QuantileSketch()
        empty.merge(_sketch_of(_dyadic_stream(9, 100)))
        assert empty.series_view() == before


# ---------------------------------------------------------------------------
# Edges
# ---------------------------------------------------------------------------


class TestEdges:
    def test_empty_sketch(self):
        sketch = QuantileSketch()
        assert sketch.quantile(0.5) is None
        assert sketch.count == 0
        view = sketch.series_view()
        assert view["count"] == 0 and view["min"] is None

    def test_zero_and_subtrackable_collapse(self):
        sketch = QuantileSketch()
        for v in (0.0, 1e-15, -1e-15):
            sketch.observe(v)
        view = sketch.series_view()
        assert view["zero"] == 3 and not view["pos"] and not view["neg"]
        assert sketch.quantile(0.5) == 0.0

    def test_nan_dropped(self):
        sketch = QuantileSketch()
        sketch.observe(float("nan"))
        assert sketch.count == 0

    def test_single_value(self):
        sketch = QuantileSketch()
        sketch.observe(42.0)
        for q in (0.0, 0.5, 1.0):
            assert_within_relative(sketch.quantile(q), 42.0, 0.01)

    def test_huge_magnitude_saturation(self):
        sketch = QuantileSketch()
        for v in (1e300, 2e300, 1.0):
            sketch.observe(v)
        assert_within_relative(sketch.quantile(0.99), 1e300, 0.01)
        assert sketch.quantile(1.0) == 2e300
        assert sketch.quantile(0.0) == 1.0

    def test_invalid_quantile_and_accuracy(self):
        with pytest.raises(ValueError):
            QuantileSketch().quantile(1.5)
        with pytest.raises(ValueError):
            QuantileSketch(relative_accuracy=0.0)
        with pytest.raises(ValueError):
            QuantileSketch(extreme="sideways")


# ---------------------------------------------------------------------------
# Exemplars
# ---------------------------------------------------------------------------


class TestExemplars:
    def test_high_extreme_keeps_slowest(self):
        sketch = QuantileSketch(extreme="high")
        for i in range(20):
            sketch.observe(float(i), trace_id=f"req-{i}")
        view = sketch.series_view()
        kept = {e["value"] for e in view["exemplars"]}
        assert len(kept) == DEFAULT_MAX_EXEMPLARS
        assert kept == set(float(i) for i in range(12, 20))

    def test_low_extreme_keeps_most_unfair(self):
        sketch = QuantileSketch(extreme="low", max_exemplars=3)
        for v in (0.5, -0.9, 0.1, -0.2, 0.8):
            sketch.observe(v, trace_id=f"t{v}")
        kept = {e["value"] for e in sketch.series_view()["exemplars"]}
        assert kept == {-0.9, -0.2, 0.1}

    def test_untraced_observations_leave_no_exemplar(self):
        sketch = QuantileSketch()
        sketch.observe(1.0)
        assert sketch.series_view()["exemplars"] == []

    def test_exemplars_survive_merge(self):
        a = QuantileSketch(extreme="high")
        b = QuantileSketch(extreme="high")
        a.observe(10.0, trace_id="slow-a")
        b.observe(99.0, trace_id="slow-b")
        a.merge(b)
        ids = {e["trace_id"] for e in a.series_view()["exemplars"]}
        assert ids == {"slow-a", "slow-b"}


# ---------------------------------------------------------------------------
# Snapshot-series algebra (diff / merge / quantile on plain dicts)
# ---------------------------------------------------------------------------


class TestSeriesAlgebra:
    def test_diff_is_exact_store_subtraction(self):
        sketch = _sketch_of(_dyadic_stream(10, 50))
        before = sketch.series_view()
        extra = _dyadic_stream(11, 25)
        for v in extra:
            sketch.observe(v)
        delta = diff_sketch_series(before, sketch.series_view())
        assert delta["count"] == 25
        only_extra = _sketch_of(extra).series_view()
        assert delta["pos"] == only_extra["pos"]
        assert delta["neg"] == only_extra["neg"]
        assert delta["zero"] == only_extra["zero"]

    def test_diff_idle_series_is_none(self):
        view = _sketch_of([1.0, 2.0]).series_view()
        assert diff_sketch_series(view, view) is None
        assert diff_sketch_series(None, QuantileSketch().series_view()) is None

    def test_series_merge_matches_sketch_merge(self):
        a, b = _dyadic_stream(12, 80), _dyadic_stream(13, 80)
        target = dict(_sketch_of(a).series_view())
        merge_sketch_series(target, _sketch_of(b).series_view())
        pooled = _sketch_of(a + b).series_view()
        for key in ("count", "sum", "min", "max", "zero", "pos", "neg"):
            assert target[key] == pooled[key]
        assert quantile_from_series(target, 0.95) == quantile_from_series(
            pooled, 0.95)

    def test_from_dict_round_trip(self):
        sketch = _sketch_of(_dyadic_stream(14, 60), relative_accuracy=0.05,
                            extreme="low")
        clone = QuantileSketch.from_dict(sketch.to_dict())
        assert clone.series_view() == sketch.series_view()
        assert clone.relative_accuracy == 0.05
        assert clone.quantile(0.9) == sketch.quantile(0.9)


# ---------------------------------------------------------------------------
# Snapshot federation
# ---------------------------------------------------------------------------


def _federation_registry():
    registry = Registry()
    latency = registry.sketch(
        "lat", "latency", labels=("replica", "outcome"))
    requests = registry.counter(
        "reqs_total", "requests", labels=("replica",))
    occupancy = registry.gauge("occ", "occupancy", labels=("replica",))
    streams = {
        "r0": _dyadic_stream(20, 100),
        "r1": _dyadic_stream(21, 150),
        "r2": _dyadic_stream(22, 50),
    }
    for name, values in streams.items():
        for v in values:
            latency.labels(name, "ok").observe(abs(v))
        requests.labels(name).inc(len(values))
        occupancy.labels(name).set(0.5)
    return registry, streams


class TestFederation:
    def test_fleet_p99_equals_pooled_p99_exactly(self):
        registry, streams = _federation_registry()
        fed = federate_snapshot(registry.snapshot())
        family = fed["families"]["lat"]
        fleet = [s for s in family["series"]
                 if s["labels"]["replica"] == "fleet"]
        assert len(fleet) == 1
        pooled = QuantileSketch()
        for values in streams.values():
            for v in values:
                pooled.observe(abs(v))
        body = {k: v for k, v in fleet[0].items() if k != "labels"}
        assert body["pos"] == pooled.series_view()["pos"]
        for q in (0.5, 0.9, 0.99):
            assert quantile_from_series(body, q) == pooled.quantile(q)

    def test_per_replica_series_preserved(self):
        registry, streams = _federation_registry()
        fed = federate_snapshot(registry.snapshot())
        replicas = {s["labels"]["replica"]
                    for s in fed["families"]["lat"]["series"]}
        assert replicas == {"r0", "r1", "r2", "fleet"}

    def test_counters_sum_and_gauges_skipped(self):
        registry, streams = _federation_registry()
        fed = federate_snapshot(registry.snapshot())
        counter = fed["families"]["reqs_total"]["series"]
        fleet = [s for s in counter if s["labels"]["replica"] == "fleet"]
        assert fleet[0]["value"] == sum(len(v) for v in streams.values())
        gauge_labels = {s["labels"]["replica"]
                        for s in fed["families"]["occ"]["series"]}
        assert "fleet" not in gauge_labels

    def test_idempotent_on_already_federated_snapshot(self):
        registry, _ = _federation_registry()
        once = federate_snapshot(registry.snapshot())
        twice = federate_snapshot(once)
        assert twice == once
