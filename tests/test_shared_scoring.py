"""Shared-context scoring (one prefill, broadcast-trunk continuations).

The scorer must be indistinguishable from the full-sequence path: the
backend routes same-context groups through
``shared_context_token_logprobs`` and everything else through the classic
batch, and both must yield identical ScoreResults.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from consensus_tpu.backends.base import ScoreRequest
from consensus_tpu.backends.tpu import TPUBackend
from consensus_tpu.models import transformer as T
from consensus_tpu.models.config import get_model_config
from consensus_tpu.models.quant import quantize_params


@pytest.fixture(scope="module")
def setup():
    cfg = get_model_config("tiny-gemma2")
    params = T.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    return cfg, params


def _ragged_conts(cfg, lengths, seed=3):
    rng = np.random.default_rng(seed)
    conts = [rng.integers(1, cfg.vocab_size, size=n).tolist() for n in lengths]
    width = max(lengths)
    tokens = np.zeros((len(conts), width), np.int32)
    valid = np.zeros((len(conts), width), bool)
    for i, ids in enumerate(conts):
        tokens[i, : len(ids)] = ids
        valid[i, : len(ids)] = True
    return conts, jnp.asarray(tokens), jnp.asarray(valid)


class TestPrimitive:
    def test_matches_full_sequence_scorer(self, setup):
        """Exact parity with token_logprobs_streamed on the concatenation —
        incl. sliding-window layers crossing the context boundary
        (tiny-gemma2 window=16 < ctx+cont)."""
        cfg, params = setup
        C = 24
        ctx = jnp.asarray(
            np.random.default_rng(1).integers(1, cfg.vocab_size, size=(1, C)),
            jnp.int32,
        )
        conts, cont_tok, cont_val = _ragged_conts(cfg, [8, 5, 1])
        shared = np.asarray(
            T.shared_context_token_logprobs(
                params, cfg, ctx, jnp.ones((1, C), bool), cont_tok, cont_val,
                vocab_chunk=64,
            )
        )
        for i, ids in enumerate(conts):
            full = jnp.asarray(
                np.concatenate([np.asarray(ctx[0]), ids])[None], jnp.int32
            )
            oracle = np.asarray(
                T.token_logprobs_streamed(
                    params, cfg, full, jnp.ones_like(full, bool), vocab_chunk=64
                )
            )[0, C : C + len(ids)]
            np.testing.assert_allclose(shared[i, : len(ids)], oracle, atol=1e-5)
            assert (shared[i, len(ids):] == 0.0).all()

    def test_right_padded_context(self, setup):
        """A right-padded context row must score like its unpadded form."""
        cfg, params = setup
        rng = np.random.default_rng(5)
        real = rng.integers(1, cfg.vocab_size, size=12)
        padded = np.zeros((1, 20), np.int32)
        padded[0, :12] = real
        ctx_valid = np.zeros((1, 20), bool)
        ctx_valid[0, :12] = True
        conts, cont_tok, cont_val = _ragged_conts(cfg, [6, 4], seed=6)
        a = np.asarray(
            T.shared_context_token_logprobs(
                params, cfg, jnp.asarray(padded), jnp.asarray(ctx_valid),
                cont_tok, cont_val, vocab_chunk=64,
            )
        )
        b = np.asarray(
            T.shared_context_token_logprobs(
                params, cfg, jnp.asarray(real[None].astype(np.int32)),
                jnp.ones((1, 12), bool), cont_tok, cont_val, vocab_chunk=64,
            )
        )
        np.testing.assert_allclose(a, b, atol=1e-5)

    def test_int8_params_supported(self, setup):
        cfg, params = setup
        qp = quantize_params(params)
        C = 16
        ctx = jnp.asarray(
            np.random.default_rng(7).integers(1, cfg.vocab_size, size=(1, C)),
            jnp.int32,
        )
        conts, cont_tok, cont_val = _ragged_conts(cfg, [5, 5], seed=8)
        shared = np.asarray(
            T.shared_context_token_logprobs(
                qp, cfg, ctx, jnp.ones((1, C), bool), cont_tok, cont_val,
                vocab_chunk=64,
            )
        )
        full = np.asarray(
            T.token_logprobs_streamed(
                qp, cfg,
                jnp.asarray(np.concatenate([np.asarray(ctx[0]), conts[0]])[None],
                            jnp.int32),
                jnp.ones((1, C + 5), bool), vocab_chunk=64,
            )
        )[0, C:]
        np.testing.assert_allclose(shared[0, :5], full, atol=1e-4)


class TestBackendRouting:
    @pytest.fixture(scope="class")
    def backend(self):
        return TPUBackend(
            model="tiny-gemma2", dtype="float32", max_context=128,
            shared_context_scoring=True,
        )

    def test_default_off_uses_legacy_path(self):
        """With the option off (default), grouped requests still score
        correctly through the classic batch."""
        legacy = TPUBackend(model="tiny-gemma2", dtype="float32", max_context=128)
        reqs = [
            ScoreRequest(context="ctx", continuation=c)
            for c in ("aa", "bb", "cc", "dd")
        ]
        results = legacy.score(reqs)
        assert all(r.ok for r in results)

    def test_grouped_equals_individual(self, backend):
        """Candidates sharing one context (shared path, group >=4) must
        score exactly like each scored alone (legacy path: single-request
        groups fall through to the classic batch)."""
        context = "Issue: parks.\n\nAgent's opinion:\nMore green space.\n\n"
        cands = [
            "We should build parks.",
            "No new parks.",
            "Pilot one park.",
            "Let residents vote.",
        ]
        grouped = backend.score(
            [ScoreRequest(context=context, continuation=c) for c in cands]
        )
        for cand, got in zip(cands, grouped):
            solo = backend.score(
                [ScoreRequest(context=context, continuation=cand)]
            )[0]
            assert got.tokens == solo.tokens
            np.testing.assert_allclose(
                got.logprobs, solo.logprobs, atol=1e-4
            )

    def test_mixed_batch_order_preserved(self, backend):
        """A batch mixing two context groups and a singleton returns results
        in request order with the right spans."""
        reqs = [
            ScoreRequest(context="ctx A", continuation="one"),
            ScoreRequest(context="ctx B", continuation="two"),
            ScoreRequest(context="ctx A", continuation="three"),
            ScoreRequest(context="ctx C", continuation="four"),
            ScoreRequest(context="ctx B", continuation="five"),
            ScoreRequest(context="ctx A", continuation="six"),
            ScoreRequest(context="ctx A", continuation="seven"),
        ]
        results = backend.score(reqs)
        assert len(results) == 7
        for req, res in zip(reqs, results):
            assert res.ok
            assert "".join(res.tokens) == req.continuation
            assert len(res.logprobs) == len(res.tokens)

    def test_oversized_group_falls_back(self, backend):
        """Context too long for the window -> legacy truncating path, which
        still returns a (possibly shortened) valid span."""
        context = "x" * 500  # byte tokenizer: 500 tokens >> max_context=128
        results = backend.score(
            [
                ScoreRequest(context=context, continuation="abcdef"),
                ScoreRequest(context=context, continuation="ghijkl"),
            ]
        )
        for res in results:
            assert res.ok
            assert all(lp <= 1e-5 for lp in res.logprobs)
