"""Multichip mesh serving path (the MULTICHIP dryrun, promoted to pytest).

The PR 9 contract, pinned here:

* mesh construction and ``mesh={'dp': N, 'tp': M}`` spec parsing;
* regex partition rules cover EVERY param path of both tiny model
  families (and an unmatched path fails loudly, naming the path);
* the sharded paged slot programs (prefill / decode / gather) under a
  dp x tp mesh reproduce the single-device logits to fp32 tolerance;
* the engine's mesh mode partitions slots + page pools over dp shards
  with balanced admission, and aggregate capacity really is dp x the
  per-shard pool;
* statements are byte-identical across dp widths through the real
  backend (``texts_match_dp``), and the dp=1/tp=1 mesh path returns the
  exact bytes of the plain PR 6 engine path;
* ``kv_cache_identity`` partitions the prefix-cache keyspace by tp (tp
  changes the bytes in a page) but not by dp (pages replicate over data).

Runs on the 8-virtual-device CPU mesh forced by conftest.py.
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from consensus_tpu.backends.base import GenerationRequest
from consensus_tpu.backends.engine import DecodeEngine
from consensus_tpu.backends.fake import FakeBackend
from consensus_tpu.models import stepper
from consensus_tpu.models.config import get_model_config
from consensus_tpu.models.quant import QTensor, quantize_params
from consensus_tpu.models.transformer import init_params, project_logits
from consensus_tpu.obs.metrics import Registry
from consensus_tpu.ops.kv_pages import BlockTable, PagePool
from consensus_tpu.parallel import (
    make_mesh,
    match_partition_rules,
    param_shardings,
    parse_mesh_spec,
    shard_params,
)
from consensus_tpu.parallel.mesh import MODEL_AXIS

TINY_MODELS = ["tiny-gemma2", "tiny-llama3"]


# ---------------------------------------------------------------------------
# Mesh construction + spec parsing
# ---------------------------------------------------------------------------


class TestMeshSpec:
    def test_make_mesh_serving_shapes(self):
        plan = make_mesh(dp=4, tp=2)
        assert plan.dp == 4 and plan.tp == 2 and plan.n_devices == 8
        assert plan.mesh.axis_names == ("data", "model")

    def test_parse_accepts_str_dict_plan_none(self):
        assert parse_mesh_spec(None) is None
        assert parse_mesh_spec("dp=4,tp=2") == {"dp": 4, "tp": 2}
        assert parse_mesh_spec("tp=2") == {"dp": 1, "tp": 2}
        assert parse_mesh_spec({"dp": 3}) == {"dp": 3, "tp": 1}
        plan = make_mesh(tp=2)
        assert parse_mesh_spec(plan) == {"dp": plan.dp, "tp": 2}

    def test_parse_rejects_garbage(self):
        with pytest.raises(ValueError):
            parse_mesh_spec("replicas=4")
        with pytest.raises(ValueError):
            parse_mesh_spec({"dp": 0})
        with pytest.raises(ValueError):
            parse_mesh_spec("dp")


# ---------------------------------------------------------------------------
# Partition-rule coverage (satellite: fails on any unmatched param path)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("cfg_name", TINY_MODELS)
class TestPartitionRules:
    def test_rules_cover_every_param_path(self, cfg_name):
        cfg = get_model_config(cfg_name)
        params = init_params(cfg, jax.random.PRNGKey(0))
        specs = match_partition_rules(params)
        # Megatron layout: attention/ffn first matmuls split output
        # features, second matmuls split input features, vocab rows shard.
        assert tuple(specs["layers"]["wq"])[-1] == MODEL_AXIS
        assert tuple(specs["layers"]["wo"])[1] == MODEL_AXIS
        assert tuple(specs["layers"]["w_down"])[1] == MODEL_AXIS
        assert tuple(specs["embed"])[0] == MODEL_AXIS
        assert all(a is None for a in tuple(specs["layers"]["attn_norm"]))

    def test_unmatched_param_path_fails_loudly(self, cfg_name):
        cfg = get_model_config(cfg_name)
        params = init_params(cfg, jax.random.PRNGKey(0))
        params["layers"]["mystery_weight"] = jnp.ones((2, 4, 4))
        with pytest.raises(ValueError, match="layers/mystery_weight"):
            match_partition_rules(params)

    def test_param_shardings_int8_scale_replicates(self, cfg_name):
        """QTensor q shards like the weight; squeezed scale axes go None."""
        cfg = get_model_config(cfg_name)
        qparams = quantize_params(init_params(cfg, jax.random.PRNGKey(0)))
        shardings = param_shardings(qparams, make_mesh(tp=2).mesh)
        wq = shardings["layers"]["wq"]
        assert isinstance(wq, QTensor)
        assert tuple(wq.q.spec)[-1] == MODEL_AXIS
        wo = shardings["layers"]["wo"]
        # wo contracts its (sharded) input axis, so its per-output-channel
        # scale has size 1 there and must replicate.
        assert all(a is None for a in tuple(wo.scale.spec))


# ---------------------------------------------------------------------------
# Sharded paged programs: tp=2 logits vs single-device
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("cfg_name", TINY_MODELS)
class TestShardedPagedPrograms:
    def test_tp_mesh_matches_single_device(self, cfg_name):
        """prefill -> greedy decode -> gather under a dp=4,tp=2 mesh
        reproduces the unsharded paged path's logits and token choices."""
        cfg = get_model_config(cfg_name)
        params = init_params(cfg, jax.random.PRNGKey(0))
        rng = np.random.RandomState(2)
        prompt = rng.randint(1, cfg.vocab_size, size=(8,)).astype(np.int32)
        page_size, num_pages, max_blocks, n_decode = 4, 16, 8, 3

        def run(mesh, run_params):
            pool = PagePool(num_pages, page_size)
            state = stepper.make_page_state(
                cfg, num_pages, page_size, jnp.float32, mesh=mesh
            )
            sink = num_pages
            table = BlockTable(0)
            table.append_tokens(pool, 8)
            tok = np.zeros((2, 8), np.int32)
            cvalid = np.zeros((2, 8), bool)
            wp = np.full((2, 8), sink, np.int32)
            wo = np.zeros((2, 8), np.int32)
            tok[0] = prompt
            cvalid[0] = True
            for t in range(8):
                wp[0, t] = table.pages[t // page_size]
                wo[0, t] = t % page_size
            tables = np.full((2, max_blocks), -1, np.int32)
            tables[0] = table.as_array(max_blocks)
            hidden, state = stepper.paged_prefill_chunk(
                run_params, cfg, jnp.asarray(tok), jnp.asarray(cvalid),
                state, jnp.asarray(tables),
                jnp.asarray([8, 0], np.int32), jnp.asarray(wp),
                jnp.asarray(wo), mesh=mesh,
            )
            trace = [np.asarray(project_logits(run_params, cfg, hidden)[0])]
            tokens = []
            last = trace[0]
            for _ in range(n_decode):
                nxt = int(np.argmax(last))
                tokens.append(nxt)
                table.append_tokens(pool, 1)
                page, offset = table.write_cursor(pool)
                tables = np.full((2, max_blocks), -1, np.int32)
                tables[0] = table.as_array(max_blocks)
                lg, state = stepper.paged_decode_step(
                    run_params, cfg, jnp.asarray([nxt, 0], jnp.int32),
                    state, jnp.asarray(tables),
                    jnp.asarray([table.num_tokens, 0], np.int32),
                    jnp.asarray([page, sink], np.int32),
                    jnp.asarray([offset, 0], np.int32), mesh=mesh,
                )
                last = np.asarray(lg[0])
                trace.append(last)
            g_logits, _ = stepper.paged_gather_step(
                run_params, cfg,
                jnp.asarray([int(prompt[-1]), 0], jnp.int32), state,
                jnp.asarray(tables),
                jnp.asarray([table.num_tokens, 0], np.int32), mesh=mesh,
            )
            trace.append(np.asarray(g_logits[0]))
            return tokens, trace

        ref_tokens, ref_trace = run(None, params)
        plan = make_mesh(dp=4, tp=2)
        sh_tokens, sh_trace = run(
            plan.mesh, shard_params(params, plan.mesh)
        )
        assert sh_tokens == ref_tokens
        for ref, got in zip(ref_trace, sh_trace):
            np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


# ---------------------------------------------------------------------------
# Engine mesh mode: dp-partitioned slots, pools, balanced admission
# ---------------------------------------------------------------------------


def _submit_async(engine, requests):
    out = {}

    def worker():
        try:
            out["result"] = engine.submit("generate", requests)
        except BaseException as exc:  # noqa: BLE001 - test captures verbatim
            out["error"] = exc

    thread = threading.Thread(target=worker, daemon=True)
    thread.start()
    return thread, out


def _wait_until(predicate, timeout=5.0):
    import time

    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(0.002)
    return False


class TestEngineMeshMode:
    def test_dp_partitions_pools_and_balances_admission(self):
        """4 rows needing 5 pages each all become resident at once under
        dp=4 with 8-page per-shard pools (aggregate capacity is dp x the
        per-shard pool — a dp=1 engine with the same per-shard pool holds
        one); admission spreads them one per shard."""
        reg = Registry()
        engine = DecodeEngine(
            FakeBackend(), slots=8, page_size=4, num_pages=8,
            auto_start=False, mesh={"dp": 4, "tp": 2}, registry=reg,
        )
        assert engine.mesh_dp == 4 and engine.mesh_tp == 2
        assert len(engine.pools) == 4
        assert len({id(p) for p in engine.pools}) == 4

        reqs = [
            GenerationRequest(
                user_prompt="one two three four five", max_tokens=12, seed=i,
            )
            for i in range(4)
        ]
        solo = FakeBackend().generate(reqs)
        threads = [_submit_async(engine, [r]) for r in reqs]
        assert _wait_until(lambda: engine.stats()["queue_depth"] == 4)
        with engine._lock:
            engine._admit()
        shards = sorted(s.shard for s in engine._slots if s is not None)
        assert shards == [0, 1, 2, 3]
        stats = engine.stats()
        assert stats["slots_occupied"] == 4
        assert stats["mesh"]["dp"] == 4 and stats["mesh"]["tp"] == 2
        assert [s["slots_occupied"] for s in stats["mesh"]["per_shard"]] == [
            1, 1, 1, 1,
        ]
        assert all(
            s["kv_pages_reserved"] == 5 for s in stats["mesh"]["per_shard"]
        )

        for _ in range(4):
            engine.run_iteration()
        for thread, _ in threads:
            thread.join(timeout=5.0)
        assert [out["result"][0].text for _, out in threads] == [
            r.text for r in solo
        ]
        stats = engine.stats()
        assert stats["slots_occupied"] == 0
        assert all(pool.in_use == 0 for pool in engine.pools)
        assert stats["kv_pages_reserved"] == 0
        engine.close()

    def test_mesh_gauges_emitted(self):
        reg = Registry()
        engine = DecodeEngine(
            FakeBackend(), slots=4, num_pages=16, auto_start=False,
            mesh="dp=2,tp=1", registry=reg,
        )
        families = reg.snapshot()["families"]
        dp_series = families["engine_mesh_dp"]["series"]
        tp_series = families["engine_mesh_tp"]["series"]
        assert dp_series[0]["value"] == 2
        assert tp_series[0]["value"] == 1
        engine.close()

    def test_dp1_mesh_is_the_legacy_engine(self):
        """mesh={'dp': 1} must be structurally the PR 6 engine: one pool,
        aliased as .pool, legacy FIFO admission order."""
        engine = DecodeEngine(
            FakeBackend(), slots=2, num_pages=16, auto_start=False,
            mesh={"dp": 1, "tp": 1},
        )
        assert engine.pools == [engine.pool]
        assert engine.mesh_dp == 1
        engine.close()


# ---------------------------------------------------------------------------
# End-to-end: dp-width text identity through the real backend
# ---------------------------------------------------------------------------


class TestMeshServingEndToEnd:
    N_REQUESTS = 6
    MAX_TOKENS = 4

    @pytest.fixture(scope="class")
    def base_backend(self):
        from consensus_tpu.backends.tpu import TPUBackend

        backend = TPUBackend(model="tiny-gemma2", max_context=128)
        yield backend

    def _requests(self):
        return [
            GenerationRequest(
                user_prompt=f"Draft a statement on issue {i}.",
                max_tokens=self.MAX_TOKENS, temperature=0.8, seed=100 + i,
                chat=False,
            )
            for i in range(self.N_REQUESTS)
        ]

    def _texts(self, backend, mesh):
        from consensus_tpu.backends.batching import BatchingBackend
        from concurrent.futures import ThreadPoolExecutor

        batching = BatchingBackend(
            backend, registry=Registry(), engine=True,
            engine_options={
                "slots": 8, "page_size": 16, "num_pages": 4,
                **({"mesh": mesh} if mesh is not None else {}),
            },
        )
        try:
            with ThreadPoolExecutor(max_workers=self.N_REQUESTS) as pool:
                futures = [
                    pool.submit(batching.generate, [r])
                    for r in self._requests()
                ]
                return [f.result()[0].text for f in futures]
        finally:
            batching.close()

    def test_texts_match_dp(self, base_backend):
        """The MULTICHIP dryrun invariant: statements are identical across
        dp widths, and the dp=1/tp=1 mesh path is byte-identical to the
        plain single-device engine path."""
        from consensus_tpu.backends.tpu import TPUBackend

        plain = self._texts(base_backend, None)
        dp1 = self._texts(base_backend, {"dp": 1, "tp": 1})
        assert dp1 == plain  # dp=1/tp=1 == the PR 6 engine path, exactly

        wide_backend = TPUBackend(
            model="tiny-gemma2", max_context=128, dp=4,
            params=base_backend.params, config=base_backend.config,
        )
        dp4 = self._texts(wide_backend, {"dp": 4, "tp": 1})
        assert dp4 == dp1  # texts_match_dp

    def test_kv_cache_identity_partitions_by_tp_not_dp(self, base_backend):
        """tp changes the bytes a page holds (each chip's kv-head slice),
        so it must partition the prefix-cache keyspace; dp replicates
        pages and must NOT."""
        from consensus_tpu.backends.tpu import TPUBackend

        tp1 = base_backend.kv_cache_identity()
        assert ("tp", 1) in tp1
        tp2 = TPUBackend(
            model="tiny-gemma2", max_context=128, tp=2,
            params=base_backend.params, config=base_backend.config,
        ).kv_cache_identity()
        assert tp1 != tp2
        dp2 = TPUBackend(
            model="tiny-gemma2", max_context=128, dp=2,
            params=base_backend.params, config=base_backend.config,
        ).kv_cache_identity()
        assert dp2 == tp1
