"""Fairness regression suite (PR 18): welfare-gap tables pinned per
adversarial corpus family, through the PR 10 score-matrix path.

Goldens live under tests/golden/fairness/ and are regenerated with
``scripts/gen_fairness_goldens.py``.  The fake-backend tables are exact
(blake2b-deterministic scores); the tiny-gemma2 tables come from
PRNGKey(0) random weights and are likewise deterministic for a fixed
jax build.  The adversarial families make the rules disagree for a
*structural* reason: blocs/sybils repeat opinion text verbatim, so
candidate utilities repeat per clone — multiplicity moves the
utilitarian sum but never the egalitarian min.
"""

import json
import pathlib
import urllib.error
import urllib.request

import pytest

from consensus_tpu.backends.fake import FakeBackend
from consensus_tpu.data.scenarios.fairness import (
    BIG_SLATE,
    RULES,
    separated_families,
    welfare_gap_table,
)
from consensus_tpu.data.scenarios.registry import resolve_scenario_ref

GOLDEN_DIR = pathlib.Path(__file__).resolve().parent / "golden" / "fairness"

#: Pinned fake-backend scenarios (mirrors scripts/gen_fairness_goldens.py).
FAKE_SCENARIOS = (
    "polarized-0004",
    "sybil-0006",
    "holdout-0005",
    "contradictory-0003",
    "paraphrase-0004",
    "polarized-500",
)
FAKE_TABLE_KWARGS = {"n_candidates": 6, "max_tokens": 16, "seed": 0}

TINY_SCENARIOS = ("polarized-0004", "polarized-500")


def _golden(name):
    path = GOLDEN_DIR / f"{name}.json"
    assert path.exists(), (
        f"missing golden {path}; run scripts/gen_fairness_goldens.py")
    return json.loads(path.read_text())


def _assert_close(got, want, path="table", rel=1e-4, abs_tol=1e-6):
    """Structural equality with float tolerance: XLA's threaded CPU
    reductions make 500-term float32 sums run-to-run different in the
    last ulp, so the tiny-gemma2 tables can't be compared bit-exactly."""
    if isinstance(want, dict):
        assert isinstance(got, dict) and set(got) == set(want), path
        for key in want:
            _assert_close(got[key], want[key], f"{path}.{key}", rel, abs_tol)
    elif isinstance(want, (list, tuple)):
        assert len(got) == len(want), path
        for i, (g, w) in enumerate(zip(got, want)):
            _assert_close(g, w, f"{path}[{i}]", rel, abs_tol)
    elif isinstance(want, float) and not isinstance(want, bool):
        assert got == pytest.approx(want, rel=rel, abs=abs_tol), (
            f"{path}: {got} != {want}")
    else:
        assert got == want, f"{path}: {got!r} != {want!r}"


# ---------------------------------------------------------------------------
# Fake backend: exact tables + the rule-separation acceptance bar
# ---------------------------------------------------------------------------


class TestFakeWelfareGaps:
    @pytest.fixture(scope="class")
    def backend(self):
        return FakeBackend()

    @pytest.fixture(scope="class")
    def tables(self, backend):
        return {
            sid: welfare_gap_table(
                backend, resolve_scenario_ref(f"corpus:v2:{sid}"),
                **FAKE_TABLE_KWARGS)
            for sid in FAKE_SCENARIOS
        }

    @pytest.mark.parametrize("sid", FAKE_SCENARIOS)
    def test_table_matches_golden(self, tables, sid):
        assert tables[sid] == _golden(f"fake_{sid}")

    def test_rules_separated_on_at_least_three_families(self, tables):
        families = separated_families(tables.values(), channel="mean_prob")
        assert len(families) >= 3, families

    def test_three_way_separation_on_at_least_three_families(self, tables):
        # Stronger than pairwise: all THREE rules pick distinct winners.
        three_way = sorted({
            t["family"] for t in tables.values()
            if len(set(t["channels"]["mean_prob"]["winners"].values()))
            == len(RULES)
        })
        assert len(three_way) >= 3, three_way

    def test_gaps_are_nonnegative_and_zero_for_egalitarian(self, tables):
        for table in tables.values():
            for channel in table["channels"].values():
                gaps = channel["gaps"]
                assert gaps["egalitarian_price_of_egalitarian"] == 0.0
                assert all(v >= 0.0 for v in gaps.values()), gaps

    def test_big_scenario_covers_500_agents(self, tables):
        table = tables["polarized-500"]
        assert table["n_agents"] == 500
        assert table["family"] == "polarized"
        assert table["channels"]["mean_prob"]["rules_separated"]


# ---------------------------------------------------------------------------
# tiny-gemma2: fused score-matrix path, 500 agents chunked under budget
# ---------------------------------------------------------------------------


class TestTinyGemmaWelfareGaps:
    @pytest.fixture(scope="class")
    def backend(self):
        from consensus_tpu.backends.tpu import TPUBackend

        # The corpus agent prompts tokenize to ~670 ids under the tiny
        # near-char-level tokenizer; max_context must cover prefix +
        # candidate or _score_matrix_fused falls back.
        return TPUBackend(model="tiny-gemma2", dtype="float32",
                          max_context=1024)

    @pytest.mark.parametrize("sid", TINY_SCENARIOS)
    def test_table_matches_golden(self, backend, sid):
        scenario = resolve_scenario_ref(f"corpus:v2:{sid}")
        before = backend.matrix_stats["chunks"]
        table = welfare_gap_table(backend, scenario, candidates=BIG_SLATE)
        table["matrix_chunks"] = backend.matrix_stats["chunks"] - before
        _assert_close(table, _golden(f"tiny-gemma2_{sid}"))

    def test_500_agents_take_the_fused_path_chunked(self, backend):
        golden = _golden("tiny-gemma2_polarized-500")
        assert golden["matrix_path"] == "fused"
        assert golden["matrix_chunks"] > 1  # under the HBM session budget
        assert golden["n_agents"] == 500


# ---------------------------------------------------------------------------
# End-to-end: the 500-agent scenario served through the DecodeEngine
# ---------------------------------------------------------------------------


class TestBigScenarioServe:
    def test_polarized_500_served_via_scenario_ref(self):
        from consensus_tpu.obs.metrics import Registry
        from consensus_tpu.serve import create_server

        # The 500-opinion reference prompt needs more KV pages than the
        # default 1024-page pool; size the pool for the big scenario the
        # same way a real deployment would.
        instance = create_server(
            backend=FakeBackend(), port=0, max_inflight=2,
            max_queue_depth=8, registry=Registry(), engine=True,
            engine_options={"num_pages": 16384},
        ).start()
        try:
            request = urllib.request.Request(
                instance.base_url + "/v1/consensus",
                data=json.dumps({
                    "scenario": "corpus:v2:polarized-500",
                    "method": "best_of_n",
                    "params": {"n": 2, "max_tokens": 16},
                    "seed": 7,
                    "evaluate": False,
                    "request_id": "big-1",
                }).encode("utf-8"),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            with urllib.request.urlopen(request, timeout=120.0) as response:
                assert response.status == 200
                body = json.loads(response.read().decode())
        finally:
            instance.stop()
        assert body["request_id"] == "big-1"
        assert body["statement"].strip()
        # The server resolved the 500-agent scenario itself: the request
        # payload above carries no opinions, only the registry ref.
        scenario = resolve_scenario_ref("corpus:v2:polarized-500")
        assert len(scenario["agent_opinions"]) == 500
