"""Transport-seam chaos conformance: partitions, drops, and corruption
against a live multi-replica fleet.

Covers the PR-19 acceptance claims:

* One seeded :class:`FaultPlan` addresses BOTH domains: backend ops
  (``generate``/``score``/...) through :class:`FaultInjectingBackend` and
  transport ops (``ship``/``fetch``/``probe``) through
  :class:`FaultyTransport`, with per-op fired counters in the SAME
  ``faults_injected_total{kind,op}`` registry family.
* PageStore shipping is chunked, resumable, and end-to-end verified:
  corrupt or truncated blobs are NEVER admitted (typed
  :class:`PageIntegrityError` on the local path too), interrupted
  transfers resume from the chunks the store already holds, and a run
  that expires or is evicted mid-fetch aborts that adoption cleanly.
* Degradation is graceful: a client whose transport stays down past the
  retry budget goes DEGRADED (``pagestore_degraded`` gauge, enter/exit
  windows in stats), fast-fails instead of hanging, and auto-heals.
* The ReplicaManager's transport probes detect a partitioned replica
  (DEGRADED, routed around), record the partition event, and clear it
  within a bounded interval after the window ends.
* Fleet conformance under the standard seeded schedule (ship/fetch
  drops + one partition + low-rate corruption): availability >= 0.99,
  ZERO lost or duplicated requests, and byte-identity with a fault-free
  run for every completed request.
* Exactly-once delivery across failover: schedulers record completed
  results in the fleet :class:`IdempotencyCache`; the router resolves a
  failed-over ticket from the cache instead of executing it again.
"""

import threading
import time
import types

import pytest

from consensus_tpu.backends import FakeBackend, ScoreRequest
from consensus_tpu.backends.faults import (
    FaultInjectingBackend,
    FaultPlan,
    FaultSpec,
)
from consensus_tpu.obs.metrics import Registry
from consensus_tpu.ops.kv_pages import PagePool, PrefixCache
from consensus_tpu.serve import (
    FaultyTransport,
    FleetRouter,
    IdempotencyCache,
    LoopbackTransport,
    PageIntegrityError,
    PageStore,
    Replica,
    ReplicaManager,
    TransportDropped,
    TransportError,
    TransportPartitioned,
    parse_request,
)
from consensus_tpu.serve.fleet import DEGRADED
from consensus_tpu.serve.pagestore import (
    _content_hash,
    _serialize_run,
)
from consensus_tpu.serve.scheduler import idempotency_key

pytestmark = pytest.mark.chaos_fleet

ISSUE = "Should we invest in public transport?"
OPINIONS = {
    "Agent 1": "Yes, buses are vital.",
    "Agent 2": "Only with congestion pricing.",
}


def _payload(seed=7, issue=ISSUE, **overrides):
    payload = {
        "issue": issue,
        "agent_opinions": dict(OPINIONS),
        "method": "best_of_n",
        "params": {"n": 2, "max_tokens": 16},
        "seed": seed,
        "request_id": f"req-{seed}",
    }
    payload.update(overrides)
    return payload


def _wait_for(predicate, timeout=10.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


def _donor_cache(tokens=tuple(range(8)), identity=("m", "tp1"),
                 page_size=4):
    pool = PagePool(num_pages=32, page_size=page_size)
    cache = PrefixCache(pool, max_pages=32, identity=identity)
    pages = pool.alloc(len(tokens) // page_size)
    assert cache.insert(tokens, pages)
    pool.free(pages)
    return cache


class _OneCacheEngine:
    def __init__(self, cache):
        self.prefix_caches = [cache]
        self.inner = None


def _run_blob(tokens=tuple(range(8)), identity=("m", "tp1"), page_size=4):
    blob = _serialize_run({
        "identity": identity,
        "key": b"key-" + bytes(tokens[:4]),
        "tokens": tokens,
        "n_tokens": len(tokens),
        "page_size": page_size,
        "n_pages": len(tokens) // page_size,
        "payload": b"",
    })
    return blob, _content_hash(blob)


# ---------------------------------------------------------------------------
# transport primitives
# ---------------------------------------------------------------------------


class TestTransportPrimitives:
    def test_loopback_round_trip_and_unknown_routes(self):
        hub = LoopbackTransport()
        hub.register("store", {"echo": lambda m: {"ok": True, "x": m["x"]}})
        assert hub.call("a", "store", "echo", {"x": 1}) == {"ok": True,
                                                           "x": 1}
        assert hub.peers() == ["store"]
        with pytest.raises(TransportError):
            hub.call("a", "nowhere", "echo", {})
        with pytest.raises(TransportError):
            hub.call("a", "store", "no-such-op", {})
        hub.unregister("store")
        with pytest.raises(TransportError):
            hub.call("a", "store", "echo", {})

    def test_seeded_faults_are_deterministic(self):
        plan = FaultPlan(seed=3, faults=[
            FaultSpec(kind="drop", op="ship", rate=0.5)])

        def outcomes():
            hub = LoopbackTransport()
            hub.register("store", {"ship": lambda m: {"ok": True}})
            faulty = FaultyTransport(hub, plan, registry=Registry())
            dropped = []
            for _ in range(32):
                try:
                    faulty.call("a", "store", "ship", {})
                    dropped.append(False)
                except TransportDropped:
                    dropped.append(True)
            return dropped

        first, second = outcomes(), outcomes()
        assert first == second
        assert True in first and False in first

    def test_drop_fires_at_exact_call_index(self):
        hub = LoopbackTransport()
        hub.register("store", {"ship": lambda m: {"ok": True}})
        faulty = FaultyTransport(
            hub,
            FaultPlan(seed=1, faults=[
                FaultSpec(kind="drop", op="ship", call_index=0)]),
            registry=Registry(),
        )
        with pytest.raises(TransportDropped):
            faulty.call("a", "store", "ship", {})
        assert faulty.call("a", "store", "ship", {})["ok"]

    def test_duplicate_delivers_twice(self):
        calls = []
        hub = LoopbackTransport()
        hub.register("store", {
            "ship": lambda m: calls.append(1) or {"ok": True}})
        faulty = FaultyTransport(
            hub,
            FaultPlan(seed=1, faults=[
                FaultSpec(kind="duplicate", op="ship", call_index=0)]),
            registry=Registry(),
        )
        assert faulty.call("a", "store", "ship", {})["ok"]
        assert len(calls) == 2  # handlers must be idempotent; PageStore's are
        assert faulty.call("a", "store", "ship", {})["ok"]
        assert len(calls) == 3

    @staticmethod
    def _bit_distance(a: bytes, b: bytes) -> int:
        return sum(bin(x ^ y).count("1") for x, y in zip(a, b))

    def test_bit_flip_corrupts_exactly_one_request_bit(self):
        seen = []
        hub = LoopbackTransport()
        hub.register("store", {
            "ship": lambda m: seen.append(bytes(m["data"])) or {"ok": True}})
        faulty = FaultyTransport(
            hub,
            FaultPlan(seed=9, faults=[
                FaultSpec(kind="bit_flip", op="ship", call_index=0)]),
            registry=Registry(),
        )
        original = bytes(range(16))
        faulty.call("a", "store", "ship", {"data": original})
        assert self._bit_distance(seen[0], original) == 1

    def test_bit_flip_corrupts_response_when_request_has_no_data(self):
        payload = bytes(range(16))
        hub = LoopbackTransport()
        hub.register("store", {
            "fetch": lambda m: {"ok": True, "data": payload}})
        faulty = FaultyTransport(
            hub,
            FaultPlan(seed=9, faults=[
                FaultSpec(kind="bit_flip", op="fetch", call_index=0)]),
            registry=Registry(),
        )
        response = faulty.call("a", "store", "fetch", {"index": 0})
        assert self._bit_distance(bytes(response["data"]), payload) == 1

    def test_partition_window_is_bidirectional_and_scheduled(self):
        now = [0.0]
        hub = LoopbackTransport()
        hub.register("store", {"probe": lambda m: {"ok": True}})
        faulty = FaultyTransport(
            hub,
            FaultPlan(seed=2, faults=[
                FaultSpec(kind="partition", op="*", peer="r1",
                          after_s=1.0, duration_s=2.0)]),
            registry=Registry(),
            clock=lambda: now[0],
        )
        assert faulty.call("r1", "store", "probe", {})["ok"]
        now[0] = 1.5
        with pytest.raises(TransportPartitioned):
            faulty.call("r1", "store", "probe", {})  # src partitioned
        with pytest.raises(TransportPartitioned):
            faulty.call("store", "r1", "probe", {})  # dst partitioned
        assert faulty.call("r0", "store", "probe", {})["ok"]  # other routes
        assert faulty.partitioned("r1", "store")
        assert not faulty.partitioned("r0", "store")
        now[0] = 3.5
        assert faulty.call("r1", "store", "probe", {})["ok"]
        (peer, start, end), = faulty.partition_windows()
        assert peer == "r1" and end - start == pytest.approx(2.0)

    def test_one_plan_addresses_backend_and_transport_ops(self):
        registry = Registry()
        plan = FaultPlan(seed=4, faults=[
            FaultSpec(kind="transient_error", op="score", call_index=0),
            FaultSpec(kind="drop", op="ship", call_index=0),
        ])
        backend = FaultInjectingBackend(FakeBackend(), plan,
                                        registry=registry)
        with pytest.raises(Exception):
            backend.score([ScoreRequest(context="p", continuation="c")])
        hub = LoopbackTransport()
        hub.register("store", {"ship": lambda m: {"ok": True}})
        faulty = FaultyTransport(hub, plan, registry=registry)
        with pytest.raises(TransportDropped):
            faulty.call("a", "store", "ship", {})
        # Both injections land in the SAME registry family: one scrape
        # shows the whole scripted incident across both domains.
        prom = registry.to_prometheus()
        assert ('faults_injected_total{kind="transient_error",op="score"} 1'
                in prom)
        assert 'faults_injected_total{kind="drop",op="ship"} 1' in prom


# ---------------------------------------------------------------------------
# PageStore shipping over the seam
# ---------------------------------------------------------------------------


class TestPageStoreShipping:
    def test_chunked_loopback_shipping_round_trips(self):
        registry = Registry()
        # chunk_bytes far below the blob size: loopback shipping spans
        # several begin/chunk/commit messages, not one call.
        store = PageStore(registry=registry, chunk_bytes=8)
        donor = _donor_cache()
        assert store.capture_cache(donor) == 1
        assert len(store) == 1
        joiner = PrefixCache(PagePool(num_pages=32, page_size=4),
                             max_pages=32, identity=("m", "tp1"))
        assert store.seed_engine(_OneCacheEngine(joiner)) == 1
        found, n_tokens = joiner.lookup(tuple(range(8)))
        assert n_tokens == 8 and len(found) == 2

    def test_interrupted_ship_resumes_from_held_chunks(self):
        store = PageStore(registry=Registry(), chunk_bytes=4)
        blob, blob_hash = _run_blob()
        chunks = [blob[i:i + 4] for i in range(0, len(blob), 4)]
        begin = {"phase": "begin", "transfer": "t1", "hash": blob_hash,
                 "n_chunks": len(chunks), "blob_len": len(blob)}
        assert store._handle_ship(begin) == {
            "ok": True, "done": False, "have": []}
        assert store._handle_ship({
            "phase": "chunk", "transfer": "t1", "index": 0,
            "data": chunks[0], "chunk_hash": _content_hash(chunks[0]),
        })["ok"]
        # Commit before all chunks arrive: refused with the missing list.
        commit = store._handle_ship({"phase": "commit", "transfer": "t1"})
        assert not commit["ok"] and commit["reason"] == "missing_chunks"
        assert commit["missing"] == list(range(1, len(chunks)))
        # A second begin (the transfer interrupted and retried) reports
        # the chunks already held, so only the remainder is re-sent.
        assert store._handle_ship(begin)["have"] == [0]
        for index in range(1, len(chunks)):
            assert store._handle_ship({
                "phase": "chunk", "transfer": "t1", "index": index,
                "data": chunks[index],
                "chunk_hash": _content_hash(chunks[index]),
            })["ok"]
        assert store._handle_ship(
            {"phase": "commit", "transfer": "t1"})["ok"]
        assert len(store) == 1
        assert store.runs()[0]["hash"] == blob_hash
        # Re-shipping an admitted blob short-circuits at begin.
        assert store._handle_ship(begin) == {
            "ok": True, "done": True, "have": []}

    def test_corrupt_chunks_are_rejected_in_flight(self):
        store = PageStore(registry=Registry(), chunk_bytes=4)
        blob, blob_hash = _run_blob()
        store._handle_ship({
            "phase": "begin", "transfer": "t1", "hash": blob_hash,
            "n_chunks": 2, "blob_len": len(blob)})
        rejected = store._handle_ship({
            "phase": "chunk", "transfer": "t1", "index": 0,
            "data": b"corrupted!", "chunk_hash": _content_hash(b"honest"),
        })
        assert not rejected["ok"]
        assert rejected["reason"] == "chunk_integrity"

    def test_full_corruption_is_never_admitted(self):
        registry = Registry()
        plan = FaultPlan(seed=7, faults=[
            FaultSpec(kind="bit_flip", op="ship", rate=1.0)])
        transport = FaultyTransport(LoopbackTransport(), plan,
                                    registry=registry)
        store = PageStore(registry=registry, transport=transport,
                          chunk_bytes=8)
        # Every chunk is corrupted in flight; the store rejects each one
        # on its chunk hash and the capture gives up WITHOUT admitting.
        assert store.capture_cache(_donor_cache()) == 0
        assert len(store) == 0

    def test_seeded_drops_resume_and_ship_completes(self):
        registry = Registry()
        plan = FaultPlan(seed=11, faults=[
            FaultSpec(kind="drop", op="ship", rate=0.2)])
        transport = FaultyTransport(LoopbackTransport(), plan,
                                    registry=registry)
        store = PageStore(registry=registry, transport=transport,
                          chunk_bytes=8)
        assert store.capture_cache(_donor_cache()) == 1
        assert len(store) == 1
        # The drops really fired — the transfer survived them by retrying
        # and resuming, not by never being interrupted.
        assert ('faults_injected_total{kind="drop",op="ship"}'
                in registry.to_prometheus())

    def test_local_admission_rejects_hash_mismatch(self):
        registry = Registry()
        store = PageStore(registry=registry)
        blob, blob_hash = _run_blob()
        with pytest.raises(PageIntegrityError):
            store.admit_blob(blob[:-3], blob_hash)  # truncated
        with pytest.raises(PageIntegrityError):
            store.admit_blob(blob, "0" * 32)  # wrong expectation
        # Correct hash over garbage bytes: hash verification passes but
        # deserialization cannot — still refused, still typed.
        garbage = b"not a pickled run at all"
        with pytest.raises(PageIntegrityError):
            store.admit_blob(garbage, _content_hash(garbage))
        assert len(store) == 0
        assert ("pagestore_integrity_rejects_total 3"
                in registry.to_prometheus())
        # The honest blob still admits fine afterwards.
        store.admit_blob(blob, blob_hash)
        assert len(store) == 1

    def test_lease_expiry_aborts_fetch_mid_transfer(self):
        now = [0.0]
        registry = Registry()
        store = PageStore(registry=registry, lease_s=5.0,
                          clock=lambda: now[0], chunk_bytes=8)
        assert store.capture_cache(_donor_cache()) == 1
        client = store.client("joiner")
        listing = client._call("fetch", {"phase": "list"})
        meta = listing["runs"][0]
        assert meta["n_chunks"] > 1
        # First chunk arrives while the lease is live...
        first = client._call("fetch", {
            "phase": "chunk", "identity": meta["identity"],
            "key": meta["key"], "index": 0})
        assert first["ok"]
        # ...then the run expires mid-transfer: the next chunk is gone and
        # the client aborts the adoption cleanly (no partial run).
        now[0] = 6.0
        assert len(store) == 0
        gone = client._call("fetch", {
            "phase": "chunk", "identity": meta["identity"],
            "key": meta["key"], "index": 1})
        assert not gone["ok"] and gone["reason"] == "gone"
        assert client._fetch_blob(meta) is None
        assert "pagestore_fetch_aborts_total 1" in registry.to_prometheus()
        joiner = PrefixCache(PagePool(num_pages=32, page_size=4),
                             max_pages=32, identity=("m", "tp1"))
        assert store.seed_engine(_OneCacheEngine(joiner)) == 0

    def test_eviction_mid_fetch_aborts_cleanly(self):
        registry = Registry()
        store = PageStore(max_runs=1, registry=registry, chunk_bytes=8)
        assert store.capture_cache(_donor_cache(tokens=tuple(range(8)))) == 1
        client = store.client("joiner")
        meta = client._call("fetch", {"phase": "list"})["runs"][0]
        # A newer run evicts the one being fetched (max_runs=1).
        assert store.capture_cache(
            _donor_cache(tokens=tuple(range(8, 16)))) == 1
        assert client._fetch_blob(meta) is None
        assert "pagestore_fetch_aborts_total 1" in registry.to_prometheus()

    def test_degraded_client_fast_fails_then_heals(self):
        class _FlakyHub:
            def __init__(self, inner):
                self.inner = inner
                self.down = False
                self.calls = 0

            def register(self, peer, handlers):
                self.inner.register(peer, handlers)

            def unregister(self, peer):
                self.inner.unregister(peer)

            def peers(self):
                return self.inner.peers()

            def call(self, src, dst, op, msg):
                self.calls += 1
                if self.down:
                    raise TransportError("seam down")
                return self.inner.call(src, dst, op, msg)

        registry = Registry()
        hub = _FlakyHub(LoopbackTransport())
        store = PageStore(registry=registry, transport=hub)
        client = store.client("r0")
        hub.down = True
        assert store.client("r0").capture_cache(_donor_cache()) == 0
        assert client.degraded
        stats = store.stats()
        assert stats["degraded_clients"] == ["r0"]
        (window,) = [w for w in stats["degradation_windows"]
                     if w["client"] == "r0"]
        assert window["exit_s"] is None
        assert "pagestore_degraded 1" in registry.to_prometheus()
        # Degraded capture pays ONE probe, not the full retry ladder.
        before = hub.calls
        assert client.capture_cache(_donor_cache()) == 0
        assert hub.calls == before + 1
        # Seam back: the next probe heals the client and closes the window.
        hub.down = False
        assert client.probe()
        assert not client.degraded
        stats = store.stats()
        assert stats["degraded_clients"] == []
        (window,) = [w for w in stats["degradation_windows"]
                     if w["client"] == "r0"]
        assert window["exit_s"] is not None
        assert "pagestore_degraded 0" in registry.to_prometheus()
        assert client.capture_cache(_donor_cache()) == 1


# ---------------------------------------------------------------------------
# fleet harness over the transport seam
# ---------------------------------------------------------------------------


def _seam_fleet(n=3, *, registry=None, plan=None, store_kwargs=None,
                manager_kwargs=None):
    """A FleetRouter over FakeBackend engine replicas whose PageStore
    traffic crosses a (optionally faulty) transport, plus the lifecycle
    manager probing that seam and a fleet-shared idempotency cache."""
    registry = registry if registry is not None else Registry()
    transport = LoopbackTransport()
    if plan is not None:
        transport = FaultyTransport(transport, plan, registry=registry)
    store = PageStore(registry=registry, transport=transport,
                      **(store_kwargs or {}))
    idempotency = IdempotencyCache()
    scheduler_options = {
        "max_inflight": 2, "max_queue_depth": 32,
        "default_timeout_s": 30.0, "retry_backoff_s": 0.001,
        "engine": True, "engine_options": {"prefix_cache": True},
        "idempotency": idempotency,
    }

    def factory(name, tier=None):
        return Replica(
            name, FakeBackend(), tier=tier or "full", registry=registry,
            scheduler_options=dict(scheduler_options),
        )

    replicas = [factory(f"r{i}") for i in range(n)]
    router = FleetRouter(replicas, registry=registry,
                         idempotency_cache=idempotency).start()
    kwargs = {
        "respawn_backoff_s": 0.05,
        "respawn_backoff_max_s": 0.4,
        "check_interval_s": 0.05,
        "harvest_interval_s": 0.1,
        "retire_timeout_s": 1.0,
        "transport_probe_failures": 2,
    }
    kwargs.update(manager_kwargs or {})
    manager = ReplicaManager(
        router, factory, page_store=store, registry=registry, **kwargs,
    )
    return router, manager, store, transport, idempotency


def _shutdown(router):
    router.shutdown(drain=False, timeout=10.0)


# ---------------------------------------------------------------------------
# manager transport probes: partition detection + bounded recovery
# ---------------------------------------------------------------------------


class TestFleetTransportHealth:
    def test_partition_detected_routed_around_and_healed(self):
        registry = Registry()
        plan = FaultPlan(seed=5, faults=[
            FaultSpec(kind="partition", op="*", peer="r1",
                      after_s=0.0, duration_s=0.8)])
        router, manager, store, transport, _ = _seam_fleet(
            3, registry=registry, plan=plan)
        try:
            # Probes fail from t0: within a couple of ticks r1 is marked
            # transport-partitioned and its health drops to DEGRADED —
            # routed around, NOT lost (no respawn churn for a net blip).
            assert _wait_for(
                lambda: not router._replica("r1").transport_ok, timeout=5.0)
            replica = router._replica("r1")
            assert replica.health == DEGRADED
            assert not replica.lost
            assert "r1" in manager.snapshot()["partitioned"]
            assert "transport" in replica.snapshot()
            # The window ends; the next passing probe heals the replica
            # and records the partition event with both timestamps.
            assert _wait_for(
                lambda: router._replica("r1").transport_ok, timeout=10.0)
            assert router._replica("r1").health != DEGRADED
            events = manager.snapshot()["partition_events"]
            assert events and events[-1]["replica"] == "r1"
            event = events[-1]
            assert event["cleared_s"] >= event["detected_s"]
            # Bounded recovery: the heal lands within a few probe ticks of
            # the scheduled window end, not eventually.
            (_, _, window_end), = transport.partition_windows()
            assert 0.0 <= event["cleared_s"] - window_end < 3.0
            assert manager.snapshot()["respawns"] == 0
        finally:
            _shutdown(router)


# ---------------------------------------------------------------------------
# fleet conformance under the standard seeded schedule
# ---------------------------------------------------------------------------


def _standard_plan():
    """The acceptance schedule: steady ship/fetch drops, low-rate
    corruption everywhere, and one scheduled partition of r1."""
    return FaultPlan(seed=7, faults=[
        FaultSpec(kind="drop", op="ship", rate=0.05),
        FaultSpec(kind="drop", op="fetch", rate=0.05),
        FaultSpec(kind="bit_flip", op="*", rate=0.01),
        FaultSpec(kind="partition", op="*", peer="r1",
                  after_s=0.5, duration_s=2.0),
    ])


def _drive(router, payloads, batch=0, pace_s=0.0):
    """Submit every payload exactly once; return per-request-id outcome
    and statement maps.  Every ticket MUST resolve (zero lost)."""
    tickets = []
    for index, payload in enumerate(payloads):
        request = parse_request(payload)
        tickets.append((request, router.submit(request)))
        if batch and pace_s and (index + 1) % batch == 0:
            time.sleep(pace_s)
    outcomes, statements = {}, {}
    for request, ticket in tickets:
        assert ticket.wait(30.0), f"lost request {request.request_id}"
        assert request.request_id not in outcomes, "duplicated request id"
        outcomes[request.request_id] = ticket.outcome
        if ticket.outcome in ("ok", "degraded"):
            statements[request.request_id] = ticket.result()["statement"]
    return outcomes, statements


class TestChaosConformance:
    N_REQUESTS = 36

    def _payloads(self):
        return [_payload(seed=200 + i, issue=f"issue {i % 6}")
                for i in range(self.N_REQUESTS)]

    def test_standard_schedule_meets_conformance_bars(self):
        # Fault-free reference run: the byte-identity baseline.
        router, _, _, _, _ = _seam_fleet(3)
        try:
            baseline_outcomes, baseline = _drive(router, self._payloads())
        finally:
            _shutdown(router)
        assert all(o == "ok" for o in baseline_outcomes.values())

        registry = Registry()
        router, manager, store, transport, _ = _seam_fleet(
            3, registry=registry, plan=_standard_plan())
        try:
            # Pace submissions across ~3s so the partition window (0.5s to
            # 2.5s) overlaps live traffic AND live harvest/seed cycles.
            outcomes, statements = _drive(
                router, self._payloads(), batch=6, pace_s=0.4)
            # Zero lost or duplicated: exactly one terminal outcome per
            # offered request id (asserted per-ticket in _drive too).
            assert sorted(outcomes) == sorted(baseline_outcomes)
            # Availability >= 0.99 under the standard schedule.
            ok = sum(1 for o in outcomes.values() if o == "ok")
            availability = ok / float(self.N_REQUESTS)
            assert availability >= 0.99, f"availability {availability}"
            # Byte-identity: transport faults change where prefill comes
            # from (warm pages vs cold), never the bytes served.
            for request_id, statement in statements.items():
                assert statement == baseline[request_id], request_id
            # Bounded recovery: the partition was detected and cleared
            # within a few probe ticks of the scheduled window end.
            assert _wait_for(
                lambda: manager.snapshot()["partition_events"], timeout=10.0)
            event = manager.snapshot()["partition_events"][-1]
            assert event["replica"] == "r1"
            (_, _, window_end), = transport.partition_windows()
            recovery_s = event["cleared_s"] - window_end
            assert 0.0 <= recovery_s < 5.0, f"recovery took {recovery_s}s"
            # The seam really carried traffic under faults: runs were
            # harvested into the store despite drops and corruption.
            assert len(store) > 0
        finally:
            _shutdown(router)


# ---------------------------------------------------------------------------
# exactly-once across failover: idempotency cache
# ---------------------------------------------------------------------------


class TestIdempotency:
    def test_key_binds_id_and_semantic_fields(self):
        request = parse_request(_payload(seed=1))
        same = parse_request(_payload(seed=1))
        assert idempotency_key(request, "best_of_n") == idempotency_key(
            same, "best_of_n")
        # Reused id with different content must NOT collide.
        different = parse_request(_payload(seed=1, issue="another issue"))
        assert idempotency_key(request, "best_of_n") != idempotency_key(
            different, "best_of_n")
        assert idempotency_key(request, "beam") != idempotency_key(
            request, "best_of_n")
        anonymous = types.SimpleNamespace(request_id=None)
        assert idempotency_key(anonymous, "best_of_n") is None

    def test_cache_is_bounded_lru(self):
        cache = IdempotencyCache(max_entries=2)
        cache.put("a", {"outcome": "ok"})
        cache.put("b", {"outcome": "ok"})
        assert cache.get("a") is not None  # refreshes a
        cache.put("c", {"outcome": "ok"})  # evicts b, the LRU entry
        assert cache.get("b") is None
        assert cache.get("a") is not None and cache.get("c") is not None
        assert len(cache) == 2
        stats = cache.stats()
        assert stats["entries"] == 2 and stats["puts"] == 3
        assert stats["hits"] == 3

    def test_scheduler_records_completed_results(self):
        registry = Registry()
        cache = IdempotencyCache()
        replica = Replica(
            "r0", FakeBackend(), registry=registry,
            scheduler_options={
                "max_inflight": 2, "max_queue_depth": 8,
                "default_timeout_s": 30.0, "engine": True,
                "idempotency": cache,
            },
        )
        replica.scheduler.start()
        try:
            request = parse_request(_payload(seed=3))
            ticket = replica.scheduler.submit(request)
            assert ticket.wait(30.0) and ticket.outcome == "ok"
            record = cache.get(idempotency_key(request, request.method))
            assert record is not None
            assert record["outcome"] == "ok"
            assert record["replica"] == "r0"
            assert record["value"]["statement"] == (
                ticket.result()["statement"])
        finally:
            replica.scheduler.shutdown(drain=False, timeout=10.0)

    def test_router_replays_cached_result_instead_of_reexecuting(self):
        registry = Registry()
        cache = IdempotencyCache()
        hang_plan = lambda: FaultPlan(seed=1, faults=[  # noqa: E731
            FaultSpec(kind="hang", op="generate", call_index=0)])
        injectors = []

        def replica_of(name):
            backend = FaultInjectingBackend(FakeBackend(), hang_plan(),
                                            registry=registry)
            injectors.append(backend)
            return Replica(
                name, backend, registry=registry,
                scheduler_options={
                    "max_inflight": 2, "max_queue_depth": 8,
                    "default_timeout_s": 30.0, "engine": True,
                    "idempotency": cache,
                },
            )

        router = FleetRouter(
            [replica_of("r0"), replica_of("r1")], registry=registry,
            idempotency_cache=cache,
        ).start()
        try:
            request = parse_request(_payload(seed=9))
            serving = router.route_for(request).name
            ticket = router.submit(request)
            assert _wait_for(
                lambda: any(i.hangs_active >= 1 for i in injectors),
                timeout=10.0)
            # The replica computed and recorded the result but died before
            # delivering it (simulated: seed the fleet cache by hand, then
            # kill the server).  Failover must replay, not re-execute.
            cache.put(idempotency_key(request, request.method), {
                "outcome": "ok",
                "value": {"statement": "the-bytes-already-computed"},
                "replica": serving, "tier": "full",
            })
            router.kill_replica(serving, reason="chaos")
            assert ticket.wait(30.0)
            assert ticket.outcome == "ok"
            value = ticket.result()
            assert value["statement"] == "the-bytes-already-computed"
            assert value["idempotent_replay"] is True
            assert value["served_by"] == serving
            assert ("fleet_idempotent_hits_total 1"
                    in registry.to_prometheus())
        finally:
            for injector in injectors:
                injector.release_hangs()
            _shutdown(router)
