"""Continuous-batching decode engine (backends/engine.py) + paged KV cache
(ops/kv_pages.py).

The PR 6 contract, pinned here:

* byte-identity — every GENERATOR_MAP method produces the same statement
  through the engine, the legacy flush path, and a solo backend;
* page-pool soundness — all-or-nothing allocation, no aliasing under
  churn, double/foreign frees raise;
* graceful OOM — a request that can never fit the pool gets the serving
  tier's typed ``SchedulerRejected("kv_oom")``, not a crash;
* interleaved chunked prefill never perturbs decode results;
* cancellation evicts resident rows and returns their KV pages;
* engine mode keeps ``flush_reason="timeout"`` unreachable and
  ``batching_spurious_wakeups_total`` at 0, and stays recompile-flat
  across ragged load.
"""

import threading
import time

import pytest

from consensus_tpu.backends.base import GenerationRequest, RequestCancelled
from consensus_tpu.backends.batching import BatchingBackend
from consensus_tpu.backends.engine import DecodeEngine
from consensus_tpu.backends.fake import FakeBackend
from consensus_tpu.methods import get_method_generator
from consensus_tpu.obs.backends import bucket_recompiles
from consensus_tpu.obs.metrics import Registry, diff_snapshots
from consensus_tpu.ops.kv_pages import (
    BlockTable,
    PagePool,
    PagePoolExhausted,
    PrefixCache,
)

ISSUE = "Should the city invest in more bike lanes?"
OPINIONS = {
    "Agent 1": "Bike lanes make streets safer and should be expanded.",
    "Agent 2": "Road space is scarce; cars and buses need priority.",
    "Agent 3": "Invest only where cycling demand is proven.",
}

#: Small-but-real params for every method in GENERATOR_MAP (same settings
#: the per-method suites use, so any drift shows up in one place).
METHOD_PARAMS = {
    "zero_shot": {"seed": 42, "max_tokens": 30},
    "predefined": {"predefined_statement": "Exactly this statement."},
    "best_of_n": {"num_best_of_n": 4, "seed": 7, "max_tokens": 24},
    "beam_search": {"beam_width": 2, "max_tokens": 6, "seed": 5},
    "finite_lookahead": {
        "branching_factor": 2, "max_depth": 2, "max_tokens": 5, "seed": 9,
    },
    "mcts": {
        "num_simulations": 4, "expansion_sample_width": 3, "max_tokens": 4,
        "rollout_depth": 3, "seed": 2,
    },
    "habermas_machine": {
        "num_candidates": 3, "num_rounds": 1, "seed": 42, "max_tokens": 64,
    },
}


def _counter_total(registry, name, **labels):
    family = registry.snapshot()["families"].get(name)
    total = 0.0
    for series in (family or {}).get("series", ()):
        if all(series["labels"].get(k) == v for k, v in labels.items()):
            total += series["value"]
    return total


def _wait_until(predicate, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(0.002)
    return False


# ---------------------------------------------------------------------------
# Page pool / block table
# ---------------------------------------------------------------------------


class TestPagePool:
    def test_alloc_free_roundtrip(self):
        pool = PagePool(8, page_size=4)
        pages = pool.alloc(3, owner="a")
        assert len(set(pages)) == 3
        assert pool.in_use == 3 and pool.free_count == 5
        pool.free(pages)
        assert pool.in_use == 0 and pool.free_count == 8

    def test_exhaustion_is_all_or_nothing(self):
        pool = PagePool(4, page_size=4)
        pool.alloc(3, owner="a")
        with pytest.raises(PagePoolExhausted):
            pool.alloc(2, owner="b")
        # The failed alloc must not have consumed the last free page.
        assert pool.free_count == 1
        pool.alloc(1, owner="b")

    def test_double_free_raises(self):
        pool = PagePool(4)
        pages = pool.alloc(2)
        pool.free(pages)
        with pytest.raises(ValueError, match="double free|not allocated"):
            pool.free(pages)

    def test_foreign_page_free_raises(self):
        pool = PagePool(4)
        with pytest.raises(ValueError):
            pool.free([99])

    def test_no_aliasing_under_churn(self):
        """Interleaved alloc/free never hands one page to two live owners."""
        pool = PagePool(16, page_size=4)
        live = {}
        for step in range(200):
            if step % 3 == 2 and live:
                victim = sorted(live)[step % len(live)]
                pool.free(live.pop(victim))
            else:
                n = 1 + step % 3
                if n <= pool.free_count:
                    live[step] = pool.alloc(n, owner=step)
            held = [p for pages in live.values() for p in pages]
            assert len(held) == len(set(held))  # no page in two hands
            assert pool.in_use == len(held)
        assert pool.stats().high_water <= pool.num_pages

    def test_pages_for_tokens_ceil(self):
        pool = PagePool(8, page_size=16)
        assert pool.pages_for_tokens(0) == 0
        assert pool.pages_for_tokens(1) == 1
        assert pool.pages_for_tokens(16) == 1
        assert pool.pages_for_tokens(17) == 2

    # -- refcounted sharing (prefix cache) ---------------------------------

    def test_shared_page_survives_first_free(self):
        """free() drops one reference; the page rejoins the free list only
        when the LAST holder lets go."""
        pool = PagePool(8, page_size=4)
        pages = pool.alloc(2, owner="slot")
        pool.share(pages)  # cache pins them
        assert all(pool.refcount(p) == 2 for p in pages)
        pool.free(pages)  # slot retires
        assert pool.in_use == 2 and pool.free_count == 6
        assert all(pool.refcount(p) == 1 for p in pages)
        pool.free(pages)  # cache evicts
        assert pool.in_use == 0 and pool.free_count == 8

    def test_double_free_of_shared_page_still_raises(self):
        """Sharing must not launder a double free: once every reference is
        gone, another free raises exactly like the unshared case."""
        pool = PagePool(4, page_size=4)
        pages = pool.alloc(1)
        pool.share(pages)
        pool.free(pages)
        pool.free(pages)
        with pytest.raises(ValueError, match="double free|not allocated"):
            pool.free(pages)

    def test_share_free_page_raises(self):
        pool = PagePool(4, page_size=4)
        pages = pool.alloc(1)
        pool.free(pages)
        with pytest.raises(ValueError, match="cannot share a free page"):
            pool.share(pages)
        with pytest.raises(ValueError):
            pool.share([99])

    def test_freed_while_refcounted_page_is_not_reallocated(self):
        """A page another holder still references must never come back out
        of alloc() — the aliasing bug refcounting exists to prevent."""
        pool = PagePool(4, page_size=4)
        shared = pool.alloc(2, owner="a")
        pool.share(shared)
        pool.free(shared)  # one reference remains
        grabbed = pool.alloc(2, owner="b")  # only the 2 never-shared pages
        assert not (set(grabbed) & set(shared))
        with pytest.raises(PagePoolExhausted):
            pool.alloc(1, owner="c")

    def test_no_aliasing_under_churn_with_sharing(self):
        """Mixed private/shared churn keeps the invariant: at every step a
        page is either free, or held by exactly its current reference
        holders — never handed out twice."""
        pool = PagePool(16, page_size=4)
        private = {}  # step -> pages (one ref)
        shared = {}  # step -> pages (two refs: "slot" + "cache")
        for step in range(300):
            action = step % 5
            if action == 0 and pool.free_count >= 2:
                private[step] = pool.alloc(2, owner=step)
            elif action == 1 and pool.free_count >= 1:
                pages = pool.alloc(1, owner=step)
                pool.share(pages)
                shared[step] = pages
            elif action == 2 and private:
                pool.free(private.pop(sorted(private)[0]))
            elif action == 3 and shared:
                # Drop ONE of the two references; entry stays live.
                key = sorted(shared)[0]
                pool.free(shared[key])
                private[key] = shared.pop(key)
            elif action == 4 and private:
                pool.free(private.pop(sorted(private)[-1]))
            held = [
                p for pages in list(private.values()) + list(shared.values())
                for p in pages
            ]
            assert len(held) == len(set(held))
            assert pool.in_use == len(held)
            for pages in shared.values():
                assert all(pool.refcount(p) == 2 for p in pages)
        for pages in private.values():
            pool.free(pages)
        for pages in shared.values():
            pool.free(pages)
            pool.free(pages)
        assert pool.in_use == 0 and pool.free_count == 16

    def test_adopt_shared_requires_alignment_and_empty_table(self):
        pool = PagePool(8, page_size=4)
        donor = BlockTable(0)
        donor.append_tokens(pool, 8)
        table = BlockTable(1)
        with pytest.raises(ValueError, match="page-aligned"):
            table.adopt_shared(pool, donor.pages, 7)
        table.adopt_shared(pool, donor.pages, 8)
        assert table.num_tokens == 8 and table.pages == donor.pages
        with pytest.raises(ValueError, match="empty block table"):
            table.adopt_shared(pool, donor.pages, 8)
        # The adopter's release leaves the donor's reference intact.
        table.release(pool)
        assert pool.in_use == 2
        donor.release(pool)
        assert pool.in_use == 0


class TestBlockTable:
    def test_append_allocates_on_page_boundaries_only(self):
        pool = PagePool(8, page_size=4)
        table = BlockTable(0)
        assert len(table.append_tokens(pool, 3)) == 1  # first page
        assert table.append_tokens(pool, 1) == []  # fills page 0
        assert len(table.append_tokens(pool, 5)) == 2  # crosses into 2 more
        assert table.num_tokens == 9 and len(table.pages) == 3

    def test_write_cursor_tracks_last_token(self):
        pool = PagePool(8, page_size=4)
        table = BlockTable(0)
        table.append_tokens(pool, 5)
        page, offset = table.write_cursor(pool)
        assert page == table.pages[1] and offset == 0

    def test_release_returns_everything(self):
        pool = PagePool(8, page_size=4)
        table = BlockTable(0)
        table.append_tokens(pool, 9)
        table.release(pool)
        assert pool.in_use == 0 and table.num_tokens == 0

    def test_as_array_pads_and_bounds(self):
        pool = PagePool(8, page_size=4)
        table = BlockTable(0)
        table.append_tokens(pool, 6)
        arr = table.as_array(4)
        assert arr.tolist()[:2] == table.pages and set(arr.tolist()[2:]) == {-1}
        with pytest.raises(ValueError, match="max_blocks"):
            table.as_array(1)


# ---------------------------------------------------------------------------
# Byte-identity: engine vs legacy flush vs solo, all seven methods
# ---------------------------------------------------------------------------


class TestByteIdentity:
    @pytest.mark.parametrize("method", sorted(METHOD_PARAMS))
    def test_engine_matches_legacy_and_solo(self, method):
        params = METHOD_PARAMS[method]
        solo = get_method_generator(
            method, FakeBackend(), dict(params)
        ).generate_statement(ISSUE, OPINIONS)

        legacy = BatchingBackend(FakeBackend(), flush_ms=1.0, engine=False)
        via_legacy = get_method_generator(
            method, legacy, dict(params)
        ).generate_statement(ISSUE, OPINIONS)

        engined = BatchingBackend(
            FakeBackend(), engine=True,
            engine_options={"slots": 4, "num_pages": 512},
        )
        try:
            via_engine = get_method_generator(
                method, engined, dict(params)
            ).generate_statement(ISSUE, OPINIONS)
        finally:
            engined.close()

        assert via_engine == solo, f"{method}: engine result diverged"
        assert via_legacy == solo, f"{method}: legacy result diverged"


# ---------------------------------------------------------------------------
# Prefix KV cache
# ---------------------------------------------------------------------------


class TestPrefixCache:
    def _cache(self, num_pages=16, max_pages=8, identity=("m", "dense")):
        pool = PagePool(num_pages, page_size=4)
        return pool, PrefixCache(pool, max_pages, identity=identity)

    def test_miss_then_hit_roundtrip(self):
        pool, cache = self._cache()
        tokens = list(range(8))
        assert cache.lookup(tokens) == ([], 0)
        pages = pool.alloc(2, owner="slot")
        assert cache.insert(tokens, pages)
        got_pages, got_tokens = cache.lookup(tokens + [99, 98])
        assert got_pages == pages and got_tokens == 8
        # Three holders now: slot, cache, and the lookup's adopter.
        assert all(pool.refcount(p) == 3 for p in pages)
        stats = cache.stats()
        assert stats["hits"] == 1 and stats["misses"] == 1
        assert stats["tokens_saved"] == 8

    def test_lookup_returns_longest_prefix(self):
        pool, cache = self._cache()
        short, long_ = list(range(4)), list(range(8))
        p_short = pool.alloc(1, owner="a")
        p_long = pool.alloc(2, owner="b")
        assert cache.insert(short, p_short)
        assert cache.insert(long_, p_long)
        pages, n = cache.lookup(long_ + [42])
        assert (pages, n) == (p_long, 8)
        # A stream sharing only the first page matches the short entry.
        pages, n = cache.lookup(short + [77, 77, 77, 77])
        assert (pages, n) == (p_short, 4)

    def test_unaligned_or_oversized_insert_rejected(self):
        pool, cache = self._cache(max_pages=1)
        pages = pool.alloc(2, owner="a")
        assert not cache.insert(list(range(7)), pages)  # unaligned
        assert not cache.insert(list(range(8)), pages)  # over budget
        assert not cache.insert([], [])  # empty
        assert pool.refcount(pages[0]) == 1  # no stray references taken

    def test_identity_partitions_the_keyspace(self):
        """Same token stream, different (tier, quant) identity — never the
        same entry: two tiers' KV bytes must not alias."""
        pool = PagePool(16, page_size=4)
        a = PrefixCache(pool, 8, identity=("m", "dense"))
        b = PrefixCache(pool, 8, identity=("m", "int8"))
        tokens = list(range(8))
        pages = pool.alloc(2, owner="x")
        assert a.insert(tokens, pages)
        assert b.lookup(tokens) == ([], 0)
        assert a.lookup(tokens)[1] == 8

    def test_lru_eviction_frees_cache_reference_only(self):
        pool, cache = self._cache(max_pages=2)
        first = pool.alloc(2, owner="a")
        assert cache.insert(list(range(8)), first)
        pool.free(first)  # slot retires; cache holds the last reference
        second = pool.alloc(2, owner="b")
        assert cache.insert(list(range(100, 108)), second)  # evicts first
        assert cache.stats()["evictions"] == 1
        assert cache.stats()["pages"] == 2
        # The evicted entry's pages went back to the free list...
        assert pool.in_use == 2
        # ...and the survivor is still servable.
        assert cache.lookup(list(range(100, 108)))[1] == 8

    def test_eviction_spares_pages_adopted_by_live_slots(self):
        pool, cache = self._cache(max_pages=2)
        first = pool.alloc(2, owner="a")
        assert cache.insert(list(range(8)), first)
        pool.free(first)
        adopted, n = cache.lookup(list(range(8)))  # a live slot adopts
        assert n == 8
        second = pool.alloc(2, owner="b")
        assert cache.insert(list(range(100, 108)), second)  # evicts entry
        # The entry is gone but the adopter's reference keeps pages alive.
        assert cache.lookup(list(range(8)))[1] == 0
        assert all(pool.refcount(p) == 1 for p in adopted)
        pool.free(adopted)
        assert pool.in_use == 2  # only the second entry's pages remain


class TestEnginePrefixByteIdentity:
    """With the prefix cache ON the engine must return byte-identical
    results for every method — the cache only changes which prefill work
    runs, never what any request computes."""

    @pytest.mark.parametrize("method", sorted(METHOD_PARAMS))
    def test_cache_on_equals_cache_off(self, method):
        params = METHOD_PARAMS[method]

        def run(**engine_options):
            backend = BatchingBackend(
                FakeBackend(), engine=True,
                engine_options={"slots": 4, "num_pages": 512,
                                **engine_options},
            )
            try:
                statement = get_method_generator(
                    method, backend, dict(params)
                ).generate_statement(ISSUE, OPINIONS)
                stats = backend.engine.stats()
            finally:
                backend.close()
            return statement, stats

        off, stats_off = run()
        on, stats_on = run(prefix_cache=True)
        assert on == off, f"{method}: prefix cache changed the statement"
        assert stats_off["prefix_cache"] == {"enabled": False}
        assert stats_on["prefix_cache"]["enabled"]

    def test_repeated_requests_hit_and_leave_no_leak(self):
        backend = BatchingBackend(
            FakeBackend(), engine=True,
            engine_options={"slots": 4, "page_size": 4, "num_pages": 64,
                            "prefix_cache": True},
        )
        req = GenerationRequest(
            user_prompt="alpha beta gamma delta epsilon zeta eta theta",
            max_tokens=8, seed=3,
        )
        solo = FakeBackend().generate([req, req])
        try:
            first = backend.generate([req])
            second = backend.generate([req])
            stats = backend.engine.stats()["prefix_cache"]
            engine = backend.engine
            # Cached pages stay pinned by the cache; nothing else leaks.
            assert engine.pool.in_use == stats["pages"]
        finally:
            backend.close()
        assert first[0].text == solo[0].text
        assert second[0].text == solo[1].text
        assert stats["hits"] >= 1
        assert stats["tokens_saved"] > 0
        assert stats["inserted_pages"] >= 1

    def test_prefix_metrics_families_emitted(self):
        reg = Registry()
        engine = DecodeEngine(
            FakeBackend(), slots=2, page_size=4, num_pages=64,
            prefix_cache=True, registry=reg,
        )
        req = GenerationRequest(
            user_prompt="one two three four five six seven eight",
            max_tokens=4, seed=1,
        )
        try:
            engine.submit("generate", [req])
            engine.submit("generate", [req])
        finally:
            engine.close()
        assert _counter_total(reg, "prefix_cache_hits_total") >= 1
        assert _counter_total(reg, "prefix_cache_misses_total") >= 1
        assert _counter_total(reg, "prefix_tokens_saved_total") > 0
        assert _counter_total(reg, "prefix_cache_inserted_pages_total") >= 1


# ---------------------------------------------------------------------------
# Scheduling semantics (deterministic stepping via auto_start=False)
# ---------------------------------------------------------------------------


def _submit_async(engine, requests, probe=None):
    """Run ``engine.submit`` in a thread; returns (thread, outbox dict)."""
    out = {}

    def worker():
        try:
            out["result"] = engine.submit("generate", requests, probe=probe)
        except BaseException as exc:  # noqa: BLE001 - test captures verbatim
            out["error"] = exc

    thread = threading.Thread(target=worker, daemon=True)
    thread.start()
    return thread, out


class TestEngineScheduling:
    def test_full_slot_table_occupancy(self):
        """8 co-batched statements keep the whole slot table busy —
        occupancy mean >= 0.8 is the BENCH_ENGINE acceptance floor."""
        reg = Registry()
        engine = DecodeEngine(
            FakeBackend(), slots=8, num_pages=512, auto_start=False,
            registry=reg,
        )
        threads = []
        for i in range(8):
            t, _ = _submit_async(
                engine,
                [GenerationRequest(
                    user_prompt=f"prompt {i} with a few extra words",
                    max_tokens=8, seed=i,
                )],
            )
            threads.append(t)
        assert _wait_until(lambda: engine.stats()["queue_depth"] == 8)
        engine.run_iteration()
        for t in threads:
            t.join(timeout=5.0)
        stats = engine.stats()
        assert stats["slot_occupancy_mean"] >= 0.8
        assert stats["slots_occupied"] == 0  # everything retired
        assert engine.pool.in_use == 0

    def test_admission_is_reservation_bounded(self):
        """Admission reserves prompt+max_tokens pages, so a resident row can
        always finish; the backlog holds FIFO until pages free up."""
        engine = DecodeEngine(
            FakeBackend(), slots=4, page_size=4, num_pages=8,
            auto_start=False, min_fill=1,
        )
        # Each request needs ceil((5 + 12)/4) = 5 pages; two can't coexist
        # in an 8-page pool.
        reqs = [
            GenerationRequest(
                user_prompt="one two three four five", max_tokens=12, seed=i,
            )
            for i in range(2)
        ]
        threads = [_submit_async(engine, [r])[0] for r in reqs]
        assert _wait_until(lambda: engine.stats()["queue_depth"] == 2)
        engine.run_iteration()
        stats = engine.stats()
        assert stats["kv_pages_reserved"] <= 8
        # Second row waited its turn; a later iteration retires it too.
        for _ in range(4):
            engine.run_iteration()
        for t in threads:
            t.join(timeout=5.0)
        assert engine.stats()["kv_pages_reserved"] == 0
        assert engine.pool.in_use == 0

    def test_oversized_request_rejected_as_kv_oom(self):
        from consensus_tpu.serve.scheduler import SchedulerRejected

        backend = BatchingBackend(
            FakeBackend(), engine=True,
            engine_options={"slots": 2, "page_size": 4, "num_pages": 2},
        )
        try:
            with pytest.raises(SchedulerRejected) as excinfo:
                backend.generate(
                    [GenerationRequest(
                        user_prompt="this prompt is fine",
                        max_tokens=256, seed=0,
                    )]
                )
        finally:
            backend.close()
        assert excinfo.value.reason == "kv_oom"

    def test_interleaved_prefill_does_not_perturb_decode(self):
        """A second request arriving mid-prefill (chunk=1 drip) must not
        change the first request's tokens — token-for-token vs solo."""
        reqs = [
            GenerationRequest(
                user_prompt="alpha beta gamma delta epsilon zeta",
                max_tokens=8, seed=11,
            ),
            GenerationRequest(
                user_prompt="one two three four five six seven eight nine",
                max_tokens=8, seed=12,
            ),
        ]
        solo = FakeBackend().generate(reqs)

        engine = DecodeEngine(
            FakeBackend(), slots=2, page_size=4, num_pages=64,
            prefill_chunk=1, min_fill=1, auto_start=False,
        )
        t1, out1 = _submit_async(engine, [reqs[0]])
        assert _wait_until(lambda: engine.stats()["queue_depth"] == 1)
        engine.run_iteration()  # admit + first 1-token prefill chunk
        assert engine.stats()["slots_occupied"] == 1
        t2, out2 = _submit_async(engine, [reqs[1]])
        assert _wait_until(lambda: engine.stats()["queue_depth"] == 1)
        for _ in range(40):
            if out1 and out2:
                break
            engine.run_iteration()
        t1.join(timeout=5.0)
        t2.join(timeout=5.0)
        assert out1["result"][0].text == solo[0].text
        assert out2["result"][0].text == solo[1].text
        assert engine.pool.in_use == 0

    def test_cancellation_evicts_and_frees_pages(self):
        reg = Registry()
        engine = DecodeEngine(
            FakeBackend(), slots=2, page_size=4, num_pages=64,
            prefill_chunk=2, auto_start=False, registry=reg,
        )
        flag = {"cancelled": False}
        thread, out = _submit_async(
            engine,
            [GenerationRequest(
                user_prompt="one two three four five six seven eight",
                max_tokens=4, seed=3,
            )],
            probe=lambda: flag["cancelled"],
        )
        assert _wait_until(lambda: engine.stats()["queue_depth"] == 1)
        engine.run_iteration()  # admit + partial prefill (2 of 8 tokens)
        assert engine.stats()["slots_occupied"] == 1
        assert engine.pool.in_use > 0
        flag["cancelled"] = True
        engine.run_iteration()
        thread.join(timeout=5.0)
        assert isinstance(out.get("error"), RequestCancelled)
        assert engine.pool.in_use == 0
        assert engine.stats()["slots_occupied"] == 0
        assert _counter_total(reg, "engine_evicted_total") >= 1

    def test_submit_after_close_raises(self):
        engine = DecodeEngine(FakeBackend(), auto_start=False)
        engine.close()
        with pytest.raises(RuntimeError, match="closed"):
            engine.submit(
                "generate",
                [GenerationRequest(user_prompt="late", max_tokens=4, seed=0)],
            )


# ---------------------------------------------------------------------------
# Obs pins: no timeout flushes, no spurious wakeups, recompile-flat
# ---------------------------------------------------------------------------


class TestEngineObservability:
    def _run_ragged_load(self, registry, inner=None):
        inner = inner if inner is not None else FakeBackend(registry=registry)
        backend = BatchingBackend(
            inner, engine=True, registry=registry,
            engine_options={"slots": 4, "num_pages": 512},
        )
        results = {}

        def worker(i):
            with backend.session():
                results[i] = backend.generate(
                    [GenerationRequest(
                        user_prompt="word " * (3 + 7 * i),  # ragged lengths
                        max_tokens=8, seed=i,
                    )]
                )[0]

        threads = [
            threading.Thread(target=worker, args=(i,)) for i in range(6)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10.0)
        backend.close()
        assert len(results) == 6
        return backend

    def test_no_timeout_flushes_and_no_spurious_wakeups(self):
        reg = Registry()
        self._run_ragged_load(reg)
        assert _counter_total(
            reg, "batching_flushes_total", reason="timeout") == 0
        assert _counter_total(reg, "batching_flushes_total") == 0
        assert _counter_total(reg, "batching_spurious_wakeups_total") == 0

    def test_engine_metric_families_recorded(self):
        reg = Registry()
        self._run_ragged_load(reg)
        snap = reg.snapshot()["families"]
        assert "engine_slot_occupancy" in snap
        assert _counter_total(reg, "engine_admitted_total") >= 6
        assert _counter_total(reg, "engine_prefill_chunks_total") >= 6
        tokens_iter = snap["engine_tokens_per_iteration"]["series"]
        assert tokens_iter and tokens_iter[0]["count"] >= 1
        pages = snap["kv_pages_in_use"]["series"]
        assert pages and pages[0]["max"] >= 1

    def test_bucket_recompiles_flat_across_ragged_load(self):
        """Slot lengths are data, not shapes: after warmup, ragged prompt
        lengths must add zero new compiled program shapes."""
        reg = Registry()
        inner = FakeBackend(registry=reg)
        self._run_ragged_load(reg, inner)  # warmup: first shape sightings
        cut = reg.snapshot()
        self._run_ragged_load(reg, inner)  # same bucketed shapes, new lengths
        delta = diff_snapshots(cut, reg.snapshot())
        assert bucket_recompiles(delta) == 0

    def test_engine_stats_surface(self):
        backend = BatchingBackend(
            FakeBackend(), engine=True,
            engine_options={"slots": 4, "num_pages": 128},
        )
        try:
            backend.generate(
                [GenerationRequest(user_prompt="hello", max_tokens=4, seed=0)]
            )
            stats = backend.engine.stats()
        finally:
            backend.close()
        assert stats["slots"] == 4
        assert stats["kv_pages"] == 128
        assert stats["iterations"] >= 1
        assert stats["kv_pages_high_water"] >= 1
        assert backend.batch_counts["generate"] >= 1  # aliased dispatch count


# ---------------------------------------------------------------------------
# Paged slot programs: token-for-token vs the dense forward pass
# ---------------------------------------------------------------------------


class TestPagedProgramNumerics:
    """Chunked paged prefill + paged decode must reproduce the dense
    ``forward`` pass exactly — same greedy tokens AND close logits — with
    the second slot idle (writes routed to the sink page)."""

    @pytest.mark.parametrize("cfg_name", ["tiny-gemma2", "tiny-llama3"])
    def test_matches_dense_forward(self, cfg_name):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from consensus_tpu.models import stepper
        from consensus_tpu.models.config import get_model_config
        from consensus_tpu.models.transformer import (
            forward, init_params, make_cache, project_logits,
        )

        cfg = get_model_config(cfg_name)
        params = init_params(cfg, jax.random.PRNGKey(0))
        rng = np.random.RandomState(0)
        prompt = rng.randint(1, cfg.vocab_size, size=(7,)).astype(np.int32)
        n_decode = 5

        # Dense reference: prefill then greedy decode through KVCache.
        cache = make_cache(cfg, 1, 32, jnp.float32)
        logits, cache = forward(
            params, cfg, jnp.asarray(prompt)[None, :],
            jnp.arange(7)[None, :], jnp.ones((1, 7), bool), cache, 0,
        )
        dense_tokens, dense_logits = [], []
        last, cur = logits[0, -1], 7
        for _ in range(n_decode):
            nxt = int(jnp.argmax(last))
            dense_tokens.append(nxt)
            dense_logits.append(np.asarray(last))
            lg, cache = forward(
                params, cfg, jnp.asarray([[nxt]], jnp.int32),
                jnp.asarray([[cur]], jnp.int32), jnp.ones((1, 1), bool),
                cache, cur,
            )
            last, cur = lg[0, -1], cur + 1

        # Paged path: 2 slots (slot 1 idle), 4-token prefill chunks.
        page_size, num_pages, max_blocks, chunk = 4, 16, 8, 4
        pool = PagePool(num_pages, page_size)
        state = stepper.make_page_state(cfg, num_pages, page_size, jnp.float32)
        sink = num_pages
        table = BlockTable(0)

        def write_cursors(n_new):
            return [
                (table.pages[t // page_size], t % page_size)
                for t in range(table.num_tokens - n_new, table.num_tokens)
            ]

        def slot_arrays():
            tables = np.full((2, max_blocks), -1, np.int32)
            tables[0] = table.as_array(max_blocks)
            lengths = np.array([table.num_tokens, 0], np.int32)
            return jnp.asarray(tables), jnp.asarray(lengths)

        hidden = None
        for start in range(0, len(prompt), chunk):
            piece = prompt[start : start + chunk]
            table.append_tokens(pool, len(piece))
            tok = np.zeros((2, chunk), np.int32)
            cvalid = np.zeros((2, chunk), bool)
            wp = np.full((2, chunk), sink, np.int32)
            wo = np.zeros((2, chunk), np.int32)
            tok[0, : len(piece)] = piece
            cvalid[0, : len(piece)] = True
            for j, (p, o) in enumerate(write_cursors(len(piece))):
                wp[0, j], wo[0, j] = p, o
            tables, lengths = slot_arrays()
            hidden, state = stepper.paged_prefill_chunk(
                params, cfg, jnp.asarray(tok), jnp.asarray(cvalid), state,
                tables, lengths, jnp.asarray(wp), jnp.asarray(wo),
            )
        last = project_logits(params, cfg, hidden)[0]

        paged_tokens = []
        for step in range(n_decode):
            nxt = int(jnp.argmax(last))
            paged_tokens.append(nxt)
            np.testing.assert_allclose(
                np.asarray(last), dense_logits[step], rtol=2e-4, atol=2e-4,
            )
            table.append_tokens(pool, 1)
            page, offset = table.write_cursor(pool)
            tables, lengths = slot_arrays()
            lg, state = stepper.paged_decode_step(
                params, cfg, jnp.asarray([nxt, 0], jnp.int32), state,
                tables, lengths,
                jnp.asarray([page, sink], np.int32),
                jnp.asarray([offset, 0], np.int32),
            )
            last = lg[0]
        assert paged_tokens == dense_tokens

    @pytest.mark.parametrize("cfg_name", ["tiny-gemma2", "tiny-llama3"])
    def test_gather_step_reads_shared_pages_without_copying(self, cfg_name):
        """The prefix-cache gather path: slot 1 adopts slot 0's prompt
        pages (refcounted, read-only) and ``paged_gather_step`` must
        reproduce the dense last-prompt-position logits from them — while
        leaving every shared page's bytes untouched."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from consensus_tpu.models import stepper
        from consensus_tpu.models.config import get_model_config
        from consensus_tpu.models.transformer import (
            forward, init_params, make_cache, project_logits,
        )

        cfg = get_model_config(cfg_name)
        params = init_params(cfg, jax.random.PRNGKey(0))
        rng = np.random.RandomState(1)
        prompt = rng.randint(1, cfg.vocab_size, size=(8,)).astype(np.int32)

        # Dense reference logits at the last prompt position.
        cache = make_cache(cfg, 1, 32, jnp.float32)
        logits, _ = forward(
            params, cfg, jnp.asarray(prompt)[None, :],
            jnp.arange(8)[None, :], jnp.ones((1, 8), bool), cache, 0,
        )
        dense_last = np.asarray(logits[0, -1])

        # Slot 0 prefills the prompt into its own pages (page-aligned).
        page_size, num_pages, max_blocks = 4, 16, 8
        pool = PagePool(num_pages, page_size)
        state = stepper.make_page_state(cfg, num_pages, page_size, jnp.float32)
        sink = num_pages
        owner = BlockTable(0)
        owner.append_tokens(pool, 8)
        tok = np.zeros((2, 8), np.int32)
        cvalid = np.zeros((2, 8), bool)
        wp = np.full((2, 8), sink, np.int32)
        wo = np.zeros((2, 8), np.int32)
        tok[0] = prompt
        cvalid[0] = True
        for t in range(8):
            wp[0, t] = owner.pages[t // page_size]
            wo[0, t] = t % page_size
        tables = np.full((2, max_blocks), -1, np.int32)
        tables[0] = owner.as_array(max_blocks)
        hidden, state = stepper.paged_prefill_chunk(
            params, cfg, jnp.asarray(tok), jnp.asarray(cvalid), state,
            jnp.asarray(tables), jnp.asarray([8, 0], np.int32),
            jnp.asarray(wp), jnp.asarray(wo),
        )
        prefill_last = np.asarray(project_logits(params, cfg, hidden)[0])
        np.testing.assert_allclose(
            prefill_last, dense_last, rtol=2e-4, atol=2e-4
        )

        # Slot 1 adopts the SAME pages via the refcounted share path.
        adopter = BlockTable(1)
        adopter.adopt_shared(pool, owner.pages, 8)
        assert all(pool.refcount(p) == 2 for p in owner.pages)
        g_tables = np.full((2, max_blocks), -1, np.int32)
        g_tables[0] = owner.as_array(max_blocks)
        g_tables[1] = adopter.as_array(max_blocks)
        shared_before = np.asarray(
            state.k_pages[:, owner.pages, :, :, :]
        ).copy()
        g_logits, state = stepper.paged_gather_step(
            params, cfg,
            jnp.asarray([int(prompt[-1]), int(prompt[-1])], jnp.int32),
            state, jnp.asarray(g_tables), jnp.asarray([8, 8], np.int32),
        )
        # Both slots read the one shared copy and reproduce the dense
        # logits at the last prompt position...
        np.testing.assert_allclose(
            np.asarray(g_logits[0]), dense_last, rtol=2e-4, atol=2e-4
        )
        np.testing.assert_allclose(
            np.asarray(g_logits[1]), dense_last, rtol=2e-4, atol=2e-4
        )
        # ...and the shared pages' bytes are bit-identical afterwards
        # (every write went to the sink page).
        shared_after = np.asarray(state.k_pages[:, owner.pages, :, :, :])
        np.testing.assert_array_equal(shared_before, shared_after)


# ---------------------------------------------------------------------------
# Multi-token decode: K-step on-device windows (PR 15)
# ---------------------------------------------------------------------------


class TestMultiTokenByteIdentity:
    """``decode_steps`` must never change results — for any K, for every
    method: the K-step scan replays the sequential per-row key-split
    schedule and the engine's stream scheduling retires the same rows."""

    @pytest.mark.parametrize("method", sorted(METHOD_PARAMS))
    def test_engine_k_family_matches_legacy_all_methods(self, method):
        params = METHOD_PARAMS[method]
        solo = get_method_generator(
            method, FakeBackend(), dict(params)
        ).generate_statement(ISSUE, OPINIONS)

        for k in (1, 4, 8):
            engined = BatchingBackend(
                FakeBackend(), engine=True,
                engine_options={"slots": 4, "num_pages": 512,
                                "decode_steps": k},
            )
            try:
                via_engine = get_method_generator(
                    method, engined, dict(params)
                ).generate_statement(ISSUE, OPINIONS)
                stats = engined.engine.stats()
            finally:
                engined.close()
            assert via_engine == solo, f"{method}: K={k} diverged"
            assert stats["decode_steps"] == k


def _drain_stream(stream):
    """Drive a generate stream to completion; returns (results, windows)."""
    results, windows = {}, 0
    while not stream.finished:
        stream.dispatch()
        _, finished = stream.collect()
        results.update(finished)
        windows += 1
        assert windows < 200, "stream failed to drain"
    stream.close()
    return results, windows


class TestMultiTokenDecodeTPU:
    """Real-model multi-token decode: the paged K-step scan against the
    paged K=1 stream, the dense legacy path, and the engine seam.

    Dense-vs-paged comparisons ride on a pinned cohort verified free of
    argmax/sampling near-ties (paged and dense forwards differ by ~2e-4 in
    the logits; a near-tie can legitimately flip a sampled token, which is
    a numerics property, not a scheduling bug — the K-family comparisons
    are exact by construction and carry the real invariant)."""

    COHORT = (
        ("Say something about apples.", 11, 12, 0.8),
        ("Hi", 22, 5, 0.0),
        ("A longer prompt that should span several pages of the stream "
         "pool for testing purposes.", 33, 20, 0.9),
    )

    @pytest.fixture(scope="class")
    def tpu_backend(self):
        from consensus_tpu.backends.tpu import TPUBackend

        return TPUBackend(model="tiny-gemma2", max_context=128, base_seed=7)

    def _requests(self):
        return [
            GenerationRequest(
                user_prompt=prompt, seed=seed, max_tokens=mt, temperature=t,
            )
            for prompt, seed, mt, t in self.COHORT
        ]

    def test_k_family_byte_identical_and_matches_dense(self, tpu_backend):
        legacy = tpu_backend.generate(self._requests())
        outputs = {}
        for k in (1, 4, 8):
            stream = tpu_backend.generate_stream(
                self._requests(), decode_steps=k
            )
            results, windows = _drain_stream(stream)
            outputs[k] = [
                (results[i].text, results[i].token_ids,
                 results[i].finish_reason)
                for i in range(len(self.COHORT))
            ]
            # Window count collapses with K: 21 sample steps (20-token
            # budget + eos-check) need 21 / 6 / 3 dispatches.
            assert windows <= -(-21 // k) + 1
        assert outputs[1] == outputs[4] == outputs[8]
        assert outputs[1] == [
            (r.text, r.token_ids, r.finish_reason) for r in legacy
        ]

    def test_engine_decode_steps_matches_direct_stream(self, tpu_backend):
        direct = _drain_stream(
            tpu_backend.generate_stream(self._requests(), decode_steps=4)
        )[0]
        engined = BatchingBackend(
            tpu_backend, engine=True,
            engine_options={"slots": 4, "num_pages": 512, "decode_steps": 4},
        )
        try:
            via_engine = engined.generate(self._requests())
            stats = engined.engine.stats()
            mfu = stats["mfu_attribution"]
        finally:
            engined.close()
        for i, result in enumerate(via_engine):
            assert (result.text, result.token_ids, result.finish_reason) == (
                direct[i].text, direct[i].token_ids, direct[i].finish_reason
            )
        # The whole point: way fewer host iterations than tokens.
        assert stats["iterations"] / max(mfu["tokens"], 1) < 0.5

    def test_eos_early_exit_freezes_row_mid_scan(self, tpu_backend):
        """A row that samples EOS inside a K-step window must freeze there:
        emitted stops, lengths stop advancing, hit_eos latches, and every
        later write of that row lands in the sink — pool pages beyond the
        frozen cursor stay byte-identical to their post-prefill state."""
        import numpy as np

        # Learn the greedy continuation, then declare its 3rd token EOS.
        probe = _drain_stream(
            tpu_backend.generate_stream(
                [GenerationRequest(
                    user_prompt="freeze me", seed=5, max_tokens=8,
                    temperature=0.0,
                )],
                decode_steps=1,
            )
        )[0][0]
        assert len(probe.token_ids) == 8
        eos_token = probe.token_ids[2]
        if eos_token in probe.token_ids[:2]:
            pytest.skip("greedy continuation repeats the chosen EOS early")

        original_eos = tpu_backend.tokenizer.eos_ids
        tpu_backend.tokenizer.eos_ids = (int(eos_token),)
        try:
            stream = tpu_backend.generate_stream(
                [GenerationRequest(
                    user_prompt="freeze me", seed=5, max_tokens=8,
                    temperature=0.0,
                )],
                decode_steps=8,
            )
            prefill_pages = np.asarray(stream._state.k_pages).copy()
            prompt_len = int(np.asarray(stream._lengths)[0])
            tables = np.asarray(stream._tables)
            page_size = prefill_pages.shape[2]
            stream.dispatch()
            _, finished = stream.collect()
            assert stream.finished  # froze inside the FIRST window
            frozen_len = int(np.asarray(stream._lengths)[0])
            pages_after = np.asarray(stream._state.k_pages)
            stream.close()
        finally:
            tpu_backend.tokenizer.eos_ids = original_eos

        result = finished[0]
        assert result.finish_reason == "stop"
        assert result.token_ids == probe.token_ids[:2]
        # The cursor froze after two emitted tokens; the EOS sample and
        # every later step of the window wrote only the sink.
        assert frozen_len == prompt_len + 2
        row_pages = [int(p) for p in tables[0] if p >= 0]
        # Reserved pages wholly beyond the frozen cursor: byte-identical
        # to their post-prefill state (all-zero init, never written).
        first_free = -(-frozen_len // page_size)
        for page in row_pages[first_free:]:
            np.testing.assert_array_equal(
                pages_after[:, page], prefill_pages[:, page]
            )
        # The partially-filled page: offsets past the cursor untouched.
        if frozen_len % page_size:
            page = row_pages[frozen_len // page_size]
            np.testing.assert_array_equal(
                pages_after[:, page, frozen_len % page_size:],
                prefill_pages[:, page, frozen_len % page_size:],
            )

    def test_window_crossing_page_boundary_spares_shared_pages(
        self, tpu_backend
    ):
        """A K-step window that crosses a page boundary in-scan writes only
        pages reserved at dispatch time.  Rows adopting shared prefix pages
        (prefix-cache discipline) must leave the shared bytes untouched."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from consensus_tpu.models import stepper
        from consensus_tpu.models.config import get_model_config
        from consensus_tpu.models.transformer import init_params, project_logits

        cfg = get_model_config("tiny-gemma2")
        params = init_params(cfg, jax.random.PRNGKey(0))
        rng = np.random.RandomState(3)
        prompt = rng.randint(1, cfg.vocab_size, size=(8,)).astype(np.int32)
        page_size, max_blocks = 4, 8
        # Pages: 0-1 shared prompt, 2-3 row0 private, 4-5 row1 private.
        num_pages, sink = 6, 6
        state = stepper.make_page_state(cfg, num_pages, page_size, jnp.float32)
        tables = np.full((2, max_blocks), -1, np.int32)
        tables[0, :4] = [0, 1, 2, 3]
        tables[1, :4] = [0, 1, 4, 5]  # adopts the shared prompt pages

        # Prefill the shared prompt ONCE through row 0's table.
        tok = np.zeros((2, 8), np.int32)
        cval = np.zeros((2, 8), bool)
        wp = np.full((2, 8), sink, np.int32)
        wo = np.zeros((2, 8), np.int32)
        tok[0] = prompt
        cval[0] = True
        for t in range(8):
            wp[0, t] = t // page_size
            wo[0, t] = t % page_size
        hidden, state = stepper.paged_prefill_chunk(
            params, cfg, jnp.asarray(tok), jnp.asarray(cval), state,
            jnp.asarray(tables), jnp.asarray([8, 0], np.int32),
            jnp.asarray(wp), jnp.asarray(wo),
        )
        shared_before = np.asarray(state.k_pages[:, :2]).copy()
        logits0 = project_logits(params, cfg, hidden)
        logits = jnp.stack([logits0[0], logits0[0]])

        # Both rows decode 6 greedy tokens from the shared prefix: the
        # window crosses the page-2 boundary (length 8 -> 14) in-scan.
        keys = jax.vmap(jax.random.PRNGKey)(jnp.asarray([1, 2], jnp.uint32))
        out = stepper.paged_decode_steps(
            params, cfg, logits, state, jnp.asarray(tables),
            jnp.asarray([8, 8], np.int32), keys,
            jnp.zeros(2, bool), jnp.asarray([6, 6], np.int32),
            jnp.zeros(2, bool),
            temperature=jnp.zeros(2, jnp.float32), num_steps=8,
        )
        tokens, emitted, state_after = out[0], out[1], out[3]
        tokens, emitted = np.asarray(tokens), np.asarray(emitted)
        # Identical rows, identical greedy continuations across the
        # boundary; both emit exactly the 6-token budget.
        np.testing.assert_array_equal(tokens[0], tokens[1])
        assert emitted.sum(axis=1).tolist() == [6, 6]
        np.testing.assert_array_equal(
            np.asarray(out[4]), [14, 14]  # lengths advanced to 8 + 6
        )
        # Shared prompt pages: byte-identical after the window.
        np.testing.assert_array_equal(
            shared_before, np.asarray(state_after.k_pages[:, :2])
        )
        # Each row's private writes live in its OWN reserved pages and the
        # two rows' continuation KV bytes match (same tokens, positions).
        kp = np.asarray(state_after.k_pages)
        np.testing.assert_array_equal(kp[:, 2:4], kp[:, 4:6])

    def test_dp4_matches_dp1(self):
        """Sharding the stream's slot axis over data must not change a
        single emitted token (conftest provides 8 virtual CPU devices)."""
        from consensus_tpu.backends.tpu import TPUBackend

        def run(dp):
            backend = TPUBackend(
                model="tiny-gemma2", max_context=128, base_seed=7, dp=dp,
            )
            requests = [
                GenerationRequest(
                    user_prompt=f"device parallel prompt {i}", seed=100 + i,
                    max_tokens=6 + i, temperature=0.7,
                )
                for i in range(4)
            ]
            results = _drain_stream(
                backend.generate_stream(requests, decode_steps=4)
            )[0]
            return [
                (results[i].text, results[i].token_ids,
                 results[i].finish_reason)
                for i in range(4)
            ]

        assert run(1) == run(4)


class TestLedgerDispatchBlockSplit:
    """PR 15 splits the ledger's device axis into dispatch (host enqueue)
    and block (waiting on results); the sum must still cover wall time."""

    def test_split_sums_and_coverage(self):
        engine = DecodeEngine(
            FakeBackend(), slots=8, num_pages=512, auto_start=False,
            decode_steps=4,
        )
        outboxes, threads = [], []
        try:
            for i in range(4):
                out = {}

                def worker(i=i, out=out):
                    out["result"] = engine.submit("generate", [
                        GenerationRequest(
                            user_prompt=f"prompt {i} with extra words",
                            max_tokens=8, seed=i,
                        )])

                thread = threading.Thread(target=worker, daemon=True)
                thread.start()
                threads.append(thread)
                outboxes.append(out)
            assert _wait_until(
                lambda: engine.stats()["queue_depth"] == 4)
            for _ in range(12):
                engine.run_iteration()
                if all("result" in out for out in outboxes):
                    break
            for thread in threads:
                thread.join(timeout=10.0)
            assert all("result" in out for out in outboxes)
            report = engine.stats()["mfu_attribution"]
            assert report["coverage"] >= 0.95  # the acceptance bar
            assert report["dispatch_s"] >= 0.0
            assert report["block_s"] > 0.0
            assert report["device_s"] == pytest.approx(
                report["dispatch_s"] + report["block_s"], abs=1e-5)
            # Fractions round to 4 decimals independently, so the split can
            # differ from device_fraction by one ulp each.
            assert report["dispatch_fraction"] + report["block_fraction"] \
                == pytest.approx(report["device_fraction"], abs=2e-4)
            # The CPU caveat ships in the report itself, not just the docs.
            assert "note" in report and "CPU" in report["note"]
            assert engine.stats()["decode_steps"] == 4
        finally:
            engine.close()

    def test_legacy_device_kwarg_books_as_block(self):
        from consensus_tpu.obs.trace import IterationLedger

        ledger = IterationLedger()
        ledger.record(
            start_s=0.0, end_s=1.0, idle_s=0.1, device_s=0.5,
            host={"sweep": 0.2}, tokens=4, cohort=1,
        )
        report = ledger.mfu_attribution()
        assert report["block_s"] == pytest.approx(0.5)
        assert report["dispatch_s"] == 0.0
        assert report["device_s"] == pytest.approx(0.5)
