"""Token-search sessions: TPU incremental KV-cache path vs full-prefix oracle.

The TPU session (backends/tpu.py:TPUTokenSearchSession) must produce the
same proposals and agent scores as the cacheless fallback
(backends/session.py:PrefixTokenSearchSession), which re-runs full prefixes
through the same backend.  With the byte tokenizer, decode+re-encode is
exact, so the two paths see identical token sequences and should agree to
float tolerance.
"""

import numpy as np
import pytest

from consensus_tpu.backends.session import (
    PrefixTokenSearchSession,
    SearchSpec,
    open_token_search,
)
from consensus_tpu.backends.tpu import TPUBackend, TPUTokenSearchSession


@pytest.fixture(scope="module")
def backend():
    return TPUBackend(model="tiny-gemma2", dtype="float32", max_context=256)


def make_spec(**kw):
    defaults = dict(
        ref_system="You draft consensus statements.",
        ref_user="Issue: taxes.\nOpinions: A wants more, B wants less.\nStatement:",
        agent_prompts=(
            ("Agent context.", "Opinion: A wants more.\nStatement:"),
            ("Agent context.", "Opinion: B wants less.\nStatement:"),
        ),
        n_slots=2,
        k=3,
        temperature=1.0,
        seed=11,
        sample=False,  # deterministic top-k: both paths must pick the same ids
        max_steps=8,
    )
    defaults.update(kw)
    return SearchSpec(**defaults)


def test_factory_prefers_tpu_session(backend):
    session = open_token_search(backend, make_spec())
    assert isinstance(session, TPUTokenSearchSession)


def test_factory_falls_back_over_cache_cap(backend):
    session = open_token_search(backend, make_spec(n_slots=10_000_000))
    assert isinstance(session, PrefixTokenSearchSession)


@pytest.mark.xfail(
    strict=False,
    reason="id->string->token parity between the incremental session and "
    "the re-encoding oracle is numerics-sensitive on random tiny-model "
    "weights, which emit garbage byte tokens that do not round-trip",
)
def test_incremental_matches_full_prefix(backend):
    spec = make_spec()
    tpu = TPUTokenSearchSession(backend, spec)
    oracle = PrefixTokenSearchSession(backend, spec)

    tpu_props = tpu.propose()
    oracle_props = oracle.propose()
    for step in range(3):
        assert len(tpu_props) == spec.n_slots
        for slot in range(spec.n_slots):
            t_ids = [c.token_id for c in tpu_props[slot]]
            o_ids = [c.token_id for c in oracle_props[slot]]
            assert t_ids == o_ids, f"step {step} slot {slot}"
            np.testing.assert_allclose(
                [c.ref_logprob for c in tpu_props[slot]],
                [c.ref_logprob for c in oracle_props[slot]],
                atol=5e-4,
            )
            for t_cand, o_cand in zip(tpu_props[slot], oracle_props[slot]):
                # Agent-score parity holds only for tokens whose string
                # round-trips to the same single id: the fallback scores the
                # re-encoded *string* (all an API backend can do), so special
                # tokens like <eos> re-encode as literal characters there
                # while the TPU path scores the true id.
                if backend.tokenizer.encode(t_cand.token) != [t_cand.token_id]:
                    continue
                np.testing.assert_allclose(
                    t_cand.agent_logprobs, o_cand.agent_logprobs, atol=5e-4
                )
        # Advance: slot 0 takes its best candidate, slot 1 branches from
        # slot 0's second-best (exercises the cross-slot cache gather).
        # Both must round-trip id -> string -> id, or the oracle's string
        # state diverges from the TPU session's id state by construction.
        roundtrip = [
            c for c in tpu_props[0]
            if backend.tokenizer.encode(c.token) == [c.token_id]
        ]
        assert len(roundtrip) >= 2, "test model proposed only special tokens"
        parents = [0, 0]
        chosen = [roundtrip[0], roundtrip[1]]
        tpu_props = tpu.advance_and_propose(parents, chosen)
        oracle_props = oracle.advance_and_propose(parents, chosen)


def test_gumbel_proposals_are_seed_deterministic(backend):
    spec = make_spec(sample=True, seed=5)
    a = TPUTokenSearchSession(backend, spec).propose()
    b = TPUTokenSearchSession(backend, spec).propose()
    assert [c.token_id for c in a[0]] == [c.token_id for c in b[0]]
    different = TPUTokenSearchSession(backend, make_spec(sample=True, seed=6)).propose()
    assert [c.token_id for c in a[0]] != [c.token_id for c in different[0]]


def test_session_exhaustion_raises(backend):
    spec = make_spec(max_steps=1)
    session = TPUTokenSearchSession(backend, spec)
    props = session.propose()
    parents = [0, 1]
    chosen = [props[0][0], props[1][0]]
    props = session.advance_and_propose(parents, chosen)
    with pytest.raises(ValueError):
        session.advance_and_propose(parents, [props[0][0], props[1][0]])


def test_suffix_propose_matches_full_prefix(backend):
    """Trunk-shared tree expansion == fallback full-prefix expansion."""
    spec = make_spec(n_slots=1, sample=False, k=2)
    tpu = TPUTokenSearchSession(backend, spec)
    oracle = PrefixTokenSearchSession(backend, spec)

    t_root = tpu.propose()[0]
    o_root = oracle.propose()[0]
    assert [c.token_id for c in t_root] == [c.token_id for c in o_root]

    roundtrip = [
        c for c in t_root
        if backend.tokenizer.encode(c.token) == [c.token_id]
    ]
    assert roundtrip, "test model proposed only special tokens"
    # Two level-1 paths off the same trunk (duplicated to test row padding).
    suffixes = [[roundtrip[0]], [roundtrip[0]]]
    t_props = tpu.propose_suffixes(suffixes, salt=3)
    o_props = oracle.propose_suffixes(suffixes, salt=3)
    assert len(t_props) == len(o_props) == 2
    for t_slot, o_slot in zip(t_props, o_props):
        assert [c.token_id for c in t_slot] == [c.token_id for c in o_slot]
        np.testing.assert_allclose(
            [c.ref_logprob for c in t_slot],
            [c.ref_logprob for c in o_slot],
            atol=5e-4,
        )
        for t_cand, o_cand in zip(t_slot, o_slot):
            if backend.tokenizer.encode(t_cand.token) != [t_cand.token_id]:
                continue
            np.testing.assert_allclose(
                t_cand.agent_logprobs, o_cand.agent_logprobs, atol=5e-4
            )
    # Depth-2 suffixes exercise the in-suffix causal attention.
    deeper = [
        [roundtrip[0], c] for c in t_props[0]
        if backend.tokenizer.encode(c.token) == [c.token_id]
    ]
    if deeper:
        t2 = tpu.propose_suffixes(deeper, salt=4)
        o2 = oracle.propose_suffixes(deeper, salt=4)
        for t_slot, o_slot in zip(t2, o2):
            assert [c.token_id for c in t_slot] == [c.token_id for c in o_slot]

    # The trunk cache must be untouched: advancing the trunk afterwards
    # still matches the oracle.
    t_next = tpu.advance_and_propose([0], [roundtrip[0]])
    o_next = oracle.advance_and_propose([0], [roundtrip[0]])
    assert [c.token_id for c in t_next[0]] == [c.token_id for c in o_next[0]]


def test_batching_backend_delegates_sessions_to_inner(backend):
    """A concurrent sweep cell must get the fast inner-session path through
    the generic factory (the call decoders make), not the O(T^2) fallback
    over the batching queue."""
    from consensus_tpu.backends.batching import BatchingBackend

    batching = BatchingBackend(backend, engine=False)
    session = open_token_search(batching, make_spec())
    assert isinstance(session, TPUTokenSearchSession)
    assert session.backend is backend
    session.close()
    # Over-cap spec: the fallback must run over the WRAPPER so its calls
    # keep merging through the batching queue.
    fallback = open_token_search(batching, make_spec(n_slots=10_000_000))
    assert isinstance(fallback, PrefixTokenSearchSession)
    assert fallback.backend is batching


def test_session_budget_blocks_then_releases(backend):
    import threading

    from consensus_tpu.backends.tpu import _SessionBudget

    budget = _SessionBudget(100)
    budget.acquire(70)
    acquired = threading.Event()

    def second():
        budget.acquire(60)
        acquired.set()
        budget.release(60)

    t = threading.Thread(target=second)
    t.start()
    assert not acquired.wait(0.2)  # 70 + 60 > 100: blocked
    budget.release(70)
    assert acquired.wait(2.0)
    t.join()
    assert budget.used == 0


def test_closed_session_rejects_use_and_releases_budget(backend):
    spec = make_spec()
    before = backend._session_budget.used
    session = TPUTokenSearchSession(backend, spec)
    assert backend._session_budget.used > before
    session.propose()
    session.close()
    assert backend._session_budget.used == before
    session.close()  # idempotent
    with pytest.raises(ValueError):
        session.propose()


def test_suffix_propose_requires_trunk_session(backend):
    spec = make_spec(n_slots=2, sample=False)
    session = TPUTokenSearchSession(backend, spec)
    session.propose()
    with pytest.raises(ValueError):
        session.propose_suffixes([[]], salt=0)


def test_finite_lookahead_runs_on_tpu_session(backend):
    from consensus_tpu.methods import get_method_generator

    issue = "Should the town build a new library?"
    opinions = {
        "Agent 1": "Yes, libraries anchor the community.",
        "Agent 2": "Only if it does not raise taxes.",
    }
    cfg = {"branching_factor": 2, "max_depth": 2, "max_tokens": 5, "seed": 4}
    gen = get_method_generator("finite_lookahead", backend, cfg)
    statement = gen.generate_statement(issue, opinions)
    assert isinstance(statement, str)
    gen2 = get_method_generator("finite_lookahead", backend, cfg)
    assert gen2.generate_statement(issue, opinions) == statement


def test_rollout_from_matches_id_level_oracle(backend):
    """Device rollout (one fused call) == teacher-forced scoring of the same
    token-id sequence.  The oracle works at the id level: sampled bytes need
    not round-trip through decoded strings (random weights emit non-UTF8
    bytes whose decoded form re-encodes differently)."""
    import jax.numpy as jnp

    from consensus_tpu.models.transformer import token_logprobs

    spec = make_spec(n_slots=1, sample=False, temperature=0.0, k=2)
    tpu = TPUTokenSearchSession(backend, spec)
    t_root = tpu.propose()[0]

    start = t_root[0]
    depth = 4
    rollout_ids, t_text, t_totals, t_ok = tpu.rollout_from(
        [start], depth=depth, salt=9
    )
    assert t_ok and len(t_totals) == len(spec.agent_prompts)

    # Deterministic: the same call reproduces ids and totals exactly.
    ids2, _, totals2, _ = tpu.rollout_from([start], depth=depth, salt=9)
    assert (rollout_ids, t_totals) == (ids2, totals2)

    tok = backend.tokenizer
    if not rollout_ids:
        pytest.skip("rollout hit EOS immediately")
    for agent_index, (a_system, a_user) in enumerate(spec.agent_prompts):
        prefix_ids = tok.encode(
            tok.raw_prompt(a_user, a_system), add_bos=True
        )
        ids = prefix_ids + [start.token_id] + rollout_ids
        arr = jnp.asarray([ids], jnp.int32)
        valid = jnp.ones_like(arr, dtype=bool)
        lps = np.asarray(token_logprobs(backend.params, backend.config, arr, valid))
        oracle_total = lps[0, len(prefix_ids) + 1 :].sum()
        np.testing.assert_allclose(t_totals[agent_index], oracle_total, atol=2e-3)


def test_mcts_runs_on_tpu_session(backend):
    from consensus_tpu.methods import get_method_generator

    issue = "Should the town build a new library?"
    opinions = {
        "Agent 1": "Yes, libraries anchor the community.",
        "Agent 2": "Only if it does not raise taxes.",
    }
    cfg = {
        "num_simulations": 3, "expansion_sample_width": 2,
        "max_tokens": 3, "rollout_depth": 2, "seed": 6,
    }
    gen = get_method_generator("mcts", backend, cfg)
    statement = gen.generate_statement(issue, opinions)
    assert isinstance(statement, str)
    gen2 = get_method_generator("mcts", backend, cfg)
    assert gen2.generate_statement(issue, opinions) == statement


def test_beam_search_runs_on_tpu_session(backend):
    from consensus_tpu.methods import get_method_generator

    issue = "Should the town build a new library?"
    opinions = {
        "Agent 1": "Yes, libraries anchor the community.",
        "Agent 2": "Only if it does not raise taxes.",
    }
    gen = get_method_generator(
        "beam_search", backend,
        {"beam_width": 2, "max_tokens": 6, "seed": 3},
    )
    statement = gen.generate_statement(issue, opinions)
    assert isinstance(statement, str)
    # Determinism: a fresh run with the same seed reproduces the statement.
    gen2 = get_method_generator(
        "beam_search", backend,
        {"beam_width": 2, "max_tokens": 6, "seed": 3},
    )
    assert gen2.generate_statement(issue, opinions) == statement


def test_rollout_many_matches_rollout_from(backend):
    """Batched device rollouts (one fused multi-path program per span
    group) == the singleton rollout path, token-for-token: each row folds
    the same (family=2, salt) PRNG stream, and the shared-trunk scratch
    cache sees the same prefix state."""
    spec = make_spec(n_slots=1, sample=False, k=3)
    tpu = TPUTokenSearchSession(backend, spec)
    root = tpu.propose()[0]
    suf_a, suf_b = [root[0]], [root[1]]
    suf_deep = [root[0], root[0]]

    singles = [
        tpu.rollout_from(suf_a, depth=4, salt=9),
        tpu.rollout_from(suf_b, depth=4, salt=10),
        tpu.rollout_from(suf_deep, depth=4, salt=11),
    ]
    # Mixed-length batch: span-1 group {a, b} fuses into one program,
    # span-2 group {deep} is a singleton and delegates to rollout_from.
    batch = tpu.rollout_many(
        [suf_a, suf_b, suf_deep], depth=4, salts=[9, 10, 11]
    )
    assert len(batch) == 3
    for got, want in zip(batch, singles):
        assert got[0] == want[0]  # token ids
        assert got[1] == want[1]  # text
        np.testing.assert_allclose(got[2], want[2], atol=2e-3)
        assert got[3] == want[3]

    # Determinism across repeat batched calls.
    again = tpu.rollout_many(
        [suf_a, suf_b, suf_deep], depth=4, salts=[9, 10, 11]
    )
    assert [r[0] for r in again] == [r[0] for r in batch]
    tpu.close()


def test_rollout_many_chunks_within_budget(backend):
    """More paths than the HBM-derived chunk cap still come back right —
    the batch is split into cap-sized fused calls."""
    spec = make_spec(n_slots=1, sample=False, k=3)
    tpu = TPUTokenSearchSession(backend, spec)
    root = tpu.propose()[0]
    cap = tpu._rollout_chunk_cap(1, 3)
    assert cap >= 1
    n = cap + 2  # force at least two chunks
    suffixes = [[root[i % len(root)]] for i in range(n)]
    salts = list(range(30, 30 + n))
    before = tpu.dispatch_count
    batch = tpu.rollout_many(suffixes, depth=3, salts=salts)
    assert tpu.dispatch_count - before >= 2
    for i, got in enumerate(batch):
        want = tpu.rollout_from(suffixes[i], depth=3, salt=salts[i])
        assert got[0] == want[0]
        np.testing.assert_allclose(got[2], want[2], atol=2e-3)
    tpu.close()


def test_mixed_length_propose_suffixes(backend):
    """propose_suffixes now accepts mixed suffix lengths in one call by
    grouping per span; results come back in input order and singleton-span
    calls keep the historical plain-salt PRNG stream."""
    spec = make_spec(n_slots=1, sample=False, k=2)
    tpu = TPUTokenSearchSession(backend, spec)
    root = tpu.propose()[0]
    s1, s2 = [root[0]], [root[1]]
    deep = [root[0], root[0]]

    mixed = tpu.propose_suffixes([s1, deep, s2], salt=5)
    assert len(mixed) == 3
    # Each span group matches a homogeneous call with that group's salt
    # (salt ^ (span << 20) once more than one span is present).
    only1 = tpu.propose_suffixes([s1, s2], salt=5 ^ (1 << 20))
    only2 = tpu.propose_suffixes([deep], salt=5 ^ (2 << 20))
    assert [c.token_id for c in mixed[0]] == [c.token_id for c in only1[0]]
    assert [c.token_id for c in mixed[2]] == [c.token_id for c in only1[1]]
    assert [c.token_id for c in mixed[1]] == [c.token_id for c in only2[0]]
    with pytest.raises(ValueError):
        tpu.propose_suffixes([s1, []], salt=6)
    tpu.close()


def test_mcts_wave_runs_on_tpu_session(backend):
    """Wave-parallel MCTS end-to-end through the fused TPU session: batched
    expansion + batched rollouts, deterministic across fresh runs."""
    from consensus_tpu.methods import get_method_generator

    issue = "Should the town build a new library?"
    opinions = {
        "Agent 1": "Yes, libraries anchor the community.",
        "Agent 2": "Only if it does not raise taxes.",
    }
    cfg = {
        "num_simulations": 4, "expansion_sample_width": 2,
        "max_tokens": 3, "rollout_depth": 2, "seed": 6,
        "mcts_wave_size": 4,
    }
    gen = get_method_generator("mcts", backend, cfg)
    statement = gen.generate_statement(issue, opinions)
    assert isinstance(statement, str)
    assert gen.search_stats["device_dispatches"] > 0
    gen2 = get_method_generator("mcts", backend, cfg)
    assert gen2.generate_statement(issue, opinions) == statement
