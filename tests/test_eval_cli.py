"""L5-L7 parity tail tests (VERDICT r1 #9): standalone evaluation CLI,
--skip-llm-judge, OpenAI judge aliasing, aggregation column ordering."""

import pandas as pd
import pytest
import yaml

from consensus_tpu.aggregation import format_aggregated_columns
from consensus_tpu.backends.api import JUDGE_MODEL_ALIASES, OpenAIBackend


class TestOpenAIJudgeBackend:
    def test_o3_aliases_to_gpt41(self):
        backend = OpenAIBackend(model="o3")
        assert backend.requested_model == "o3"
        assert backend.model == "gpt-4.1"
        assert JUDGE_MODEL_ALIASES == {"o3": "gpt-4.1"}

    def test_other_models_pass_through(self):
        assert OpenAIBackend(model="gpt-4-turbo").model == "gpt-4-turbo"

    def test_degrades_to_sentinels_offline(self):
        from consensus_tpu.backends.base import GenerationRequest, ScoreRequest

        backend = OpenAIBackend()
        result = backend.generate([GenerationRequest(user_prompt="hi")])[0]
        assert not result.ok and result.text.startswith("[ERROR")
        assert backend.score([ScoreRequest(context="a", continuation="b")])[0].ok is False
        assert backend.next_token_logprobs([]) == []

    def test_registered_in_get_backend(self):
        from consensus_tpu.backends import get_backend

        backend = get_backend("openai", model="o3")
        assert isinstance(backend, OpenAIBackend)


class TestEvaluateCli:
    def test_statements_file_path(self, tmp_path):
        from consensus_tpu.cli.evaluate import main

        config = {
            "scenario": {
                "issue": "Should X happen?",
                "agent_opinions": {"A": "Yes.", "B": "No."},
            }
        }
        config_path = tmp_path / "cfg.yaml"
        config_path.write_text(yaml.safe_dump(config))
        statements_path = tmp_path / "statements.yaml"
        statements_path.write_text(
            yaml.safe_dump({"m1": "Statement one here.", "m2": "Another one."})
        )
        out = tmp_path / "out"
        rc = main(
            [
                "--config", str(config_path),
                "--statements-file", str(statements_path),
                "--backend", "fake",
                "--output-dir", str(out),
                "--quiet",
            ]
        )
        assert rc == 0
        frame = pd.read_csv(out / "evaluation_results.csv")
        assert set(frame["method"]) == {"m1", "m2"}
        assert "egalitarian_welfare_perplexity" in frame.columns

    def test_results_file_path(self, tmp_path):
        from consensus_tpu.backends.fake import FakeBackend
        from consensus_tpu.cli.evaluate import main
        from consensus_tpu.experiment import Experiment

        config = {
            "experiment_name": "cli_eval",
            "seed": 1,
            "scenario": {
                "issue": "Should X happen?",
                "agent_opinions": {"A": "Yes.", "B": "No."},
            },
            "methods_to_run": ["zero_shot"],
            "zero_shot": {"max_tokens": 8},
            "output_dir": str(tmp_path),
        }
        experiment = Experiment(config, backend=FakeBackend())
        experiment.run()
        rc = main(
            [
                "--results-file", str(tmp_path / experiment.run_dir.name / "results.csv")
                if hasattr(experiment.run_dir, "name")
                else str(experiment.run_dir) + "/results.csv",
                "--backend", "fake",
                "--quiet",
            ]
        )
        assert rc == 0

    def test_requires_input(self, capsys):
        from consensus_tpu.cli.evaluate import main

        with pytest.raises(SystemExit):
            main(["--backend", "fake"])


class TestSkipLlmJudgeFlag:
    def test_flag_accepted_and_pipeline_runs(self, tmp_path):
        from consensus_tpu.cli.run_experiment_with_eval import main

        config = {
            "experiment_name": "skipjudge",
            "seed": 1,
            "backend": "fake",
            "scenario": {
                "issue": "Should X happen?",
                "agent_opinions": {"A": "Yes.", "B": "No."},
            },
            "methods_to_run": ["zero_shot", "predefined"],
            "zero_shot": {"max_tokens": 8},
            "predefined": {"predefined_statement": "We will pilot it."},
            "output_dir": str(tmp_path),
        }
        config_path = tmp_path / "cfg.yaml"
        config_path.write_text(yaml.safe_dump(config))
        rc = main(
            [
                "-c", str(config_path),
                "--skip-llm-judge",
                "--skip-comparative-ranking",
                "--quiet",
            ]
        )
        assert rc == 0
        run_dirs = [d for d in tmp_path.iterdir() if d.name.startswith("skipjudge")]
        assert run_dirs
        eval_csvs = list(run_dirs[0].glob("evaluation/*/seed_0/evaluation_results.csv"))
        assert eval_csvs
        frame = pd.read_csv(eval_csvs[0])
        # Judge skipped: no judge-score columns in standard evaluation.
        assert not any(c.startswith("judge_score_") for c in frame.columns)


class TestAggregationBeautifier:
    def test_column_ordering(self):
        frame = pd.DataFrame(
            [
                {
                    "zzz_extra": 1.0,
                    "modelA_egalitarian_welfare_perplexity_std": 0.1,
                    "modelA_egalitarian_welfare_perplexity_mean": 5.0,
                    "avg_rank_mean": 2.0,
                    "modelA_cosine_similarity_Agent 1_mean": 0.5,
                    "modelA_egalitarian_welfare_cosine_mean": 0.4,
                    "param_n": 3,
                    "method_with_params": "best_of_n (n=3)",
                    "method": "best_of_n",
                    "modelA_utilitarian_welfare_perplexity_mean": 9.0,
                }
            ]
        )
        ordered = list(format_aggregated_columns(frame).columns)
        assert ordered[:3] == ["method", "method_with_params", "param_n"]
        # perplexity family first: egalitarian (mean before std) then
        # utilitarian; then cosine family (egalitarian before agent);
        # then rank; unmatched trail.
        assert ordered[3:] == [
            "modelA_egalitarian_welfare_perplexity_mean",
            "modelA_egalitarian_welfare_perplexity_std",
            "modelA_utilitarian_welfare_perplexity_mean",
            "modelA_egalitarian_welfare_cosine_mean",
            "modelA_cosine_similarity_Agent 1_mean",
            "avg_rank_mean",
            "zzz_extra",
        ]

    def test_roundtrip_no_loss(self):
        frame = pd.DataFrame([{"method": "m", "a_perplexity_mean": 1.0, "x": 2}])
        out = format_aggregated_columns(frame)
        assert set(out.columns) == set(frame.columns)
        assert out.iloc[0]["a_perplexity_mean"] == 1.0
