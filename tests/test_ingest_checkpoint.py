"""Checkpoint ingest (HF dir -> quantized orbax) and backend restore.

The ingest command is the only step between a real mounted checkpoint and
a sweep (VERDICT r3 #2); these tests pin the full loop on a synthetic
checkpoint with the production key schema: HF save_pretrained dir ->
``ingest()`` -> ``TPUBackend(checkpoint=<ingested>)`` restore, asserting
the restored backend scores/generates identically to one loading the raw
HF directory.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")
pytest.importorskip("orbax.checkpoint")

from consensus_tpu.backends.base import GenerationRequest, ScoreRequest  # noqa: E402
from consensus_tpu.backends.tpu import TPUBackend  # noqa: E402
from consensus_tpu.cli.ingest_checkpoint import ingest  # noqa: E402


@pytest.fixture(scope="module")
def hf_dir(tmp_path_factory):
    from tests.test_hf_numerics import _hf_tiny_gemma2, _save_hf_model

    return _save_hf_model(_hf_tiny_gemma2(), tmp_path_factory.mktemp("hf"))


def test_ingest_writes_manifest_and_params(hf_dir, tmp_path):
    out = ingest(hf_dir, str(tmp_path / "ingested"), model="tiny-gemma2",
                 quantization="int8", dtype="float32")
    assert (out / "ingest.json").exists()
    assert (out / "params").exists()
    import json

    meta = json.loads((out / "ingest.json").read_text())
    assert meta["model"] == "tiny-gemma2"
    assert meta["quantization"] == "int8"


def test_restored_backend_matches_hf_loaded(hf_dir, tmp_path):
    out = ingest(hf_dir, str(tmp_path / "ingested"), model="tiny-gemma2",
                 quantization="int8", dtype="float32")
    direct = TPUBackend(
        model="tiny-gemma2", checkpoint=hf_dir, dtype="float32",
        quantization="int8", max_context=128,
    )
    restored = TPUBackend(
        model="tiny-gemma2", checkpoint=str(out), dtype="float32",
        quantization="int8", max_context=128,
    )
    from consensus_tpu.models.quant import is_quantized

    assert is_quantized(restored.params)  # restored already int8, no re-pass

    score_req = [ScoreRequest(context="The town", continuation=" voted today")]
    a = direct.score(score_req)[0]
    b = restored.score(score_req)[0]
    np.testing.assert_allclose(a.logprobs, b.logprobs, atol=1e-5)

    gen_req = [
        GenerationRequest(
            user_prompt="Hi", max_tokens=8, temperature=0.0, seed=1
        )
    ]
    assert direct.generate(gen_req)[0].token_ids == (
        restored.generate(gen_req)[0].token_ids
    )


def test_unquantized_ingest_roundtrip(hf_dir, tmp_path):
    out = ingest(hf_dir, str(tmp_path / "plain"), model="tiny-gemma2",
                 quantization="none", dtype="float32")
    restored = TPUBackend(
        model="tiny-gemma2", checkpoint=str(out), dtype="float32",
        max_context=128,
    )
    direct = TPUBackend(
        model="tiny-gemma2", checkpoint=hf_dir, dtype="float32",
        max_context=128,
    )
    req = [ScoreRequest(context="Alpha", continuation=" beta gamma")]
    np.testing.assert_allclose(
        direct.score(req)[0].logprobs, restored.score(req)[0].logprobs,
        atol=1e-5,
    )
