"""Tests for zero_shot / predefined / best_of_n on the deterministic fake
backend — the decoder-logic coverage the reference never had (SURVEY §4:
"No mocks / fake backends for the LLM")."""

import numpy as np
import pytest

from consensus_tpu.backends.fake import FakeBackend
from consensus_tpu.methods import GENERATOR_MAP, get_method_generator
from consensus_tpu.methods.prompts import clean_statement

ISSUE = "Should the city invest in more bike lanes?"
OPINIONS = {
    "Agent 1": "Bike lanes make streets safer and should be expanded.",
    "Agent 2": "Road space is scarce; cars and buses need priority.",
    "Agent 3": "Invest only where cycling demand is proven.",
}


@pytest.fixture()
def backend():
    return FakeBackend()


def test_factory_unknown_method_raises(backend):
    with pytest.raises(ValueError, match="Unknown method"):
        get_method_generator("definitely_not_a_method", backend)


def test_factory_known_methods(backend):
    for name in GENERATOR_MAP:
        gen = get_method_generator(name, backend, {"seed": 1})
        assert gen.backend is backend


class TestCleanStatement:
    def test_strips_prefix(self):
        assert clean_statement("Statement: We agree.") == "We agree."
        assert (
            clean_statement("Here is the consensus statement: We agree.")
            == "We agree."
        )

    def test_strips_eos_markers(self):
        assert clean_statement("We agree.<|eot_id|>") == "We agree."
        assert clean_statement("We agree.<end_of_turn><eos>") == "We agree."

    def test_empty(self):
        assert clean_statement("") == ""
        assert clean_statement("   ") == ""


class TestZeroShot:
    def test_generates_real_statement(self, backend):
        gen = get_method_generator("zero_shot", backend, {"seed": 42, "max_tokens": 30})
        statement = gen.generate_statement(ISSUE, OPINIONS)
        assert statement and "Placeholder" not in statement
        assert backend.call_counts["generate"] == 1

    def test_deterministic_in_seed(self, backend):
        gen = get_method_generator("zero_shot", backend, {"seed": 42})
        s1 = gen.generate_statement(ISSUE, OPINIONS)
        s2 = gen.generate_statement(ISSUE, OPINIONS)
        assert s1 == s2
        gen2 = get_method_generator("zero_shot", backend, {"seed": 43})
        assert gen2.generate_statement(ISSUE, OPINIONS) != s1


class TestPredefined:
    def test_returns_configured_statement(self, backend):
        gen = get_method_generator(
            "predefined", backend, {"predefined_statement": "Exactly this."}
        )
        assert gen.generate_statement(ISSUE, OPINIONS) == "Exactly this."
        assert backend.call_counts["generate"] == 0

    def test_missing_statement_error_sentinel(self, backend):
        gen = get_method_generator("predefined", backend, {})
        assert gen.generate_statement(ISSUE, OPINIONS).startswith("[ERROR")


class TestBestOfN:
    def test_two_backend_calls_total(self, backend):
        gen = get_method_generator(
            "best_of_n", backend, {"num_best_of_n": 5, "seed": 7}
        )
        statement = gen.generate_statement(ISSUE, OPINIONS)
        assert statement
        # 5 generation requests in ONE call; 5x3 score requests in ONE call.
        assert backend.call_counts["generate"] == 5
        assert backend.call_counts["score"] == 15

    def test_picks_egalitarian_argmax(self, backend):
        gen = get_method_generator("best_of_n", backend, {"n": 4, "seed": 3})
        statement = gen.generate_statement(ISSUE, OPINIONS)

        # Recompute expected winner from the same deterministic backend.
        candidates = gen._generate_candidates(ISSUE, OPINIONS, 4, 50, 1.0, 3)
        utilities = gen.score_candidates(ISSUE, OPINIONS, candidates)
        assert utilities.shape == (len(candidates), 3)
        expected = candidates[int(np.argmin(-utilities.min(axis=1)))]
        assert statement == expected

    def test_utilities_are_mean_logprobs(self, backend):
        gen = get_method_generator("best_of_n", backend, {"seed": 0})
        utilities = gen.score_candidates(ISSUE, OPINIONS, ["We support change."])
        assert utilities.shape == (1, 3)
        assert np.all(utilities <= 0.0) and np.all(utilities > -7.0)

    def test_seed_variation_changes_candidates(self, backend):
        gen = get_method_generator("best_of_n", backend, {"n": 3, "seed": 11})
        c1 = gen._generate_candidates(ISSUE, OPINIONS, 3, 50, 1.0, 11)
        assert len(set(c1)) == 3  # distinct seeds -> distinct candidates
