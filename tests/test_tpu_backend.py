"""TPUBackend protocol tests on a tiny random-weight model (CPU devices).

Random weights make statements noise, but every protocol property —
shapes, determinism, logprob validity, batching, EOS/stop handling —
is exactly what production runs rely on.
"""

import numpy as np
import pytest

from consensus_tpu.backends.base import (
    GenerationRequest,
    NextTokenRequest,
    ScoreRequest,
)
from consensus_tpu.backends.tpu import TPUBackend

ISSUE = "Should the town build a new playground?"


@pytest.fixture(scope="module")
def backend():
    return TPUBackend(model="tiny-gemma2", max_context=256, base_seed=0)


class TestGenerate:
    def test_batch_generation(self, backend):
        requests = [
            GenerationRequest(user_prompt=f"Prompt {i}", max_tokens=8, seed=i)
            for i in range(3)
        ]
        results = backend.generate(requests)
        assert len(results) == 3
        for result in results:
            assert result.finish_reason in ("stop", "length")
            assert len(result.token_ids) <= 8

    def test_deterministic_for_same_batch(self, backend):
        requests = [GenerationRequest(user_prompt="Same prompt", max_tokens=6, seed=1)]
        r1 = backend.generate(requests)[0]
        r2 = backend.generate(requests)[0]
        assert r1.text == r2.text

    def test_stop_string_truncates(self, backend):
        request = GenerationRequest(user_prompt="Hi", max_tokens=6, seed=0)
        full = backend.generate([request])[0]
        if len(full.text) > 1:
            stop_char = full.text[1]
            stopped = backend.generate(
                [GenerationRequest(user_prompt="Hi", max_tokens=6, seed=0,
                                   stop=(stop_char,))]
            )[0]
            assert stop_char not in stopped.text

    def test_same_request_independent_of_batch(self, backend):
        """Regression (VERDICT r1 #7): a request's output must not depend on
        which other requests share its device batch — per-row PRNG keys."""
        probe = GenerationRequest(
            user_prompt="Independent request", max_tokens=6, seed=7,
            temperature=0.9,
        )
        alone = backend.generate([probe])[0]
        other = GenerationRequest(
            user_prompt="A different companion", max_tokens=6, seed=11,
            temperature=0.9,
        )
        batched = backend.generate([other, probe])[1]
        assert alone.text == batched.text
        assert alone.token_ids == batched.token_ids

    def test_unseeded_duplicate_requests_stay_diverse(self, backend):
        """Unseeded identical prompts in one batch (best_of_n drafts,
        habermas candidates) must each get a distinct sampling stream."""
        requests = [
            GenerationRequest(
                user_prompt="Draft a statement", max_tokens=8, seed=None,
                temperature=1.0,
            )
            for _ in range(3)
        ]
        results = backend.generate(requests)
        token_sets = {r.token_ids for r in results}
        assert len(token_sets) > 1

    def test_greedy_at_zero_temperature(self, backend):
        requests = [
            GenerationRequest(user_prompt="Greedy", max_tokens=5, temperature=0.0,
                              seed=s)
            for s in (1, 2)
        ]
        results = backend.generate(requests)
        assert results[0].text == results[1].text  # greedy ignores seed


class TestScore:
    def test_continuation_logprobs_only(self, backend):
        result = backend.score(
            [ScoreRequest(context="The town meeting", continuation=" agreed today")]
        )[0]
        assert result.ok
        assert all(lp <= 0.0 for lp in result.logprobs)
        # Tokens decode back to the continuation text.
        assert "".join(result.tokens).strip().startswith("agreed")

    def test_batch_matches_single(self, backend):
        requests = [
            ScoreRequest(context="Alpha beta", continuation=" gamma"),
            ScoreRequest(context="One two", continuation=" three four"),
        ]
        batched = backend.score(requests)
        singles = [backend.score([r])[0] for r in requests]
        for b, s in zip(batched, singles):
            np.testing.assert_allclose(b.logprobs, s.logprobs, atol=1e-3)

    def test_mean_and_total(self, backend):
        result = backend.score(
            [ScoreRequest(context="ctx", continuation=" something longer here")]
        )[0]
        assert result.mean() == pytest.approx(np.mean(result.logprobs))
        assert result.total() == pytest.approx(np.sum(result.logprobs))


class TestNextToken:
    def test_topk_distinct_sorted(self, backend):
        candidates = backend.next_token_logprobs(
            [NextTokenRequest(user_prompt="Next", k=5, mode="topk")]
        )[0]
        assert len(candidates) == 5
        ids = [c.token_id for c in candidates]
        assert len(set(ids)) == 5
        lps = [c.logprob for c in candidates]
        assert lps == sorted(lps, reverse=True)

    def test_sample_mode_seed_dependence(self, backend):
        a = backend.next_token_logprobs(
            [NextTokenRequest(user_prompt="Next", k=4, mode="sample", seed=1)]
        )[0]
        b = backend.next_token_logprobs(
            [NextTokenRequest(user_prompt="Next", k=4, mode="sample", seed=1)]
        )[0]
        c = backend.next_token_logprobs(
            [NextTokenRequest(user_prompt="Next", k=4, mode="sample", seed=2)]
        )[0]
        assert [x.token_id for x in a] == [x.token_id for x in b]
        assert any(
            x.token_id != y.token_id for x, y in zip(a, c)
        ) or len(a) != len(c)

    def test_sample_independent_of_batch(self, backend):
        """Device-side Gumbel-top-k uses per-row keys: candidates for a
        request match whether it runs alone or batched."""
        probe = NextTokenRequest(user_prompt="Probe", k=4, mode="sample", seed=5)
        alone = backend.next_token_logprobs([probe])[0]
        other = NextTokenRequest(
            user_prompt="Companion prompt", k=4, mode="sample", seed=9
        )
        batched = backend.next_token_logprobs([other, probe])[1]
        assert [c.token_id for c in alone] == [c.token_id for c in batched]

    def test_larger_k_is_prefix_superset(self, backend):
        """Gumbel-top-k without replacement: asking for more candidates keeps
        the smaller request's set (same row key, same scores)."""
        small = backend.next_token_logprobs(
            [NextTokenRequest(user_prompt="Prefix", k=3, mode="sample", seed=4)]
        )[0]
        large = backend.next_token_logprobs(
            [NextTokenRequest(user_prompt="Prefix", k=6, mode="sample", seed=4)]
        )[0]
        assert {c.token_id for c in small} <= {c.token_id for c in large}

    def test_bias_suppresses_tokens(self, backend):
        top = backend.next_token_logprobs(
            [NextTokenRequest(user_prompt="Bias test", k=3, mode="topk")]
        )[0]
        banned = top[0].token
        if banned.strip():
            rebiased = backend.next_token_logprobs(
                [
                    NextTokenRequest(
                        user_prompt="Bias test", k=3, mode="topk",
                        bias_against_tokens=(banned,),
                    )
                ]
            )[0]
            assert all(banned not in c.token for c in rebiased)


class TestEmbed:
    def test_unit_norm_and_shape(self, backend):
        vectors = backend.embed(["hello world", "completely different text"])
        assert vectors.shape[0] == 2
        np.testing.assert_allclose(
            np.linalg.norm(vectors, axis=1), [1.0, 1.0], atol=1e-5
        )

    def test_identical_texts_identical_vectors(self, backend):
        vectors = backend.embed(["same text", "same text"])
        np.testing.assert_allclose(vectors[0], vectors[1], atol=1e-6)


class TestDecoderIntegration:
    def test_best_of_n_runs_on_tpu_backend(self, backend):
        from consensus_tpu.methods import get_method_generator

        gen = get_method_generator(
            "best_of_n", backend, {"n": 2, "max_tokens": 6, "seed": 3}
        )
        statement = gen.generate_statement(
            ISSUE, {"A": "Yes, kids need it.", "B": "Too expensive."}
        )
        assert isinstance(statement, str)

    def test_experiment_with_tpu_backend(self, backend, tmp_path):
        from consensus_tpu.experiment import Experiment

        config = {
            "experiment_name": "tpu_smoke",
            "seed": 1,
            "num_seeds": 1,
            "scenario": {
                "issue": ISSUE,
                "agent_opinions": {"A": "Build it.", "B": "Save the money."},
            },
            "methods_to_run": ["zero_shot"],
            "zero_shot": {"max_tokens": 6},
            "output_dir": str(tmp_path),
        }
        frame = Experiment(config, backend=backend).run()
        assert len(frame) == 1
        assert frame["error_message"].iloc[0] == ""


class TestGenerateChunking:
    """HBM-aware decode-batch chunking (backends/tpu.py:_generate_rows_allowed)."""

    def make(self, **kw):
        from consensus_tpu.backends.tpu import TPUBackend

        return TPUBackend(model="tiny-gemma2", dtype="float32", max_context=128, **kw)

    def test_rows_allowed_rounds_down_to_pow2(self, monkeypatch):
        import consensus_tpu.backends.tpu as tpu_mod

        backend = self.make()
        unit = (
            2 * backend.config.n_layers * backend.config.n_kv_heads
            * backend.config.head_dim * 4  # float32
        )
        budget_free = (
            tpu_mod._HBM_BYTES - backend._params_bytes
            - tpu_mod._ACTIVATION_RESERVE_BYTES
        )
        # Choose width/max_new so exactly 5 rows fit -> pow2 floor is 4.
        per_row_cols = budget_free // (5 * unit)
        width = int(per_row_cols) - 2 * 16
        assert backend._generate_rows_allowed(width, 16) == 4

    def test_rows_allowed_floor_is_one(self, monkeypatch):
        import consensus_tpu.backends.tpu as tpu_mod

        backend = self.make()
        monkeypatch.setattr(tpu_mod, "_HBM_BYTES", backend._params_bytes + 1)
        assert backend._generate_rows_allowed(4096, 512) == 1

    def test_live_sessions_shrink_the_allowance(self):
        backend = self.make()
        base = backend._generate_rows_allowed(1024, 128)
        backend._session_budget.acquire(backend._session_budget.cap // 2)
        try:
            assert backend._generate_rows_allowed(1024, 128) <= base
        finally:
            backend._session_budget.release(backend._session_budget.cap // 2)

    def test_segmented_allowance_models_the_block_peak(self):
        """The segmented row allowance (backends/tpu.py:
        _segmented_rows_allowed) tracks the block-list HBM peak — the
        single-buffered frozen blocks (no concat transient: segments
        append to a list), the double-buffered live tail, and one seg_len
        of compaction-gather transient; int8 KV halves the column cost
        (plus a scale-plane margin) — while beating the monolithic
        allowance (whose full-budget tail is double-buffered by the
        carry copy)."""
        backend = self.make()  # kv_quant defaults ON
        exact = self.make(kv_quant=False)
        max_new, seg = 768, 128
        cols = (max_new - seg) + 2 * seg + seg  # frozen + dbuf tail + gather
        assert exact._segmented_rows_allowed(0, max_new, seg) == (
            exact._generate_rows_allowed(cols - 2 * seg, seg)
        )
        quant_cols = (cols + 1) // 2 + seg // 4
        assert backend._segmented_rows_allowed(0, max_new, seg) == (
            backend._generate_rows_allowed(quant_cols - 2 * seg, seg)
        )
        # int8 KV must raise capacity, and both must beat monolithic.
        assert backend._segmented_rows_allowed(0, max_new, seg) > (
            exact._segmented_rows_allowed(0, max_new, seg)
        )
        assert exact._segmented_rows_allowed(0, max_new, seg) >= (
            exact._generate_rows_allowed(0, max_new)
        )
        # Classic layout (wide per-row prompt trunk): under kv_quant the
        # trunk is int8 at decode time, but the prefill→quantize transient
        # (1.5x bf16 trunk) is the binding peak at production widths.
        width = 1024
        quant_cols = (cols + 1) // 2 + seg // 4
        expected = max(
            width + width // 2 + 2 * seg,
            (width + 1) // 2 + width // 16 + quant_cols,
        )
        assert backend._segmented_rows_allowed(width, max_new, seg) == (
            backend._generate_rows_allowed(expected - 2 * seg, seg)
        )
        assert backend._segmented_rows_allowed(width, max_new, seg) >= (
            exact._segmented_rows_allowed(width, max_new, seg)
        )

    def test_oversized_batch_chunks_and_results_match(self, monkeypatch):
        from consensus_tpu.backends.base import GenerationRequest
        from consensus_tpu.backends.tpu import TPUBackend

        backend = self.make()
        requests = [
            GenerationRequest(
                user_prompt=f"Issue number {i}.", max_tokens=4, seed=100 + i
            )
            for i in range(6)
        ]
        whole = backend.generate(requests)
        # Force single-row chunks: per-request results must be identical
        # (per-row PRNG keys make rows batch-composition independent).
        monkeypatch.setattr(
            TPUBackend, "_generate_rows_allowed", lambda self, w, m: 1
        )
        chunked = backend.generate(requests)
        assert [r.text for r in whole] == [r.text for r in chunked]
        assert backend.call_counts["generate"] == 12  # 6 + 6, not double-counted
