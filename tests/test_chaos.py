"""Chaos suite: the ISSUE-4 acceptance proofs, pytest-marked ``chaos``.

* **Determinism under faults**: a seeded transient-fault sweep whose fault
  count stays under the retry budget produces a ``results.csv``
  byte-identical to a fault-free run, and its ``metrics.json`` shows
  ``supervisor_retries_total > 0`` (the faults really happened and were
  really absorbed).
* **Crash-safe resume**: a sweep killed after K of N rows, resumed with
  ``resume: true``, re-executes only the N-K missing rows and merges to a
  byte-identical ``results.csv``.
* **Structured error rows**: ``on_error: skip`` converts a permanent
  backend loss into an error row carrying the typed exception name.
* **Poison-row isolation**: one NaN row inside a merged device batch fails
  only the session that owns it (typed ``BackendIntegrityError``); sibling
  sessions' results stay bit-identical to a clean run.
"""

import json

import pytest

from consensus_tpu.backends import FakeBackend, ScoreRequest, wrap_backend
from consensus_tpu.backends.base import BackendIntegrityError
from consensus_tpu.backends.batching import BatchingBackend
from consensus_tpu.experiment import Experiment, run_config_hash
from consensus_tpu.utils.io_atomic import read_journal

pytestmark = pytest.mark.chaos

ISSUE = "Should the town build a new park?"
OPINIONS = {"alice": "Yes, green space matters.", "bob": "Too expensive."}


def base_config(tmp_path, sub, **overrides):
    config = {
        "experiment_name": "chaos",
        "seed": 42,
        "num_seeds": 2,
        "backend": "fake",
        "models": {"generation_model": "fake-lm"},
        "scenario": {"issue": ISSUE, "agent_opinions": dict(OPINIONS)},
        "methods_to_run": ["zero_shot", "best_of_n"],
        "best_of_n": {"n": [2, 3], "max_tokens": 16},
        "output_dir": str(tmp_path / sub),
        # Wall-clock columns zeroed so byte-identity proofs are meaningful.
        "deterministic_artifacts": True,
    }
    config.update(overrides)
    return config


def run_bytes(experiment):
    experiment.run()
    return (experiment.run_dir / "results.csv").read_bytes()


class TestChaosDeterminism:
    def test_faulted_sweep_byte_identical_and_retries_recorded(self, tmp_path):
        clean = run_bytes(Experiment(base_config(tmp_path, "clean")))
        # Sequential execution pins per-op call indices, so the pinned
        # transient faults deterministically hit real calls.
        plan = {"seed": 7, "faults": [
            {"kind": "transient_error", "op": "generate", "call_index": 0},
            {"kind": "timeout_error", "op": "score", "call_index": 1},
        ]}
        chaotic = Experiment(base_config(
            tmp_path, "chaos", fault_plan=plan, concurrent_execution=False))
        assert run_bytes(chaotic) == clean
        metrics = json.loads((chaotic.run_dir / "metrics.json").read_text())
        families = metrics["metrics"]["families"]
        retries = sum(
            s["value"]
            for s in families["supervisor_retries_total"]["series"])
        injected = sum(
            s["value"] for s in families["faults_injected_total"]["series"])
        assert retries > 0 and injected > 0

    def test_concurrent_faulted_sweep_byte_identical(self, tmp_path):
        clean = run_bytes(Experiment(base_config(tmp_path, "clean")))
        plan = {"seed": 11, "faults": [
            {"kind": "transient_error", "op": "*", "rate": 0.2}]}
        chaotic = Experiment(base_config(tmp_path, "chaos", fault_plan=plan))
        assert run_bytes(chaotic) == clean


class TestResume:
    def test_killed_sweep_resumes_and_merges_byte_identical(self, tmp_path):
        clean = run_bytes(Experiment(base_config(tmp_path, "clean")))

        # "Kill" after K rows: a permanent device loss at the 3rd
        # sequential generate call with on_error=fail aborts the sweep
        # mid-flight (faults unsupervised so nothing absorbs the loss).
        crash_config = base_config(
            tmp_path, "crash",
            fault_plan={"faults": [
                {"kind": "device_lost", "op": "generate", "call_index": 2}]},
            supervisor=False,
            on_error="fail",
            concurrent_execution=False,
        )
        crashed = Experiment(crash_config)
        with pytest.raises(Exception):
            crashed.run()
        journaled = read_journal(crashed.run_dir / "journal.jsonl")
        completed = len(journaled)
        assert 0 < completed < 6  # mid-sweep, not empty, not done

        # Resume with a healthy backend: only the missing rows execute.
        resumed = Experiment(base_config(tmp_path, "crash", resume=True))
        assert resumed.run_dir == crashed.run_dir
        assert run_bytes(resumed) == clean
        after = read_journal(resumed.run_dir / "journal.jsonl")
        assert len(after) == 6  # N total: K reused + (N-K) new appends
        reexecuted = {r["run_index"] for r in after[completed:]}
        original = {r["run_index"] for r in after[:completed]}
        assert not (reexecuted & original)  # nothing ran twice

    def test_fully_journaled_resume_executes_nothing(self, tmp_path):
        first = Experiment(base_config(tmp_path, "full"))
        clean = run_bytes(first)
        resumed = Experiment(base_config(tmp_path, "full", resume=True))
        assert run_bytes(resumed) == clean
        # No new journal appends: every row came from the journal.
        assert len(read_journal(resumed.run_dir / "journal.jsonl")) == 6

    def test_resume_without_prior_run_starts_fresh(self, tmp_path):
        experiment = Experiment(base_config(tmp_path, "fresh", resume=True))
        assert not experiment.resumed
        assert len(experiment.run()) == 6

    def test_journal_key_is_stable_and_seed_free(self):
        assert run_config_hash({"n": 2, "seed": 1}) == \
            run_config_hash({"n": 2, "seed": 9})
        assert run_config_hash({"n": 2}) != run_config_hash({"n": 3})


class TestOnErrorPolicies:
    def test_skip_records_structured_error_row(self, tmp_path):
        frame = Experiment(base_config(
            tmp_path, "skip",
            num_seeds=1,
            methods_to_run=["zero_shot"],
            fault_plan={"faults": [
                {"kind": "device_lost", "op": "*", "call_index": 0}]},
            on_error="skip",
        )).run()
        assert len(frame) == 1
        row = frame.iloc[0]
        assert row["statement"] == ""
        assert row["error_message"].startswith("BackendLostError")
        assert row["evaluation_status"] == "pending"

    def test_fail_aborts_the_sweep(self, tmp_path):
        experiment = Experiment(base_config(
            tmp_path, "fail",
            num_seeds=1,
            methods_to_run=["zero_shot"],
            fault_plan={"faults": [
                {"kind": "device_lost", "op": "*", "call_index": 0}]},
            on_error="fail",
        ))
        with pytest.raises(Exception):
            experiment.run()

    def test_retry_policy_reruns_the_row(self, tmp_path):
        # Fault exhausts the supervisor budget (rate 1.0 on the first
        # row's generate calls is too blunt) — instead fail the row once
        # at the experiment level via an unsupervised transient fault.
        frame = Experiment(base_config(
            tmp_path, "retry",
            num_seeds=1,
            methods_to_run=["zero_shot"],
            concurrent_execution=False,
            fault_plan={"faults": [
                {"kind": "transient_error", "op": "generate",
                 "call_index": 0}]},
            supervisor=False,
            on_error="retry",
        )).run()
        row = frame.iloc[0]
        assert row["error_message"] == ""
        assert row["statement"]

    def test_invalid_policy_rejected(self, tmp_path):
        with pytest.raises(ValueError, match="on_error"):
            Experiment(base_config(tmp_path, "bad", on_error="explode"))


class TestPoisonRowIsolation:
    def test_one_nan_row_fails_one_session_siblings_bit_identical(self):
        # Three sessions' score calls merge into ONE device batch; the
        # fault poisons merged row 1 only.
        plan = {"faults": [
            {"kind": "nan_logprobs", "op": "score", "call_index": 0,
             "row_index": 1}]}
        from consensus_tpu.obs.metrics import Registry
        registry = Registry()
        stack = wrap_backend(
            FakeBackend(), fault_plan=plan, registry=registry)
        batching = BatchingBackend(
            stack, flush_ms=50.0, expected_sessions=3, registry=registry,
            engine=False)

        reqs = [ScoreRequest(context="ctx", continuation=f"row {i}")
                for i in range(3)]
        clean = FakeBackend().score(reqs)
        results = {}

        import threading

        def worker(i):
            with batching.session():
                try:
                    results[i] = batching.score([reqs[i]])[0]
                except Exception as exc:  # noqa: BLE001 - recorded for assert
                    results[i] = exc

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)

        assert batching.batch_counts["score"] == 1  # really merged
        # Merged row 1 belongs to whichever session arrived second in the
        # queue — exactly one session fails, typed; siblings bit-identical.
        failed = [i for i in range(3) if isinstance(results[i], Exception)]
        assert len(failed) == 1
        assert isinstance(results[failed[0]], BackendIntegrityError)
        for i in range(3):
            if i not in failed:
                assert results[i].logprobs == clean[i].logprobs
        assert 'batching_row_errors_total{kind="score"} 1' in \
            registry.to_prometheus()


class TestEngineChaos:
    """ISSUE-7 satellite: the chaos invariants hold through the
    continuous-batching engine path — faults surface and resolve via
    ``DecodeEngine.submit``, not just the legacy flush merge."""

    @staticmethod
    def _engine_stack(plan, registry, **engine_options):
        stack = wrap_backend(
            FakeBackend(), fault_plan=plan, supervise=True,
            registry=registry)
        options = {"slots": 4, "num_pages": 512}
        options.update(engine_options)
        return BatchingBackend(
            stack, engine=True, engine_options=options, registry=registry)

    def test_transient_fault_absorbed_below_engine_submit(self):
        from consensus_tpu.obs.metrics import Registry

        plan = {"seed": 7, "faults": [
            {"kind": "transient_error", "op": "score", "call_index": 0}]}
        registry = Registry()
        batching = self._engine_stack(plan, registry)
        reqs = [ScoreRequest(context="ctx", continuation=f"row {i}")
                for i in range(3)]
        try:
            results = batching.score(reqs)
        finally:
            batching.close()
        clean = FakeBackend().score(reqs)
        assert [r.logprobs for r in results] == [r.logprobs for r in clean]
        retries = sum(
            s["value"] for s in registry.snapshot()["families"]
            ["supervisor_retries_total"]["series"])
        assert retries > 0

    def test_nan_poison_row_fails_one_engine_session_siblings_identical(self):
        # Three sessions submit one score row each into the engine; the
        # fault poisons merged row 1 of the first device batch.  The
        # supervisor bisects, the engine slices the PartialBatchError per
        # item: exactly one session fails, typed, siblings bit-identical.
        from consensus_tpu.obs.metrics import Registry

        plan = {"faults": [
            {"kind": "nan_logprobs", "op": "score", "call_index": 0,
             "row_index": 1}]}
        registry = Registry()
        batching = self._engine_stack(plan, registry)
        reqs = [ScoreRequest(context="ctx", continuation=f"row {i}")
                for i in range(3)]
        clean = FakeBackend().score(reqs)
        results = {}

        import threading

        barrier = threading.Barrier(3)

        def worker(i):
            with batching.session():
                barrier.wait(timeout=10)
                try:
                    results[i] = batching.score([reqs[i]])[0]
                except Exception as exc:  # noqa: BLE001 - asserted below
                    results[i] = exc

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(3)]
        try:
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
        finally:
            batching.close()

        failed = [i for i in range(3) if isinstance(results[i], Exception)]
        assert len(failed) == 1
        assert isinstance(results[failed[0]], BackendIntegrityError)
        for i in range(3):
            if i not in failed:
                assert results[i].logprobs == clean[i].logprobs

    def test_device_lost_is_sticky_through_engine_submit(self):
        from consensus_tpu.backends.base import BackendLostError
        from consensus_tpu.obs.metrics import Registry

        plan = {"faults": [
            {"kind": "device_lost", "op": "score", "call_index": 0}]}
        registry = Registry()
        batching = self._engine_stack(plan, registry)
        reqs = [ScoreRequest(context="ctx", continuation="row")]
        try:
            with pytest.raises(BackendLostError):
                batching.score(reqs)
            # The engine latched the loss (the fleet router's passive
            # health signal) and stays lost for every later submit.
            assert batching.engine.backend_lost
            assert batching.engine.stats()["backend_lost"]
            with pytest.raises(BackendLostError):
                batching.score(reqs)
        finally:
            batching.close()
