"""AAMAS config-tree validation (VERDICT r1 #4).

The committed tree must mirror the reference's sweep surface:
configs/appendix/{gemma,llama}/scenario_{1..5}/{habermas_only,
habermas_vs_best_of_n,beam_search,finite_lookahead}.yaml, plus
configs/main_body/scenario_{1,2,3}.yaml and the MCTS example
(reference run_aamas_experiments.py:21-90 glob surface).
"""

import itertools
import pathlib

import pytest
import yaml

from consensus_tpu.data.aamas_scenarios import MAIN_BODY, SCENARIOS

REPO = pathlib.Path(__file__).resolve().parent.parent
METHODS = ["habermas_only", "habermas_vs_best_of_n", "beam_search", "finite_lookahead"]


def _load(path):
    with open(path) as f:
        return yaml.safe_load(f)


class TestAppendixTree:
    @pytest.mark.parametrize(
        "family,scenario,method",
        list(itertools.product(["gemma", "llama"], range(1, 6), METHODS)),
    )
    def test_config_exists_and_valid(self, family, scenario, method):
        path = REPO / "configs/appendix" / family / f"scenario_{scenario}" / f"{method}.yaml"
        assert path.exists(), path
        config = _load(path)
        # Scenario text is the paper's exact survey data.
        assert config["scenario"]["issue"] == SCENARIOS[scenario]["issue"]
        assert (
            config["scenario"]["agent_opinions"]
            == SCENARIOS[scenario]["agent_opinions"]
        )
        assert config["num_seeds"] == 3
        assert config["backend"] == "tpu"
        for name in config["methods_to_run"]:
            method_key = name if name in config else name
            assert method_key in config, f"{name} section missing in {path}"

    def test_reference_grid_parity(self):
        """Spot-check the grids the paper sweeps (reference appendix YAMLs)."""
        beam = _load(REPO / "configs/appendix/gemma/scenario_1/beam_search.yaml")
        assert beam["beam_search"]["beam_width"] == [2, 4, 6, 8]
        assert beam["beam_search"]["max_tokens"] == 50
        assert beam["beam_search"]["brushup"] is True

        look = _load(REPO / "configs/appendix/llama/scenario_3/finite_lookahead.yaml")
        assert look["finite_lookahead"]["branching_factor"] == 3
        assert look["finite_lookahead"]["max_depth"] == [1, 2, 3]

        bon = _load(REPO / "configs/appendix/gemma/scenario_2/habermas_vs_best_of_n.yaml")
        assert bon["best_of_n"]["n"] == [1, 3, 5, 10, 20, 50]
        assert bon["habermas_machine"]["num_candidates"] == [1, 3, 5, 10, 20, 50]

        hab = _load(REPO / "configs/appendix/llama/scenario_5/habermas_only.yaml")
        assert hab["habermas_machine"]["num_candidates"] == [2, 5, 10]
        assert hab["habermas_machine"]["num_rounds"] == [1, 2]

    def test_family_models(self):
        for scenario in range(1, 6):
            gemma = _load(
                REPO / f"configs/appendix/gemma/scenario_{scenario}/beam_search.yaml"
            )
            llama = _load(
                REPO / f"configs/appendix/llama/scenario_{scenario}/beam_search.yaml"
            )
            assert gemma["models"]["generation_model"] == "gemma2-9b"
            assert llama["models"]["generation_model"] == "llama3-8b"


class TestMainBodyAndExamples:
    @pytest.mark.parametrize("scenario", [1, 2, 3])
    def test_main_body(self, scenario):
        config = _load(REPO / f"configs/main_body/scenario_{scenario}.yaml")
        assert config["scenario"]["issue"] == MAIN_BODY[scenario]["scenario"]["issue"]
        assert set(config["methods_to_run"]) == {
            "best_of_n", "finite_lookahead", "habermas_machine",
            "predefined", "beam_search",
        }
        # The predefined control statement anchors cross-backend A/B parity.
        assert (
            config["predefined"]["predefined_statement"]
            == MAIN_BODY[scenario]["predefined_statement"]
        )

    def test_mcts_example(self):
        config = _load(REPO / "configs/examples/mcts.yaml")
        assert config["methods_to_run"] == ["mcts"]
        assert config["mcts"]["num_simulations"] == 3
        assert config["mcts"]["mcts_wave_size"] == 8

    def test_north_star_tree(self):
        paths = sorted((REPO / "configs/north_star").glob("*/scenario_*/*.yaml"))
        assert len(paths) == 25  # 5 scenarios x 5 method files (incl. mcts)
        for path in paths:
            config = _load(path)
            assert config["backend_options"]["model"] == "gemma2-2b"
            assert config["num_seeds"] == 5
        mcts = [p for p in paths if p.name == "mcts.yaml"]
        assert len(mcts) == 5
        for path in mcts:
            config = _load(path)
            # Reference-default search scale, wave-parallel device path on.
            assert config["mcts"]["num_simulations"] == 50
            assert config["mcts"]["mcts_wave_size"] == 8

    def test_mcts_timing_sweeps_wave_widths(self):
        config = _load(REPO / "configs/examples/mcts_timing.yaml")
        assert config["mcts"]["mcts_wave_size"] == [1, 8]


class TestSweepDriverDiscovery:
    def test_find_config_files_filters(self):
        from consensus_tpu.cli.run_sweep import find_config_files

        all_appendix = find_config_files(str(REPO / "configs/appendix"))
        assert len(all_appendix) == 40
        gemma_only = find_config_files(
            str(REPO / "configs/appendix"), models=["gemma"]
        )
        assert len(gemma_only) == 20
        subset = find_config_files(
            str(REPO / "configs/appendix"),
            models=["llama"], scenarios=[2, 4], methods=["beam_search"],
        )
        assert len(subset) == 2

    def test_experiment_accepts_appendix_config(self, tmp_path):
        """An appendix config drives the experiment engine end-to-end on the
        fake backend (grid expansion, param columns, run dir)."""
        from consensus_tpu.backends.fake import FakeBackend
        from consensus_tpu.experiment import Experiment

        config = _load(REPO / "configs/appendix/gemma/scenario_1/habermas_only.yaml")
        config["output_dir"] = str(tmp_path)
        config["num_seeds"] = 1
        config["habermas_machine"]["num_candidates"] = [2]
        config["habermas_machine"]["num_rounds"] = [1]
        frame = Experiment(config, backend=FakeBackend()).run()
        assert len(frame) == 1
        assert (frame["error_message"] == "").all()
