"""Speculative rollout verification: drafts, rejection, and equivalence.

The speculative path (backends/speculative.py:NGramProposer +
models/stepper.rollout_verify_many + the session's _rollout_many_spec loop)
must be invisible in results: token streams identical to the sequential
rollout scan, agent totals to float tolerance (the one-pass verify
projects logits at a different matmul shape than the step-by-step scan —
same contract the batched rollout tests pin for rollout_many vs
rollout_from), and whole-method statements byte-identical with
``speculative_rollouts`` on vs off.
"""

import numpy as np
import pytest

from consensus_tpu.backends.session import SearchSpec
from consensus_tpu.backends.speculative import NGramProposer
from consensus_tpu.backends.tpu import TPUBackend, TPUTokenSearchSession

ISSUE = "Should the town build a new library?"
OPINIONS = {
    "Agent 1": "Yes, libraries anchor the community.",
    "Agent 2": "Only if it does not raise taxes.",
}


# ---------------------------------------------------------------------------
# Host-side proposer (no model, no jax)
# ---------------------------------------------------------------------------


class TestNGramProposer:
    def test_draft_replays_observed_pattern(self):
        p = NGramProposer(max_order=3)
        p.observe([1, 2, 3, 4, 1, 2, 3])
        # Longest suffix (2, 3) was followed by 4; then (3, 4) by 1, ...
        assert p.draft([1, 2, 3], 4) == [4, 1, 2, 3]

    def test_latest_occurrence_wins(self):
        p = NGramProposer(max_order=2)
        p.observe([5, 6, 7])  # (5, 6) -> 7
        p.observe([5, 6, 9])  # (5, 6) -> 9 overwrites
        assert p.draft([5, 6], 1) == [9]

    def test_longest_order_preferred(self):
        p = NGramProposer(max_order=3)
        p.observe([1, 2, 3, 8])  # (1,2,3)->8, (2,3)->8, (3,)->8
        p.observe([9, 2, 3, 4])  # (9,2,3)->4, (2,3)->4, (3,)->4
        # Order-3 context (1, 2, 3) still remembers 8 even though the
        # order-2 table was overwritten with 4.
        assert p.draft([1, 2, 3], 1) == [8]
        assert p.draft([7, 2, 3], 1) == [4]

    def test_unseen_context_repeats_last_token(self):
        p = NGramProposer()
        p.observe([1, 2])
        assert p.draft([40, 41], 3) == [41, 41, 41]
        assert p.draft([], 2) == [0, 0]

    def test_deterministic_across_instances(self):
        history = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5]
        a, b = NGramProposer(), NGramProposer()
        a.observe(history)
        b.observe(history)
        assert a.draft([5, 3, 5], 6) == b.draft([5, 3, 5], 6)


# ---------------------------------------------------------------------------
# Device verify path vs the sequential scan (tiny real model)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def backend():
    return TPUBackend(model="tiny-gemma2", dtype="float32", max_context=256)


def make_spec(**kw):
    defaults = dict(
        ref_system="You draft consensus statements.",
        ref_user="Issue: taxes.\nOpinions: A wants more, B wants less."
                 "\nStatement:",
        agent_prompts=(
            ("Agent context.", "Opinion: A wants more.\nStatement:"),
            ("Agent context.", "Opinion: B wants less.\nStatement:"),
        ),
        n_slots=1, k=3, temperature=1.0, seed=11, sample=False, max_steps=8,
    )
    defaults.update(kw)
    return SearchSpec(**defaults)


def test_spec_rollouts_match_sequential_scan(backend):
    """Speculative rollout_many == plain rollout_many: exact ids and text
    (the rejection construction replays every sampling decision), totals
    to float tolerance."""
    plain = TPUTokenSearchSession(backend, make_spec())
    root = plain.propose()[0]
    suffixes = [[root[0]], [root[1]], [root[0], root[1]]]
    want = plain.rollout_many(suffixes, depth=5, salts=[9, 10, 11])
    plain.close()

    spec = TPUTokenSearchSession(backend, make_spec(speculative=True))
    root2 = spec.propose()[0]
    assert [c.token_id for c in root2] == [c.token_id for c in root]
    suffixes2 = [[root2[0]], [root2[1]], [root2[0], root2[1]]]
    got = spec.rollout_many(suffixes2, depth=5, salts=[9, 10, 11])
    # Determinism across repeat speculative calls (proposer state grew).
    again = spec.rollout_many(suffixes2, depth=5, salts=[9, 10, 11])
    spec.close()

    for i, (g, w) in enumerate(zip(got, want)):
        assert g[0] == w[0], f"path {i}: token ids diverged"
        assert g[1] == w[1], f"path {i}: text diverged"
        np.testing.assert_allclose(g[2], w[2], atol=2e-3)
        assert g[3] == w[3]
    assert [r[0] for r in again] == [r[0] for r in got]


def test_spec_rollouts_emit_draft_counters(backend):
    from consensus_tpu.obs.metrics import diff_snapshots

    reg = backend.instruments.registry
    before = reg.snapshot()
    spec = TPUTokenSearchSession(backend, make_spec(speculative=True))
    root = spec.propose()[0]
    spec.rollout_many([[root[0]], [root[1]]], depth=4, salts=[1, 2])
    spec.close()
    delta = diff_snapshots(before, reg.snapshot())

    def total(name):
        family = (delta.get("families") or {}).get(name) or {}
        return sum(s.get("value", 0) for s in family.get("series", []))

    proposed = total("spec_draft_proposed_tokens_total")
    verified = total("spec_draft_verified_tokens_total")
    assert proposed > 0
    assert 0 <= verified <= proposed


@pytest.mark.parametrize("method,cfg", [
    ("mcts", {"num_simulations": 3, "expansion_sample_width": 2,
              "max_tokens": 3, "rollout_depth": 3, "seed": 6}),
    ("finite_lookahead", {"branching_factor": 2, "max_depth": 2,
                          "max_tokens": 3, "rollout_depth": 3, "seed": 9}),
])
def test_method_statement_identical_spec_on_off(backend, method, cfg):
    from consensus_tpu.methods import get_method_generator

    plain = get_method_generator(
        method, backend, dict(cfg)
    ).generate_statement(ISSUE, OPINIONS)
    spec = get_method_generator(
        method, backend, {**cfg, "speculative_rollouts": True}
    ).generate_statement(ISSUE, OPINIONS)
    assert spec == plain


def test_finite_lookahead_rollout_depth_zero_is_unchanged(backend):
    """rollout_depth is opt-in: the default config must take the exact
    pre-change path (no rollout dispatches at all)."""
    from consensus_tpu.methods import get_method_generator

    cfg = {"branching_factor": 2, "max_depth": 2, "max_tokens": 2, "seed": 4}
    a = get_method_generator(
        method := "finite_lookahead", backend, dict(cfg)
    ).generate_statement(ISSUE, OPINIONS)
    b = get_method_generator(
        method, backend, {**cfg, "rollout_depth": 0}
    ).generate_statement(ISSUE, OPINIONS)
    assert a == b


def test_fallback_session_accepts_speculative_flag():
    """The cacheless fallback session ignores ``speculative`` (its rollout
    is already one batched generate) — methods must run unchanged on
    backends without a TPU session."""
    from consensus_tpu.backends.fake import FakeBackend
    from consensus_tpu.methods import get_method_generator

    cfg = {"num_simulations": 2, "expansion_sample_width": 2,
           "max_tokens": 2, "rollout_depth": 2, "seed": 1}
    plain = get_method_generator(
        "mcts", FakeBackend(), dict(cfg)
    ).generate_statement(ISSUE, OPINIONS)
    spec = get_method_generator(
        "mcts", FakeBackend(), {**cfg, "speculative_rollouts": True}
    ).generate_statement(ISSUE, OPINIONS)
    assert spec == plain
