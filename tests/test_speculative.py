"""Speculative rollout verification: drafts, rejection, and equivalence.

The speculative path (backends/speculative.py:NGramProposer +
models/stepper.rollout_verify_many + the session's _rollout_many_spec loop)
must be invisible in results: token streams identical to the sequential
rollout scan, agent totals to float tolerance (the one-pass verify
projects logits at a different matmul shape than the step-by-step scan —
same contract the batched rollout tests pin for rollout_many vs
rollout_from), and whole-method statements byte-identical with
``speculative_rollouts`` on vs off.
"""

import numpy as np
import pytest

from consensus_tpu.backends.session import SearchSpec
from consensus_tpu.backends.speculative import NGramProposer
from consensus_tpu.backends.tpu import TPUBackend, TPUTokenSearchSession

ISSUE = "Should the town build a new library?"
OPINIONS = {
    "Agent 1": "Yes, libraries anchor the community.",
    "Agent 2": "Only if it does not raise taxes.",
}


# ---------------------------------------------------------------------------
# Host-side proposer (no model, no jax)
# ---------------------------------------------------------------------------


class TestNGramProposer:
    def test_draft_replays_observed_pattern(self):
        p = NGramProposer(max_order=3)
        p.observe([1, 2, 3, 4, 1, 2, 3])
        # Longest suffix (2, 3) was followed by 4; then (3, 4) by 1, ...
        assert p.draft([1, 2, 3], 4) == [4, 1, 2, 3]

    def test_latest_occurrence_wins(self):
        p = NGramProposer(max_order=2)
        p.observe([5, 6, 7])  # (5, 6) -> 7
        p.observe([5, 6, 9])  # (5, 6) -> 9 overwrites
        assert p.draft([5, 6], 1) == [9]

    def test_longest_order_preferred(self):
        p = NGramProposer(max_order=3)
        p.observe([1, 2, 3, 8])  # (1,2,3)->8, (2,3)->8, (3,)->8
        p.observe([9, 2, 3, 4])  # (9,2,3)->4, (2,3)->4, (3,)->4
        # Order-3 context (1, 2, 3) still remembers 8 even though the
        # order-2 table was overwritten with 4.
        assert p.draft([1, 2, 3], 1) == [8]
        assert p.draft([7, 2, 3], 1) == [4]

    def test_unseen_context_repeats_last_token(self):
        p = NGramProposer()
        p.observe([1, 2])
        assert p.draft([40, 41], 3) == [41, 41, 41]
        assert p.draft([], 2) == [0, 0]

    def test_deterministic_across_instances(self):
        history = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5]
        a, b = NGramProposer(), NGramProposer()
        a.observe(history)
        b.observe(history)
        assert a.draft([5, 3, 5], 6) == b.draft([5, 3, 5], 6)


# ---------------------------------------------------------------------------
# Device verify path vs the sequential scan (tiny real model)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def backend():
    return TPUBackend(model="tiny-gemma2", dtype="float32", max_context=256)


def make_spec(**kw):
    defaults = dict(
        ref_system="You draft consensus statements.",
        ref_user="Issue: taxes.\nOpinions: A wants more, B wants less."
                 "\nStatement:",
        agent_prompts=(
            ("Agent context.", "Opinion: A wants more.\nStatement:"),
            ("Agent context.", "Opinion: B wants less.\nStatement:"),
        ),
        n_slots=1, k=3, temperature=1.0, seed=11, sample=False, max_steps=8,
    )
    defaults.update(kw)
    return SearchSpec(**defaults)


def test_spec_rollouts_match_sequential_scan(backend):
    """Speculative rollout_many == plain rollout_many: exact ids and text
    (the rejection construction replays every sampling decision), totals
    to float tolerance."""
    plain = TPUTokenSearchSession(backend, make_spec())
    root = plain.propose()[0]
    suffixes = [[root[0]], [root[1]], [root[0], root[1]]]
    want = plain.rollout_many(suffixes, depth=5, salts=[9, 10, 11])
    plain.close()

    spec = TPUTokenSearchSession(backend, make_spec(speculative=True))
    root2 = spec.propose()[0]
    assert [c.token_id for c in root2] == [c.token_id for c in root]
    suffixes2 = [[root2[0]], [root2[1]], [root2[0], root2[1]]]
    got = spec.rollout_many(suffixes2, depth=5, salts=[9, 10, 11])
    # Determinism across repeat speculative calls (proposer state grew).
    again = spec.rollout_many(suffixes2, depth=5, salts=[9, 10, 11])
    spec.close()

    for i, (g, w) in enumerate(zip(got, want)):
        assert g[0] == w[0], f"path {i}: token ids diverged"
        assert g[1] == w[1], f"path {i}: text diverged"
        np.testing.assert_allclose(g[2], w[2], atol=2e-3)
        assert g[3] == w[3]
    assert [r[0] for r in again] == [r[0] for r in got]


def test_spec_rollouts_emit_draft_counters(backend):
    from consensus_tpu.obs.metrics import diff_snapshots

    reg = backend.instruments.registry
    before = reg.snapshot()
    spec = TPUTokenSearchSession(backend, make_spec(speculative=True))
    root = spec.propose()[0]
    spec.rollout_many([[root[0]], [root[1]]], depth=4, salts=[1, 2])
    spec.close()
    delta = diff_snapshots(before, reg.snapshot())

    def total(name):
        family = (delta.get("families") or {}).get(name) or {}
        return sum(s.get("value", 0) for s in family.get("series", []))

    proposed = total("spec_draft_proposed_tokens_total")
    verified = total("spec_draft_verified_tokens_total")
    assert proposed > 0
    assert 0 <= verified <= proposed


@pytest.mark.parametrize("method,cfg", [
    ("mcts", {"num_simulations": 3, "expansion_sample_width": 2,
              "max_tokens": 3, "rollout_depth": 3, "seed": 6}),
    ("finite_lookahead", {"branching_factor": 2, "max_depth": 2,
                          "max_tokens": 3, "rollout_depth": 3, "seed": 9}),
])
def test_method_statement_identical_spec_on_off(backend, method, cfg):
    from consensus_tpu.methods import get_method_generator

    plain = get_method_generator(
        method, backend, dict(cfg)
    ).generate_statement(ISSUE, OPINIONS)
    spec = get_method_generator(
        method, backend, {**cfg, "speculative_rollouts": True}
    ).generate_statement(ISSUE, OPINIONS)
    assert spec == plain


def test_finite_lookahead_rollout_depth_zero_is_unchanged(backend):
    """rollout_depth is opt-in: the default config must take the exact
    pre-change path (no rollout dispatches at all)."""
    from consensus_tpu.methods import get_method_generator

    cfg = {"branching_factor": 2, "max_depth": 2, "max_tokens": 2, "seed": 4}
    a = get_method_generator(
        method := "finite_lookahead", backend, dict(cfg)
    ).generate_statement(ISSUE, OPINIONS)
    b = get_method_generator(
        method, backend, {**cfg, "rollout_depth": 0}
    ).generate_statement(ISSUE, OPINIONS)
    assert a == b


def test_fallback_session_accepts_speculative_flag():
    """The cacheless fallback session ignores ``speculative`` (its rollout
    is already one batched generate) — methods must run unchanged on
    backends without a TPU session."""
    from consensus_tpu.backends.fake import FakeBackend
    from consensus_tpu.methods import get_method_generator

    cfg = {"num_simulations": 2, "expansion_sample_width": 2,
           "max_tokens": 2, "rollout_depth": 2, "seed": 1}
    plain = get_method_generator(
        "mcts", FakeBackend(), dict(cfg)
    ).generate_statement(ISSUE, OPINIONS)
    spec = get_method_generator(
        "mcts", FakeBackend(), {**cfg, "speculative_rollouts": True}
    ).generate_statement(ISSUE, OPINIONS)
    assert spec == plain


# ---------------------------------------------------------------------------
# Engine-native speculative decoding: draft-and-verify in the K-step
# serving window (``engine_options={"speculative": true}``)
# ---------------------------------------------------------------------------

#: Same small-but-real per-method params the engine byte-identity matrix
#: in test_engine.py uses (kept in sync by eye — any drift fails both).
ENGINE_METHOD_PARAMS = {
    "zero_shot": {"seed": 42, "max_tokens": 30},
    "predefined": {"predefined_statement": "Exactly this statement."},
    "best_of_n": {"num_best_of_n": 4, "seed": 7, "max_tokens": 24},
    "beam_search": {"beam_width": 2, "max_tokens": 6, "seed": 5},
    "finite_lookahead": {
        "branching_factor": 2, "max_depth": 2, "max_tokens": 5, "seed": 9,
    },
    "mcts": {
        "num_simulations": 4, "expansion_sample_width": 3, "max_tokens": 4,
        "rollout_depth": 3, "seed": 2,
    },
    "habermas_machine": {
        "num_candidates": 3, "num_rounds": 1, "seed": 42, "max_tokens": 64,
    },
}

ENGINE_ISSUE = "Should the city invest in more bike lanes?"
ENGINE_OPINIONS = {
    "Agent 1": "Bike lanes make streets safer and should be expanded.",
    "Agent 2": "Road space is scarce; cars and buses need priority.",
    "Agent 3": "Invest only where cycling demand is proven.",
}


class TestEngineSpecByteIdentity:
    """Speculative decoding must be invisible in engine results: spec-on
    == spec-off == legacy solo for every method and every K (spec-off ==
    solo is already pinned by the PR 15 matrix; this anchors spec-on to
    the same solo baseline)."""

    @pytest.mark.parametrize("method", sorted(ENGINE_METHOD_PARAMS))
    def test_spec_engine_matches_legacy_all_methods(self, method):
        from consensus_tpu.backends.batching import BatchingBackend
        from consensus_tpu.backends.fake import FakeBackend
        from consensus_tpu.methods import get_method_generator

        params = ENGINE_METHOD_PARAMS[method]
        solo = get_method_generator(
            method, FakeBackend(), dict(params)
        ).generate_statement(ENGINE_ISSUE, ENGINE_OPINIONS)

        for k in (1, 4, 8):
            engined = BatchingBackend(
                FakeBackend(), engine=True,
                engine_options={"slots": 4, "num_pages": 512,
                                "decode_steps": k, "speculative": True},
            )
            try:
                via_engine = get_method_generator(
                    method, engined, dict(params)
                ).generate_statement(ENGINE_ISSUE, ENGINE_OPINIONS)
                stats = engined.engine.stats()
            finally:
                engined.close()
            assert via_engine == solo, f"{method}: spec K={k} diverged"
            spec = stats["speculative"]
            assert spec["enabled"]
            assert spec["proposed_tokens"] >= spec["accepted_tokens"] >= 0

    def test_spec_engine_exports_draft_counters(self):
        from consensus_tpu.backends.batching import BatchingBackend
        from consensus_tpu.backends.fake import FakeBackend
        from consensus_tpu.methods import get_method_generator
        from consensus_tpu.obs.metrics import diff_snapshots

        inner = FakeBackend()
        before = inner.instruments.registry.snapshot()
        engined = BatchingBackend(
            inner, engine=True,
            engine_options={"slots": 4, "num_pages": 512,
                            "decode_steps": 4, "speculative": True},
        )
        try:
            get_method_generator(
                "zero_shot", engined, {"seed": 42, "max_tokens": 30}
            ).generate_statement(ENGINE_ISSUE, ENGINE_OPINIONS)
            stats = engined.engine.stats()
        finally:
            engined.close()
        delta = diff_snapshots(before, inner.instruments.registry.snapshot())

        def total(name):
            family = (delta.get("families") or {}).get(name) or {}
            return sum(s.get("value", 0) for s in family.get("series", []))

        proposed = total("spec_draft_proposed_tokens_total")
        verified = total("spec_draft_verified_tokens_total")
        assert proposed > 0
        assert 0 <= verified <= proposed
        # The engine's stats aggregate the same stream counters.
        assert stats["speculative"]["proposed_tokens"] == proposed
        assert stats["speculative"]["accepted_tokens"] == verified
        # Ledger attribution mirrors the totals.
        mfu = stats["mfu_attribution"]
        assert mfu["draft_proposed_tokens"] == proposed
        assert mfu["draft_accepted_tokens"] == verified


def _drain_stream(stream):
    """Drive a generate stream to completion; returns (results, windows)."""
    results, windows = {}, 0
    while not stream.finished:
        stream.dispatch()
        _, finished = stream.collect()
        results.update(finished)
        windows += 1
        assert windows < 200, "stream failed to drain"
    stream.close()
    return results, windows


class TestSpecStreamTPU:
    """The speculative serving stream on the tiny real model: accepted
    prefixes and corrections must reproduce the sequential scan's sampling
    decisions bit-for-bit."""

    COHORT = (
        ("Say something about apples.", 11, 12, 0.8),
        ("Hi", 22, 5, 0.0),
        ("A longer prompt that should span several pages of the stream "
         "pool for testing purposes.", 33, 20, 0.9),
    )

    def _requests(self):
        from consensus_tpu.backends.base import GenerationRequest

        return [
            GenerationRequest(
                user_prompt=prompt, seed=seed, max_tokens=mt, temperature=t,
            )
            for prompt, seed, mt, t in self.COHORT
        ]

    def test_spec_stream_byte_identical_to_legacy(self, backend):
        legacy = backend.generate(self._requests())
        for k in (1, 4):
            stream = backend.generate_stream(
                self._requests(), decode_steps=k, speculative=True,
            )
            results, _ = _drain_stream(stream)
            got = [
                (results[i].text, results[i].token_ids,
                 results[i].finish_reason)
                for i in range(len(self.COHORT))
            ]
            assert got == [
                (r.text, r.token_ids, r.finish_reason) for r in legacy
            ], f"spec stream K={k} diverged from legacy"

    def test_accepted_prefix_and_correction_exact(self, backend):
        """A greedy row on a self-similar prompt accepts drafts (the
        n-gram proposer replays the repetition) — and the output is STILL
        byte-identical: both the accepted prefix and the post-rejection
        correction token replay the sequential decisions exactly."""
        from consensus_tpu.backends.base import GenerationRequest

        req = lambda: [GenerationRequest(  # noqa: E731
            user_prompt="one two three one two three one two three "
                        "one two three",
            seed=1, max_tokens=40, temperature=0.0,
        )]
        legacy = backend.generate(req())
        stream = backend.generate_stream(
            req(), decode_steps=4, speculative=True,
        )
        results, windows = _drain_stream(stream)
        got = results[0]
        assert (got.text, got.token_ids, got.finish_reason) == (
            legacy[0].text, legacy[0].token_ids, legacy[0].finish_reason
        )
        # Acceptance did real work: each window consumes 1 + accepted
        # sequential decisions, so accepted drafts shave exactly that many
        # dispatches off the 41-decision budget (40 emits + eos-check).
        assert stream.spec_accepted > 0
        assert stream.spec_proposed >= stream.spec_accepted
        assert windows <= 41 - stream.spec_accepted + 1

    def test_eos_inside_accepted_draft_freezes_row(self, backend):
        """A row that samples EOS mid-window freezes there: the result
        matches the sequential truncation, and once the row is done every
        later window's writes land in the sink — its pool pages stay
        byte-identical while a co-resident row keeps decoding."""
        import numpy as np

        from consensus_tpu.backends.base import GenerationRequest

        probe = _drain_stream(
            backend.generate_stream(
                [GenerationRequest(
                    user_prompt="freeze me", seed=5, max_tokens=8,
                    temperature=0.0,
                )],
                decode_steps=1,
            )
        )[0][0]
        assert len(probe.token_ids) == 8
        # Declare EOS the first continuation token that has no earlier
        # occurrence (an earlier repeat would truncate the probe itself).
        cut = next(
            (t for t in (2, 3, 4, 5, 6, 1)
             if probe.token_ids[t] not in probe.token_ids[:t]),
            None,
        )
        if cut is None:
            pytest.skip("greedy continuation repeats every candidate EOS")
        eos_token = probe.token_ids[cut]

        requests = [
            GenerationRequest(
                user_prompt="freeze me", seed=5, max_tokens=8,
                temperature=0.0,
            ),
            GenerationRequest(
                user_prompt="keep decoding for a good while longer",
                seed=77, max_tokens=24, temperature=0.9,
            ),
        ]
        original_eos = backend.tokenizer.eos_ids
        backend.tokenizer.eos_ids = (int(eos_token),)
        try:
            stream = backend.generate_stream(
                requests, decode_steps=4, speculative=True,
            )
            tables = np.asarray(stream._tables)
            row0_pages = [int(p) for p in tables[0] if p >= 0]
            results, frozen_snapshot = {}, None
            windows = 0
            while not stream.finished:
                stream.dispatch()
                _, finished = stream.collect()
                results.update(finished)
                windows += 1
                assert windows < 200
                if 0 in results and frozen_snapshot is None:
                    frozen_snapshot = np.asarray(
                        stream._state.k_pages[:, row0_pages]
                    ).copy()
                    frozen_len = int(np.asarray(stream._lengths)[0])
            final_pages = np.asarray(stream._state.k_pages[:, row0_pages])
            final_len = int(np.asarray(stream._lengths)[0])
            stream.close()
        finally:
            backend.tokenizer.eos_ids = original_eos

        assert results[0].finish_reason == "stop"
        assert results[0].token_ids == probe.token_ids[:cut]
        assert 1 in results  # the co-resident row drained too
        # Row 0 froze before the stream did (its EOS came early)...
        assert frozen_snapshot is not None
        assert final_len == frozen_len
        # ...and every post-freeze window wrote its row-0 columns to the
        # sink: the row's pool pages never changed again.
        np.testing.assert_array_equal(frozen_snapshot, final_pages)

    def test_dp4_matches_dp1_through_spec_stream(self):
        """Sharding the spec stream's slot axis over data must not change
        a single emitted token (conftest provides 8 virtual CPU devices)."""
        from consensus_tpu.backends.base import GenerationRequest

        def run(dp):
            be = TPUBackend(
                model="tiny-gemma2", max_context=128, base_seed=7, dp=dp,
            )
            requests = [
                GenerationRequest(
                    user_prompt=f"device parallel prompt {i}", seed=100 + i,
                    max_tokens=6 + i, temperature=0.7,
                )
                for i in range(4)
            ]
            results = _drain_stream(
                be.generate_stream(
                    requests, decode_steps=4, speculative=True,
                )
            )[0]
            return [
                (results[i].text, results[i].token_ids,
                 results[i].finish_reason)
                for i in range(4)
            ]

        assert run(1) == run(4)


class TestVerifyKernelPageBoundary:
    """Kernel-level write discipline: a fully-accepted verify window that
    crosses a page boundary writes only pages the cursors name — rows
    adopting shared prefix pages leave the shared bytes untouched."""

    def test_accepted_window_crosses_boundary_spares_shared_pages(self):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from consensus_tpu.models import stepper
        from consensus_tpu.models.config import get_model_config
        from consensus_tpu.models.transformer import (
            init_params,
            project_logits,
        )

        cfg = get_model_config("tiny-gemma2")
        params = init_params(cfg, jax.random.PRNGKey(0))
        rng = np.random.RandomState(3)
        prompt = rng.randint(1, cfg.vocab_size, size=(8,)).astype(np.int32)
        page_size, max_blocks = 4, 8
        # Pages: 0-1 shared prompt, 2-3 row0 private, 4-5 row1 private.
        num_pages, sink = 6, 6

        def prefill():
            state = stepper.make_page_state(
                cfg, num_pages, page_size, jnp.float32
            )
            tables = np.full((2, max_blocks), -1, np.int32)
            tables[0, :4] = [0, 1, 2, 3]
            tables[1, :4] = [0, 1, 4, 5]  # adopts the shared prompt pages
            tok = np.zeros((2, 8), np.int32)
            cval = np.zeros((2, 8), bool)
            wp = np.full((2, 8), sink, np.int32)
            wo = np.zeros((2, 8), np.int32)
            tok[0] = prompt
            cval[0] = True
            for t in range(8):
                wp[0, t] = t // page_size
                wo[0, t] = t % page_size
            hidden, state = stepper.paged_prefill_chunk(
                params, cfg, jnp.asarray(tok), jnp.asarray(cval), state,
                jnp.asarray(tables), jnp.asarray([8, 0], np.int32),
                jnp.asarray(wp), jnp.asarray(wo),
            )
            logits0 = project_logits(params, cfg, hidden)
            logits = jnp.stack([logits0[0], logits0[0]])
            return state, jnp.asarray(tables), logits

        # Sequential ground truth: 6 greedy tokens through the K-step scan
        # (state donated, so prefill fresh for the verify run below).
        state, tables, logits = prefill()
        keys = jax.vmap(jax.random.PRNGKey)(jnp.asarray([1, 2], jnp.uint32))
        seq = stepper.paged_decode_steps(
            params, cfg, logits, state, tables,
            jnp.asarray([8, 8], np.int32), keys,
            jnp.zeros(2, bool), jnp.asarray([6, 6], np.int32),
            jnp.zeros(2, bool),
            temperature=jnp.zeros(2, jnp.float32), num_steps=8,
        )
        greedy = np.asarray(seq[0])[0][np.asarray(seq[1])[0]].tolist()
        assert len(greedy) == 6

        # Verify window 1 (no pending): a PERFECT K=4 draft — the window
        # accepts all 4 and emits the bonus token, crossing the page-2
        # boundary (length 8 -> 12) in one dispatch.
        state, tables, logits = prefill()
        shared_before = np.asarray(state.k_pages[:, :2]).copy()
        keys = jax.vmap(jax.random.PRNGKey)(jnp.asarray([1, 2], jnp.uint32))
        drafts = jnp.asarray(
            np.stack([greedy[:4], greedy[:4]]).astype(np.int32)
        )
        out = stepper.paged_verify_steps(
            params, cfg, logits, state, tables,
            jnp.asarray([8, 8], np.int32), keys,
            jnp.zeros(2, bool), jnp.asarray([6, 6], np.int32),
            jnp.zeros(2, bool),
            temperature=jnp.zeros(2, jnp.float32),
            draft_tokens=drafts,
            pending=jnp.zeros(2, jnp.int32),
            num_steps=4, has_pending=False,
        )
        (tokens, emitted, accepted, pending, state, lengths, keys, done,
         budgets, hit_eos, _) = out
        np.testing.assert_array_equal(np.asarray(tokens)[0], greedy[:5])
        np.testing.assert_array_equal(np.asarray(emitted), True)
        np.testing.assert_array_equal(np.asarray(accepted), [4, 4])
        np.testing.assert_array_equal(np.asarray(pending), [greedy[4]] * 2)
        np.testing.assert_array_equal(np.asarray(lengths), [12, 12])
        np.testing.assert_array_equal(np.asarray(done), [False, False])

        # Verify window 2 (pending column): one budgeted token left — the
        # pending K/V lands, the last token emits, the row retires.
        out = stepper.paged_verify_steps(
            params, cfg, None, state, tables, lengths, keys, done,
            budgets, hit_eos,
            temperature=jnp.zeros(2, jnp.float32),
            draft_tokens=drafts, pending=pending,
            num_steps=4, has_pending=True,
        )
        (tokens, emitted, accepted, pending, state, lengths, keys, done,
         budgets, hit_eos, _) = out
        tokens, emitted = np.asarray(tokens), np.asarray(emitted)
        assert tokens[0][emitted[0]].tolist() == [greedy[5]]
        assert emitted.sum(axis=1).tolist() == [1, 1]
        if not bool(np.asarray(done)[0]):
            # The stale draft column missed, so the row's decision chain
            # ended on the budget-spending emit: the eos-check (the 41st
            # sequential split, which latches done) lands at the NEXT
            # window's first decision — exactly like the sequential scan's
            # one extra sample at budgets == 0.
            np.testing.assert_array_equal(np.asarray(lengths), [13, 13])
            out = stepper.paged_verify_steps(
                params, cfg, None, state, tables, lengths, keys, done,
                budgets, hit_eos,
                temperature=jnp.zeros(2, jnp.float32),
                draft_tokens=drafts, pending=pending,
                num_steps=4, has_pending=True,
            )
            (tokens, emitted, accepted, pending, state, lengths, keys,
             done, budgets, hit_eos, _) = out
            assert np.asarray(emitted).sum() == 0
        # Either way both rows land done at length 14: prompt 8 + the
        # 6-token budget, every emitted token's K/V written exactly once.
        np.testing.assert_array_equal(np.asarray(lengths), [14, 14])
        np.testing.assert_array_equal(np.asarray(done), [True, True])

        # Shared prompt pages: byte-identical after both windows; the two
        # rows' private continuation K/V bytes match (same tokens, same
        # positions, own pages).
        kp = np.asarray(state.k_pages)
        np.testing.assert_array_equal(shared_before, kp[:, :2])
        np.testing.assert_array_equal(kp[:, 2:4], kp[:, 4:6])
