"""tp=2 FULL-pipeline pin (VERDICT r4 #6).

tests/test_dp_pipeline.py pins dp=8 == dp=1 at the sweep surface;
tests/test_parallel.py pins tp at the session level only.  TP is the
stated answer for models past one chip's HBM (8B+ bf16, 27B-class), so
the same end-to-end guarantee must hold: one north-star config (real
structure — habermas + best_of_n Cartesian grids, shared scoring — at
test scale on the tiny model over virtual CPU devices) runs through the
full ``run_experiment_with_eval`` pipeline at tp=2 (model sharded over 2
devices) and at tp=2 x dp=4 (both mesh axes), and every artifact CSV
must agree with the unsharded tp=1 run: results.csv statements
byte-identical; metric columns to 1e-4 relative.  Unlike dp (row
sharding — per-row math untouched, pinned exact at 1e-6), tp SPLITS each
matmul's contraction over devices and psums the partials, so float32
reduction order legitimately differs; observed drift is ~2.5e-6 relative
on aggregated std columns (cancellation-amplified), with every greedy
token decision — hence every statement — identical.
"""

import pathlib

import pandas as pd
import yaml

NORTH_STAR = pathlib.Path(
    "configs/north_star/gemma/scenario_1/habermas_vs_best_of_n.yaml"
)


def _run(tmp_path, tag: str, tp: int, dp: int) -> pathlib.Path:
    from consensus_tpu.cli.run_experiment_with_eval import run_pipeline

    config = yaml.safe_load(NORTH_STAR.read_text())
    config["num_seeds"] = 2
    config["backend_options"].update(
        {"model": "tiny-gemma2", "dtype": "float32", "max_context": 256,
         "quantization": None, "tp": tp, "dp": dp}
    )
    config["models"] = {
        "generation_model": "tiny-gemma2",
        "evaluation_models": ["tiny-gemma2"],
    }
    config["best_of_n"].update({"n": [1, 3], "max_tokens": 24})
    config["habermas_machine"].update(
        {"num_candidates": [1, 2], "max_tokens": 48}
    )
    config["experiment_name"] = f"tp_pipeline_{tag}"
    config["output_dir"] = str(tmp_path / tag)
    cfg_path = tmp_path / f"{tag}.yaml"
    cfg_path.write_text(yaml.safe_dump(config))
    return pathlib.Path(
        run_pipeline(str(cfg_path), skip_comparative_ranking=True)
    )


#: TP changes matmul reduction order (psum over shards): float32 metrics
#: drift ~1e-6 relative, amplified by cancellation in aggregated _std
#: columns.  Statements stay byte-identical (greedy argmax margins dwarf
#: the drift at test scale), so only metric columns get this tolerance.
TP_ATOL = 1e-5
TP_RTOL = 1e-4


def _assert_artifacts_equal(run_a: pathlib.Path, run_b: pathlib.Path) -> None:
    from consensus_tpu.utils.diff import statement_parity_report

    a = pd.read_csv(run_a / "results.csv")
    b = pd.read_csv(run_b / "results.csv")
    # Statement parity first, at token granularity: a reduction-order flake
    # flips ONE greedy argmax at ONE position, and this names it (row,
    # token index, both tokens) instead of dumping both frames.
    parity = statement_parity_report(
        a["statement"].fillna("").tolist(),
        b["statement"].fillna("").tolist(),
        run_a.name,
        run_b.name,
    )
    assert parity is None, parity
    pd.testing.assert_frame_equal(
        a.drop(columns=["generation_time_s"]),
        b.drop(columns=["generation_time_s"]),
    )

    for seed_dir in sorted((run_a / "evaluation" / "tiny-gemma2").iterdir()):
        eval_a = pd.read_csv(seed_dir / "evaluation_results.csv")
        eval_b = pd.read_csv(
            run_b / "evaluation" / "tiny-gemma2" / seed_dir.name
            / "evaluation_results.csv"
        )
        drop = [c for c in eval_a.columns if c.endswith("_time_s")]
        pd.testing.assert_frame_equal(
            eval_a.drop(columns=drop), eval_b.drop(columns=drop),
            check_exact=False, atol=TP_ATOL, rtol=TP_RTOL,
        )

    agg_a = pd.read_csv(
        run_a / "evaluation" / "improved_aggregate" / "aggregated_metrics.csv"
    )
    agg_b = pd.read_csv(
        run_b / "evaluation" / "improved_aggregate" / "aggregated_metrics.csv"
    )
    drop = [c for c in agg_a.columns if "time" in c]
    pd.testing.assert_frame_equal(
        agg_a.drop(columns=drop), agg_b.drop(columns=drop),
        check_exact=False, atol=TP_ATOL, rtol=TP_RTOL,
    )


def test_tp2_pipeline_artifacts_match_tp1(tmp_path):
    run_tp1 = _run(tmp_path, "tp1", tp=1, dp=1)
    run_tp2 = _run(tmp_path, "tp2", tp=2, dp=1)
    _assert_artifacts_equal(run_tp1, run_tp2)

    # Both mesh axes live at once: tp=2 model sharding x dp=4 row sharding
    # (the full 8-virtual-device grid) must still match unsharded artifacts.
    run_tp2dp4 = _run(tmp_path, "tp2dp4", tp=2, dp=4)
    _assert_artifacts_equal(run_tp1, run_tp2dp4)
