"""Serving-level prefix KV cache smoke: repeated-scenario load hits the
cache, statements stay byte-identical with it on vs off, and the obs
families / report keys surface the win.  This is the tier-1 CI
"prefix smoke" step (hardware-free: fake backend, in-process server).
"""

from typing import Any, Dict, List

import pytest

from consensus_tpu.obs.metrics import Registry
from consensus_tpu.serve import create_server
from consensus_tpu.serve.loadgen import run_loadgen, scenario_requests

PARAMS = {"num_best_of_n": 2, "max_tokens": 16}


def _family_total(registry: Registry, name: str) -> float:
    family = registry.snapshot()["families"].get(name) or {}
    return sum(s.get("value", 0) for s in family.get("series", ()))


def _serve(payloads: List[Dict[str, Any]], **engine_options):
    registry = Registry()
    server = create_server(
        backend="fake", port=0, max_inflight=4,
        engine=True,
        engine_options={"slots": 4, "num_pages": 512, **engine_options},
        registry=registry,
    ).start()
    try:
        report = run_loadgen(server.base_url, payloads, rate_rps=200.0)
    finally:
        server.stop()
    return report, registry


def test_repeated_scenario_load_hits_prefix_cache():
    payloads = scenario_requests(
        12, method="best_of_n", params=PARAMS, scenario_repeat="fixed:2"
    )
    report, registry = _serve(payloads, prefix_cache=True)
    assert report["availability"] == 1.0
    assert report["prefix_cache"]["hits"] > 0
    assert report["prefix_hit_fraction"] > 0.5
    assert _family_total(registry, "prefix_cache_hits_total") > 0
    assert _family_total(registry, "prefix_tokens_saved_total") > 0


def test_statements_byte_identical_cache_on_off():
    payloads = scenario_requests(
        10, method="best_of_n", params=PARAMS, scenario_repeat="zipf:1.2"
    )
    on, _ = _serve(payloads, prefix_cache=True)
    off, registry_off = _serve(payloads)
    assert on["availability"] == off["availability"] == 1.0
    by_id_on = {o.request_id: o.statement for o in on["outcomes"]}
    by_id_off = {o.request_id: o.statement for o in off["outcomes"]}
    assert by_id_on == by_id_off
    # The control run really ran cache-less.
    assert _family_total(registry_off, "prefix_cache_hits_total") == 0
    assert "prefix_hit_fraction" not in off


def test_scenario_repeat_validation():
    with pytest.raises(ValueError, match="scenario_repeat"):
        scenario_requests(4, scenario_repeat="bogus")
    fixed = scenario_requests(6, scenario_repeat="fixed:1")
    assert len({p["issue"] for p in fixed}) == 1
    zipf = scenario_requests(50, scenario_repeat="zipf:2.0")
    assert len({p["issue"] for p in zipf}) >= 1
    # Deterministic: same seed, same mix.
    again = scenario_requests(50, scenario_repeat="zipf:2.0")
    assert [p["issue"] for p in zipf] == [p["issue"] for p in again]
