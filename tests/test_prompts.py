"""Prompt-template parity tests (VERDICT r1 #10).

Pins each decoder's templates to the reference's exact strings — the
welfare numbers are sensitive to them (SURVEY §7.3):
best_of_n.py:29-35, beam_search.py:58-80, finite_lookahead.py:20-34,
mcts.py:55-77, opinions block best_of_n.py:89-94.
"""

from consensus_tpu.methods.prompts import (
    agent_prompt,
    format_opinions,
    reference_prompt,
)

ISSUE = "Should X happen?"
OPINIONS = {"Agent 1": "Yes.", "Agent 2": "No."}


def test_opinions_block_is_reference_format():
    assert format_opinions(OPINIONS) == "Participant 1: Yes.\n\nParticipant 2: No."


def test_best_of_n_templates():
    system, user = reference_prompt(ISSUE, OPINIONS, variant="best_of_n")
    assert user == (
        "Issue: Should X happen?\n\n"
        "Participants' opinions:\n"
        "Participant 1: Yes.\n\nParticipant 2: No.\n\n"
        "Consensus statement (less than 50 tokens): "
    )
    assert system.startswith(
        "You are generating a consensus statement that represents the views "
        "of multiple participants.\n"
    )
    assert system.endswith("ONLY WRITE THE STATEMENT AND NOTHING ELSE.")

    a_system, a_user = agent_prompt(ISSUE, "Yes.", variant="best_of_n")
    assert a_user == (
        "Issue: Should X happen?\n\n"
        "Agent's opinion:\nYes.\n\n"
        "Statement reflecting this opinion (less than 50 tokens): "
    )
    assert a_system.startswith(
        "You are generating a statement that represents the views of a "
        "single participant.\n"
    )


def test_beam_search_newline_form_and_participant_wording():
    _, user = reference_prompt(ISSUE, OPINIONS, variant="beam_search")
    assert user.startswith("Issue:\nShould X happen?\n\n")
    assert user.endswith("Consensus statement (less than 50 tokens):\n")

    _, a_user = agent_prompt(ISSUE, "Yes.", variant="beam_search")
    assert "Participant's opinion:\nYes.\n\n" in a_user
    assert a_user.endswith(
        "Statement reflecting ONLY this participant's opinion "
        "(less than 50 tokens):\n"
    )


def test_finite_lookahead_mixes_newline_form_with_agent_wording():
    _, user = reference_prompt(ISSUE, OPINIONS, variant="finite_lookahead")
    assert user.startswith("Issue:\n")
    _, a_user = agent_prompt(ISSUE, "Yes.", variant="finite_lookahead")
    assert "Agent's opinion:\nYes.\n\n" in a_user
    assert a_user.endswith("Statement reflecting this opinion (less than 50 tokens):\n")


def test_mcts_coherent_system_and_no_token_cap():
    system, user = reference_prompt(ISSUE, OPINIONS, variant="mcts")
    assert "Be concise and coherent." in system
    assert "ONLY WRITE THE CONSENSUS STATEMENT AND NOTHING ELSE." in system
    assert "less than 50 tokens" not in user
    assert user.endswith("Consensus statement:\n")

    a_system, a_user = agent_prompt(ISSUE, "Yes.", variant="mcts")
    assert "Be concise and coherent." in a_system
    assert a_user.endswith("Statement reflecting ONLY this participant's opinion:\n")


def test_default_variant_is_best_of_n():
    assert reference_prompt(ISSUE, OPINIONS) == reference_prompt(
        ISSUE, OPINIONS, variant="best_of_n"
    )
