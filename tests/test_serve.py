"""Online serving subsystem tests (ISSUE 3).

Three layers, all on FakeBackend (hardware-free, deterministic):

* scheduler unit tests — admission rejection at capacity, deadline expiry,
  retry-then-succeed, graceful drain with no orphaned tickets;
* HTTP end-to-end — a real socket, ``POST /v1/consensus`` round-trip,
  ``/healthz`` and ``/metrics`` schema, structured JSON errors;
* the acceptance proof — N=16 concurrent open-loop clients against a
  capacity-bounded server: every accepted statement byte-identical to the
  same seeded request run serially through ``Experiment``, overload
  explicitly rejected, and device-batch accounting showing concurrent
  requests coalesced into fewer device calls than serial execution.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from consensus_tpu.backends.fake import FakeBackend
from consensus_tpu.obs.metrics import Registry
from consensus_tpu.serve import (
    ConsensusRequest,
    ConsensusServer,
    ConsensusService,
    RequestScheduler,
    RequestTimeout,
    RequestValidationError,
    SchedulerRejected,
    create_server,
    parse_request,
)

ISSUE = "Should we invest in public transport?"
OPINIONS = {
    "Agent 1": "Yes, buses and trains are vital public goods.",
    "Agent 2": "Only alongside congestion pricing for cars.",
    "Agent 3": "Prefer cycling infrastructure over big rail projects.",
}
PARAMS = {"n": 4, "max_tokens": 24}


def _request(seed=7, **overrides):
    payload = {
        "issue": ISSUE,
        "agent_opinions": OPINIONS,
        "method": "best_of_n",
        "params": dict(PARAMS),
        "seed": seed,
        "evaluate": False,
    }
    payload.update(overrides)
    return parse_request(payload)


def _post(base_url, payload, timeout=30.0):
    """POST /v1/consensus; returns (status, decoded body)."""
    request = urllib.request.Request(
        base_url + "/v1/consensus",
        data=json.dumps(payload).encode("utf-8"),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(request, timeout=timeout) as response:
            return response.status, json.loads(response.read().decode())
    except urllib.error.HTTPError as exc:
        return exc.code, json.loads(exc.read().decode())


class SlowCountingBackend:
    """FakeBackend with a dispatch delay (forces request overlap so
    coalescing is deterministic in tests) and device-batch counters."""

    name = "slow-counting"

    def __init__(self, delay_s=0.02):
        self.inner = FakeBackend()
        self.delay_s = delay_s
        self.batches = {"generate": 0, "score": 0, "next_token": 0, "embed": 0}

    def _dispatch(self, kind, fn, requests):
        self.batches[kind] += 1
        time.sleep(self.delay_s)
        return fn(requests)

    def generate(self, requests):
        return self._dispatch("generate", self.inner.generate, requests)

    def score(self, requests):
        return self._dispatch("score", self.inner.score, requests)

    def next_token_logprobs(self, requests):
        return self._dispatch(
            "next_token", self.inner.next_token_logprobs, requests)

    def embed(self, texts):
        return self._dispatch("embed", self.inner.embed, texts)


# ---------------------------------------------------------------------------
# request validation
# ---------------------------------------------------------------------------


class TestParseRequest:
    def test_valid_round_trip(self):
        request = _request(seed=3, timeout_s=5, request_id="r-1")
        assert isinstance(request, ConsensusRequest)
        assert request.method == "best_of_n"
        assert request.seed == 3
        assert request.timeout_s == 5.0
        assert request.request_id == "r-1"

    def test_collects_every_error(self):
        with pytest.raises(RequestValidationError) as excinfo:
            parse_request({"issue": "", "agent_opinions": {},
                           "method": "nope", "seed": "x", "bogus": 1})
        errors = "\n".join(excinfo.value.errors)
        assert "'issue'" in errors
        assert "'agent_opinions'" in errors
        assert "'method'" in errors
        assert "'seed'" in errors
        assert "bogus" in errors

    def test_sweep_grid_params_rejected(self):
        """List-valued params are an offline sweep axis (the
        Experiment.expand_param_grid surface), not a single request."""
        with pytest.raises(RequestValidationError) as excinfo:
            _request(params={"n": [2, 4], "max_tokens": 24})
        assert "sweep" in str(excinfo.value)

    def test_non_object_body_rejected(self):
        with pytest.raises(RequestValidationError):
            parse_request([1, 2, 3])


# ---------------------------------------------------------------------------
# scheduler units
# ---------------------------------------------------------------------------


def _scheduler(handler, registry=None, **kwargs):
    kwargs.setdefault("max_queue_depth", 4)
    kwargs.setdefault("max_inflight", 1)
    kwargs.setdefault("default_timeout_s", 30.0)
    kwargs.setdefault("retry_backoff_s", 0.001)
    return RequestScheduler(
        handler, FakeBackend(),
        registry=registry if registry is not None else Registry(),
        **kwargs,
    )


def _counter_total(registry, name):
    family = registry.snapshot()["families"].get(name)
    if not family:
        return 0
    return sum(s["value"] for s in family["series"])


class TestSchedulerAdmission:
    def test_rejects_at_capacity_with_explicit_reason(self):
        release = threading.Event()
        entered = threading.Event()

        def handler(request, backend):
            entered.set()
            release.wait(10.0)
            return {"ok": True}

        registry = Registry()
        scheduler = _scheduler(
            handler, registry, max_inflight=1, max_queue_depth=2).start()
        try:
            running = scheduler.submit(_request(0))
            assert entered.wait(5.0)
            queued = [scheduler.submit(_request(i)) for i in (1, 2)]
            with pytest.raises(SchedulerRejected) as excinfo:
                scheduler.submit(_request(3))
            assert excinfo.value.reason == "queue_full"
            assert _counter_total(registry, "serve_rejected_total") == 1
            assert _counter_total(registry, "serve_accepted_total") == 3
            release.set()
            for ticket in [running] + queued:
                assert ticket.wait(10.0)
                assert ticket.result() == {"ok": True}
        finally:
            release.set()
            scheduler.shutdown()

    def test_draining_rejects_new_submissions(self):
        scheduler = _scheduler(lambda r, b: {"ok": True}).start()
        scheduler.shutdown(drain=True)
        with pytest.raises(SchedulerRejected) as excinfo:
            scheduler.submit(_request(0))
        assert excinfo.value.reason == "draining"


class TestSchedulerDeadlines:
    def test_queued_request_expires_at_deadline(self):
        release = threading.Event()
        entered = threading.Event()

        def handler(request, backend):
            entered.set()
            release.wait(10.0)
            return {"ok": True}

        registry = Registry()
        scheduler = _scheduler(handler, registry, max_inflight=1).start()
        try:
            blocker = scheduler.submit(_request(0))
            assert entered.wait(5.0)
            doomed = scheduler.submit(_request(1), timeout_s=0.05)
            time.sleep(0.1)  # let the deadline lapse while queued
            release.set()
            assert doomed.wait(10.0)
            assert doomed.outcome == "timeout"
            with pytest.raises(RequestTimeout):
                doomed.result()
            assert blocker.wait(10.0) and blocker.outcome == "ok"
            assert _counter_total(registry, "serve_timeout_total") == 1
        finally:
            release.set()
            scheduler.shutdown()

    def test_cancelled_ticket_reports_timeout(self):
        release = threading.Event()
        entered = threading.Event()

        def handler(request, backend):
            entered.set()
            release.wait(10.0)
            return {"ok": True}

        scheduler = _scheduler(handler, max_inflight=1).start()
        try:
            blocker = scheduler.submit(_request(0))
            assert entered.wait(5.0)
            abandoned = scheduler.submit(_request(1))
            abandoned.cancel()  # waiter gave up before it was popped
            release.set()
            assert abandoned.wait(10.0)
            assert abandoned.outcome == "timeout"
            assert blocker.wait(10.0)
        finally:
            release.set()
            scheduler.shutdown()


class TestSchedulerRetries:
    def test_transient_failure_retries_then_succeeds(self):
        attempts = []

        def handler(request, backend):
            attempts.append(1)
            if len(attempts) < 3:
                raise RuntimeError("transient backend wobble")
            return {"ok": True}

        registry = Registry()
        scheduler = _scheduler(handler, registry, max_retries=2).start()
        try:
            ticket = scheduler.submit(_request(0))
            assert ticket.wait(10.0)
            assert ticket.outcome == "ok"
            assert ticket.result() == {"ok": True}
            assert ticket.attempts == 3
            assert _counter_total(registry, "serve_retried_total") == 2
            assert _counter_total(registry, "serve_failed_total") == 0
        finally:
            scheduler.shutdown()

    def test_retries_are_bounded(self):
        def handler(request, backend):
            raise RuntimeError("permanently transient-looking")

        registry = Registry()
        scheduler = _scheduler(handler, registry, max_retries=2).start()
        try:
            ticket = scheduler.submit(_request(0))
            assert ticket.wait(10.0)
            assert ticket.outcome == "failed"
            assert ticket.attempts == 3  # 1 try + 2 retries
            with pytest.raises(RuntimeError):
                ticket.result()
            assert _counter_total(registry, "serve_failed_total") == 1
        finally:
            scheduler.shutdown()

    def test_validation_style_errors_never_retry(self):
        attempts = []

        def handler(request, backend):
            attempts.append(1)
            raise ValueError("bad method config")

        scheduler = _scheduler(handler, max_retries=5).start()
        try:
            ticket = scheduler.submit(_request(0))
            assert ticket.wait(10.0)
            assert ticket.outcome == "failed"
            assert len(attempts) == 1
        finally:
            scheduler.shutdown()


class TestSchedulerDrain:
    def test_drain_completes_everything_and_leaves_no_orphans(self):
        def handler(request, backend):
            time.sleep(0.01)
            return {"seed": request.seed}

        scheduler = _scheduler(
            handler, max_inflight=2, max_queue_depth=16).start()
        tickets = [scheduler.submit(_request(i)) for i in range(10)]
        scheduler.shutdown(drain=True, timeout=30.0)
        # Every ticket resolved with its own result — nothing orphaned.
        for i, ticket in enumerate(tickets):
            assert ticket.done()
            assert ticket.result() == {"seed": i}
        stats = scheduler.stats()
        assert stats["queue_depth"] == 0
        assert stats["inflight"] == 0
        assert stats["workers_alive"] == 0

    def test_non_drain_shutdown_fails_queued_tickets(self):
        release = threading.Event()
        entered = threading.Event()

        def handler(request, backend):
            entered.set()
            release.wait(10.0)
            return {"ok": True}

        scheduler = _scheduler(
            handler, max_inflight=1, max_queue_depth=8).start()
        running = scheduler.submit(_request(0))
        assert entered.wait(5.0)
        queued = [scheduler.submit(_request(i)) for i in (1, 2)]

        def finish_soon():
            time.sleep(0.05)
            release.set()

        threading.Thread(target=finish_soon, daemon=True).start()
        scheduler.shutdown(drain=False, timeout=30.0)
        # In-flight work completed; queued work failed fast and explicitly.
        assert running.result() == {"ok": True}
        for ticket in queued:
            assert ticket.done()
            with pytest.raises(SchedulerRejected):
                ticket.result()


class TestSchedulerCoalescing:
    def test_concurrent_requests_share_device_batches(self):
        """The scheduler's worker pool drives one shared BatchingBackend:
        in-flight requests' generate/score calls merge into wider device
        batches, so N requests cost far fewer than N× the solo dispatch
        count — the whole point of putting a scheduler in front of the
        batched engine."""
        inner = SlowCountingBackend(delay_s=0.02)
        service = ConsensusService(inner)
        registry = Registry()
        scheduler = RequestScheduler(
            service.run, inner,
            max_inflight=4, max_queue_depth=16,
            registry=registry, flush_ms=50.0,
        ).start()
        try:
            tickets = [scheduler.submit(_request(seed=100 + i))
                       for i in range(8)]
            for ticket in tickets:
                assert ticket.wait(60.0)
                assert ticket.outcome == "ok"
        finally:
            scheduler.shutdown()
        # Serial execution = 8 generate + 8 score dispatches; merged must
        # be strictly fewer on both kinds.
        assert inner.batches["generate"] < 8
        assert inner.batches["score"] < 8
        # Per-kind completion wakeups stay surgical under mixed-kind load
        # (ADVICE r5 item 4): nobody is woken while its request is pending.
        assert _counter_total(
            registry, "batching_spurious_wakeups_total") == 0


# ---------------------------------------------------------------------------
# service determinism vs the offline Experiment harness
# ---------------------------------------------------------------------------


def _experiment_statements(tmp_path, seeds, scenario_issue, opinions):
    """Serial (non-concurrent) Experiment runs: seed -> statement."""
    from consensus_tpu.experiment import Experiment

    config = {
        "experiment_name": "serve_parity",
        "output_dir": str(tmp_path / "exp"),
        "scenario": {"issue": scenario_issue, "agent_opinions": opinions},
        "methods_to_run": ["best_of_n"],
        "best_of_n": dict(PARAMS),
        "seed": seeds[0],
        "num_seeds": len(seeds),
        "concurrent_execution": False,
    }
    frame = Experiment(config, backend=FakeBackend()).run()
    assert list(frame["seed"]) == list(seeds)
    assert (frame["error_message"] == "").all()
    return dict(zip(frame["seed"], frame["statement"]))


class TestServiceDeterminism:
    def test_service_matches_experiment(self, tmp_path):
        expected = _experiment_statements(tmp_path, [7], ISSUE, OPINIONS)
        service = ConsensusService(FakeBackend())
        response = service.run(_request(seed=7))
        assert response["statement"] == expected[7]


# ---------------------------------------------------------------------------
# HTTP end-to-end (real socket)
# ---------------------------------------------------------------------------


@pytest.fixture()
def server():
    instance = create_server(
        backend=FakeBackend(), port=0, max_inflight=2, max_queue_depth=8,
        registry=Registry(),
    ).start()
    yield instance
    instance.stop()


class TestHTTPEndToEnd:
    def test_consensus_round_trip_matches_experiment(self, server, tmp_path):
        expected = _experiment_statements(tmp_path, [11], ISSUE, OPINIONS)
        status, body = _post(server.base_url, {
            "issue": ISSUE, "agent_opinions": OPINIONS,
            "method": "best_of_n", "params": PARAMS, "seed": 11,
            "evaluate": True, "request_id": "e2e-1",
        })
        assert status == 200
        assert body["statement"] == expected[11]
        assert body["request_id"] == "e2e-1"
        assert body["method"] == "best_of_n"
        assert set(body["utilities"]) == set(OPINIONS)
        for scores in body["utilities"].values():
            assert {"cosine_similarity", "avg_logprob", "perplexity"} <= set(
                scores)
        assert "egalitarian_welfare_cosine" in body["welfare"]
        assert body["generation_time_s"] >= 0

    def test_healthz_schema(self, server):
        with urllib.request.urlopen(server.base_url + "/healthz") as response:
            assert response.status == 200
            health = json.loads(response.read().decode())
        assert health["status"] == "ok"
        assert health["queue_depth"] == 0
        assert health["inflight"] == 0
        assert health["max_inflight"] == 2
        assert health["max_queue_depth"] == 8
        assert health["workers_alive"] == 2
        assert health["backend"]["alive"] is True
        assert set(health["device_batches"]) == {
            "generate", "score", "next_token", "embed", "score_matrix"}

    def test_metrics_exposes_serve_families(self, server):
        _post(server.base_url, {
            "issue": ISSUE, "agent_opinions": OPINIONS,
            "method": "best_of_n", "params": PARAMS, "seed": 1,
            "evaluate": False,
        })
        with urllib.request.urlopen(server.base_url + "/metrics") as response:
            assert response.status == 200
            assert response.headers["Content-Type"].startswith("text/plain")
            text = response.read().decode()
        for family in (
            "serve_queue_depth",
            "serve_inflight",
            "serve_request_latency_seconds",
            "serve_accepted_total",
        ):
            assert family in text, family
        assert 'outcome="ok"' in text

    def test_validation_error_is_structured_json(self, server):
        status, body = _post(server.base_url, {
            "issue": "", "agent_opinions": {}, "method": "nope"})
        assert status == 400
        assert body["error"]["type"] == "validation"
        assert any("'method'" in d for d in body["error"]["details"])

    def test_bad_json_and_unknown_route(self, server):
        request = urllib.request.Request(
            server.base_url + "/v1/consensus", data=b"not json{",
            headers={"Content-Type": "application/json"}, method="POST")
        with pytest.raises(urllib.error.HTTPError) as excinfo:
            urllib.request.urlopen(request, timeout=10.0)
        assert excinfo.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as excinfo:
            urllib.request.urlopen(server.base_url + "/nope", timeout=10.0)
        assert excinfo.value.code == 404

    def test_timeout_returns_504(self):
        instance = create_server(
            backend=SlowCountingBackend(delay_s=0.5), port=0,
            max_inflight=1, registry=Registry(),
        ).start()
        try:
            status, body = _post(instance.base_url, {
                "issue": ISSUE, "agent_opinions": OPINIONS,
                "method": "best_of_n", "params": PARAMS, "seed": 1,
                "evaluate": False, "timeout_s": 0.05,
            })
            assert status == 504
            assert body["error"]["type"] == "timeout"
        finally:
            instance.stop()


# ---------------------------------------------------------------------------
# acceptance proof: 16 concurrent clients vs serial Experiment
# ---------------------------------------------------------------------------


class TestServingAcceptance:
    def test_sixteen_concurrent_clients_capacity_bounded(self, tmp_path):
        """ISSUE 3 acceptance: accepted responses byte-identical to serial
        Experiment runs, overload explicitly rejected, and device-batch
        accounting strictly below serial execution's dispatch count."""
        from consensus_tpu.serve.loadgen import run_loadgen

        n_clients = 16
        seeds = list(range(500, 500 + n_clients))
        expected = _experiment_statements(tmp_path, seeds, ISSUE, OPINIONS)

        inner = SlowCountingBackend(delay_s=0.03)
        registry = Registry()
        instance = create_server(
            backend=inner, port=0,
            max_inflight=2, max_queue_depth=6,  # capacity-bounded: 16 > 2+6
            registry=registry, flush_ms=100.0,
            engine=False,  # pins the legacy flush-coalescing accounting
        ).start()
        payloads = [
            {
                "issue": ISSUE, "agent_opinions": OPINIONS,
                "method": "best_of_n", "params": PARAMS,
                "seed": seed, "evaluate": False,
                "request_id": f"accept-{seed}",
            }
            for seed in seeds
        ]
        try:
            report = run_loadgen(
                instance.base_url, payloads, rate_rps=1000.0,
                client_timeout_s=60.0,
            )
        finally:
            instance.stop()

        # Every client got a definite answer: a statement or a rejection.
        assert report["completed"] + report["rejected"] == n_clients
        assert report["failed"] == 0 and report["timeouts"] == 0
        # Overload produced explicit rejections (16 arrivals vs 2 in
        # flight + 6 queued), and plenty were still served.
        assert report["rejected"] >= 1
        assert report["completed"] >= 8
        assert report["rejection_rate"] == pytest.approx(
            report["rejected"] / n_clients)

        # Byte-identical to the same seeded requests run serially through
        # Experiment (per-request PRNG keys: batch composition is
        # invisible to results).
        for outcome in report["outcomes"]:
            if outcome.status != 200:
                continue
            seed = int(outcome.request_id.split("-")[1])
            assert outcome.statement == expected[seed], seed

        # Coalescing: serial execution issues one generate + one score
        # dispatch per statement; the shared BatchingBackend must do
        # strictly better on both kinds.
        completed = report["completed"]
        assert inner.batches["generate"] < completed
        assert inner.batches["score"] < completed

        # The serve_* obs families recorded the run.
        snapshot = registry.snapshot()["families"]
        assert _counter_total(
            registry, "serve_accepted_total") == completed
        assert _counter_total(registry, "serve_rejected_total") == \
            report["rejected"]
        latency = snapshot["serve_request_latency_seconds"]["series"]
        assert sum(s["count"] for s in latency) == completed
        # Mixed-kind serving load keeps completion wakeups surgical.
        assert _counter_total(
            registry, "batching_spurious_wakeups_total") == 0


# ---------------------------------------------------------------------------
# graceful degradation surface (ISSUE 5)
# ---------------------------------------------------------------------------


class TestDegradationSurface:
    def test_504_carries_retry_after(self):
        """A deadline expiry with no completed wave stays a 504 — now with
        a Retry-After hint (the anytime clock is born expired: the 0.05 s
        budget is smaller than the anytime margin, so BudgetExpired fires
        before any device work)."""
        instance = create_server(
            backend=SlowCountingBackend(delay_s=0.5), port=0,
            max_inflight=1, registry=Registry(),
        ).start()
        try:
            request = urllib.request.Request(
                instance.base_url + "/v1/consensus",
                data=json.dumps({
                    "issue": ISSUE, "agent_opinions": OPINIONS,
                    "method": "best_of_n", "params": PARAMS, "seed": 1,
                    "evaluate": False, "timeout_s": 0.05,
                }).encode("utf-8"),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            with pytest.raises(urllib.error.HTTPError) as excinfo:
                urllib.request.urlopen(request, timeout=30.0)
            assert excinfo.value.code == 504
            assert excinfo.value.headers["Retry-After"] is not None
            body = json.loads(excinfo.value.read().decode())
            assert body["error"]["type"] == "timeout"
        finally:
            instance.stop()

    def test_cancelled_ticket_mid_wave_sibling_unaffected(self):
        """Cancel a ticket while its search is mid-flight in the shared
        BatchingBackend: the cancelled request resolves to its anytime
        partial (outcome "degraded"), and a co-batched sibling's statement
        stays byte-identical to a solo run — a cancelled ticket in a merged
        batch must never corrupt its siblings."""
        from consensus_tpu.methods import get_method_generator

        slow = SlowCountingBackend(delay_s=0.05)
        service = ConsensusService(slow)
        scheduler = RequestScheduler(
            service.run, slow, max_queue_depth=8, max_inflight=2,
            default_timeout_s=60.0, registry=Registry(), flush_ms=20.0,
        )
        scheduler.start()
        try:
            long_ticket = scheduler.submit(_request(
                seed=5, method="beam_search",
                params={"beam_width": 2, "max_tokens": 30}))
            sibling_params = {"n": 4, "max_tokens": 24}
            sibling_ticket = scheduler.submit(_request(
                seed=77, params=dict(sibling_params)))
            time.sleep(0.4)  # both in flight, sharing merged batches
            long_ticket.cancel()
            assert sibling_ticket.wait(timeout=30.0)
            assert long_ticket.wait(timeout=30.0)
        finally:
            scheduler.shutdown(drain=True, timeout=30.0)

        # The sibling is untouched by its co-batched neighbour's death.
        assert sibling_ticket.outcome == "ok"
        expected = get_method_generator(
            "best_of_n", FakeBackend(), {**sibling_params, "seed": 77}
        ).generate_statement(ISSUE, OPINIONS)
        assert sibling_ticket.result()["statement"] == expected

        # The cancelled search surfaced its best-so-far wave.
        assert long_ticket.outcome == "degraded"
        value = long_ticket.result()
        assert value["degraded"] is True
        assert value["degraded_reason"] == "cancelled"
        assert value["statement"]

    def test_untagged_late_success_still_discarded(self):
        """A FULL (non-degraded) result that completes after cancellation
        is still reported as a timeout — only degraded-tagged values earn
        late delivery."""
        release = threading.Event()

        def slow_handler(request, backend):
            release.wait(timeout=10.0)
            return {"statement": "too late", "seed": request.seed}

        scheduler = RequestScheduler(
            slow_handler, FakeBackend(), max_queue_depth=4, max_inflight=1,
            default_timeout_s=30.0, registry=Registry(),
        )
        scheduler.start()
        try:
            ticket = scheduler.submit(_request(seed=1))
            time.sleep(0.05)  # let the worker enter the handler
            ticket.cancel()
            release.set()
            assert ticket.wait(timeout=10.0)
            assert ticket.outcome == "timeout"
            with pytest.raises(RequestTimeout):
                ticket.result()
        finally:
            scheduler.shutdown(drain=True, timeout=10.0)


class TestKvOomRejection:
    def test_oversized_request_maps_to_http_413(self):
        """SchedulerRejected(kv_oom) from the engine's page-pool admission
        surfaces as 413 (the REQUEST is too large — retrying unchanged can
        never succeed), distinct from the 429 queue_full overload path,
        and is counted under serve_rejected_total{reason="kv_oom"}."""
        registry = Registry()
        server = create_server(
            backend=FakeBackend(), port=0, registry=registry,
            engine=True,
            engine_options={"slots": 2, "page_size": 4, "num_pages": 2},
        ).start()
        try:
            status, body = _post(server.base_url, {
                "issue": ISSUE,
                "agent_opinions": OPINIONS,
                "method": "best_of_n",
                "params": {"n": 2, "max_tokens": 256},
                "seed": 3,
            })
        finally:
            server.stop()
        assert status == 413
        assert body["error"]["type"] == "rejected"
        assert body["error"]["reason"] == "kv_oom"
        assert 'serve_rejected_total{reason="kv_oom"} 1' in \
            registry.to_prometheus()
