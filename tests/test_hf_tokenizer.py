"""HFTokenizer wrapper tests against a locally built fast tokenizer.

The zero-egress environment has no pretrained tokenizer on disk, so the
test builds a tiny byte-level BPE tokenizer with Gemma-style special tokens
using the ``tokenizers`` library, saves it in HF format, and exercises the
production ``HFTokenizer`` code path (AutoTokenizer local load, EOS-id
discovery, chat templating, substring token matching — the behaviours the
reference grounds in token strings, SURVEY §7.3).
"""

import json

import pytest
from tokenizers import Tokenizer, decoders, models, pre_tokenizers, trainers

from consensus_tpu.models.tokenizer import HFTokenizer, get_tokenizer

CORPUS = [
    "Should the city center become car-free on weekends?",
    "Pedestrian zones boost local shops and make streets safer.",
    "Deliveries and disabled access need vehicles.",
    "We will pilot car-free weekends one Sunday a month.",
    "The quick brown fox jumps over the lazy dog.",
]

SPECIALS = ["<pad>", "<bos>", "<eos>", "<start_of_turn>", "<end_of_turn>"]


@pytest.fixture(scope="module")
def hf_dir(tmp_path_factory):
    path = tmp_path_factory.mktemp("hf_tok")
    tok = Tokenizer(models.BPE(unk_token=None))
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tok.decoder = decoders.ByteLevel()
    trainer = trainers.BpeTrainer(vocab_size=384, special_tokens=SPECIALS)
    tok.train_from_iterator(CORPUS, trainer)
    tok.save(str(path / "tokenizer.json"))
    (path / "tokenizer_config.json").write_text(
        json.dumps(
            {
                "tokenizer_class": "PreTrainedTokenizerFast",
                "bos_token": "<bos>",
                "eos_token": "<eos>",
                "pad_token": "<pad>",
            }
        )
    )
    return str(path)


@pytest.fixture(scope="module")
def tokenizer(hf_dir):
    return HFTokenizer(hf_dir, family="gemma")


def test_get_tokenizer_dispatches_to_hf(hf_dir):
    tok = get_tokenizer(hf_dir, family="gemma")
    assert isinstance(tok, HFTokenizer)


def test_encode_decode_roundtrip(tokenizer):
    text = "car-free weekends boost local shops"
    ids = tokenizer.encode(text)
    assert ids and all(isinstance(i, int) for i in ids)
    assert tokenizer.decode(ids) == text


def test_bos_prefixed_when_requested(tokenizer):
    plain = tokenizer.encode("hello")
    with_bos = tokenizer.encode("hello", add_bos=True)
    assert with_bos == [tokenizer.bos_id] + plain


def test_eos_ids_include_end_of_turn(tokenizer):
    """Gemma family: both <eos> and <end_of_turn> must stop generation
    (reference EOS string set, beam_search.py:26-35)."""
    eot = tokenizer._tok.convert_tokens_to_ids("<end_of_turn>")
    assert tokenizer._tok.eos_token_id in tokenizer.eos_ids
    assert eot in tokenizer.eos_ids


def test_decode_skips_pad_and_specials(tokenizer):
    ids = tokenizer.encode("pilot", add_bos=True)
    padded = [tokenizer.pad_id] * 3 + ids
    assert tokenizer.decode(padded) == "pilot"


def test_gemma_chat_template(tokenizer):
    prompt = tokenizer.chat_prompt("What do you think?", system="Be brief.")
    assert prompt.startswith("<start_of_turn>user\n")
    assert "Be brief.\n\nWhat do you think?" in prompt
    assert prompt.endswith("<start_of_turn>model\n")
    # Gemma has no system role: system folds into the user turn.
    assert "system" not in prompt


def test_llama_chat_template(hf_dir):
    tok = HFTokenizer(hf_dir, family="llama")
    prompt = tok.chat_prompt("Hi", system="Sys")
    assert "<|start_header_id|>system<|end_header_id|>" in prompt
    assert prompt.endswith("<|start_header_id|>assistant<|end_header_id|>\n\n")


def test_token_ids_containing_substring(tokenizer):
    ids = tokenizer.token_ids_containing("week")
    assert ids
    for token_id in ids:
        assert "week" in tokenizer.token_str(token_id)


def test_tpu_backend_accepts_hf_tokenizer(hf_dir):
    """End-to-end: TPUBackend on the HF tokenizer path generates and scores."""
    from consensus_tpu.backends.base import GenerationRequest, ScoreRequest
    from consensus_tpu.backends.tpu import TPUBackend

    backend = TPUBackend(
        model="tiny-gemma2", tokenizer=hf_dir, max_context=128, base_seed=0
    )
    result = backend.generate(
        [GenerationRequest(user_prompt="weekends", max_tokens=4, seed=1)]
    )[0]
    assert result.finish_reason in ("stop", "length")
    score = backend.score(
        [ScoreRequest(context="car-free", continuation=" weekends")]
    )[0]
    assert score.ok and all(lp <= 0.0 for lp in score.logprobs)


# ---------------------------------------------------------------------------
# Chat-template certification vs transformers' apply_chat_template
# ---------------------------------------------------------------------------
# The official checkpoints ship their chat template as a jinja string in
# tokenizer_config.json; zero egress means no checkpoint, so the public
# template strings are pinned here and our hand-rendered ``chat_prompt``
# strings are asserted identical to the official rendering.  The Llama
# template is the NO-TOOLS reduction of the Meta-Llama-3.1-8B-Instruct
# template (the reference's main-body generation model): the system header
# always renders, carrying the knowledge-cutoff/date lines, with the
# template's default date pinned for reproducibility.

GEMMA2_CHAT_TEMPLATE = (
    "{{ bos_token }}{% if messages[0]['role'] == 'system' %}"
    "{{ raise_exception('System role not supported') }}{% endif %}"
    "{% for message in messages %}"
    "{% if (message['role'] == 'user') != (loop.index0 % 2 == 0) %}"
    "{{ raise_exception('Conversation roles must alternate user/assistant/user/assistant/...') }}"
    "{% endif %}{% if (message['role'] == 'assistant') %}"
    "{% set role = 'model' %}{% else %}{% set role = message['role'] %}{% endif %}"
    "{{ '<start_of_turn>' + role + '\n' + message['content'] | trim + '<end_of_turn>\n' }}"
    "{% endfor %}{% if add_generation_prompt %}{{'<start_of_turn>model\n'}}{% endif %}"
)

LLAMA31_CHAT_TEMPLATE = (
    "{{- bos_token }}"
    "{%- if not date_string is defined %}{%- set date_string = '26 Jul 2024' %}{%- endif %}"
    "{%- if messages[0]['role'] == 'system' %}"
    "{%- set system_message = messages[0]['content'] | trim %}"
    "{%- set messages = messages[1:] %}"
    "{%- else %}{%- set system_message = '' %}{%- endif %}"
    "{{- '<|start_header_id|>system<|end_header_id|>\n\n' }}"
    "{{- 'Cutting Knowledge Date: December 2023\n' }}"
    "{{- 'Today Date: ' + date_string + '\n\n' }}"
    "{{- system_message }}{{- '<|eot_id|>' }}"
    "{%- for message in messages %}"
    "{{- '<|start_header_id|>' + message['role'] + '<|end_header_id|>\n\n' "
    "+ message['content'] | trim + '<|eot_id|>' }}"
    "{%- endfor %}"
    "{%- if add_generation_prompt %}"
    "{{- '<|start_header_id|>assistant<|end_header_id|>\n\n' }}"
    "{%- endif %}"
)


def test_gemma_chat_prompt_matches_official_template(hf_dir):
    """Gemma has no system role — the official template raises on one, and
    the production convention (system folded into the user turn) must render
    byte-identically to the official template applied to the folded turn."""
    tok = HFTokenizer(hf_dir, family="gemma")  # fresh: don't mutate fixtures
    tok._tok.chat_template = GEMMA2_CHAT_TEMPLATE
    system, user = "Be brief.", "What do you think?"
    official = tok._tok.apply_chat_template(
        [{"role": "user", "content": f"{system}\n\n{user}"}],
        tokenize=False,
        add_generation_prompt=True,
    )
    # The template prepends bos_token (ours is added at encode time via
    # add_bos) and ends the user turn with a newline before the model turn.
    assert official == "<bos>" + tok.chat_prompt(user, system=system)

    with pytest.raises(Exception):
        tok._tok.apply_chat_template(
            [
                {"role": "system", "content": system},
                {"role": "user", "content": user},
            ],
            tokenize=False,
        )


def test_llama_chat_prompt_matches_official_template(hf_dir):
    tok = HFTokenizer(hf_dir, family="llama")
    tok._tok.chat_template = LLAMA31_CHAT_TEMPLATE
    system, user = "Sys", "Hi"
    official = tok._tok.apply_chat_template(
        [
            {"role": "system", "content": system},
            {"role": "user", "content": user},
        ],
        tokenize=False,
        add_generation_prompt=True,
    )
    # Our rendering uses the literal Llama-3 bos string; the tiny test
    # tokenizer's bos token is <bos>.
    ours = tok.chat_prompt(user, system=system).replace("<|begin_of_text|>", "<bos>")
    assert official == ours


def test_llama_chat_prompt_no_system_still_has_date_header(hf_dir):
    """The 3.1 template emits the system header (with date lines) even when
    no system message is supplied."""
    tok = HFTokenizer(hf_dir, family="llama")
    tok._tok.chat_template = LLAMA31_CHAT_TEMPLATE
    official = tok._tok.apply_chat_template(
        [{"role": "user", "content": "Hi"}],
        tokenize=False,
        add_generation_prompt=True,
    )
    ours = tok.chat_prompt("Hi").replace("<|begin_of_text|>", "<bos>")
    assert official == ours
