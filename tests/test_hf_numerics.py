"""Certify the JAX transformer's numerics against HuggingFace ``transformers``.

The box has no real checkpoint (zero egress), so quality parity vs the
API baseline can't be measured directly.  The strongest evidence available
is architectural: build a tiny-but-faithful Gemma-2 / Llama-3 model, load
*identical* random weights into torch ``Gemma2ForCausalLM`` /
``LlamaForCausalLM`` (CPU, float32, eager attention) and into our runtime
via the production HF-checkpoint path (``models/loader.py:load_params`` on
a ``save_pretrained`` directory), and assert logit agreement.

This certifies every architectural detail the reference's scoring
semantics depend on (reference scores via API logprobs,
/root/reference/src/utils.py:201-281): RoPE theta + Llama-3.1 rope
scaling, attn/final logit softcaps, sliding-window layer alternation,
GQA head grouping, RMSNorm style (Gemma 1+w vs Llama w), embedding
scaling, tied vs untied LM heads, and the activation functions.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax.numpy as jnp  # noqa: E402

from consensus_tpu.models.config import get_model_config  # noqa: E402
from consensus_tpu.models.loader import load_params  # noqa: E402
from consensus_tpu.models import transformer  # noqa: E402

# Sequence longer than the sliding window (16) so local layers actually clip.
BATCH, SEQ = 2, 48


def _save_hf_model(model, tmp_path):
    d = tmp_path / "ckpt"
    model.save_pretrained(str(d), safe_serialization=True)
    return str(d)


def _hf_tiny_gemma2():
    cfg = transformers.Gemma2Config(
        vocab_size=512,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=4,
        num_attention_heads=4,
        num_key_value_heads=2,
        head_dim=16,
        query_pre_attn_scalar=16,
        sliding_window=16,
        attn_logit_softcapping=50.0,
        final_logit_softcapping=30.0,
        rope_theta=10_000.0,
        rms_norm_eps=1e-6,
        hidden_activation="gelu_pytorch_tanh",
        max_position_embeddings=256,
        tie_word_embeddings=True,
        attention_dropout=0.0,
    )
    cfg._attn_implementation = "eager"
    torch.manual_seed(0)
    model = transformers.Gemma2ForCausalLM(cfg)
    model.eval()
    return model


def _hf_tiny_llama3(rope_scaling=None):
    cfg = transformers.LlamaConfig(
        vocab_size=512,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        rope_theta=500_000.0,
        rms_norm_eps=1e-5,
        max_position_embeddings=256,
        tie_word_embeddings=False,
        attention_bias=False,
        mlp_bias=False,
        rope_scaling=rope_scaling,
    )
    cfg._attn_implementation = "eager"
    torch.manual_seed(1)
    model = transformers.LlamaForCausalLM(cfg)
    model.eval()
    return model


def _jax_logits(ckpt_dir, config, tokens, positions, valid):
    params = load_params(ckpt_dir, config, dtype=jnp.float32)
    logits, _ = transformer.forward(
        params,
        config,
        jnp.asarray(tokens, jnp.int32),
        jnp.asarray(positions, jnp.int32),
        jnp.asarray(valid, bool),
    )
    return np.asarray(logits)


def _hf_logits(model, tokens, positions, valid):
    with torch.no_grad():
        out = model(
            input_ids=torch.tensor(tokens, dtype=torch.long),
            attention_mask=torch.tensor(valid, dtype=torch.long),
            position_ids=torch.tensor(positions, dtype=torch.long),
        )
    return out.logits.float().numpy()


def _full_valid_inputs(vocab):
    rng = np.random.default_rng(42)
    tokens = rng.integers(0, vocab, size=(BATCH, SEQ))
    positions = np.broadcast_to(np.arange(SEQ), (BATCH, SEQ)).copy()
    valid = np.ones((BATCH, SEQ), dtype=bool)
    return tokens, positions, valid


def _left_pad_inputs(vocab, pad=7):
    tokens, positions, valid = _full_valid_inputs(vocab)
    valid[0, :pad] = False
    tokens[0, :pad] = 0
    # Positions restart at 0 on the first real token (the runtime's
    # left-padded layout); HF gets the same explicit position_ids.
    positions[0] = np.concatenate([np.zeros(pad, int), np.arange(SEQ - pad)])
    return tokens, positions, valid


def test_gemma2_logits_match_hf(tmp_path):
    model = _hf_tiny_gemma2()
    ckpt = _save_hf_model(model, tmp_path)
    config = get_model_config("tiny-gemma2")
    tokens, positions, valid = _full_valid_inputs(config.vocab_size)

    ours = _jax_logits(ckpt, config, tokens, positions, valid)
    theirs = _hf_logits(model, tokens, positions, valid)

    np.testing.assert_allclose(ours, theirs, atol=2e-4, rtol=2e-4)


def test_gemma2_logits_match_hf_left_padded(tmp_path):
    model = _hf_tiny_gemma2()
    ckpt = _save_hf_model(model, tmp_path)
    config = get_model_config("tiny-gemma2")
    tokens, positions, valid = _left_pad_inputs(config.vocab_size)

    ours = _jax_logits(ckpt, config, tokens, positions, valid)
    theirs = _hf_logits(model, tokens, positions, valid)

    np.testing.assert_allclose(
        ours[valid], theirs[valid], atol=2e-4, rtol=2e-4
    )


def test_llama3_logits_match_hf(tmp_path):
    model = _hf_tiny_llama3()
    ckpt = _save_hf_model(model, tmp_path)
    config = get_model_config("tiny-llama3")
    tokens, positions, valid = _full_valid_inputs(config.vocab_size)

    ours = _jax_logits(ckpt, config, tokens, positions, valid)
    theirs = _hf_logits(model, tokens, positions, valid)

    np.testing.assert_allclose(ours, theirs, atol=2e-4, rtol=2e-4)


def test_llama31_rope_scaling_matches_hf(tmp_path):
    """Llama-3.1 'llama3' rope frequency scaling (the reference's main-body
    generation model is Meta-Llama-3.1-8B-Instruct-Turbo)."""
    scaling = {
        "rope_type": "llama3",
        "factor": 8.0,
        "low_freq_factor": 1.0,
        "high_freq_factor": 4.0,
        "original_max_position_embeddings": 64,
    }
    model = _hf_tiny_llama3(rope_scaling=scaling)
    ckpt = _save_hf_model(model, tmp_path)
    config = get_model_config(
        "tiny-llama3", rope_scaling=(8.0, 1.0, 4.0, 64)
    )
    tokens, positions, valid = _full_valid_inputs(config.vocab_size)

    ours = _jax_logits(ckpt, config, tokens, positions, valid)
    theirs = _hf_logits(model, tokens, positions, valid)

    np.testing.assert_allclose(ours, theirs, atol=2e-4, rtol=2e-4)


def test_gemma2_decode_path_matches_hf(tmp_path):
    """The KV-cache prefill+decode path (what generation actually runs)
    must agree with HF on the decoded positions, not just the
    teacher-forced path."""
    model = _hf_tiny_gemma2()
    ckpt = _save_hf_model(model, tmp_path)
    config = get_model_config("tiny-gemma2")
    params = load_params(ckpt, config, dtype=jnp.float32)

    rng = np.random.default_rng(3)
    prompt_len, decode_len = 20, 6
    total = prompt_len + decode_len
    tokens = rng.integers(0, config.vocab_size, size=(1, total))

    # HF: one full forward, take the last decode_len logits.
    positions = np.arange(total)[None, :]
    valid = np.ones((1, total), dtype=bool)
    theirs = _hf_logits(model, tokens, positions, valid)[0, prompt_len - 1 : -1]

    # Ours: prefill the prompt into a cache, then decode token by token.
    cache = transformer.make_cache(config, batch=1, max_len=total, dtype=jnp.float32)
    logits, cache = transformer.forward(
        params,
        config,
        jnp.asarray(tokens[:, :prompt_len], jnp.int32),
        jnp.asarray(positions[:, :prompt_len], jnp.int32),
        jnp.ones((1, prompt_len), bool),
        cache=cache,
        write_index=0,
    )
    steps = [np.asarray(logits[:, -1])]
    for i in range(prompt_len, total - 1):
        logits, cache = transformer.forward(
            params,
            config,
            jnp.asarray(tokens[:, i : i + 1], jnp.int32),
            jnp.asarray([[i]], jnp.int32),
            jnp.ones((1, 1), bool),
            cache=cache,
            write_index=i,
        )
        steps.append(np.asarray(logits[:, -1]))
    ours = np.concatenate(steps, axis=0)

    np.testing.assert_allclose(ours, theirs, atol=2e-4, rtol=2e-4)
