"""Beam search / finite lookahead / MCTS on the deterministic fake backend.

The reference's token-level decoders are untestable without the live API
(SURVEY §4); these tests pin the search semantics bit-reproducibly.
"""

import pytest

from consensus_tpu.backends.fake import FakeBackend
from consensus_tpu.methods import get_method_generator
from consensus_tpu.methods.beam_search import (
    BeamSearchGenerator,
    EOS_TOKENS,
    MIN_WORDS,
)

ISSUE = "Should schools adopt a four-day week?"
OPINIONS = {
    "Agent 1": "A shorter week improves wellbeing for students and teachers.",
    "Agent 2": "Childcare burdens would fall on working parents.",
    "Agent 3": "Evidence on learning outcomes is mixed; pilot first.",
}


@pytest.fixture()
def backend():
    return FakeBackend()


class TestBeamSearch:
    def make(self, backend, **cfg):
        base = {"beam_width": 2, "max_tokens": 6, "seed": 5}
        base.update(cfg)
        return get_method_generator("beam_search", backend, base)

    def test_produces_statement_and_batches_calls(self, backend):
        gen = self.make(backend)
        statement = gen.generate_statement(ISSUE, OPINIONS)
        assert isinstance(statement, str) and statement
        # Per step: ONE next-token batch + ONE score batch. 6 steps max.
        assert backend.call_counts["next_token"] <= 6 * 2  # <= steps x beams
        assert gen.pre_brushup_statement == statement  # no brushup configured

    def test_deterministic(self):
        s1 = self.make(FakeBackend()).generate_statement(ISSUE, OPINIONS)
        s2 = self.make(FakeBackend()).generate_statement(ISSUE, OPINIONS)
        assert s1 == s2

    def test_prune_moves_eos_to_completed(self):
        from consensus_tpu.backends.session import ScoredCandidate

        def cand(token):
            return ScoredCandidate(token, 7, -1.0, (-1.0, -1.0))

        eos = next(iter(EOS_TOKENS))
        candidates = [
            ("good seq one two three four five", [2.0, 1.0], cand("tok"), 0),
            ("done seq" + eos, [0.5, 0.4], cand(eos), 0),
            ("bad seq", [-5.0, -9.0], cand("tok"), 1),
        ]
        beams, completed = BeamSearchGenerator._prune(candidates, [], beam_width=1)
        assert len(beams) == 1 and beams[0][0].startswith("good")
        assert len(completed) == 1 and completed[0][0].startswith("done")

    def test_select_best_filters_short_sequences(self):
        completed = [
            ("short one", [10.0, 10.0]),  # 2 words: filtered despite reward
            ("a much longer sequence of words here", [1.0, 2.0]),
        ]
        assert BeamSearchGenerator._select_best(completed).startswith("a much")

    def test_select_best_falls_back_when_all_short(self):
        completed = [("tiny", [1.0]), ("small one", [3.0])]
        assert BeamSearchGenerator._select_best(completed) == "small one"

    def test_min_words_constant_matches_reference(self):
        assert MIN_WORDS == 5

    def test_brushup_sets_pre_brushup_statement(self, backend):
        gen = self.make(backend, brushup=True)
        statement = gen.generate_statement(ISSUE, OPINIONS)
        assert gen.pre_brushup_statement is not None
        assert isinstance(statement, str)


class TestFiniteLookahead:
    def make(self, backend, **cfg):
        base = {"branching_factor": 2, "max_depth": 2, "max_tokens": 5, "seed": 9}
        base.update(cfg)
        return get_method_generator("finite_lookahead", backend, base)

    def test_produces_statement(self, backend):
        gen = self.make(backend)
        statement = gen.generate_statement(ISSUE, OPINIONS)
        assert isinstance(statement, str) and statement

    def test_deterministic(self):
        s1 = self.make(FakeBackend()).generate_statement(ISSUE, OPINIONS)
        s2 = self.make(FakeBackend()).generate_statement(ISSUE, OPINIONS)
        assert s1 == s2

    def test_tree_level_batching(self, backend):
        from consensus_tpu.backends.session import SearchSpec, open_token_search
        from consensus_tpu.methods.finite_lookahead import FiniteLookaheadGenerator
        from consensus_tpu.methods.prompts import agent_prompt, reference_prompt

        system, user = reference_prompt(ISSUE, OPINIONS, variant="finite_lookahead")
        session = open_token_search(
            backend,
            SearchSpec(
                ref_system=system, ref_user=user,
                agent_prompts=tuple(
                    agent_prompt(ISSUE, o, variant="finite_lookahead")
                    for o in OPINIONS.values()
                ),
                n_slots=1, k=2, seed=1, max_steps=4,
            ),
        )
        root = session.propose()[0]
        best = FiniteLookaheadGenerator._best_path(
            session, root, branching=2, max_depth=3, step=0
        )
        # Level-batched tree: root (1 request) + frontier levels of <=2 and
        # <=4 paths — one batched next_token call per level, counts track
        # requests.
        assert 1 <= backend.call_counts["next_token"] <= 1 + 2 + 4
        assert best is not None
        path, sums = best
        assert 1 <= len(path) <= 3
        assert len(sums) == len(OPINIONS)

    def test_appends_only_first_token_per_step(self, backend):
        gen = self.make(backend, max_tokens=1)
        statement = gen.generate_statement(ISSUE, OPINIONS)
        # After one outer step the statement is exactly one token.
        paths = []  # statement must equal some single proposed token
        assert len(statement) < 30


class TestMCTS:
    def make(self, backend, **cfg):
        base = {
            "num_simulations": 4,
            "expansion_sample_width": 3,
            "max_tokens": 4,
            "rollout_depth": 3,
            "seed": 2,
        }
        base.update(cfg)
        return get_method_generator("mcts", backend, base)

    def test_produces_statement_without_crashing(self, backend):
        """The reference MCTS raises NameError in every rollout evaluation
        (mcts.py:614-616); ours must complete."""
        gen = self.make(backend)
        statement = gen.generate_statement(ISSUE, OPINIONS)
        assert isinstance(statement, str) and statement

    def test_deterministic(self):
        s1 = self.make(FakeBackend()).generate_statement(ISSUE, OPINIONS)
        s2 = self.make(FakeBackend()).generate_statement(ISSUE, OPINIONS)
        assert s1 == s2

    def test_visits_accumulate(self, backend):
        from consensus_tpu.backends.session import ScoredCandidate
        from consensus_tpu.methods.mcts import MCTSGenerator, Node

        root = Node(None, None)
        child = Node(ScoredCandidate("x", 1, -1.0, (-1.0,)), root)
        MCTSGenerator._backpropagate(child, 1.5)
        MCTSGenerator._backpropagate(child, 0.5)
        assert child.visits == 2 and root.visits == 2
        assert child.value == pytest.approx(1.0)
        assert [c.token for c in child.suffix()] == ["x"]

    def test_most_visited_child_advances(self, backend):
        from consensus_tpu.backends.session import ScoredCandidate
        from consensus_tpu.methods.mcts import MCTSGenerator, Node

        root = Node(None, None)
        a = Node(ScoredCandidate("a", 1, -1.0, (-1.0,)), root)
        b = Node(ScoredCandidate("b", 2, -1.0, (-1.0,)), root)
        root.children = {"a": a, "b": b}
        a.visits, b.visits = 3, 7
        assert MCTSGenerator._most_visited_child(root) is b
