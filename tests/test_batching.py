"""Cross-run device batching tests (VERDICT r1 #8).

The sweep's parallelism axis must become device batch width: concurrent
(seed × param) combos share device batches through BatchingBackend, with
results bit-identical to sequential execution (per-request PRNG keys).
"""

import threading

import numpy as np
import pytest

from consensus_tpu.backends.base import GenerationRequest, ScoreRequest
from consensus_tpu.backends.batching import BatchingBackend
from consensus_tpu.backends.fake import FakeBackend


class CountingBackend:
    """FakeBackend wrapper counting device-batch invocations."""

    name = "counting"

    def __init__(self):
        self.inner = FakeBackend()
        self.batches = {"generate": 0, "score": 0, "next_token": 0,
                        "embed": 0, "score_matrix": 0}

    def generate(self, requests):
        self.batches["generate"] += 1
        return self.inner.generate(requests)

    def score(self, requests):
        self.batches["score"] += 1
        return self.inner.score(requests)

    def next_token_logprobs(self, requests):
        self.batches["next_token"] += 1
        return self.inner.next_token_logprobs(requests)

    def embed(self, texts):
        self.batches["embed"] += 1
        return self.inner.embed(texts)

    def score_matrix(self, requests):
        self.batches["score_matrix"] += 1
        from consensus_tpu.backends.score_matrix import (
            fallback_score_matrix_many,
        )

        return fallback_score_matrix_many(self.inner, requests)


class TestBatchingBackend:
    def test_concurrent_sessions_share_one_batch(self):
        counting = CountingBackend()
        batching = BatchingBackend(counting, flush_ms=50.0, engine=False)
        results = {}
        barrier = threading.Barrier(3)

        def worker(tag):
            with batching.session():
                barrier.wait()
                results[tag] = batching.generate(
                    [GenerationRequest(user_prompt=f"p{tag}", max_tokens=4, seed=tag)]
                )[0]

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert counting.batches["generate"] == 1  # 3 sessions, ONE device batch
        assert len(results) == 3

    def test_batched_results_match_solo(self):
        counting = CountingBackend()
        batching = BatchingBackend(counting, flush_ms=20.0, engine=False)
        requests = [
            GenerationRequest(user_prompt=f"prompt {i}", max_tokens=6, seed=i)
            for i in range(3)
        ]
        solo = FakeBackend().generate(requests)
        results = [None] * 3
        barrier = threading.Barrier(3)

        def worker(i):
            with batching.session():
                barrier.wait()
                results[i] = batching.generate([requests[i]])[0]

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for got, expected in zip(results, solo):
            assert got.text == expected.text

    def test_mixed_kinds_flush_independently(self):
        counting = CountingBackend()
        batching = BatchingBackend(counting, flush_ms=20.0, engine=False)
        out = {}
        barrier = threading.Barrier(2)

        def gen_worker():
            with batching.session():
                barrier.wait()
                out["gen"] = batching.generate(
                    [GenerationRequest(user_prompt="a", max_tokens=4, seed=1)]
                )

        def score_worker():
            with batching.session():
                barrier.wait()
                out["score"] = batching.score(
                    [ScoreRequest(context="ctx", continuation=" more")]
                )

        threads = [
            threading.Thread(target=gen_worker),
            threading.Thread(target=score_worker),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert out["gen"][0].text is not None
        assert out["score"][0].ok

    def test_embed_slicing(self):
        counting = CountingBackend()
        batching = BatchingBackend(counting, flush_ms=20.0, engine=False)
        out = {}
        barrier = threading.Barrier(2)

        def worker(tag, texts):
            with batching.session():
                barrier.wait()
                out[tag] = batching.embed(texts)

        threads = [
            threading.Thread(target=worker, args=("a", ["one", "two"])),
            threading.Thread(target=worker, args=("b", ["three"])),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert out["a"].shape[0] == 2
        assert out["b"].shape[0] == 1
        assert counting.batches["embed"] == 1
        solo = FakeBackend().embed(["one", "two"])
        np.testing.assert_allclose(out["a"], solo, atol=1e-6)

    def test_error_propagates_to_all_waiters(self):
        class Exploding(CountingBackend):
            def generate(self, requests):
                raise RuntimeError("device on fire")

        batching = BatchingBackend(Exploding(), flush_ms=20.0, engine=False)
        errors = []
        barrier = threading.Barrier(2)

        def worker():
            with batching.session():
                barrier.wait()
                try:
                    batching.generate(
                        [GenerationRequest(user_prompt="x", max_tokens=2)]
                    )
                except RuntimeError as exc:
                    errors.append(str(exc))

        threads = [threading.Thread(target=worker) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert errors == ["device on fire", "device on fire"]


class TestBatchingMetrics:
    def test_queue_wait_and_batch_fill_recorded_under_concurrency(self):
        """Concurrent sessions leave an observability trail: queue-wait
        samples per merged request, batch-fill = sessions per flush, a
        flush-reason counter, and merged-request totals — recorded into
        the injected registry, not the process-global one."""
        from consensus_tpu.obs import Registry

        registry = Registry()
        counting = CountingBackend()
        batching = BatchingBackend(
            counting, flush_ms=50.0, expected_sessions=3, registry=registry, engine=False)
        barrier = threading.Barrier(3)

        def worker(tag):
            with batching.session():
                barrier.wait()
                batching.generate(
                    [GenerationRequest(user_prompt=f"p{tag}", max_tokens=4, seed=tag)]
                )

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert counting.batches["generate"] == 1

        families = registry.snapshot()["families"]

        def series(name, **labels):
            for entry in families[name]["series"]:
                if all(entry["labels"].get(k) == v for k, v in labels.items()):
                    return entry
            raise AssertionError(f"no {name} series with {labels}: {families[name]}")

        wait = series("batching_queue_wait_seconds", kind="generate")
        assert wait["count"] == 3  # one sample per merged session call
        assert wait["sum"] >= 0.0 and wait["max"] < 30.0

        fill = series("batching_batch_fill_sessions", kind="generate")
        assert fill["count"] == 1  # one flush
        assert fill["min"] == fill["max"] == 3.0  # all 3 sessions merged

        merged = series("batching_merged_requests_total", kind="generate")
        assert merged["value"] == 3.0

        reasons = {
            s["labels"]["reason"]: s["value"]
            for s in families["batching_flushes_total"]["series"]
            if s["labels"]["kind"] == "generate"
        }
        assert sum(reasons.values()) == 1.0
        assert set(reasons) <= {"all_blocked", "timeout"}

    def test_timeout_flush_reason_recorded(self):
        """A lone session (below expected_sessions) can only flush via the
        quiescence timeout — the reason label must say so."""
        from consensus_tpu.obs import Registry

        registry = Registry()
        batching = BatchingBackend(
            CountingBackend(), flush_ms=5.0, expected_sessions=4,
            registry=registry, engine=False)
        with batching.session():
            batching.score([ScoreRequest(context="ctx", continuation=" more")])
        families = registry.snapshot()["families"]
        reasons = {
            (s["labels"]["kind"], s["labels"]["reason"]): s["value"]
            for s in families["batching_flushes_total"]["series"]
        }
        assert reasons == {("score", "timeout"): 1.0}


class TestExperimentConcurrency:
    CONFIG = {
        "experiment_name": "batch_test",
        "seed": 7,
        "num_seeds": 3,
        "scenario": {
            "issue": "Should X happen?",
            "agent_opinions": {"A": "Yes.", "B": "No."},
        },
        "methods_to_run": ["best_of_n"],
        "best_of_n": {"n": 2, "max_tokens": 6},
    }

    def _run(self, tmp_path, concurrent):
        from consensus_tpu.experiment import Experiment

        config = dict(self.CONFIG)
        config["concurrent_execution"] = concurrent
        config["batch_flush_ms"] = 200.0  # generous window: deflake CI timing
        config["output_dir"] = str(tmp_path / ("conc" if concurrent else "seq"))
        backend = CountingBackend()
        experiment = Experiment(config, backend=backend)
        frame = experiment.run()
        return frame, backend, experiment

    def test_results_identical_and_batches_fewer(self, tmp_path):
        seq_frame, seq_backend, _ = self._run(tmp_path, concurrent=False)
        conc_frame, conc_backend, experiment = self._run(tmp_path, concurrent=True)

        # Bit-identical statements per (seed): concurrency never changes results.
        seq = seq_frame.sort_values("seed")["statement"].tolist()
        conc = conc_frame.sort_values("seed")["statement"].tolist()
        assert seq == conc
        assert (conc_frame["error_message"] == "").all()

        # The measurable speedup proxy: fewer device batches than sequential.
        seq_total = sum(seq_backend.batches.values())
        conc_total = sum(conc_backend.batches.values())
        assert conc_total < seq_total
        assert experiment.last_batch_counts == conc_backend.batches


class TestFlushSingleFile:
    def test_no_concurrent_inner_calls_and_arrivals_merge(self):
        """The flush runs with the lock RELEASED so arrivals can enqueue
        during a device call — but inner-backend dispatches must stay
        single-file, and requests arriving mid-flush must merge into the
        NEXT batch rather than fragmenting into solo dispatches.

        Follower arrival is gated on an event set inside the inner
        ``generate`` (and the first dispatch holds until all followers have
        enqueued), so arrival-mid-flush is guaranteed rather than raced
        against a fixed sleep (ADVICE r4)."""
        import time

        class SlowInner:
            name = "slow"

            def __init__(self):
                self.inner = FakeBackend()
                self.calls = []          # row counts per dispatch
                self._in_call = False
                self.overlapped = False
                self.batching = None      # wired up after wrapper construction
                self.first_dispatch = threading.Event()

            def generate(self, requests):
                if self._in_call:
                    self.overlapped = True
                self._in_call = True
                try:
                    if not self.first_dispatch.is_set():
                        self.first_dispatch.set()
                        # Hold the first "device call" open until every
                        # follower has enqueued — guaranteed mid-flush
                        # arrival, bounded so a broken follower can't hang.
                        deadline = time.monotonic() + 10.0
                        while time.monotonic() < deadline:
                            with self.batching._lock:
                                if len(self.batching._queues["generate"]) >= 5:
                                    break
                            time.sleep(0.005)
                    time.sleep(0.01)      # device-call stand-in
                    return self.inner.generate(requests)
                finally:
                    self.calls.append(len(requests))
                    self._in_call = False

            def score(self, requests):
                return self.inner.score(requests)

            def next_token_logprobs(self, requests):
                return self.inner.next_token_logprobs(requests)

            def embed(self, texts):
                return self.inner.embed(texts)

        inner = SlowInner()
        batching = BatchingBackend(inner, flush_ms=5.0, expected_sessions=6, engine=False)
        inner.batching = batching
        done = []

        def leader():
            with batching.session():
                done.append(
                    batching.generate(
                        [GenerationRequest(user_prompt="lead", max_tokens=4, seed=0)]
                    )
                )

        def follower(i):
            with batching.session():
                # Enqueue only once the leader's dispatch has started; the
                # inner call then waits for all 5 of us before returning.
                assert inner.first_dispatch.wait(timeout=10.0)
                done.append(
                    batching.generate(
                        [GenerationRequest(user_prompt=f"f{i}", max_tokens=4, seed=i)]
                    )
                )

        threads = [threading.Thread(target=leader)] + [
            threading.Thread(target=follower, args=(i,)) for i in range(5)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not inner.overlapped, "two flushes ran concurrently"
        assert len(done) == 6
        # The 5 followers all arrived during the leader's device call (the
        # inner generate held until their entries were queued) and must ride
        # ONE follow-up batch — 3 dispatches would mean the timeout path
        # re-fragmented a mid-flush arrival.
        assert len(inner.calls) <= 2
        assert sum(inner.calls) == 6


class TestAbortedFlushFailsWaiters:
    def test_base_exception_mid_flush_errors_stranded_entries(self):
        """A non-Exception abort between per-kind dispatches (e.g.
        KeyboardInterrupt) must not strand waiters whose kind never ran:
        their snapshot entries are off the queues, so _flush's finally has
        to error them or the waiter threads block forever (ADVICE r4)."""

        class AbortingInner:
            name = "aborting"

            def __init__(self):
                self.inner = FakeBackend()

            def generate(self, requests):
                # Abort mid-flush with a BaseException: "score" entries in
                # the same snapshot never get dispatched.
                raise KeyboardInterrupt

            def score(self, requests):
                return self.inner.score(requests)

            def next_token_logprobs(self, requests):
                return self.inner.next_token_logprobs(requests)

            def embed(self, texts):
                return self.inner.embed(texts)

        # Huge window: the scorer must NOT timeout-flush its entry solo —
        # only the all-blocked path (triggered by the generate below) may
        # flush, so both kinds land in one snapshot.
        batching = BatchingBackend(
            AbortingInner(), flush_ms=30_000.0, expected_sessions=2, engine=False)
        score_outcome = {}

        def scorer():
            with batching.session():
                try:
                    batching.score(
                        [ScoreRequest(context="ctx", continuation=" more")]
                    )
                    score_outcome["result"] = "ok"
                except RuntimeError as exc:
                    score_outcome["result"] = str(exc)

        scorer_thread = threading.Thread(target=scorer)
        with batching.session():
            scorer_thread.start()
            # Wait for the scorer's entry to be queued so the all-blocked
            # flush snapshots BOTH kinds, then trigger it via generate.
            import time

            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                with batching._lock:
                    if batching._queues["score"]:
                        break
                time.sleep(0.005)
            with pytest.raises(KeyboardInterrupt):
                batching.generate(
                    [GenerationRequest(user_prompt="g", max_tokens=4, seed=0)]
                )
        scorer_thread.join(timeout=10.0)
        assert not scorer_thread.is_alive(), "score waiter was stranded"
        assert "aborted" in score_outcome.get("result", "")


class TestPerKindWakeups:
    def test_no_spurious_wakeups_across_kinds(self):
        """An all-blocked flush dispatches every kind's batch in sequence;
        a waiter parked for the score batch must sleep through the generate
        batch's completion (wakeups are routed per kind, not broadcast).
        Pinned two ways: the spurious-wakeup counter stays 0, and the
        queue-wait histogram shows each kind's entry dispatched exactly
        once."""
        import time

        from consensus_tpu.obs import Registry

        class SlowGenerate(CountingBackend):
            # Slow enough that the score waiter is reliably parked in its
            # untimed mid-flush wait while generate completes.
            def generate(self, requests):
                time.sleep(0.05)
                return super().generate(requests)

        registry = Registry()
        inner = SlowGenerate()
        batching = BatchingBackend(
            inner, flush_ms=500.0, expected_sessions=2, registry=registry, engine=False)
        out = {}

        def gen_worker():
            with batching.session():
                out["gen"] = batching.generate(
                    [GenerationRequest(user_prompt="a", max_tokens=4, seed=1)]
                )

        def score_worker():
            with batching.session():
                out["score"] = batching.score(
                    [ScoreRequest(context="ctx", continuation=" more")]
                )

        threads = [
            threading.Thread(target=gen_worker),
            threading.Thread(target=score_worker),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30.0)
        assert out["gen"][0].text is not None
        assert out["score"][0].ok
        # Both kinds rode ONE all-blocked flush (flush_ms is far above the
        # test's runtime, so a timeout flush would fail the join above).
        assert inner.batches["generate"] == 1
        assert inner.batches["score"] == 1

        families = registry.snapshot()["families"]

        def series(name):
            return {
                tuple(s["labels"].values()): s
                for s in families[name]["series"]
            }

        spurious = series("batching_spurious_wakeups_total")
        assert sum(s["value"] for s in spurious.values()) == 0, spurious
        waits = series("batching_queue_wait_seconds")
        assert waits[("generate",)]["count"] == 1
        assert waits[("score",)]["count"] == 1


class TestSessionCancellation:
    """The drop-at-flush-snapshot seam (ISSUE 5): a cancelled session's
    queued calls are withdrawn with RequestCancelled before any device time
    is spent, the probe is consulted exactly once per entry (in-flight
    entries always complete), and co-batched siblings' slices are
    bit-identical to solo execution."""

    def test_cancelled_entry_dropped_sibling_slice_identical(self):
        from consensus_tpu.backends.base import RequestCancelled
        from consensus_tpu.obs import Registry

        registry = Registry()
        counting = CountingBackend()
        batching = BatchingBackend(
            counting, flush_ms=50.0, expected_sessions=2, registry=registry, engine=False)
        live_request = GenerationRequest(
            user_prompt="live", max_tokens=4, seed=7)
        barrier = threading.Barrier(2)
        out = {}

        def live_worker():
            with batching.session():
                barrier.wait()
                out["live"] = batching.generate([live_request])[0]

        def cancelled_worker():
            with batching.session(cancelled=lambda: True):
                barrier.wait()
                try:
                    batching.generate([GenerationRequest(
                        user_prompt="gone", max_tokens=4, seed=8)])
                except RequestCancelled as exc:
                    out["cancelled"] = exc

        threads = [
            threading.Thread(target=live_worker),
            threading.Thread(target=cancelled_worker),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10.0)

        assert isinstance(out["cancelled"], RequestCancelled)
        # The sibling's result is bit-identical to a solo run: the dropped
        # entry never joined (or perturbed) the merged device batch.
        solo = FakeBackend().generate([live_request])[0]
        assert out["live"].text == solo.text
        families = registry.snapshot()["families"]
        cancelled = families["batching_cancelled_requests_total"]["series"]
        assert sum(s["value"] for s in cancelled) == 1

    def test_probe_consulted_once_per_entry_at_snapshot(self):
        """An entry whose probe is False at the flush snapshot completes
        normally even if the probe turns True later; the NEXT call of the
        same session is then dropped."""
        from consensus_tpu.backends.base import RequestCancelled

        counting = CountingBackend()
        batching = BatchingBackend(counting, flush_ms=5.0, engine=False)
        consults = {"n": 0}

        def probe():
            consults["n"] += 1
            return consults["n"] > 1  # False exactly once: the 1st snapshot

        with batching.session(cancelled=probe):
            first = batching.generate(
                [GenerationRequest(user_prompt="a", max_tokens=4, seed=1)]
            )
            assert first[0].text  # in-flight-at-snapshot work completes
            with pytest.raises(RequestCancelled):
                batching.generate(
                    [GenerationRequest(user_prompt="b", max_tokens=4, seed=2)]
                )
        assert counting.batches["generate"] == 1  # 2nd call: no device time

    def test_broken_probe_treated_as_not_cancelled(self):
        def bad_probe():
            raise RuntimeError("probe exploded")

        batching = BatchingBackend(CountingBackend(), flush_ms=5.0, engine=False)
        with batching.session(cancelled=bad_probe):
            results = batching.generate(
                [GenerationRequest(user_prompt="x", max_tokens=4, seed=3)]
            )
        assert results[0].text  # the flush survived and dispatched
