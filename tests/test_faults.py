"""Deterministic fault injection (backends/faults.py)."""

import json
import math

import numpy as np
import pytest

from consensus_tpu.backends import FakeBackend, GenerationRequest, ScoreRequest
from consensus_tpu.backends.base import BackendLostError, NextTokenRequest
from consensus_tpu.backends.faults import (
    FaultInjectingBackend,
    FaultPlan,
    FaultSpec,
)
from consensus_tpu.obs.metrics import Registry


def make(plan, **kwargs):
    return FaultInjectingBackend(
        FakeBackend(), plan, registry=Registry(), **kwargs
    )


class TestFaultSpec:
    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError, match="unknown fault kind"):
            FaultSpec(kind="nope")

    def test_unknown_op_rejected(self):
        with pytest.raises(ValueError, match="unknown op"):
            FaultSpec(kind="latency", op="frobnicate")

    def test_rate_bounds(self):
        with pytest.raises(ValueError, match="rate"):
            FaultSpec(kind="transient_error", rate=1.5)

    def test_rate_firing_is_deterministic(self):
        spec = FaultSpec(kind="transient_error", rate=0.3)
        fired = [spec.fires(7, 0, "generate", i) for i in range(64)]
        assert fired == [spec.fires(7, 0, "generate", i) for i in range(64)]
        assert any(fired) and not all(fired)
        # Different seed -> different firing pattern.
        assert fired != [spec.fires(8, 0, "generate", i) for i in range(64)]


class TestFaultPlan:
    def test_from_spec_accepts_dict_json_and_none(self):
        plan = FaultPlan.from_spec(
            {"seed": 3, "faults": [{"kind": "latency", "latency_s": 0.1}]}
        )
        assert plan.seed == 3 and plan.faults[0].kind == "latency"
        as_json = FaultPlan.from_spec(json.dumps(
            {"faults": [{"kind": "truncate", "op": "generate"}]}))
        assert as_json.faults[0].op == "generate"
        assert FaultPlan.from_spec(None) is None
        assert FaultPlan.from_spec(plan) is plan

    def test_from_spec_rejects_non_dict(self):
        with pytest.raises(ValueError, match="fault plan"):
            FaultPlan.from_spec("[1, 2]")


class TestInjection:
    def test_transient_error_at_pinned_call_index(self):
        backend = make({"faults": [
            {"kind": "transient_error", "op": "generate", "call_index": 1}]})
        req = [GenerationRequest(user_prompt="p", seed=0, max_tokens=8)]
        backend.generate(req)  # call 0: clean
        with pytest.raises(RuntimeError, match="injected transient"):
            backend.generate(req)  # call 1: faulted
        backend.generate(req)  # call 2: clean again

    def test_timeout_error_kind(self):
        backend = make({"faults": [
            {"kind": "timeout_error", "op": "score", "call_index": 0}]})
        with pytest.raises(TimeoutError):
            backend.score([ScoreRequest(context="c", continuation="x")])

    def test_truncate_halves_text_and_sets_finish_reason(self):
        clean = FakeBackend()
        backend = make({"faults": [
            {"kind": "truncate", "op": "generate", "call_index": 0}]})
        req = [GenerationRequest(user_prompt="p", seed=0, max_tokens=32)]
        ref = clean.generate(req)[0]
        res = backend.generate(req)[0]
        assert res.finish_reason == "length"
        assert res.text == ref.text[: max(1, len(ref.text) // 2)]

    def test_nan_poison_targets_one_score_row(self):
        backend = make({"faults": [
            {"kind": "nan_logprobs", "op": "score", "call_index": 0,
             "row_index": 1}]})
        reqs = [ScoreRequest(context="c", continuation=f"row {i}")
                for i in range(3)]
        results = backend.score(reqs)
        clean = FakeBackend().score(reqs)
        assert math.isnan(results[1].logprobs[0])
        assert results[0].logprobs == clean[0].logprobs
        assert results[2].logprobs == clean[2].logprobs

    def test_inf_poison_next_token(self):
        backend = make({"faults": [
            {"kind": "inf_logprobs", "op": "next_token", "call_index": 0}]})
        cands = backend.next_token_logprobs(
            [NextTokenRequest(user_prompt="p", k=3)])[0]
        assert math.isinf(cands[0].logprob)

    def test_embed_poison(self):
        backend = make({"faults": [
            {"kind": "nan_logprobs", "op": "embed", "call_index": 0,
             "row_index": 0}]})
        vectors = backend.embed(["a", "b"])
        assert np.isnan(vectors[0, 0]) and np.isfinite(vectors[1]).all()

    def test_device_lost_is_sticky(self):
        backend = make({"faults": [
            {"kind": "device_lost", "op": "generate", "call_index": 1}]})
        req = [GenerationRequest(user_prompt="p", seed=0, max_tokens=8)]
        backend.generate(req)
        with pytest.raises(BackendLostError):
            backend.generate(req)
        # Every subsequent call on every op fails: the device is gone.
        with pytest.raises(BackendLostError):
            backend.score([ScoreRequest(context="c", continuation="x")])
        with pytest.raises(BackendLostError):
            backend.embed(["a"])

    def test_latency_uses_injected_sleep(self):
        slept = []
        backend = FaultInjectingBackend(
            FakeBackend(),
            {"faults": [{"kind": "latency", "op": "generate",
                         "call_index": 0, "latency_s": 1.5}]},
            registry=Registry(),
            sleep=slept.append,
        )
        backend.generate([GenerationRequest(user_prompt="p", max_tokens=4)])
        assert slept == [1.5]

    def test_injection_counter(self):
        registry = Registry()
        backend = FaultInjectingBackend(
            FakeBackend(),
            {"faults": [{"kind": "transient_error", "op": "generate",
                         "call_index": 0}]},
            registry=registry,
        )
        with pytest.raises(RuntimeError):
            backend.generate([GenerationRequest(user_prompt="p")])
        prom = registry.to_prometheus()
        assert 'faults_injected_total{kind="transient_error",op="generate"} 1'\
            in prom

    def test_no_fused_session_escape_hatch(self):
        # Fused sessions would bypass the injection seam; the wrapper must
        # not advertise the capability.
        backend = make({"faults": []})
        assert not hasattr(backend, "open_fused_token_search")

    def test_clean_plan_is_bit_transparent(self):
        backend = make({"faults": []})
        reqs = [GenerationRequest(user_prompt="p", seed=s, max_tokens=16)
                for s in range(3)]
        assert [r.text for r in backend.generate(reqs)] == [
            r.text for r in FakeBackend().generate(reqs)]
