"""Brownout + graceful-degradation tests (ISSUE 5).

Four layers, all hardware-free:

* controller units — hysteresis (immediate escalation, dwell-gated
  single-step de-escalation), tail-biased p95 EWMA, breaker coupling,
  threshold validation;
* scheduler plumbing — budget-clock injection for clock-aware handlers,
  brownout scale stamped on dispatched tickets, legacy handlers untouched;
* HTTP surface — deadline expiry returns 200 + ``"degraded": true`` when a
  wave completed, ``/healthz`` exposes the controller snapshot;
* the overload acceptance proof — open-loop load far above capacity with
  brownout ON: every admitted request answers 200 (zero 504s, zero
  failures), a measurable fraction degraded, the tier actually rose — and
  with the controller OFF a quiet server's statement is byte-identical to
  the offline generator.
"""

import json
import urllib.request

import pytest

from consensus_tpu.backends.fake import FakeBackend
from consensus_tpu.methods import get_method_generator
from consensus_tpu.obs.metrics import Registry
from consensus_tpu.serve import RequestScheduler, create_server, parse_request
from consensus_tpu.serve.brownout import BrownoutController
from tests.test_serve import OPINIONS  # shared scenario text
from tests.test_serve import ISSUE, SlowCountingBackend, _post


def _request(seed=7, **overrides):
    payload = {
        "issue": ISSUE,
        "agent_opinions": OPINIONS,
        "method": "best_of_n",
        "params": {"n": 4, "max_tokens": 24},
        "seed": seed,
        "evaluate": False,
    }
    payload.update(overrides)
    return parse_request(payload)


# ---------------------------------------------------------------------------
# controller units (fake clock: hysteresis is about time, so own the time)
# ---------------------------------------------------------------------------


class FakeNow:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def _controller(now=None, **kwargs):
    kwargs.setdefault("registry", Registry())
    if now is not None:
        kwargs["now"] = now
    return BrownoutController(**kwargs)


class TestControllerTiers:
    def test_starts_at_tier_zero(self):
        controller = _controller()
        assert controller.tier == 0
        assert controller.scale == 1.0

    def test_escalation_is_immediate_and_multi_tier(self):
        controller = _controller(now=FakeNow())
        # Queue 120% full: straight to the top tier in ONE event.
        assert controller.update(12, 10, 0, 4) == 3
        assert controller.scale == 0.25

    def test_deescalation_needs_dwell_and_exit_threshold(self):
        now = FakeNow()
        controller = _controller(now=now, min_dwell_s=2.0)
        controller.update(7, 10, 0, 4)  # 0.70 -> tier 1
        assert controller.tier == 1
        # Below exit (0.40) but inside the dwell: stays.
        assert controller.update(1, 10, 0, 4) == 1
        now.t += 2.5
        assert controller.update(1, 10, 0, 4) == 0

    def test_hysteresis_band_holds_the_tier(self):
        now = FakeNow()
        controller = _controller(now=now, min_dwell_s=2.0)
        controller.update(7, 10, 0, 4)  # tier 1
        now.t += 10.0
        # 0.50 sits between exit (0.40) and enter (0.65): no flapping in
        # either direction, ever.
        for _ in range(5):
            assert controller.update(5, 10, 0, 4) == 1

    def test_deescalation_is_single_step(self):
        now = FakeNow()
        controller = _controller(now=now, min_dwell_s=1.0)
        controller.update(12, 10, 0, 4)  # tier 3
        now.t += 5.0
        assert controller.update(0, 10, 0, 4) == 2  # one step only
        # Each drop re-arms the dwell.
        assert controller.update(0, 10, 0, 4) == 2
        now.t += 5.0
        assert controller.update(0, 10, 0, 4) == 1

    def test_saturated_workers_alone_stay_tier_zero(self):
        controller = _controller()
        # All workers busy, empty queue: 0.6 * 1.0 < 0.65 — busy is not
        # overloaded.
        assert controller.update(0, 64, 4, 4) == 0

    def test_breaker_states_pressurize(self):
        controller = _controller(now=FakeNow())
        assert controller.update(0, 10, 0, 4, breaker_state="half_open") == 2
        assert controller.update(0, 10, 0, 4, breaker_state="open") == 3

    def test_latency_slo_term(self):
        controller = _controller(now=FakeNow(), target_p95_s=1.0)
        controller.record_latency(2.0)  # first sample seeds the estimate
        # p95/target = 2.0 >= 1.1: top tier with an empty queue.
        assert controller.update(0, 64, 0, 4) == 3

    def test_tail_biased_ewma(self):
        controller = _controller(ewma_alpha=0.3, quantile=0.95)
        controller.record_latency(1.0)
        for _ in range(20):
            controller.record_latency(0.1)  # below-estimate samples
        estimate = controller.snapshot()["p95_ewma_s"]
        # alpha_down = 0.3 * 0.05/0.95 — the estimate decays ~19x slower
        # than plain EWMA, staying near the tail.
        assert estimate > 0.6

    def test_threshold_validation(self):
        with pytest.raises(ValueError, match="strictly below"):
            _controller(enter_thresholds=(0.5, 0.8, 1.0),
                        exit_thresholds=(0.5, 0.6, 0.8))

    def test_snapshot_and_dispatch_counts(self):
        controller = _controller(now=FakeNow())
        controller.note_dispatch()
        controller.update(12, 10, 0, 4)
        controller.note_dispatch()
        snapshot = controller.snapshot()
        assert snapshot["tier"] == 3
        assert snapshot["budget_scale"] == 0.25
        assert snapshot["tier_scales"] == [1.0, 0.7, 0.45, 0.25]
        assert snapshot["tier_request_counts"] == {
            "0": 1, "1": 0, "2": 0, "3": 1}


# ---------------------------------------------------------------------------
# scheduler plumbing: per-ticket BudgetClocks
# ---------------------------------------------------------------------------


class TestSchedulerClockPlumbing:
    def _run_one(self, handler, *, brownout=None, pre_pressure=None,
                 **kwargs):
        kwargs.setdefault("max_queue_depth", 8)
        kwargs.setdefault("max_inflight", 1)
        kwargs.setdefault("default_timeout_s", 30.0)
        scheduler = RequestScheduler(
            handler, FakeBackend(), registry=Registry(), brownout=brownout,
            **kwargs,
        )
        if pre_pressure is not None:
            brownout.update(*pre_pressure)
        scheduler.start()
        try:
            ticket = scheduler.submit(_request())
            assert ticket.wait(timeout=10.0)
            return ticket
        finally:
            scheduler.shutdown(drain=True, timeout=10.0)

    def test_clock_aware_handler_gets_deadline_clock(self):
        seen = {}

        def handler(request, backend, budget_clock=None):
            seen["clock"] = budget_clock
            return {"statement": "s"}

        ticket = self._run_one(handler)
        assert ticket.outcome == "ok"
        clock = seen["clock"]
        assert clock is not None
        remaining = clock.remaining()
        # Ticket deadline (30s) minus the anytime margin.
        assert remaining is not None and 25.0 < remaining <= 29.8

    def test_brownout_scale_stamped_on_clock(self):
        seen = {}

        def handler(request, backend, budget_clock=None):
            seen["clock"] = budget_clock
            return {"statement": "s"}

        controller = _controller(min_dwell_s=60.0)  # hold the tier
        ticket = self._run_one(
            handler, brownout=controller, pre_pressure=(9, 10, 0, 1))
        assert ticket.outcome == "ok"
        clock = seen["clock"]
        assert clock.tier == 2
        assert clock.scale == 0.45
        counts = controller.snapshot()["tier_request_counts"]
        assert counts["2"] == 1

    def test_unbounded_unscaled_handler_gets_none(self):
        seen = {"called": False}

        def handler(request, backend, budget_clock=None):
            seen["called"] = True
            seen["clock"] = budget_clock
            return {"statement": "s"}

        ticket = self._run_one(handler, default_timeout_s=None)
        assert ticket.outcome == "ok"
        assert seen["called"] and seen["clock"] is None

    def test_legacy_handler_untouched(self):
        def handler(request, backend):
            return {"statement": "legacy"}

        controller = _controller(min_dwell_s=60.0)
        ticket = self._run_one(
            handler, brownout=controller, pre_pressure=(12, 10, 0, 1))
        assert ticket.outcome == "ok"
        assert ticket.result()["statement"] == "legacy"

    def test_degraded_value_outcome_and_counter(self):
        registry = Registry()

        def handler(request, backend, budget_clock=None):
            return {"statement": "partial", "degraded": True,
                    "degraded_reason": "deadline"}

        scheduler = RequestScheduler(
            handler, FakeBackend(), registry=registry,
            max_queue_depth=8, max_inflight=1, default_timeout_s=30.0,
        )
        scheduler.start()
        try:
            ticket = scheduler.submit(_request())
            assert ticket.wait(timeout=10.0)
            assert ticket.outcome == "degraded"
            assert ticket.result()["degraded"] is True
        finally:
            scheduler.shutdown(drain=True, timeout=10.0)
        family = registry.snapshot()["families"]["serve_degraded_total"]
        assert sum(s["value"] for s in family["series"]) == 1


# ---------------------------------------------------------------------------
# HTTP surface
# ---------------------------------------------------------------------------


class TestHTTPDegradedPath:
    def test_deadline_with_completed_wave_returns_degraded_200(self):
        """beam_search at ~0.3 s/step against a 1.2 s deadline: several
        steps complete, then the clock expires — the client gets 200 with
        the best-so-far statement, not a 504."""
        instance = create_server(
            backend=SlowCountingBackend(delay_s=0.15), port=0,
            max_inflight=1, registry=Registry(),
        ).start()
        try:
            status, body = _post(instance.base_url, {
                "issue": ISSUE, "agent_opinions": OPINIONS,
                "method": "beam_search",
                "params": {"beam_width": 2, "max_tokens": 20},
                "seed": 3, "evaluate": False, "timeout_s": 1.2,
            }, timeout=30.0)
            assert status == 200
            assert body["degraded"] is True
            assert body["degraded_reason"] in ("deadline", "cancelled")
            assert body["statement"]
            spent = body["budget_spent"]
            assert spent["steps_done"] < spent["steps_planned"]
        finally:
            instance.stop()

    def test_healthz_exposes_brownout_snapshot(self):
        instance = create_server(
            backend="fake", port=0, brownout=True, target_p95_ms=500.0,
            registry=Registry(),
        ).start()
        try:
            with urllib.request.urlopen(
                instance.base_url + "/healthz", timeout=5.0
            ) as response:
                health = json.loads(response.read().decode())
            brownout = health["brownout"]
            assert brownout["tier"] == 0
            assert brownout["budget_scale"] == 1.0
            assert brownout["tier_scales"] == [1.0, 0.7, 0.45, 0.25]
            assert brownout["target_p95_s"] == 0.5
            assert "tier_request_counts" in brownout
        finally:
            instance.stop()

    def test_healthz_has_no_brownout_key_when_disabled(self):
        instance = create_server(
            backend="fake", port=0, registry=Registry()).start()
        try:
            with urllib.request.urlopen(
                instance.base_url + "/healthz", timeout=5.0
            ) as response:
                health = json.loads(response.read().decode())
            assert "brownout" not in health
        finally:
            instance.stop()


# ---------------------------------------------------------------------------
# acceptance proof: overload with brownout ON; identity with it OFF
# ---------------------------------------------------------------------------


class TestBrownoutAcceptance:
    def test_overload_yields_full_availability_with_degradation(self):
        """ISSUE 5 acceptance: open-loop load far beyond capacity with the
        controller enabled — every admitted request is answered (zero 504s,
        zero failures), a measurable fraction degraded, and the tier rose."""
        from consensus_tpu.serve.loadgen import run_loadgen, scenario_requests

        n_requests = 16
        instance = create_server(
            backend=SlowCountingBackend(delay_s=0.08), port=0,
            max_inflight=2, max_queue_depth=n_requests, brownout=True,
            registry=Registry(),
        ).start()
        try:
            report = run_loadgen(
                instance.base_url,
                scenario_requests(
                    n_requests, method="best_of_n",
                    params={"n": 8, "max_tokens": 24}, timeout_s=30.0),
                rate_rps=400.0,  # ~all requests arrive instantly
                client_timeout_s=60.0,
            )
        finally:
            instance.stop()
        assert report["timeouts"] == 0
        assert report["failed"] == 0
        assert report["rejected"] == 0
        assert report["availability"] == 1.0  # the headline: no 504s at all
        assert report["degraded"] > 0
        assert report["degraded_fraction"] > 0
        # The controller actually engaged: requests dispatched above tier 0.
        tier_counts = report["tier_request_counts"]
        assert sum(
            count for tier, count in tier_counts.items() if tier != "0"
        ) > 0
        # Degraded 200s still carry statements.
        assert all(o.statement for o in report["outcomes"]
                   if o.status == 200)

    def test_controller_disabled_is_byte_identical(self):
        """With brownout OFF and no pressure, a served statement must be
        byte-identical to the same (method, params, seed) run straight
        through the generator — the seam and scheduler plumbing are inert."""
        params = {"n": 4, "max_tokens": 24}
        expected_gen = get_method_generator(
            "best_of_n", FakeBackend(), {**params, "seed": 11})
        expected = expected_gen.generate_statement(ISSUE, OPINIONS)
        assert not expected_gen.degraded

        instance = create_server(
            backend="fake", port=0, registry=Registry()).start()
        try:
            status, body = _post(instance.base_url, {
                "issue": ISSUE, "agent_opinions": OPINIONS,
                "method": "best_of_n", "params": params, "seed": 11,
                "evaluate": False,
            })
        finally:
            instance.stop()
        assert status == 200
        assert body["statement"] == expected
        assert "degraded" not in body
